"""Overlapped decode dispatch: device-resident carry + in-flight window.

The serial serving loop was a strict host<->device ping-pong: build seven
per-slot arrays with ``jnp.asarray`` (seven small H2D transfers), dispatch
one decode chunk, block on ``jax.device_get`` for its tokens, then do all
host work (emit, EOS, stop sequences, admission bookkeeping) while the
device sits idle. Under JAX async dispatch none of that serialization is
necessary — a jitted call returns futures immediately, and the ONLY true
sync point is token readback. This module restructures the loop around
that fact:

- **Device-resident carry** (``self.carry``): the per-slot decode inputs
  (``tokens``, ``positions``, ``temps``, ``top_ks``, ``top_ps``, block
  ``tables``) live on device permanently and are *donated through* every
  decode chunk, which returns them advanced (the scan already computed
  next-token and next-position — the serial loop threw that away and
  re-uploaded host copies). Host-side slot changes (admission, prefill
  completion, preemption, spec-round commits, table growth) set per-slot
  dirty flags; the next dispatch folds every dirty row into ONE jitted
  masked merge (``_apply_carry_update``: two bool masks + a packed int
  matrix + a packed float matrix + the table matrix) instead of seven
  fresh uploads per iteration. Rows that are not dirty are
  device-authoritative and never clobbered by stale host state.

- **Dispatch-ahead window** (``self.window``, depth ``dispatch_depth``):
  because the carry chains device-side, chunk N+1 can be dispatched
  immediately after chunk N without reading chunk N's tokens. Token
  readback moves to a FIFO of in-flight entries drained with non-blocking
  ``jax.Array.is_ready()`` checks; the host only blocks when the window
  is full (and then on the *oldest* entry, which the device finished or
  is about to finish while the newest computes). Emit/EOS handling for
  chunk N thus overlaps chunk N+1's decode. Depth 1 reproduces the
  serial loop exactly — it is the escape hatch
  (``DEVSPACE_ENGINE_OVERLAP=off``) and the reference the equivalence
  suite compares against.

- **Overshoot and zombies**: the engine already truncates host-side
  (a slot that hits EOS or max_new mid-chunk discards the chunk tail),
  so dispatch-ahead only widens the same speculation. A slot that
  *finishes* while later chunks still reference it becomes a zombie:
  its blocks stay allocated (``pending_free``) and the slot is not
  re-admitted until every in-flight chunk referencing it has been
  drained — the in-flight writes land in the slot's own blocks (or the
  scratch block once a later dispatch parks it), never in a peer's.

- **Tiered-restore overlap**: the engine's host-KV-tier restores
  (``_restore_spilled``) dispatch their scatter jits against the same
  donated pool chain — under async dispatch they queue behind the
  in-flight window's chunks and compute while the host drains tokens
  and schedules, so a restore costs wall-clock only what outruns the
  window. ``note_restores`` counts how many restores actually found
  chunks in flight (``kv_restores_overlapped`` in stats()).

- **Failure ladder**: a decode failure surfaces at readback (async
  dispatch defers device errors). ``abandon()`` drops the whole window
  — every in-flight chunk's requests are failed by the caller
  (``_fail_outstanding`` calls it first), refs/pending-free are cleared,
  and the carry is rebuilt from scratch (it was donated into the failed
  computation) — before the engine rebuilds the pool. Nothing is ever
  read from a poisoned future.

Per-slot stream equivalence (why depth does not change outputs): the
decode kernel holds each slot's BASE PRNG key (``PRNGKey(seed)``, never
advanced) and derives the sample key for the token at position p+1 as
``fold_in(base, p)`` — a pure function of the token's absolute
position. Attention reads only the slot's own blocks, and the slot's
carry row chains device-side from its prefill seed, so a slot's n-th
emitted token is a function of (prompt, seed, n) only — independent of
chunk sizes, co-resident membership, window depth, AND preemption
points: a preempted-and-resumed request re-derives the identical key
for committed token k regardless of where mid-chunk the preemption
landed (ROADMAP item 2, schedule-invariant sampled streams). The
pinned suite (tests/test_engine_dispatch.py) asserts byte-identical
streams between depth 1 and depth 2 across randomized admit/EOS/
preemption traces, greedy and sampled.
"""

from __future__ import annotations

import time
from collections import deque
from typing import TYPE_CHECKING, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import events as _events
from ..obs.tracing import TRACK_READBACK, device_decode_track

if TYPE_CHECKING:  # pragma: no cover — import cycle guard, types only
    from .engine import InferenceEngine


def _toks_ready(toks) -> bool:
    """Non-blocking readiness probe. ``jax.Array.is_ready()`` where
    available; otherwise report NOT ready — the conservative direction:
    an opportunistic drain is skipped, and correctness never depended on
    it (the window-full blocking drain is what bounds the queue)."""
    probe = getattr(toks, "is_ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:  # noqa: BLE001 — poisoned future: force the
        return True  # blocking path so the error surfaces in drain


class _InFlight:
    """One dispatched-but-unread decode chunk."""

    __slots__ = ("toks", "slots", "gens", "k_steps", "t_dispatch", "lane")

    def __init__(self, toks, slots: list[int], gens: list[int], k_steps: int):
        self.toks = toks  # [k_steps, B] device future
        self.slots = slots  # participating slot indices
        self.gens = gens  # slot.gen at dispatch (re-admission guard)
        self.k_steps = k_steps
        # timeline capture only: dispatch timestamp + window lane, so the
        # profiler can draw the chunk's device residency [dispatch,
        # readback] on a per-lane track and overlapping chunks render
        # side by side instead of stacking on one bar
        self.t_dispatch = 0.0
        self.lane = 0


class DecodeDispatcher:
    """Owns the in-flight decode window and the device-resident carry for
    one :class:`~devspace_tpu.inference.engine.InferenceEngine`.

    The engine's scheduler thread is the only caller — nothing here is
    locked. The dispatcher mutates engine state exactly where the serial
    loop did (``pool``/``_keys`` reassignment on dispatch, ``_emit`` and
    block freeing on drain); the engine keeps scheduling policy
    (admission, preemption ladder, spec interleaving, chunk sizing)."""

    def __init__(self, engine: "InferenceEngine", depth: int):
        if not 1 <= int(depth) <= 8:
            raise ValueError(f"dispatch_depth must be in 1..8, got {depth}")
        self.engine = engine
        self.depth = int(depth)
        B = engine.max_slots
        self.window: deque[_InFlight] = deque()
        # per-slot count of in-flight chunks / in-flight decode steps
        self.refs = [0] * B
        self.inflight_steps = [0] * B
        # slots that finished while still referenced by in-flight chunks:
        # their blocks are freed when the last reference drains (the
        # chunk's readback proves its pool writes completed)
        self.pending_free: set[int] = set()
        # host->device carry dirty flags; start all-dirty so the first
        # dispatch uploads every participant's row
        self._state_dirty = [True] * B
        self._table_dirty = [True] * B
        self.carry = self._fresh_carry()
        # overlap counters (surfaced via engine.stats())
        self.dispatches = 0
        self.carry_updates = 0
        self.occupancy_sum = 0  # window depth summed at each dispatch
        self.readback_wait_s = 0.0  # host time blocked in device_get
        self.loop_busy_s = 0.0  # scheduler-iteration time (engine adds)
        # host-tier restores (engine._restore_spilled): total scatter
        # dispatches and how many went out while decode chunks were in
        # flight — those restores' device work hides behind the window
        self.kv_restores = 0
        self.kv_restores_overlapped = 0

    # -- carry -------------------------------------------------------------
    def _fresh_carry(self) -> dict:
        B, mb = self.engine.max_slots, self.engine.max_blocks
        return {
            "tokens": jnp.zeros((B,), jnp.int32),
            "positions": jnp.zeros((B,), jnp.int32),
            "temps": jnp.zeros((B,), jnp.float32),
            "top_ks": jnp.zeros((B,), jnp.int32),
            "top_ps": jnp.ones((B,), jnp.float32),
            "tables": jnp.zeros((B, mb), jnp.int32),
        }

    def invalidate_state(self, i: int) -> None:
        """Host is now authoritative for slot i's token/position/sampling
        row (admission, prefill completion, spec commit); the next
        dispatch that includes i re-uploads it."""
        self._state_dirty[i] = True

    def invalidate_table(self, i: int) -> None:
        """Slot i's block table changed (_alloc/_free_slot_blocks)."""
        self._table_dirty[i] = True

    def _sync_carry(self, plain: list[int]) -> None:
        """Fold every dirty participating row into the device carry with
        ONE jitted masked merge — the packed update that replaces the
        serial loop's seven per-iteration ``jnp.asarray`` uploads."""
        eng = self.engine
        B = eng.max_slots
        upd = [
            i for i in plain if self._state_dirty[i] or self._table_dirty[i]
        ]
        if not upd:
            return
        state_mask = np.zeros((B,), bool)
        table_mask = np.zeros((B,), bool)
        ints = np.zeros((B, 3), np.int32)
        floats = np.zeros((B, 2), np.float32)
        for i in upd:
            s = eng.slots[i]
            if self._state_dirty[i]:
                state_mask[i] = True
                ints[i] = (s.last_token, s.length - 1, s.req.top_k)
                floats[i] = (s.req.temperature, s.req.top_p)
                self._state_dirty[i] = False
            if self._table_dirty[i]:
                table_mask[i] = True
                self._table_dirty[i] = False
        self.carry = eng._carry_update_jit(
            self.carry,
            jnp.asarray(state_mask),
            jnp.asarray(table_mask),
            jnp.asarray(ints),
            jnp.asarray(floats),
            jnp.asarray(eng._tables),
        )
        self.carry_updates += 1

    # -- window ------------------------------------------------------------
    @property
    def in_flight(self) -> int:
        return len(self.window)

    @property
    def full(self) -> bool:
        return len(self.window) >= self.depth

    def note_restores(self, n: int, overlapped: bool) -> None:
        """Engine hook: ``n`` spilled KV blocks were just restored via
        async scatter dispatches. ``overlapped=True`` when the window
        held in-flight decode chunks at restore time — the scatters
        then chain behind them device-side (the donated pool handle is
        the newest chunk's output) while peers' drain work proceeds,
        which is the overlap the tiered-restore design pays for."""
        self.kv_restores += n
        if overlapped:
            self.kv_restores_overlapped += n

    def slot_busy(self, i: int) -> bool:
        """True while in-flight chunks still reference slot i — a
        finished (zombie) slot must not be re-admitted until they drain,
        because their writes target its still-allocated blocks."""
        return self.refs[i] > 0

    def dispatch(self, plain: list[int], k_steps: int, filters_on: bool) -> None:
        """Send one decode chunk for ``plain`` (async: returns as soon as
        the futures exist) and append it to the in-flight window."""
        eng = self.engine
        self._sync_carry(plain)
        active = np.zeros((eng.max_slots,), bool)
        for i in plain:
            active[i] = True
        eng.pool, self.carry, eng._keys, toks = eng._decode_chunk[
            (k_steps, filters_on)
        ](
            eng.params,
            eng.pool,
            self.carry,
            eng._keys,
            jnp.asarray(active),
            eng._eos_ids,
            eng._min_until,
            eng._logit_bias,
        )
        entry = _InFlight(
            toks, list(plain), [eng.slots[i].gen for i in plain], k_steps
        )
        if eng._timeline is not None:
            entry.t_dispatch = time.monotonic()
            entry.lane = self.dispatches % self.depth
        self.window.append(entry)
        for i in plain:
            self.refs[i] += 1
            self.inflight_steps[i] += k_steps
        self.dispatches += 1
        self.occupancy_sum += len(self.window)
        _events.emit(
            "dispatch", "depth_change", level="debug",
            depth=len(self.window), direction="up", slots=len(plain),
        )

    def drain(self, block: bool = False) -> int:
        """Retire in-flight chunks in dispatch order. ``block=True``
        forces readback of the oldest entry (the window-full / idle
        path); after it, and always when ``block=False``, only entries
        whose tokens are already host-visible are consumed — the
        non-blocking readiness check that lets emit work overlap the
        newest chunk's decode. Returns the number of entries drained."""
        drained = 0
        while self.window:
            if not block and not _toks_ready(self.window[0].toks):
                break
            self._consume_oldest()
            drained += 1
            block = False
        return drained

    def drain_all(self) -> None:
        """Blocking drain of the whole window — required before any
        operation that assumes settled slot state: the preemption
        ladder, a speculative round (it rewrites slot K/V and commits
        host-side), and engine shutdown."""
        while self.window:
            self._consume_oldest()

    def _consume_oldest(self) -> None:
        entry = self.window.popleft()
        t0 = time.monotonic()
        try:
            toks = np.asarray(jax.device_get(entry.toks))
        finally:
            t1 = time.monotonic()
            self.readback_wait_s += t1 - t0
        eng = self.engine
        tl = eng._timeline
        if tl is not None and entry.t_dispatch:
            # device residency [dispatch, readback-complete] on the
            # chunk's window lane; the host-side blocked wait separately
            traces = [
                getattr(eng.slots[i].req, "_obs_trace", None)
                for i, g in zip(entry.slots, entry.gens)
                if eng.slots[i].req is not None and eng.slots[i].gen == g
            ]
            tl.add(
                device_decode_track(entry.lane),
                f"decode x{entry.k_steps}",
                entry.t_dispatch,
                t1,
                slots=list(entry.slots),
                k_steps=entry.k_steps,
                trace_ids=[
                    t.trace_id for t in traces if t is not None
                ],
            )
            tl.add(TRACK_READBACK, "device_get", t0, t1)
        for n, i in enumerate(entry.slots):
            self.refs[i] -= 1
            self.inflight_steps[i] -= entry.k_steps
            slot = eng.slots[i]
            if slot.req is not None and slot.gen == entry.gens[n]:
                for j in range(entry.k_steps):
                    if slot.req is None:
                        break  # finished mid-chunk; rest is speculative
                    eng._emit(i, int(toks[j, i]))
            if self.refs[i] == 0 and i in self.pending_free:
                self.pending_free.discard(i)
                eng._free_slot_blocks(i)
        _events.emit(
            "dispatch", "depth_change", level="debug",
            depth=len(self.window), direction="down",
        )

    def abandon(self) -> None:
        """Drop the whole in-flight window without reading it — the
        failure path (``_fail_outstanding`` calls this before failing
        slot-resident requests and rebuilding the pool). Every future in
        the window may be poisoned, and the carry was donated into the
        failed chain, so both are discarded; zombie blocks are released
        host-side (the allocator is about to be reset or reused)."""
        if self.window:
            _events.emit(
                "dispatch", "window_abandoned", level="warn",
                dropped=len(self.window),
            )
        self.window.clear()
        B = self.engine.max_slots
        self.refs = [0] * B
        self.inflight_steps = [0] * B
        for i in sorted(self.pending_free):
            self.engine._free_slot_blocks(i)
        self.pending_free.clear()
        self._state_dirty = [True] * B
        self._table_dirty = [True] * B
        self.carry = self._fresh_carry()

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        occ = (
            round(self.occupancy_sum / self.dispatches, 3)
            if self.dispatches
            else 0.0
        )
        return {
            "dispatch_depth": self.depth,
            "dispatch_depth_occupancy": occ,
            "decode_dispatches": self.dispatches,
            "readback_wait_s": round(self.readback_wait_s, 4),
            "host_sched_s": round(
                max(0.0, self.loop_busy_s - self.readback_wait_s), 4
            ),
            "carry_updates": self.carry_updates,
            "kv_restores_overlapped": self.kv_restores_overlapped,
        }


def resolve_dispatch_depth(dispatch_depth: Optional[int]) -> int:
    """Window depth resolution: explicit constructor arg wins, then the
    ``DEVSPACE_ENGINE_OVERLAP`` env knob (``off``/``0``/``serial`` -> the
    serial depth-1 loop; an integer -> that depth), default 2 — overlap
    is ON by default, depth 2 being the sweet spot (one chunk computing
    while one drains; deeper windows only add speculative overshoot)."""
    import os

    if dispatch_depth is not None:
        return int(dispatch_depth)
    env = os.environ.get("DEVSPACE_ENGINE_OVERLAP", "").strip().lower()
    if env in ("off", "0", "serial", "false", "no"):
        return 1
    if env in ("", "on", "true", "yes", "1", "default"):
        return 2
    try:
        return max(1, int(env))
    except ValueError:
        return 2
