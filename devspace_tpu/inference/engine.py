"""Continuous-batching inference engine (iteration-level scheduling).

The serving-side counterpart of the training stack — no reference
counterpart (the reference ships no model code, SURVEY.md §2.13); this is
what turns the llama-inference example from a one-request-at-a-time server
into a throughput engine.

Design, TPU-first:
- **Static shapes throughout**: the KV cache is preallocated at
  ``[layers, max_slots, max_len, kv_heads, head_dim]`` and every decode
  iteration runs ONE jitted step over all slots — empty slots just compute
  masked garbage (their cost is already paid; admission fills them). No
  recompilation ever happens during serving.
- **Iteration-level scheduling** (the Orca/vLLM insight): new requests are
  admitted between decode iterations, not between requests, so a long
  generation does not block a short one — per-slot positions make every
  slot's causal mask independent.
- **Bucketed prefill**: prompts are padded to power-of-two buckets and
  prefit in ONE full-sequence forward pass (``forward(return_kv=True)``
  — big MXU matmuls, not a token-by-token scan), then the K/V is
  scattered into the engine cache — a handful of compilations total,
  amortized across the process lifetime.
- **Device-side sampling + chunked decode**: sampling (greedy or
  per-slot temperature) happens inside the jitted step, and up to
  ``chunk_max`` tokens are decoded per dispatch via ``lax.scan`` — one
  host round-trip per chunk instead of per token. On a remote/tunneled
  accelerator the round-trip dominates single-token decode, so this is
  the difference between RTT-bound and compute-bound serving. A slot
  that hits EOS mid-chunk wastes at most chunk_max-1 speculative tokens
  (truncated host-side; the cache-write-ahead is safe — every position
  is rewritten in the same step that first attends to it).

Per-request sampling: greedy, temperature, top-k and top-p (nucleus);
optional EOS early stop.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..models import transformer as tfm


def sample_logits(key, logits, temperature, top_k=0, top_p=1.0):
    """One-token sampling with greedy / temperature / top-k / top-p —
    pure jnp so it runs inside the jitted decode chunk (vmapped per slot)
    and host-side for the prefill's first token.

    ``temperature <= 0`` is greedy (k/p ignored). ``top_k == 0`` and
    ``top_p >= 1`` disable their filters. Dynamic per-slot k/p: filters
    are computed by sorting rather than lax.top_k so k need not be a
    static constant."""
    logits = logits.astype(jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sorted_desc = jnp.sort(scaled)[::-1]
    # top-k: keep logits >= the k-th largest (k=0 -> keep all)
    kth = sorted_desc[jnp.clip(top_k - 1, 0, vocab - 1)]
    keep_k = jnp.where(top_k > 0, scaled >= kth, True)
    # top-p: keep tokens whose mass-before-them (sorted desc) is < top_p —
    # the shifted-cumsum form always keeps >= 1 token and is immune to
    # float32 cumsum never quite reaching top_p on a large vocab
    probs_desc = jax.nn.softmax(sorted_desc)
    shifted = jnp.cumsum(probs_desc) - probs_desc
    count = jnp.sum(shifted < top_p)
    p_threshold = sorted_desc[jnp.clip(count - 1, 0, vocab - 1)]
    keep_p = jnp.where(top_p < 1.0, scaled >= p_threshold, True)
    filtered = jnp.where(keep_k & keep_p, scaled, -jnp.inf)
    sampled = jax.random.categorical(key, filtered).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


@dataclass
class Request:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # >= 1 = disabled
    # filled by the engine
    tokens: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None

    def result(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens

    def stream(self, timeout: Optional[float] = None, poll: float = 0.02):
        """Yield tokens as they are generated (list appends by the engine
        thread are atomic under the GIL; chunked decode delivers them in
        bursts of up to chunk_max). Raises like ``result`` on error, and
        TimeoutError when no NEW token arrives within ``timeout`` (the
        deadline resets on progress — a long healthy generation never
        times out)."""
        sent = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            n = len(self.tokens)
            if n > sent and timeout is not None:
                deadline = time.monotonic() + timeout
            while sent < n:
                yield self.tokens[sent]
                sent += 1
            if self.done.is_set():
                if self.error:
                    raise RuntimeError(self.error)
                for tok in self.tokens[sent:]:
                    yield tok
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("generation stalled")
            self.done.wait(poll)


class _Slot:
    __slots__ = ("req", "length", "remaining", "last_token")

    def __init__(self):
        self.req: Optional[Request] = None


class InferenceEngine:
    """Continuous-batching engine over ``max_slots`` concurrent sequences.

    ``submit()`` is thread-safe and returns the Request whose ``result()``
    blocks until generation completes. ``start()`` spawns the scheduler
    thread; ``stop()`` drains and joins it."""

    def __init__(
        self,
        params: dict,
        cfg: tfm.TransformerConfig,
        max_slots: int = 8,
        max_len: Optional[int] = None,
        mesh=None,
        model_axis: str = "model",
        chunk_max: int = 8,
    ):
        """``mesh`` turns on tensor-parallel serving: params are placed per
        ``models.transformer.param_partition_spec`` and the KV cache is
        sharded over its head dim on ``model_axis`` (requires
        ``n_kv_heads % mesh.shape[model_axis] == 0``); the decode jit then
        runs under GSPMD, which inserts the attention/FFN collectives.
        Scheduling is unchanged — TP is invisible to the slot machinery."""
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len or cfg.max_seq_len
        self.mesh = mesh
        L, Hkv, D = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        cache_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .quantization import QuantizedLinear

            if any(
                isinstance(leaf, QuantizedLinear)
                for leaf in jax.tree_util.tree_leaves(
                    params, is_leaf=lambda x: isinstance(x, QuantizedLinear)
                )
            ):
                raise ValueError(
                    "tensor-parallel serving does not yet compose with "
                    "int8-quantized params — pass dense params with mesh, "
                    "or quantized params without"
                )
            if Hkv % mesh.shape[model_axis]:
                raise ValueError(
                    f"n_kv_heads {Hkv} not divisible by mesh axis "
                    f"'{model_axis}' ({mesh.shape[model_axis]})"
                )
            cache_sharding = NamedSharding(
                mesh, P(None, None, None, model_axis, None)
            )
            self.params = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
                params,
                tfm.param_partition_spec(cfg, model_axis=model_axis),
            )

        def fresh_cache():
            cache = {
                "k": jnp.zeros((L, max_slots, self.max_len, Hkv, D), cfg.dtype),
                "v": jnp.zeros((L, max_slots, self.max_len, Hkv, D), cfg.dtype),
            }
            if cache_sharding is not None:
                cache = {
                    k: jax.device_put(v, cache_sharding)
                    for k, v in cache.items()
                }
            return cache

        self._fresh_cache = fresh_cache
        self.cache = self._fresh_cache()
        self.slots = [_Slot() for _ in range(max_slots)]
        self.pending: queue.Queue[Request] = queue.Queue()
        # serving counters (read via stats(); mutated by the scheduler
        # thread and — for fail-outs — by stop(); read-atomic under the GIL)
        self._started_at = None  # set by start()
        self.requests_completed = 0
        self.requests_failed = 0
        self.tokens_generated = 0
        self._stop = threading.Event()
        # serializes submit's check+put against stop's set+drain, closing
        # the window where a request lands in the queue after the drain
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

        # The per-slot decode core lives with the model (single source of
        # truth for the layer math): models.transformer.decode_tokens.
        # Donating the cache is what keeps this viable at scale — an
        # undonated update would copy the multi-GB K/V buffers per token.
        # Sampling runs on device and n_steps tokens are decoded per
        # dispatch (lax.scan), so the host pays one round-trip per chunk.
        self.chunk_max = max(1, int(chunk_max))
        self._keys = jnp.zeros((max_slots, 2), jnp.uint32)

        def decode_chunk(
            params,
            cache,
            tokens,
            positions,
            temps,
            top_ks,
            top_ps,
            keys,
            n_steps,
            use_filters,
        ):
            def step(carry, _):
                cache, tok, pos, keys = carry
                logits, cache = tfm.decode_tokens(params, cache, tok, pos, cfg)
                split = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
                keys, subs = split[:, 0], split[:, 1]
                if use_filters:
                    tok = jax.vmap(sample_logits)(
                        subs, logits, temps, top_ks, top_ps
                    )
                else:
                    # cheap path: no per-token vocab sort when no active
                    # slot asked for top-k/top-p
                    sampled = jax.vmap(
                        lambda k, l, t: jax.random.categorical(
                            k, l / jnp.maximum(t, 1e-6)
                        )
                    )(subs, logits, temps).astype(jnp.int32)
                    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    tok = jnp.where(temps > 0, sampled, greedy)
                return (cache, tok, pos + 1, keys), tok

            (cache, _, _, keys), toks = jax.lax.scan(
                step, (cache, tokens, positions, keys), None, length=n_steps
            )
            return cache, keys, toks  # toks [n_steps, B]

        # one compile per (chunk size, filters on/off) — both static
        from functools import partial as _partial

        self._decode_chunk = {
            (k, filt): jax.jit(
                _partial(decode_chunk, n_steps=k, use_filters=filt),
                donate_argnums=1,
            )
            for k in self._chunk_sizes()
            for filt in (False, True)
        }

        def prefill(params, prompt):  # prompt [1, T_bucket]
            # ONE full-sequence forward (big MXU matmuls) instead of a
            # token-by-token decode scan — forward's return_kv hands back
            # the roped per-layer K/V in exactly the cache layout. Cast to
            # the cache dtype: params may be f32 while the cache is bf16.
            logits, (k, v) = tfm.forward(params, prompt, self.cfg, return_kv=True)
            return {
                "k": k.astype(self.cfg.dtype),
                "v": v.astype(self.cfg.dtype),
            }, logits  # k/v [L, 1, T_bucket, Hkv, D]

        # jit's own shape-keyed cache compiles once per prompt bucket
        self._prefill = jax.jit(prefill)

        def insert(cache, k1, v1, slot_idx):
            # Write one prefilled sequence's K/V bucket into its slot, in
            # place (donated). k1/v1: [L, bucket, Hkv, D]. Writing the pad
            # tail too is safe: positions >= the true prompt length are
            # overwritten by decode before the mask ever exposes them.
            # slot_idx stays dynamic -> one compile per prompt bucket, not
            # per (slot, length) pair.
            return {
                "k": jax.lax.dynamic_update_slice(
                    cache["k"], k1[:, None], (0, slot_idx, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    cache["v"], v1[:, None], (0, slot_idx, 0, 0, 0)
                ),
            }

        self._insert = jax.jit(insert, donate_argnums=0)

    # -- public api --------------------------------------------------------
    def submit(
        self,
        prompt_ids: list[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> Request:
        if not prompt_ids:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt_ids)}+{max_new_tokens}) "
                f"exceeds max_len {self.max_len}"
            )
        if top_k < 0 or top_p <= 0.0:
            raise ValueError("need top_k >= 0 and top_p > 0 (>= 1 disables)")
        req = Request(
            list(prompt_ids),
            int(max_new_tokens),
            temperature,
            eos_id,
            seed,
            top_k=int(top_k),
            top_p=float(top_p),
        )
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            self.pending.put(req)
        return req

    def start(self) -> "InferenceEngine":
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stats(self) -> dict:
        """Serving counters: completed/failed requests, tokens generated,
        active slots, queue depth, uptime and mean tokens/sec."""
        uptime = (
            time.monotonic() - self._started_at if self._started_at else 0.0
        )
        return {
            "requests_completed": self.requests_completed,
            "requests_failed": self.requests_failed,
            "tokens_generated": self.tokens_generated,
            "active_slots": sum(1 for s in self.slots if s.req is not None),
            "max_slots": self.max_slots,
            "queued": self.pending.qsize(),
            "uptime_s": round(uptime, 1),
            "tokens_per_sec": round(self.tokens_generated / uptime, 2)
            if uptime > 0
            else 0.0,
        }

    def stop(self) -> None:
        """Stop the scheduler and fail out any unfinished requests so no
        caller blocks forever on a dead engine."""
        with self._submit_lock:
            self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)
        with self._submit_lock:
            self._fail_outstanding("engine stopped")

    # -- scheduler ---------------------------------------------------------
    def _fail_outstanding(self, reason: str, drain_queue: bool = True) -> None:
        """Fail slot-resident requests (their K/V lives in the cache).
        ``drain_queue=False`` spares queued requests that were never
        admitted — after a cache loss they have no state to lose and a
        rebuilt cache can still serve them; only stop() drains the queue."""
        for slot in self.slots:
            req = slot.req  # snapshot: a live scheduler may race us when
            if req is None:  # stop()'s join timed out on a wedged dispatch
                continue
            slot.req = None
            if req.done.is_set():
                continue  # completed concurrently — don't double-count
            req.error = reason
            req.done.set()
            self.requests_failed += 1
        if not drain_queue:
            return
        while True:
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                break
            req.error = reason
            req.done.set()
            self.requests_failed += 1

    def _recover_cache_if_lost(self) -> None:
        """After a failed _admit: self.cache may have been donated into
        _insert without the reassignment happening. If the prefill raised
        (the common failure) the cache was never donated and co-resident
        requests are untouched; only when _insert itself died after
        donation is the buffer gone — then in-flight requests' K/V is
        unrecoverable, so fail them and rebuild, exactly like the decode
        failure path."""
        lost = False
        try:
            lost = any(a.is_deleted() for a in self.cache.values())
        except AttributeError:  # non-jax.Array leaves (tests with numpy)
            lost = False
        if lost:
            self._fail_outstanding(
                "kv cache lost in failed admission", drain_queue=False
            )
            self.cache = self._fresh_cache()

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _chunk_sizes(self) -> list[int]:
        sizes = [1]
        while sizes[-1] * 2 <= self.chunk_max:
            sizes.append(sizes[-1] * 2)
        return sizes

    def _pick_chunk(self, n: int) -> int:
        """Largest compiled chunk size <= n."""
        best = 1
        for k in self._chunk_sizes():
            if best < k <= n:
                best = k
        return best

    def _admit(self, slot_idx: int, req: Request) -> None:
        slot = self.slots[slot_idx]
        t = len(req.prompt_ids)
        bucket = self._bucket(t)
        prompt = jnp.asarray(
            [req.prompt_ids + [0] * (bucket - t)], dtype=jnp.int32
        )
        cache1, logits = self._prefill(self.params, prompt)
        self.cache = self._insert(
            self.cache,
            cache1["k"][:, 0, :bucket],
            cache1["v"][:, 0, :bucket],
            jnp.asarray(slot_idx, jnp.int32),
        )
        slot.req = req
        slot.length = t
        slot.remaining = req.max_new_tokens
        key = jax.random.PRNGKey(req.seed)
        key, sub = jax.random.split(key)
        self._keys = self._keys.at[slot_idx].set(key)
        # first generated token comes from the last REAL prompt position
        first = sample_logits(
            sub, logits[0, t - 1], req.temperature, req.top_k, req.top_p
        )
        self._emit(slot_idx, int(first))

    def _emit(self, slot_idx: int, token: int) -> None:
        slot = self.slots[slot_idx]
        req = slot.req
        req.tokens.append(token)
        self.tokens_generated += 1
        slot.last_token = token
        slot.length += 1
        slot.remaining -= 1
        if slot.remaining <= 0 or (
            req.eos_id is not None and token == req.eos_id
        ):
            req.done.set()
            slot.req = None
            self.requests_completed += 1

    def _loop(self) -> None:
        while not self._stop.is_set():
            # admit as many pending requests as there are free slots
            for i, slot in enumerate(self.slots):
                if slot.req is not None:
                    continue
                try:
                    req = self.pending.get_nowait()
                except queue.Empty:
                    break
                try:
                    self._admit(i, req)
                except Exception as e:  # noqa: BLE001 — surface per-request
                    req.error = str(e)
                    req.done.set()
                    self.slots[i].req = None
                    self.requests_failed += 1
                    self._recover_cache_if_lost()
            active = [i for i, s in enumerate(self.slots) if s.req is not None]
            if not active:
                # idle: block for the next request and admit it directly
                # (re-enqueuing would push it behind later arrivals)
                try:
                    req = self.pending.get(timeout=0.05)
                except queue.Empty:
                    continue
                try:
                    self._admit(0, req)
                except Exception as e:  # noqa: BLE001
                    req.error = str(e)
                    req.done.set()
                    self.slots[0].req = None
                    self.requests_failed += 1
                    self._recover_cache_if_lost()
                continue
            tokens = jnp.asarray(
                [
                    (s.last_token if s.req is not None else 0)
                    for s in self.slots
                ],
                dtype=jnp.int32,
            )
            positions = jnp.asarray(
                [
                    (s.length - 1 if s.req is not None else 0)
                    for s in self.slots
                ],
                dtype=jnp.int32,
            )
            temps = jnp.asarray(
                [
                    (s.req.temperature if s.req is not None else 0.0)
                    for s in self.slots
                ],
                dtype=jnp.float32,
            )
            top_ks = jnp.asarray(
                [
                    (s.req.top_k if s.req is not None else 0)
                    for s in self.slots
                ],
                dtype=jnp.int32,
            )
            top_ps = jnp.asarray(
                [
                    (s.req.top_p if s.req is not None else 1.0)
                    for s in self.slots
                ],
                dtype=jnp.float32,
            )
            # Chunk size: sized to the LONGEST remaining want (rounded
            # down to a compiled power of two) — clamping to the shortest
            # would put the whole batch back in the one-round-trip-per-
            # token regime whenever any short request is co-resident.
            # Slots that finish mid-chunk (EOS or remaining=0) truncate
            # host-side; the overshoot compute is already paid by the
            # static batch. Only the max_len write bound is a hard clamp.
            want = max(s.remaining for s in self.slots if s.req is not None)
            room = min(
                self.max_len - s.length
                for s in self.slots
                if s.req is not None
            )
            k_steps = self._pick_chunk(max(1, min(want, room + 1)))
            # NOTE positions hold the index of the last emitted token: its
            # K/V has not been written yet (prefill wrote only the prompt),
            # so the decode step both writes it and attends through it.
            filters_on = any(
                s.req is not None and (s.req.top_k > 0 or s.req.top_p < 1.0)
                for s in self.slots
            )
            try:
                self.cache, self._keys, toks = self._decode_chunk[
                    (k_steps, filters_on)
                ](
                    self.params,
                    self.cache,
                    tokens,
                    positions,
                    temps,
                    top_ks,
                    top_ps,
                    self._keys,
                )
                toks = jax.device_get(toks)  # [k_steps, B] — one round-trip
                for i in active:
                    for j in range(k_steps):
                        if self.slots[i].req is None:
                            break  # finished mid-chunk; rest is speculative
                        self._emit(i, int(toks[j, i]))
            except Exception as e:  # noqa: BLE001 — device errors (OOM, …)
                # The cache was donated into the failed call and may be
                # invalid; fail everything in flight rather than hang
                # every caller, then rebuild a clean cache and keep
                # serving new requests.
                self._fail_outstanding(f"decode failed: {e}", drain_queue=False)
                self.cache = self._fresh_cache()  # donated buffer is gone
