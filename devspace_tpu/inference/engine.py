"""Continuous-batching inference engine (iteration-level scheduling).

The serving-side counterpart of the training stack — no reference
counterpart (the reference ships no model code, SURVEY.md §2.13); this is
what turns the llama-inference example from a one-request-at-a-time server
into a throughput engine.

Design, TPU-first:
- **Static shapes throughout**: the KV cache is preallocated at
  ``[layers, max_slots, max_len, kv_heads, head_dim]`` and every decode
  iteration runs ONE jitted step over all slots — empty slots just compute
  masked garbage (their cost is already paid; admission fills them). No
  recompilation ever happens during serving.
- **Iteration-level scheduling** (the Orca/vLLM insight): new requests are
  admitted between decode iterations, not between requests, so a long
  generation does not block a short one — per-slot positions make every
  slot's causal mask independent.
- **Bucketed prefill**: prompts are padded to power-of-two buckets and
  prefit via a scanned decode on a single-slot cache, then scattered into
  the engine cache — a handful of compilations total, amortized across
  the process lifetime.

Greedy and per-request-temperature sampling; optional EOS early stop.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..models import transformer as tfm


@dataclass
class Request:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    # filled by the engine
    tokens: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None

    def result(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens


class _Slot:
    __slots__ = ("req", "length", "remaining", "last_token", "key")

    def __init__(self):
        self.req: Optional[Request] = None


class InferenceEngine:
    """Continuous-batching engine over ``max_slots`` concurrent sequences.

    ``submit()`` is thread-safe and returns the Request whose ``result()``
    blocks until generation completes. ``start()`` spawns the scheduler
    thread; ``stop()`` drains and joins it."""

    def __init__(
        self,
        params: dict,
        cfg: tfm.TransformerConfig,
        max_slots: int = 8,
        max_len: Optional[int] = None,
    ):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len or cfg.max_seq_len
        L, Hkv, D = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        self._fresh_cache = lambda: {
            "k": jnp.zeros((L, max_slots, self.max_len, Hkv, D), cfg.dtype),
            "v": jnp.zeros((L, max_slots, self.max_len, Hkv, D), cfg.dtype),
        }
        self.cache = self._fresh_cache()
        self.slots = [_Slot() for _ in range(max_slots)]
        self.pending: queue.Queue[Request] = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        # The per-slot decode core lives with the model (single source of
        # truth for the layer math): models.transformer.decode_tokens.
        # Donating the cache is what keeps this viable at scale — an
        # undonated update would copy the multi-GB K/V buffers per token.
        self._decode = jax.jit(
            lambda params, cache, tokens, positions: tfm.decode_tokens(
                params, cache, tokens, positions, cfg
            ),
            donate_argnums=1,
        )

        def prefill(params, prompt):  # prompt [1, T_bucket]
            cache = tfm.init_kv_cache(self.cfg, 1, self.max_len)

            def step(cache, tok):
                logits, cache = tfm.decode_step(params, cache, tok[:, None], self.cfg)
                return cache, logits

            cache, logits = jax.lax.scan(step, cache, jnp.moveaxis(prompt, 1, 0))
            return cache, logits  # logits [T_bucket, 1, vocab]

        # jit's own shape-keyed cache compiles once per prompt bucket
        self._prefill = jax.jit(prefill)

        def insert(cache, k1, v1, slot_idx):
            # Write one prefilled sequence's K/V bucket into its slot, in
            # place (donated). k1/v1: [L, bucket, Hkv, D]. Writing the pad
            # tail too is safe: positions >= the true prompt length are
            # overwritten by decode before the mask ever exposes them.
            # slot_idx stays dynamic -> one compile per prompt bucket, not
            # per (slot, length) pair.
            return {
                "k": jax.lax.dynamic_update_slice(
                    cache["k"], k1[:, None], (0, slot_idx, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    cache["v"], v1[:, None], (0, slot_idx, 0, 0, 0)
                ),
            }

        self._insert = jax.jit(insert, donate_argnums=0)

    # -- public api --------------------------------------------------------
    def submit(
        self,
        prompt_ids: list[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ) -> Request:
        if not prompt_ids:
            raise ValueError("empty prompt")
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt_ids)}+{max_new_tokens}) "
                f"exceeds max_len {self.max_len}"
            )
        req = Request(list(prompt_ids), int(max_new_tokens), temperature, eos_id, seed)
        self.pending.put(req)
        return req

    def start(self) -> "InferenceEngine":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the scheduler and fail out any unfinished requests so no
        caller blocks forever on a dead engine."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)
        self._fail_outstanding("engine stopped")

    # -- scheduler ---------------------------------------------------------
    def _fail_outstanding(self, reason: str) -> None:
        for slot in self.slots:
            if slot.req is not None:
                slot.req.error = reason
                slot.req.done.set()
                slot.req = None
        while True:
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                break
            req.error = reason
            req.done.set()

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _admit(self, slot_idx: int, req: Request) -> None:
        slot = self.slots[slot_idx]
        t = len(req.prompt_ids)
        bucket = self._bucket(t)
        prompt = jnp.asarray(
            [req.prompt_ids + [0] * (bucket - t)], dtype=jnp.int32
        )
        cache1, logits = self._prefill(self.params, prompt)
        self.cache = self._insert(
            self.cache,
            cache1["k"][:, 0, :bucket],
            cache1["v"][:, 0, :bucket],
            jnp.asarray(slot_idx, jnp.int32),
        )
        slot.req = req
        slot.length = t
        slot.remaining = req.max_new_tokens
        slot.key = jax.random.PRNGKey(req.seed)
        # first generated token comes from the last REAL prompt position
        first = self._sample(slot, logits[t - 1, 0])
        self._emit(slot_idx, int(first))

    def _sample(self, slot: _Slot, logits: jax.Array):
        if slot.req.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        slot.key, sub = jax.random.split(slot.key)
        return jax.random.categorical(sub, logits / slot.req.temperature)

    def _emit(self, slot_idx: int, token: int) -> None:
        slot = self.slots[slot_idx]
        req = slot.req
        req.tokens.append(token)
        slot.last_token = token
        slot.length += 1
        slot.remaining -= 1
        if slot.remaining <= 0 or (
            req.eos_id is not None and token == req.eos_id
        ):
            req.done.set()
            slot.req = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            # admit as many pending requests as there are free slots
            for i, slot in enumerate(self.slots):
                if slot.req is not None:
                    continue
                try:
                    req = self.pending.get_nowait()
                except queue.Empty:
                    break
                try:
                    self._admit(i, req)
                except Exception as e:  # noqa: BLE001 — surface per-request
                    req.error = str(e)
                    req.done.set()
                    self.slots[i].req = None
            active = [i for i, s in enumerate(self.slots) if s.req is not None]
            if not active:
                try:
                    req = self.pending.get(timeout=0.05)
                except queue.Empty:
                    continue
                self.pending.put(req)
                continue
            tokens = jnp.asarray(
                [
                    (s.last_token if s.req is not None else 0)
                    for s in self.slots
                ],
                dtype=jnp.int32,
            )
            positions = jnp.asarray(
                [
                    (s.length - 1 if s.req is not None else 0)
                    for s in self.slots
                ],
                dtype=jnp.int32,
            )
            # NOTE positions hold the index of the last emitted token: its
            # K/V has not been written yet (prefill wrote only the prompt),
            # so the decode step both writes it and attends through it.
            try:
                logits, self.cache = self._decode(
                    self.params, self.cache, tokens, positions
                )
                for i in active:
                    self._emit(i, int(self._sample(self.slots[i], logits[i])))
            except Exception as e:  # noqa: BLE001 — device errors (OOM, …)
                # The cache was donated into the failed call and may be
                # invalid; fail everything in flight rather than hang
                # every caller, then rebuild a clean cache and keep
                # serving new requests.
                self._fail_outstanding(f"decode failed: {e}")
                self.cache = self._fresh_cache()  # donated buffer is gone
