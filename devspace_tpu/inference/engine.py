"""Continuous-batching inference engine (iteration-level scheduling).

The serving-side counterpart of the training stack — no reference
counterpart (the reference ships no model code, SURVEY.md §2.13); this is
what turns the llama-inference example from a one-request-at-a-time server
into a throughput engine.

Design, TPU-first:
- **Static shapes throughout**: every decode iteration runs ONE jitted
  step over all slots — empty slots just compute masked garbage (their
  cost is already paid; admission fills them). No recompilation ever
  happens during serving.
- **Paged KV cache** (vLLM-style): K/V lives in a block pool
  ``[layers, n_blocks, kv_heads, block_size, head_dim]`` with per-slot
  block tables, so HBM is bounded by the POOL size — not
  ``max_slots x max_len`` preallocation. Blocks are allocated as
  sequences grow; when the pool runs dry the youngest request is
  preempted (recompute-style: requeued with its generated prefix) so
  older requests always finish. Block 0 is scratch: unallocated table
  entries and parked writes land there.
- **Chunked prefill, interleaved** (Sarathi-style): prompts prefill in
  bounded chunks (``prefill_chunk`` tokens per dispatch), one chunk per
  scheduler iteration BETWEEN decode chunks — co-resident decodes keep
  streaming while a long prompt is admitted, so inter-token latency is
  bounded by the chunk budget rather than the full prompt length.
- **Iteration-level scheduling** (the Orca/vLLM insight): new requests are
  admitted between decode iterations, not between requests, so a long
  generation does not block a short one — per-slot positions make every
  slot's causal mask independent.
- **Prefix caching** (vLLM-style, on by default): written full prompt
  blocks are published under their exact token-prefix key; admissions
  sharing the prefix reference the same pool blocks (refcounted, LRU
  eviction when the allocator runs dry) and prefill starts at the first
  uncached position. Lossless; shared blocks are never rewritten.
- **Device-side sampling + chunked decode**: sampling (greedy or
  per-slot temperature) happens inside the jitted step, and up to
  ``chunk_max`` tokens are decoded per dispatch via ``lax.scan`` — one
  host round-trip per chunk instead of per token. On a remote/tunneled
  accelerator the round-trip dominates single-token decode, so this is
  the difference between RTT-bound and compute-bound serving. A slot
  that hits EOS mid-chunk wastes at most chunk_max-1 speculative tokens
  (truncated host-side; the cache-write-ahead is safe — every position
  is rewritten in the same step that first attends to it).

Per-request sampling: greedy, temperature, top-k and top-p (nucleus);
optional EOS early stop.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer as tfm
from ..obs import events as _events
from ..obs.metrics import Registry, WindowedRate, metrics_enabled
from ..obs.request_trace import ServingTelemetry
from ..obs.tracing import (
    TRACK_HOST_SCHED,
    TRACK_PREFILL,
    TRACK_SPEC,
    TRACK_TIER_RESTORE,
    TimelineRecorder,
)
from .dispatch import DecodeDispatcher, resolve_dispatch_depth
from .kv_tier import (
    HostKVTier,
    KVMigrationClient,
    import_chain,
    pack_chain_envelope,
    pack_kv_payload,
    resolve_kv_tier,
    unpack_kv_payload,
)
from .prefix_cache import RadixPrefixCache
from .quantization import KV_SCALE_EPS

# Blocks per restore-scatter dispatch: one fixed shape (short chains pad
# into scratch block 0) so a chain of any length costs ceil(n/16)
# dispatches instead of n — per-block dispatch overhead would eat the
# recompute savings the tier exists to deliver.
_RESTORE_BATCH = 16

# Metric families the engine registers over its serving counters
# (pull-style: each callback reads the same ints stats() reports — ONE
# mutation site, two views; scripts/metrics_lint.py checks the names).
# Format: (name, kind, help, stats_key).
ENGINE_METRIC_FAMILIES = (
    ("engine_requests_completed_total", "counter",
     "Requests that finished successfully", "requests_completed", "sum"),
    ("engine_requests_failed_total", "counter",
     "Requests that failed (dispatch faults, bad admissions, stop())",
     "requests_failed", "sum"),
    ("engine_requests_preempted_total", "counter",
     "Preemption events (a request may be preempted more than once)",
     "requests_preempted", "sum"),
    ("engine_tokens_generated_total", "counter",
     "Generated tokens emitted across all requests", "tokens_generated", "sum"),
    ("engine_prefix_hit_blocks_total", "counter",
     "Prompt blocks served from the radix prefix cache at admission",
     "prefix_hit_blocks", "sum"),
    ("engine_prefix_hit_tokens_total", "counter",
     "Prompt tokens whose prefill was skipped at admission (resident "
     "radix hits plus host-tier restores)", "prefix_hit_tokens", "sum"),
    ("engine_recompute_tokens_saved_total", "counter",
     "Prompt tokens restored from the host KV tier instead of "
     "recompute-prefilled (the tier-attributable subset of prefix hits)",
     "recompute_tokens_saved", "sum"),
    ("engine_kv_spill_bytes_total", "counter",
     "Bytes of evicted KV copied device->host into the tier (packed, "
     "int8-quantized)", "kv_spill_bytes", "sum"),
    ("engine_kv_spill_blocks_total", "counter",
     "Evicted KV blocks spilled to the host tier", "kv_spill_blocks", "sum"),
    ("engine_kv_restore_hits_total", "counter",
     "Spilled blocks restored host->device on a radix match",
     "kv_restore_hits", "sum"),
    ("engine_kv_restore_fallbacks_total", "counter",
     "Restore attempts that fell back to recompute-prefill (tier miss, "
     "corrupt payload, or restore error)", "kv_restore_fallbacks", "sum"),
    ("engine_kv_tier_resident_bytes", "gauge",
     "Host RAM currently held by the KV tier", "kv_tier_resident_bytes", "sum"),
    # histogram families carry no stats_key: _register_metric_families
    # creates a real instrument (observed per restore event) instead of
    # a pull callback
    ("engine_kv_restore_seconds", "histogram",
     "Latency of one spilled-chain restore (tier reads + scatter "
     "dispatches; async device work excluded)", None, "sum"),
    # KV migration (disaggregated prefill/decode, ISSUE 20): chains
    # pulled from a peer replica's /kv/chain endpoint into the local
    # tier, and chain envelopes this replica served to peers
    ("engine_kv_migrate_chains_total", "counter",
     "KV chains fetched from a peer replica and imported into the "
     "local tier", "kv_migrate_chains", "sum"),
    ("engine_kv_migrate_blocks_total", "counter",
     "KV blocks promoted remote->spilled from imported migration "
     "envelopes", "kv_migrate_blocks", "sum"),
    ("engine_kv_migrate_bytes_total", "counter",
     "Envelope bytes fetched in successful KV migrations",
     "kv_migrate_bytes", "sum"),
    ("engine_kv_migrate_failures_total", "counter",
     "KV migration attempts that failed (fetch error or wire-format "
     "rejection) and degraded to recompute-prefill",
     "kv_migrate_failures", "sum"),
    ("engine_kv_export_chains_total", "counter",
     "KV chain envelopes served to peer replicas via /kv/chain",
     "kv_export_chains", "sum"),
    ("engine_kv_migrate_seconds", "histogram",
     "Latency of one KV chain migration (fetch + import + promote; "
     "the host->device scatter is counted by the restore path)",
     None, "sum"),
    ("engine_decode_dispatches_total", "counter",
     "Decode chunks dispatched by the overlapped serving loop",
     "decode_dispatches", "sum"),
    ("engine_readback_wait_seconds_total", "counter",
     "Host time blocked on decode token readback", "readback_wait_s", "sum"),
    ("engine_spec_rounds_total", "counter",
     "Speculative draft/verify rounds replayed by the host commit loop",
     "spec_rounds", "sum"),
    ("engine_spec_proposed_total", "counter",
     "Draft tokens proposed in replayed speculative rounds",
     "spec_proposed", "sum"),
    ("engine_spec_accepted_total", "counter",
     "Draft tokens accepted by target verification", "spec_accepted", "sum"),
    ("engine_spec_committed_total", "counter",
     "Tokens committed from speculative rounds", "spec_committed", "sum"),
    ("engine_active_slots", "gauge",
     "Slots currently decoding (prefill complete)", "active_slots", "sum"),
    ("engine_prefilling_slots", "gauge",
     "Slots currently in chunked prefill", "prefilling_slots", "sum"),
    ("engine_max_slots", "gauge",
     "Configured concurrent-sequence capacity", "max_slots", "sum"),
    ("engine_queued_requests", "gauge",
     "Requests waiting for a slot (pending queue + preempted resume list)",
     "queued", "sum"),
    ("engine_free_kv_blocks", "gauge",
     "Unallocated KV pool blocks", "free_blocks", "sum"),
    ("engine_kv_blocks", "gauge",
     "Allocatable KV pool blocks (excludes the scratch block)",
     "total_blocks", "sum"),
    ("engine_prefix_cached_blocks", "gauge",
     "Blocks currently published in the radix prefix cache",
     "prefix_cached_blocks", "sum"),
    ("engine_dispatch_depth", "gauge",
     "Configured dispatch-ahead window depth", "dispatch_depth", "max"),
    ("engine_dispatch_depth_occupancy", "gauge",
     "Mean in-flight window depth observed at dispatch",
     "dispatch_depth_occupancy", "avg"),
    ("engine_uptime_seconds", "gauge",
     "Seconds since the scheduler thread started", "uptime_s", "max"),
    ("engine_tokens_per_sec_10s", "gauge",
     "Generated tokens per second over the last ~10s window",
     "tokens_per_sec_10s", "sum"),
)


def sample_logits(key, logits, temperature, top_k=0, top_p=1.0):
    """One-token sampling with greedy / temperature / top-k / top-p —
    pure jnp so it runs inside the jitted decode chunk (vmapped per slot)
    and host-side for the prefill's first token.

    ``temperature <= 0`` is greedy (k/p ignored). The filter semantics
    live in ``speculative.filter_scaled_logits`` (shared with the
    speculative-sampling target distribution)."""
    from .speculative import filter_scaled_logits

    filtered = filter_scaled_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, filtered).astype(jnp.int32)
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    return jnp.where(jnp.asarray(temperature, jnp.float32) > 0, sampled, greedy)


def _req_trace_id(req) -> Optional[str]:
    """The request's distributed trace id, when telemetry minted one.
    Engine events stamp it explicitly: the scheduler thread never sees
    the submitting thread's thread-local tracer context."""
    trace = getattr(req, "_obs_trace", None)
    return trace.trace_id if trace is not None else None


@dataclass
class Request:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # >= 1 = disabled
    # token-id sequences that end generation; the matched suffix is
    # stripped from result() (stream() may have already yielded it)
    stop: Optional[list[list[int]]] = None
    # EOS (and stop sequences) are ignored until this many tokens have
    # been generated; EOS is additionally suppressed DEVICE-side so the
    # model keeps producing real tokens instead of repeated EOS
    min_new_tokens: int = 0
    # token id -> additive logit bias, applied before sampling every
    # generated token (use -inf/+inf floats to forbid/force tokens)
    logit_bias: Optional[dict[int, float]] = None
    # set at finish when a stop-sequence match is stripped: result()
    # slices to this length; ``tokens`` itself is never shrunk because a
    # stream() consumer in another thread may be mid-iteration over it
    result_len: Optional[int] = None
    # inbound W3C traceparent header (distributed tracing, ISSUE 8):
    # parsed by ServingTelemetry.on_submit so the request's lifecycle
    # trace joins the caller's trace instead of rooting a fresh one
    traceparent: Optional[str] = None
    # disaggregated prefill (ISSUE 20): base URL of the replica that
    # already holds this prompt's prefilled KV chain. At admission the
    # engine marks the uncovered prompt blocks "remote" and the restore
    # path pulls their wire envelope from here; any failure degrades to
    # recompute-prefill. Ignored without a host KV tier.
    kv_source: Optional[str] = None
    # filled by the engine
    tokens: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None
    # wakes stream() consumers on every emitted token and on completion
    # (event-driven delivery — no busy-poll); notified by the engine via
    # _notify(), always AFTER the state change it announces
    _cond: threading.Condition = field(
        default_factory=threading.Condition, repr=False
    )

    def _notify(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def result(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error:
            raise RuntimeError(self.error)
        if self.result_len is not None:
            return self.tokens[: self.result_len]
        return self.tokens

    def stream(self, timeout: Optional[float] = None, poll: float = 0.02):
        """Yield tokens as they are generated (list appends by the engine
        thread are atomic under the GIL; chunked decode delivers them in
        bursts of up to chunk_max). Raises like ``result`` on error, and
        TimeoutError when no NEW token arrives within ``timeout`` (the
        deadline resets on progress — a long healthy generation never
        times out).

        Delivery is event-driven: the engine notifies a per-request
        Condition on every emit and at completion, so a waiting consumer
        wakes immediately instead of busy-polling. ``poll`` is retained
        for backward compatibility and ignored."""
        del poll
        sent = 0
        while True:
            with self._cond:
                # every notify follows a token append or completion, so a
                # full ``timeout`` with no wakeup means no progress
                while len(self.tokens) <= sent and not self.done.is_set():
                    if not self._cond.wait(timeout):
                        raise TimeoutError("generation stalled")
                n = len(self.tokens)
                finished = self.done.is_set()
            while sent < n:
                yield self.tokens[sent]
                sent += 1
            if finished:
                if self.error:
                    raise RuntimeError(self.error)
                if sent >= len(self.tokens):
                    return


class _Slot:
    __slots__ = (
        "req", "length", "remaining", "last_token",
        "ready", "prefill_pos", "prompt", "admitted_at", "draft_ready",
        "gen",
    )

    def __init__(self):
        self.req: Optional[Request] = None
        self.ready = False
        self.draft_ready = False
        # admission generation: in-flight chunks record it at dispatch so
        # a drained chunk can never emit into a slot's NEXT occupant
        self.gen = 0


class InferenceEngine:
    """Continuous-batching engine over ``max_slots`` concurrent sequences.

    ``submit()`` is thread-safe and returns the Request whose ``result()``
    blocks until generation completes. ``start()`` spawns the scheduler
    thread; ``stop()`` drains and joins it.

    ``block_size``/``n_blocks`` size the paged KV pool: HBM for K/V is
    ``2 x layers x n_blocks x block_size x kv_heads x head_dim`` bytes
    (x dtype). The default pool holds full capacity (every slot at
    max_len); pass a smaller ``n_blocks`` to oversubscribe — short
    prompts then cost only the blocks they touch, and the preemption
    path bounds the worst case."""

    def __init__(
        self,
        params: dict,
        cfg: tfm.TransformerConfig,
        max_slots: int = 8,
        max_len: Optional[int] = None,
        mesh=None,
        model_axis: str = "model",
        chunk_max: int = 8,
        block_size: int = 64,
        n_blocks: Optional[int] = None,
        prefill_chunk: int = 512,
        draft_params: Optional[dict] = None,
        draft_cfg: Optional[tfm.TransformerConfig] = None,
        spec_k: int = 4,
        spec_depth: int = 1,
        kv_dtype: Optional[str] = None,
        prefix_cache: bool = True,
        prewarm: bool = False,
        dispatch_depth: Optional[int] = None,
        metrics: Optional[bool] = None,
        metrics_registry: Optional[Registry] = None,
        kv_tier: Optional[str] = None,
        kv_tier_bytes: int = 256 << 20,
        kv_tier_dir: Optional[str] = None,
    ):
        """``mesh`` turns on tensor-parallel serving: params are placed per
        ``models.transformer.param_partition_spec`` and the KV pool is
        sharded over its head dim on ``model_axis`` (requires
        ``n_kv_heads % mesh.shape[model_axis] == 0``); the decode jit then
        runs under GSPMD, which inserts the attention/FFN collectives.
        Scheduling is unchanged — TP is invisible to the slot machinery.

        ``draft_params``/``draft_cfg`` turn on ENGINE-level speculative
        decoding: every iteration, eligible slots (greedy and far enough
        from max_len) ride one fused dispatch — a ``spec_k``-token draft
        proposal scan plus a single paged-pool verification block
        (``models.transformer.decode_block_paged``) — committing 1..k+1
        tokens per round, while ineligible slots take the plain decode
        chunk in the SAME iteration (nothing starves). The draft keeps a
        DENSE per-slot KV cache ``[L, max_slots, max_len, Hkv_d, D_d]``:
        paging exists to bound the TARGET's multi-GB K/V — a draft is
        chosen ~10x smaller, so its dense cache is the cheap price of
        keeping the block allocator single-model. Greedy speculative
        decoding is LOSSLESS (the committed stream equals plain greedy
        decoding token-for-token) and never depends on draft-cache
        contents — a garbage draft only lowers acceptance — so draft
        state needs no preemption/recovery bookkeeping: preempted slots
        simply re-prefill both models on re-admission. Losslessness is
        an EXACT-ARITHMETIC property: in bf16 a near-tie logit (e.g.
        inside a repeated-token cycle) can argmax-flip between the
        block-verify and sequential-decode reductions — the same class
        of tie-flip the int8 KV pool documents. f32 serving is
        bit-lossless (pinned in tests).

        ``spec_depth`` chains that many draft+verify rounds inside ONE
        dispatch (``lax.scan``; acceptance is recomputed device-side to
        advance each slot's positions between rounds) — committing up to
        ``depth x (k+1)`` tokens per host round-trip. The host replays
        the same acceptance rule on the returned proposals/choices, so
        losslessness is unchanged; what changes is dispatch amortization,
        the lever that matters on high-RTT links where per-dispatch
        overhead, not compute, bounds speculative throughput
        (docs/PERF.md "Speculative decoding with a TRAINED draft").

        ``kv_dtype="int8"`` stores the paged pool quantized (per-token
        per-head scales; ops.paged_attention.quantize_kv): K/V HBM
        halves, so the same budget holds ~1.9x the blocks — fewer
        KV-pressure preemptions at the cost of ~0.5% quantization noise
        in attention reads. Outputs are no longer bit-identical to the
        bf16 pool (greedy ties can flip), which is why it is opt-in.

        ``prefix_cache`` (default on) shares full prompt blocks between
        requests with a common prefix: admission points the slot table
        at already-written pool blocks (refcounted) and prefill starts
        at the first uncached position. Freed published blocks linger
        as an LRU cache and are evicted only when the allocator runs
        dry. LOSSLESS: cached K/V is exactly what recomputation would
        produce (same tokens, same chunking, causal), and a shared
        block is never written again — decode/prefill writes land only
        in private blocks past the matched prefix.

        ``prewarm=True`` compiles every reachable program in ``start()``
        before the scheduler thread runs (see :meth:`prewarm`).

        ``kv_tier`` adds a host tier below the HBM block pool
        (inference/kv_tier.py): ``"host"`` spills evicted prefix chains
        to host RAM (``kv_tier_bytes`` LRU budget, int8-quantized with
        per-block scales), ``"host+disk"`` overflows RAM evictions to
        digest-named files under ``kv_tier_dir``. A radix match landing
        on a spilled chain restores it host->device (async scatter,
        overlapped with in-flight decode chunks) instead of
        recompute-prefilling; preempted requests' chains spill too, so
        resume restores. Default off (env knob ``DEVSPACE_KV_TIER``);
        behavior with the tier off — and in an unpressured pool with it
        on — is byte-identical to before. On a FLOAT pool restored
        blocks carry ~0.5% int8 quantization noise (greedy near-ties
        can flip, the same caveat as ``kv_dtype="int8"``); on an int8
        pool the spill copies the quantized representation verbatim and
        restores are exact.

        ``dispatch_depth`` sizes the overlapped serving loop's in-flight
        decode window (inference/dispatch.py): depth 2 (the default)
        dispatches chunk N+1 before reading chunk N's tokens, so host
        scheduling/emit work overlaps device compute; depth 1 is the
        serial reference loop (escape hatch:
        ``DEVSPACE_ENGINE_OVERLAP=off``). Token streams are identical at
        every depth (pinned by tests/test_engine_dispatch.py).

        ``metrics`` turns the telemetry subsystem (obs/) on or off:
        default ON, escape hatch ``DEVSPACE_ENGINE_METRICS=off`` (the
        bench.py overhead A/B). When on, ``self.telemetry`` records
        per-request lifecycle traces and latency histograms
        (TTFT/TPOT/queue-wait/prefill/e2e) and the engine's serving
        counters are registered as Prometheus metric families in
        ``self.metrics_registry`` (a PRIVATE obs.metrics.Registry unless
        ``metrics_registry`` shares one). ``stats()`` keys are unchanged
        either way — the registry and stats() are two views over the
        same counters."""
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len or cfg.max_seq_len
        self.mesh = mesh
        self.block_size = int(block_size)
        self.max_blocks = math.ceil(self.max_len / self.block_size)
        # +1: block 0 is reserved scratch
        full_capacity = 1 + max_slots * self.max_blocks
        self.n_blocks = int(n_blocks) if n_blocks else full_capacity
        if self.n_blocks < 1 + self.max_blocks:
            raise ValueError(
                f"n_blocks {self.n_blocks} cannot hold even one max_len "
                f"sequence ({1 + self.max_blocks} needed)"
            )
        self.prefill_chunk = max(1, int(prefill_chunk))
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self._kv_jnp_dtype = jnp.int8 if kv_dtype == "int8" else None
        L, Hkv, D = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        pool_sharding = None
        # under a mesh, the paged-attention kernel is shard_mapped over
        # the model axis (each shard streams its LOCAL KV heads) instead
        # of letting GSPMD guess at pallas_call's partitioning
        self._tp = (mesh, model_axis) if mesh is not None else None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .quantization import QuantizedLinear

            if Hkv % mesh.shape[model_axis]:
                raise ValueError(
                    f"n_kv_heads {Hkv} not divisible by mesh axis "
                    f"'{model_axis}' ({mesh.shape[model_axis]})"
                )
            # pools [L, N, Hkv, bs, D] / quant scales [L, N, Hkv, bs]:
            # both sharded on the head dim (index 2)
            pool_sharding = {
                5: NamedSharding(mesh, P(None, None, model_axis, None, None)),
                4: NamedSharding(mesh, P(None, None, model_axis, None)),
            }

            def _place(p, s):
                # weight-only int8 composes with TP: the int8 matrix
                # shards exactly like the dense weight it replaces, and
                # the per-output-channel scale shards on the OUT dim's
                # axis (replicated when the out dim is) — the dequant
                # multiply then stays local to each shard and the
                # surrounding collective pattern is unchanged
                if isinstance(p, QuantizedLinear):
                    out_axis = s[1] if len(s) > 1 else None
                    return QuantizedLinear(
                        jax.device_put(p.q, NamedSharding(mesh, s)),
                        jax.device_put(
                            p.scale, NamedSharding(mesh, P(out_axis))
                        ),
                    )
                return jax.device_put(p, NamedSharding(mesh, s))

            def _shard_params(tree, tree_cfg):
                return jax.tree_util.tree_map(
                    _place,
                    tree,
                    tfm.param_partition_spec(tree_cfg, model_axis=model_axis),
                    is_leaf=lambda x: isinstance(x, QuantizedLinear),
                )

            self.params = _shard_params(params, cfg)
            if draft_params is not None:
                if draft_cfg is None:
                    raise ValueError("draft_params requires draft_cfg")
                if draft_cfg.n_kv_heads % mesh.shape[model_axis]:
                    raise ValueError(
                        f"draft n_kv_heads {draft_cfg.n_kv_heads} not "
                        f"divisible by mesh axis '{model_axis}' "
                        f"({mesh.shape[model_axis]})"
                    )
                draft_params = _shard_params(draft_params, draft_cfg)

        def fresh_pool():
            pool = tfm.init_paged_pool(
                cfg, self.n_blocks, self.block_size, kv_dtype=self._kv_jnp_dtype
            )
            if pool_sharding is not None:
                pool = {
                    k: jax.device_put(v, pool_sharding[v.ndim])
                    for k, v in pool.items()
                }
            return pool

        self._fresh_pool = fresh_pool
        self.pool = fresh_pool()

        # speculative decoding state (None/unused when no draft model)
        if draft_params is not None and draft_cfg is None:
            raise ValueError("draft_params requires draft_cfg")
        if spec_k < 1 or spec_k > 16:
            raise ValueError("spec_k must be in 1..16")
        if spec_depth < 1 or spec_depth > 16:
            raise ValueError("spec_depth must be in 1..16")
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec_k = int(spec_k)
        self.spec_depth = int(spec_depth)
        # spec counters all measure REPLAYED slot-rounds (rounds the
        # host commit loop actually consumed): rounds/proposed/accepted
        # stay mutually consistent, and device rounds discarded when a
        # slot finishes mid-dispatch never skew committed-per-round
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_committed = 0

        def fresh_draft_cache():
            if draft_params is None:
                return None
            # +spec_k+1 scratch TAIL: a parked slot's propose scan still
            # scatters k+1 K/V writes into its own row — pointing parked
            # rows at pos0=max_len lands those writes in the tail, where
            # no live position ever reads (eligibility caps live writes
            # at max_len-1). Without this, a spec round running in the
            # same scheduler iteration that completed a peer's draft
            # prefill would overwrite the freshly-seeded prompt K/V at
            # positions 0..k and permanently poison that slot's
            # proposals (still lossless — verification absorbs it — but
            # acceptance collapses to ~0).
            c = tfm.init_kv_cache(
                draft_cfg, max_slots, self.max_len + self.spec_k + 1
            )
            if pool_sharding is not None:
                # the DENSE draft cache is [L, B, T, Hkv, D] — head dim
                # at index 3, unlike the head-major paged pool's index 2
                dense_sharding = NamedSharding(
                    mesh, P(None, None, None, model_axis, None)
                )
                c = {
                    "k": jax.device_put(c["k"], dense_sharding),
                    "v": jax.device_put(c["v"], dense_sharding),
                    "length": c["length"],
                }
            return c

        self._fresh_draft_cache = fresh_draft_cache
        self._draft_cache = fresh_draft_cache()
        # host-side allocator state
        self._free_blocks: list[int] = list(range(1, self.n_blocks))
        self._tables = np.zeros((max_slots, self.max_blocks), np.int32)
        self._nalloc = [0] * max_slots  # allocated blocks per slot
        # prefix cache (vLLM-style): full PROMPT blocks, once their K/V
        # is written, are published in a radix tree over token blocks
        # (inference/prefix_cache.py); later admissions sharing the
        # prefix point their tables at the SAME pool blocks (refcounted)
        # and skip recomputing them. Edges are literal token tuples — no
        # hash-collision risk, host memory is a few KB per cached block
        # at serving scale; match cost is O(prompt) and eviction cost is
        # O(evicted chain), never O(whole cache).
        self.prefix_cache_enabled = bool(prefix_cache)
        self._prewarm_on_start = bool(prewarm)
        # host KV tier (inference/kv_tier.py): evicted chains spill
        # device->host instead of vanishing; radix matches on spilled
        # chains restore instead of recomputing. None when off — every
        # tier code path below is gated on it, so the untiered engine
        # is byte-identical to before.
        self.kv_tier_mode = resolve_kv_tier(kv_tier)
        self._kv_tier: Optional[HostKVTier] = None
        if self.kv_tier_mode != "off" and self.prefix_cache_enabled:
            disk_dir = None
            if self.kv_tier_mode == "host+disk":
                import tempfile

                disk_dir = kv_tier_dir or os.path.join(
                    tempfile.gettempdir(), f"devspace-kv-tier-{os.getpid()}"
                )
            self._kv_tier = HostKVTier(
                max_bytes=kv_tier_bytes, disk_dir=disk_dir
            )
            self._kv_tier.on_evict = self._on_tier_evict
        elif self.kv_tier_mode != "off":
            # a tier without the prefix cache has nothing to spill
            self.kv_tier_mode = "off"
        self._prefix_cache = RadixPrefixCache(
            track_digests=self._kv_tier is not None
        )
        self._block_refs: dict[int, int] = {}  # blk -> table references
        self.prefix_hit_blocks = 0
        self.prefix_hit_tokens = 0
        self.recompute_tokens_saved = 0
        self.kv_spill_blocks = 0
        self.kv_spill_bytes = 0
        self.kv_restore_hits = 0
        self.kv_restore_fallbacks = 0
        self._kv_restore_hist = None  # set by _register_metric_families
        # KV migration (disaggregated prefill/decode, ISSUE 20)
        self.kv_migrate_chains = 0
        self.kv_migrate_blocks = 0
        self.kv_migrate_bytes = 0
        self.kv_migrate_failures = 0
        self.kv_export_chains = 0
        self._kv_migrate_hist = None  # set by _register_metric_families
        # lazy KVMigrationClient; tests inject one with a fetch_fn
        self._kv_client = None
        # export mailbox: /kv/chain handler threads post (digest, box)
        # here and the SCHEDULER services them between iterations — it
        # is the only thread that may read the pool/cache/tier
        self._kv_export_requests: queue.Queue = queue.Queue()
        self.slots = [_Slot() for _ in range(max_slots)]
        self.pending: queue.Queue[Request] = queue.Queue()
        self._resume: list[Request] = []  # preempted, re-admit first
        # serving counters (read via stats(); mutated by the scheduler
        # thread and — for fail-outs — by stop(); read-atomic under the GIL)
        self._started_at = None  # set by start()
        self.requests_completed = 0
        self.requests_failed = 0
        self.requests_preempted = 0
        self.tokens_generated = 0
        # windowed token rate (ISSUE 6 satellite): tokens_per_sec is a
        # lifetime average that goes stale after idle periods; the 10s
        # window decays to 0 when traffic stops. Always on — one clock
        # read per emitted token.
        self._tok_rate = WindowedRate(10.0)
        # telemetry (obs/): per-request lifecycle traces + latency
        # histograms + the engine's counters as metric families. None
        # when disabled (DEVSPACE_ENGINE_METRICS=off / metrics=False);
        # every hook site is guarded so the off path costs one None check
        self.telemetry: Optional[ServingTelemetry] = None
        if metrics_enabled(metrics):
            self.telemetry = ServingTelemetry(metrics_registry)
            self._register_metric_families()
        # on-demand timeline profiler (ISSUE 8): None except during a
        # capture window (start_timeline / /debug/trace). Every hook on
        # the scheduler path is a single ``is None`` check when off; on,
        # the loop/dispatcher/tier stream events onto named Chrome-trace
        # lanes so the overlapped dispatcher's concurrency is visible.
        self._timeline: Optional[TimelineRecorder] = None
        self._stop = threading.Event()
        # serializes submit's check+put against stop's set+drain, closing
        # the window where a request lands in the queue after the drain
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

        # The per-slot decode core lives with the model (single source of
        # truth for the layer math): models.transformer.decode_tokens_paged.
        # Donating the pool is what keeps this viable at scale — an
        # undonated update would copy the multi-GB K/V buffers per token.
        # Sampling runs on device and n_steps tokens are decoded per
        # dispatch (lax.scan), so the host pays one round-trip per chunk.
        self.chunk_max = max(1, int(chunk_max))
        self._keys = jnp.zeros((max_slots, 2), jnp.uint32)
        # per-slot sampling extras, resident on device and updated only
        # at admission (and only for slots that use them — see
        # _sync_sampling_extras): EOS id for device-side min-length
        # suppression, the position below which EOS is suppressed, and
        # an additive logit bias row per slot
        self._eos_ids = jnp.full((max_slots,), -1, jnp.int32)
        self._min_until = jnp.zeros((max_slots,), jnp.int32)
        self._logit_bias = jnp.zeros((max_slots, cfg.vocab_size), jnp.float32)
        self._extras_dirty = [False] * max_slots

        def decode_chunk(
            params,
            pool,
            carry,
            keys,
            active,
            eos_ids,
            min_until,
            logit_bias,
            n_steps,
            use_filters,
        ):
            # the device-resident carry (inference/dispatch.py) holds the
            # per-slot decode inputs; inactive rows (parked, mid-prefill,
            # or zombie slots whose old chunks are still in flight) get an
            # all-zeros table row so their garbage writes land in the
            # scratch block — the same convention _decode_tables used
            tables = jnp.where(active[:, None], carry["tables"], 0)
            temps = carry["temps"]
            top_ks = carry["top_ks"]
            top_ps = carry["top_ps"]

            def step(c, _):
                pool, tok, pos, keys = c
                logits, pool = tfm.decode_tokens_paged(
                    params, pool, tables, tok, pos, cfg, tp=self._tp
                )
                # sampling extras: additive bias, then EOS suppression
                # for slots that haven't reached min_new_tokens (pos is
                # the position being written = prompt_len-1+generated)
                logits = logits + logit_bias
                vocab_iota = jax.lax.broadcasted_iota(
                    jnp.int32, logits.shape, 1
                )
                suppress = (pos < min_until)[:, None] & (
                    vocab_iota == eos_ids[:, None]
                )
                logits = jnp.where(suppress, -jnp.inf, logits)
                # keys holds each slot's BASE key (PRNGKey(seed)), never
                # advanced: the sample key for the token written at
                # position pos+1 is fold_in(base, pos), a pure function
                # of the token's absolute position. The stream is then
                # invariant to co-resident membership, dispatch-window
                # depth, AND preemption points — a resumed request
                # re-derives the same key for committed token k no matter
                # where mid-chunk the preemption landed (ROADMAP item 2).
                subs = jax.vmap(jax.random.fold_in)(keys, pos)
                if use_filters:
                    tok = jax.vmap(sample_logits)(
                        subs, logits, temps, top_ks, top_ps
                    )
                else:
                    # cheap path: no per-token vocab sort when no active
                    # slot asked for top-k/top-p
                    sampled = jax.vmap(
                        lambda k, l, t: jax.random.categorical(
                            k, l / jnp.maximum(t, 1e-6)
                        )
                    )(subs, logits, temps).astype(jnp.int32)
                    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    tok = jnp.where(temps > 0, sampled, greedy)
                # parked slots (mid-prefill / empty) sit at position 0 of
                # the all-zeros table (scratch block); the clamp keeps any
                # position from indexing past its table
                pos = jnp.minimum(pos + 1, self.max_len - 1)
                return (pool, tok, pos, keys), tok

            (pool, tok, pos, keys), toks = jax.lax.scan(
                step,
                (pool, carry["tokens"], carry["positions"], keys),
                None,
                length=n_steps,
            )
            # the advanced token/position rows chain into the next chunk
            # device-side — dispatch-ahead never reads them back
            carry = dict(carry, tokens=tok, positions=pos)
            return pool, carry, keys, toks  # toks [n_steps, B]

        # one compile per (chunk size, filters on/off) — both static;
        # pool AND carry are donated: the carry threads dispatch-to-
        # dispatch exactly like the pool does
        from functools import partial as _partial

        self._decode_chunk = {
            (k, filt): jax.jit(
                _partial(decode_chunk, n_steps=k, use_filters=filt),
                donate_argnums=(1, 2),
            )
            for k in self._chunk_sizes()
            for filt in (False, True)
        }

        def apply_carry_update(carry, state_mask, table_mask, ints, floats, tables):
            # ONE packed host->device refresh for every dirty slot row
            # (ints [B,3] = token, position, top_k; floats [B,2] = temp,
            # top_p): masked merge so device-authoritative rows — whose
            # tokens/positions self-advanced inside decode chunks — are
            # never clobbered by stale host copies. Two masks because
            # table growth must not touch a live slot's token/position.
            sm = state_mask
            return {
                "tokens": jnp.where(sm, ints[:, 0], carry["tokens"]),
                "positions": jnp.where(sm, ints[:, 1], carry["positions"]),
                "top_ks": jnp.where(sm, ints[:, 2], carry["top_ks"]),
                "temps": jnp.where(sm, floats[:, 0], carry["temps"]),
                "top_ps": jnp.where(sm, floats[:, 1], carry["top_ps"]),
                "tables": jnp.where(
                    table_mask[:, None], tables, carry["tables"]
                ),
            }

        self._carry_update_jit = jax.jit(apply_carry_update, donate_argnums=0)
        # overlapped serving loop state (created LAST: the dispatcher's
        # carry shapes come from the allocator/config fields above)
        self._dispatcher = DecodeDispatcher(
            self, resolve_dispatch_depth(dispatch_depth)
        )
        self.dispatch_depth = self._dispatcher.depth
        self._prefill_cursor = -1  # rotating prefill pick (see _loop)

        # chunked prefill: jit's shape-keyed cache compiles once per chunk
        # bucket (power-of-two final chunks + the full prefill_chunk)
        self._prefill_step_jit = jax.jit(
            lambda params, pool, table, toks, offset: tfm.prefill_chunk_paged(
                params, pool, table, toks, offset, self.cfg
            ),
            donate_argnums=1,
        )

        if self._kv_tier is not None:
            int8_pool = self._kv_jnp_dtype is jnp.int8

            def restore_chain(pool, idx, kq, ks, vq, vs):
                # Up to _RESTORE_BATCH spilled blocks scattered back into
                # freshly popped pool slots in ONE dispatch (per-block
                # dispatches drown the win in launch overhead). Fixed
                # shapes idx [R], kq/vq [L, R, Hkv, bs, D], ks/vs
                # [L, R, Hkv, bs] -> exactly one compile; short chains
                # pad their index lanes with scratch block 0 (clobbering
                # it is fine — every prewarm dispatch already does). The
                # pool is donated, so under async dispatch the scatter
                # chains AFTER every in-flight decode chunk (the handle
                # it consumes is the newest chunk's output) and OVERLAPS
                # their host-side drain. An int8 pool takes the
                # quantized payload verbatim (restores are exact); a
                # float pool dequantizes here, device-side, halving H2D
                # bytes vs shipping floats.
                if int8_pool:
                    return dict(
                        pool,
                        k=pool["k"].at[:, idx].set(kq),
                        v=pool["v"].at[:, idx].set(vq),
                        k_scale=pool["k_scale"].at[:, idx].set(ks),
                        v_scale=pool["v_scale"].at[:, idx].set(vs),
                    )
                k = (kq.astype(jnp.float32) * ks[..., None]).astype(
                    pool["k"].dtype
                )
                v = (vq.astype(jnp.float32) * vs[..., None]).astype(
                    pool["v"].dtype
                )
                return dict(
                    pool,
                    k=pool["k"].at[:, idx].set(k),
                    v=pool["v"].at[:, idx].set(v),
                )

            self._restore_chain_jit = jax.jit(
                restore_chain, donate_argnums=0
            )

            def gather_chain(pool, idx):
                # Spill-side twin: up to _RESTORE_BATCH evicted blocks
                # gathered in ONE dispatch, quantized DEVICE-side for
                # float pools (same symmetric amax/127 convention as
                # quantization.quantize_kv_block) so the host copy
                # moves int8 + scales, not floats. idx is TRACED — a
                # python-int pool index would bake the block id into
                # the compiled gather and recompile per block. Padding
                # lanes read scratch block 0 and are discarded.
                k = pool["k"][:, idx]  # [L, R, Hkv, bs, D]
                v = pool["v"][:, idx]
                if int8_pool:
                    return (
                        k, pool["k_scale"][:, idx],
                        v, pool["v_scale"][:, idx],
                    )
                k32 = k.astype(jnp.float32)
                v32 = v.astype(jnp.float32)
                ks = jnp.maximum(
                    jnp.max(jnp.abs(k32), axis=-1), KV_SCALE_EPS
                ) / 127.0
                vs = jnp.maximum(
                    jnp.max(jnp.abs(v32), axis=-1), KV_SCALE_EPS
                ) / 127.0
                kq = jnp.clip(
                    jnp.round(k32 / ks[..., None]), -127, 127
                ).astype(jnp.int8)
                vq = jnp.clip(
                    jnp.round(v32 / vs[..., None]), -127, 127
                ).astype(jnp.int8)
                return kq, ks, vq, vs

            self._gather_chain_jit = jax.jit(gather_chain)

        if draft_params is not None:
            from .speculative import _draft_propose_sampled, spec_accept_commit

            k_spec = self.spec_k

            def spec_round(
                t_params, d_params, pool, d_cache, tables,
                cur, pos0_d, pos0_v, keys, temps, top_ks, top_ps,
                use_filters,
            ):
                """One fused speculative round over the full slot batch:
                draft-propose k tokens (dense per-slot cache, scan;
                SAMPLED for temps > 0 rows, argmax otherwise) + ONE
                paged verification block on the target, then the
                accept/correct rule (speculative.spec_accept_commit:
                exact greedy matching, or Leviathan sampling — lossless
                in distribution) — a single host round-trip commits
                1..k+1 tokens per eligible slot. Parked slots ride
                along with zeroed tables, draft positions in the
                scratch tail (pos0_d=max_len) and verify positions at 0
                (scratch block 0); their outputs are discarded. Active
                slots have pos0_d == pos0_v."""
                props, d_probs, d_cache, keys = _draft_propose_sampled(
                    d_params, d_cache, cur, pos0_d, draft_cfg, k_spec,
                    keys, temps,
                )
                block = jnp.concatenate([cur[:, None], props], axis=1)
                positions = (
                    pos0_v[:, None]
                    + jnp.arange(k_spec + 1, dtype=jnp.int32)[None]
                )
                logits, pool = tfm.decode_block_paged(
                    t_params, pool, tables, block, positions, cfg, tp=self._tp
                )
                commit, n_commit, keys = spec_accept_commit(
                    props, d_probs, logits, temps, keys, top_ks, top_ps,
                    use_filters=use_filters,
                )
                return pool, d_cache, commit, n_commit, keys

            # ONE dispatch surface for every depth — scan length 1 IS the
            # single round, so jit construction, prewarm and
            # _run_spec_round never fork on spec_depth (forked positional
            # signatures fail only at runtime when one site is missed)
            depth = self.spec_depth

            def spec_multi(
                t_params, d_params, pool, d_cache, tables,
                cur, pos0_d, pos0_v, keys, temps, top_ks, top_ps, active,
                use_filters,
            ):
                """``depth`` chained rounds in one dispatch: the commit
                decision (greedy match or Leviathan acceptance) runs
                device-side, advancing each active slot's current token
                and positions between rounds (parked slots stay parked
                — ``active`` is False and their positions never move).
                The host emits exactly the returned commit tokens, so
                losslessness properties are those of
                spec_accept_commit. Rejected positions' K/V is
                overwritten by the next round's writes before anything
                attends it (write-before-read, as everywhere)."""

                def body(carry, _):
                    pool, d_cache, cur, pos_d, pos_v, keys = carry
                    pool, d_cache, commit, n_commit, keys = spec_round(
                        t_params, d_params, pool, d_cache, tables,
                        cur, pos_d, pos_v, keys, temps, top_ks, top_ps,
                        use_filters,
                    )
                    # the correction/bonus token (last committed) seeds
                    # the next round
                    new_cur = jnp.take_along_axis(
                        commit, (n_commit - 1)[:, None], axis=1
                    )[:, 0]
                    pos_d = jnp.where(active, pos_d + n_commit, pos_d)
                    pos_v = jnp.where(active, pos_v + n_commit, pos_v)
                    cur = jnp.where(active, new_cur, cur)
                    return (pool, d_cache, cur, pos_d, pos_v, keys), (
                        commit,
                        n_commit,
                    )

                (pool, d_cache, _, _, _, keys), (commit_r, n_r) = (
                    jax.lax.scan(
                        body,
                        (pool, d_cache, cur, pos0_d, pos0_v, keys),
                        None,
                        length=depth,
                    )
                )
                return pool, d_cache, keys, commit_r, n_r

            # one compile per filters-on/off, like the decode chunks —
            # greedy/plain-temperature batches never pay the per-row
            # vocab sort the top-k/top-p target distribution needs
            self._spec_round_jit = {
                filt: jax.jit(
                    _partial(spec_multi, use_filters=filt),
                    donate_argnums=(2, 3),
                )
                for filt in (False, True)
            }

            def draft_prefill(d_params, d_cache, tokens, slot_idx):
                # one full-sequence draft forward (big MXU matmuls) seeds
                # the slot's dense cache row; pad-tail K/V past the real
                # prompt is rewritten by the propose scan before anything
                # attends it (write-before-read, as everywhere)
                c = tokens.shape[0]
                _, (dk, dv) = tfm.forward(
                    d_params, tokens[None], draft_cfg, return_kv=True
                )
                return {
                    "k": d_cache["k"].at[:, slot_idx, :c].set(dk[:, 0]),
                    "v": d_cache["v"].at[:, slot_idx, :c].set(dv[:, 0]),
                    "length": d_cache["length"],
                }

            self._draft_prefill_jit = jax.jit(draft_prefill, donate_argnums=1)

    # -- public api --------------------------------------------------------
    @classmethod
    def from_checkpoint(
        cls,
        path: str,
        cfg,
        *,
        step: Optional[int] = None,
        quantize: Optional[str] = None,
        draft_checkpoint: Optional[str] = None,
        draft_cfg=None,
        draft_step: Optional[int] = None,
        mesh=None,
        model_axis: str = "model",
        **engine_kwargs,
    ) -> "InferenceEngine":
        """The train->serve seam in one call: restore params from a
        training checkpoint (inference/checkpoint.py — params-only
        elastic restore, placed for this engine's topology, optionally
        int8 weight-quantized via ``quantize="int8"``) and build the
        engine. ``draft_checkpoint``/``draft_cfg`` restore a trained
        draft model for speculative decoding the same way. Remaining
        kwargs go to the constructor (call ``.start()`` as usual)."""
        from .checkpoint import load_serving_params

        params, _ = load_serving_params(
            path, cfg, step=step, mesh=mesh, model_axis=model_axis,
            quantize=quantize,
        )
        draft_params = None
        if draft_checkpoint is None and draft_cfg is not None:
            raise ValueError(
                "draft_cfg without draft_checkpoint — from_checkpoint "
                "restores draft weights, it cannot invent them"
            )
        if draft_checkpoint is not None:
            if draft_cfg is None:
                raise ValueError("draft_checkpoint requires draft_cfg")
            draft_params, _ = load_serving_params(
                draft_checkpoint, draft_cfg, step=draft_step, mesh=mesh,
                model_axis=model_axis,
            )
        return cls(
            params,
            cfg,
            mesh=mesh,
            model_axis=model_axis,
            draft_params=draft_params,
            draft_cfg=draft_cfg if draft_params is not None else None,
            **engine_kwargs,
        )

    def submit(
        self,
        prompt_ids: list[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop: Optional[list[list[int]]] = None,
        min_new_tokens: int = 0,
        logit_bias: Optional[dict[int, float]] = None,
        traceparent: Optional[str] = None,
        kv_source: Optional[str] = None,
    ) -> Request:
        if not prompt_ids:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(prompt_ids)}+{max_new_tokens}) "
                f"exceeds max_len {self.max_len}"
            )
        if top_k < 0 or top_p <= 0.0:
            raise ValueError("need top_k >= 0 and top_p > 0 (>= 1 disables)")
        if stop is not None:
            stop = [list(map(int, s)) for s in stop]
            if not stop or any(not s for s in stop):
                raise ValueError("stop must be non-empty token-id sequences")
        if not 0 <= min_new_tokens <= max_new_tokens:
            raise ValueError(
                "need 0 <= min_new_tokens <= max_new_tokens"
            )
        if logit_bias is not None:
            vocab = self.cfg.vocab_size
            logit_bias = {int(t): float(b) for t, b in logit_bias.items()}
            if any(not 0 <= t < vocab for t in logit_bias):
                raise ValueError(f"logit_bias token ids must be in [0, {vocab})")
        req = Request(
            list(prompt_ids),
            int(max_new_tokens),
            temperature,
            eos_id,
            seed,
            top_k=int(top_k),
            top_p=float(top_p),
            stop=stop,
            min_new_tokens=int(min_new_tokens),
            logit_bias=logit_bias,
            traceparent=traceparent,
            kv_source=kv_source,
        )
        # trace BEFORE the queue put: the scheduler may admit the request
        # the instant it lands, and on_admit is a no-op without the trace
        if self.telemetry is not None:
            self.telemetry.on_submit(req)
        try:
            with self._submit_lock:
                if self._stop.is_set():
                    raise RuntimeError("engine is stopped")
                self.pending.put(req)
        except BaseException:
            if self.telemetry is not None:
                self.telemetry.on_finish(req, "failed")
            raise
        return req

    def start(self) -> "InferenceEngine":
        if self._prewarm_on_start:
            self.prewarm()
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def prewarm(self) -> dict:
        """Compile every program serving can reach, BEFORE traffic does.

        Without this, compilation is lazy per shape bucket, and a
        prefix-cache hit can shift a prompt's tail into a prefill bucket
        no cold-path request ever compiled — paying a multi-second XLA
        compile mid-serving (docs/PERF.md measured 19.5s at 1.3B). The
        chunking only ever emits bucket shapes (power-of-two final
        chunks + the full ``prefill_chunk``; ``_prefill_one_chunk``
        shrinks by whole buckets at the table edge), so compiling the
        bucket set here is a complete no-new-compiles guarantee —
        pinned by tests/test_inference.py with a jit-cache-size probe.

        Every dispatch uses all-zero block tables, so writes land in the
        reserved scratch block 0 and pool contents are untouched (the
        same parked-slot convention the scheduler itself relies on).
        Returns ``{program_name: compile_seconds}``."""
        if self._thread is not None and self._thread.is_alive():
            # the scheduler thread owns the pool once it runs; racing it
            # with donated-pool dispatches would corrupt serving state
            raise RuntimeError("prewarm() must run before start()")
        timings: dict[str, float] = {}
        B = self.max_slots
        zero_tables = jnp.zeros((B, self.max_blocks), jnp.int32)
        zb = jnp.zeros((B,), jnp.int32)
        for c in self._pow2_buckets(self.prefill_chunk):
            t0 = time.monotonic()
            _, self.pool = self._prefill_step_jit(
                self.params,
                self.pool,
                zero_tables[0],
                jnp.zeros((c,), jnp.int32),
                jnp.asarray(0, jnp.int32),
            )
            timings[f"prefill_{c}"] = round(time.monotonic() - t0, 3)
        d = self._dispatcher
        all_parked = jnp.zeros((B,), bool)
        for (k, filt), fn in self._decode_chunk.items():
            t0 = time.monotonic()
            # the dispatcher's device carry is donated through, exactly
            # like serving dispatches; all-parked means zero tables, so
            # writes land in scratch block 0
            self.pool, d.carry, self._keys, _ = fn(
                self.params,
                self.pool,
                d.carry,
                self._keys,
                all_parked,
                self._eos_ids,
                self._min_until,
                self._logit_bias,
            )
            timings[f"decode_{k}{'_filters' if filt else ''}"] = round(
                time.monotonic() - t0, 3
            )
        t0 = time.monotonic()
        d.carry = self._carry_update_jit(
            d.carry,
            all_parked,
            all_parked,
            jnp.zeros((B, 3), jnp.int32),
            jnp.zeros((B, 2), jnp.float32),
            zero_tables,
        )
        timings["carry_update"] = round(time.monotonic() - t0, 3)
        if self._kv_tier is not None:
            # the host-tier restore scatter has ONE shape; scatter zeros
            # into scratch block 0 (pool contents untouched, like every
            # prewarm dispatch) so a first restore mid-serving never
            # pays a compile
            L, Hkv, D = self.cfg.n_layers, self.cfg.n_kv_heads, self.cfg.head_dim
            R = _RESTORE_BATCH
            zq = jnp.zeros((L, R, Hkv, self.block_size, D), jnp.int8)
            zs = jnp.zeros((L, R, Hkv, self.block_size), jnp.float32)
            t0 = time.monotonic()
            self.pool = self._restore_chain_jit(
                self.pool, jnp.zeros((R,), jnp.int32), zq, zs, zq, zs
            )
            timings["kv_restore_scatter"] = round(time.monotonic() - t0, 3)
            t0 = time.monotonic()
            jax.block_until_ready(
                self._gather_chain_jit(self.pool, jnp.zeros((R,), jnp.int32))
            )
            timings["kv_spill_gather"] = round(time.monotonic() - t0, 3)
        if self.draft_params is not None:
            # _draft_prefill buckets: powers of two, clamped at max_len
            # (itself a bucket when not a power of two)
            for c in self._pow2_buckets(self.max_len):
                t0 = time.monotonic()
                self._draft_cache = self._draft_prefill_jit(
                    self.draft_params,
                    self._draft_cache,
                    jnp.zeros((c,), jnp.int32),
                    jnp.asarray(0, jnp.int32),
                )
                timings[f"draft_prefill_{c}"] = round(time.monotonic() - t0, 3)
            for filt, fn in self._spec_round_jit.items():
                t0 = time.monotonic()
                # keys output discarded: self._keys holds base keys that
                # never advance (_run_spec_round derives per-round keys)
                self.pool, self._draft_cache, _, _, _ = fn(
                    self.params,
                    self.draft_params,
                    self.pool,
                    self._draft_cache,
                    zero_tables,
                    zb,
                    jnp.full((B,), self.max_len, jnp.int32),  # parked pos
                    zb,
                    self._keys,
                    jnp.zeros((B,), jnp.float32),
                    zb,
                    jnp.ones((B,), jnp.float32),
                    jnp.zeros((B,), bool),  # all parked
                )
                timings[
                    f"spec_round{'_filters' if filt else ''}"
                ] = round(time.monotonic() - t0, 3)
        jax.block_until_ready(self.pool)
        return timings

    def stats(self) -> dict:
        """Serving counters: completed/failed requests, tokens generated,
        active slots, queue depth, uptime and mean tokens/sec."""
        uptime = (
            time.monotonic() - self._started_at if self._started_at else 0.0
        )
        return {
            "requests_completed": self.requests_completed,
            "requests_failed": self.requests_failed,
            "requests_preempted": self.requests_preempted,
            "tokens_generated": self.tokens_generated,
            "active_slots": sum(
                1 for s in self.slots if s.req is not None and s.ready
            ),
            "prefilling_slots": sum(
                1 for s in self.slots if s.req is not None and not s.ready
            ),
            "max_slots": self.max_slots,
            "free_blocks": len(self._free_blocks),
            "total_blocks": self.n_blocks - 1,
            "prefix_cached_blocks": len(self._prefix_cache),
            "prefix_hit_blocks": self.prefix_hit_blocks,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "recompute_tokens_saved": self.recompute_tokens_saved,
            # host KV tier (inference/kv_tier.py) — all-zero with the
            # tier off, so dashboards can key on one schema
            "kv_tier": self.kv_tier_mode,
            "kv_spill_blocks": self.kv_spill_blocks,
            "kv_spill_bytes": self.kv_spill_bytes,
            "kv_restore_hits": self.kv_restore_hits,
            "kv_restore_fallbacks": self.kv_restore_fallbacks,
            "kv_restore_hit_rate": round(
                self.kv_restore_hits
                / (self.kv_restore_hits + self.kv_restore_fallbacks),
                4,
            )
            if (self.kv_restore_hits + self.kv_restore_fallbacks)
            else 0.0,
            "kv_tier_resident_bytes": (
                self._kv_tier.resident_bytes if self._kv_tier else 0
            ),
            "kv_tier_entries": len(self._kv_tier) if self._kv_tier else 0,
            "kv_tier_spilled_nodes": self._prefix_cache.spilled_count(),
            "kv_tier_remote_nodes": self._prefix_cache.remote_count(),
            # KV migration (disaggregated prefill/decode)
            "kv_migrate_chains": self.kv_migrate_chains,
            "kv_migrate_blocks": self.kv_migrate_blocks,
            "kv_migrate_bytes": self.kv_migrate_bytes,
            "kv_migrate_failures": self.kv_migrate_failures,
            "kv_export_chains": self.kv_export_chains,
            "queued": self.pending.qsize() + len(self._resume),
            "uptime_s": round(uptime, 1),
            "tokens_per_sec": round(self.tokens_generated / uptime, 2)
            if uptime > 0
            else 0.0,
            # windowed rate alongside the lifetime average (which goes
            # stale after idle periods — kept for compatibility)
            "tokens_per_sec_10s": round(self._tok_rate.rate(), 2),
            "spec_rounds": self.spec_rounds,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_committed": self.spec_committed,
            "spec_acceptance": round(
                self.spec_accepted / self.spec_proposed, 4
            )
            if self.spec_proposed
            else 0.0,
            # overlapped-loop observability (inference/dispatch.py):
            # window occupancy at dispatch, host time blocked on token
            # readback vs. host time spent scheduling, and how many
            # packed carry refreshes the slot churn actually cost
            **self._dispatcher.stats(),
        }

    # -- metrics (obs/) ----------------------------------------------------
    @property
    def metrics_registry(self) -> Optional[Registry]:
        """The engine's metric registry (None with metrics disabled)."""
        return self.telemetry.registry if self.telemetry is not None else None

    def metrics_text(self) -> str:
        """Prometheus text exposition of this engine's registry (serving
        counters + request-latency histograms); "" when disabled."""
        reg = self.metrics_registry
        return reg.render() if reg is not None else ""

    # -- timeline profiler (obs/tracing.py) --------------------------------
    def start_timeline(self, max_events: int = 100_000) -> TimelineRecorder:
        """Attach a timeline recorder. The scheduler loop, the decode
        dispatcher and the KV-tier restore path stream events onto named
        Chrome-trace lanes until :meth:`stop_timeline`. Idempotent-ish:
        starting over an active capture replaces it."""
        tl = TimelineRecorder(max_events=max_events)
        if self._kv_tier is not None:
            from ..obs.tracing import get_tracer

            self._kv_tier.tracer = get_tracer()
        self._timeline = tl
        return tl

    def stop_timeline(self) -> Optional[TimelineRecorder]:
        """Detach and return the active recorder (None if none)."""
        tl = self._timeline
        self._timeline = None
        if self._kv_tier is not None:
            self._kv_tier.tracer = None
        return tl

    def capture_timeline(
        self, seconds: float, max_events: int = 100_000
    ) -> dict:
        """Blocking convenience for ``/debug/trace?seconds=N``: record
        for ``seconds`` wall time, then render Chrome-trace JSON. Runs
        on the caller's thread (an HTTP handler), not the scheduler."""
        self.start_timeline(max_events=max_events)
        time.sleep(max(0.0, float(seconds)))
        tl = self.stop_timeline()
        return tl.chrome() if tl is not None else {"traceEvents": []}

    def _register_metric_families(self) -> None:
        """Register ENGINE_METRIC_FAMILIES as pull-style callbacks over
        stats() — the counters keep their single mutation site, the
        registry reads them at scrape time. Weakref'd so a registry that
        outlives the engine (shared ``metrics_registry``) reports 0
        instead of pinning the engine (and its device buffers) alive."""
        import weakref

        reg = self.telemetry.registry
        ref = weakref.ref(self)

        def reader(key):
            def fn():
                eng = ref()
                if eng is None:
                    return 0.0
                return float(eng.stats().get(key, 0) or 0)

            return fn

        for name, kind, help_, key, _agg in ENGINE_METRIC_FAMILIES:
            if kind == "histogram":
                # histograms are real instruments observed per event,
                # not pull callbacks over stats() ints
                if name == "engine_kv_restore_seconds":
                    self._kv_restore_hist = reg.histogram(name, help_)
                elif name == "engine_kv_migrate_seconds":
                    self._kv_migrate_hist = reg.histogram(name, help_)
                continue
            reg.register_callback(name, kind, help_, reader(key))

    def stop(self) -> None:
        """Stop the scheduler and fail out any unfinished requests so no
        caller blocks forever on a dead engine."""
        with self._submit_lock:
            self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)
        # _stop is set under the lock above, so no submit() can enqueue
        # past this point — failing outstanding work OUTSIDE the lock
        # keeps late submitters failing fast instead of stalling behind
        # per-request teardown (telemetry, event sinks, stream wakeups).
        self._fail_outstanding("engine stopped")

    # -- block allocator ---------------------------------------------------
    def _blocks_needed(self, slot_idx: int, upto: int) -> int:
        """Blocks to add so slot covers logical positions [0, upto)."""
        return max(0, math.ceil(upto / self.block_size) - self._nalloc[slot_idx])

    def _evictable(self) -> int:
        """Published cache blocks no table references — reclaimable."""
        return self._prefix_cache.evictable()

    def _pop_block(self) -> int:
        """Take a block for private use: free list first, then evict the
        least-recently-matched ref-0 cache entry. Caller must have
        checked availability (free + evictable). Every cached prefix
        extending the evicted block is unmatchable (_match_prefix needs
        the full ancestor chain), so the cache unpublishes the victim's
        subtree with it — ref-0 descendants return to the free list NOW,
        in-use ones are unpublished so their release frees them. Cost is
        proportional to the evicted chain (radix tree), never to the
        whole cache."""
        if self._free_blocks:
            return self._free_blocks.pop()
        if self._kv_tier is not None:
            # tiered eviction: the victim chain SPILLS (device->host
            # copy, then the nodes stay matchable as "spilled") instead
            # of vanishing; already-spilled nodes orphaned by a broken
            # ancestor chain drop their tier payloads
            spill: list = []
            dropped: list = []
            blk, freed = self._prefix_cache.pop_victim(
                collect_spill=spill, dropped=dropped
            )
            self._spill_blocks(spill)
            for d in dropped:
                self._kv_tier.discard(d)
        else:
            blk, freed = self._prefix_cache.pop_victim()
        # Invariant (and the reason the spill copy above cannot race a
        # recycled block): an evicted chain's blocks carry ZERO table
        # references when they reach the free list — pop_victim only
        # ever frees ref-0 nodes, and the cache mirrors _block_refs
        # exactly. A stale nonzero entry here would mean a slot still
        # points at a block about to be rewritten. Pop the zero entries
        # so dead blocks don't accumulate bookkeeping.
        for b in freed:
            stale = self._block_refs.pop(b, 0)
            assert stale == 0, (
                f"evicted block {b} still has {stale} table reference(s)"
            )
        stale = self._block_refs.pop(blk, 0)
        assert stale == 0, (
            f"evicted block {blk} still has {stale} table reference(s)"
        )
        self._free_blocks.extend(freed)
        return blk

    def _spill_blocks(self, items: list) -> None:
        """Copy evicted blocks device->host into the tier, BEFORE the
        caller recycles them. Reading the gather result orders after
        every in-flight decode chunk (async dispatch: the pool handle
        it consumed is the newest chunk's output), so the copy can
        never observe a half-written block — and published ref-0 blocks
        are never the target of in-flight writes anyway (writes land
        only in private, referenced blocks). One fixed-shape batched
        gather (+ device-side int8 quantization for float pools; int8
        pools ship q + scales verbatim, so their restores are exact)
        per _RESTORE_BATCH blocks: one compile total, one device sync
        per batch instead of per block."""
        R = _RESTORE_BATCH
        spilled_bytes = 0
        for lo in range(0, len(items), R):
            group = items[lo : lo + R]
            idx = [blk for _, blk in group] + [0] * (R - len(group))
            kq, ks, vq, vs = self._gather_chain_jit(
                self.pool, jnp.asarray(idx, jnp.int32)
            )
            # single readback for all four arrays — the one designed
            # device sync per batch, not four
            kq, ks, vq, vs = jax.device_get((kq, ks, vq, vs))  # lint: allow(JIT502)
            for n, (digest, _) in enumerate(group):
                payload = pack_kv_payload(
                    kq[:, n], ks[:, n], vq[:, n], vs[:, n]
                )
                self._kv_tier.put(digest, payload)
                self.kv_spill_blocks += 1
                self.kv_spill_bytes += len(payload)
                spilled_bytes += len(payload)
        _events.emit(
            "kv_tier", "spill", blocks=len(items), bytes=spilled_bytes
        )

    def _on_tier_evict(self, digest: str) -> None:
        """The tier aged out / lost a payload: prune the matching
        spilled radix node so no future match promises a restore the
        tier cannot honor. Subtree digests cascade (drop_spilled returns
        them; discard() does not re-fire this callback)."""
        dropped, freed = self._prefix_cache.drop_spilled(digest)
        self._free_blocks.extend(freed)
        for d in dropped:
            self._kv_tier.discard(d)

    def _alloc(self, slot_idx: int, upto: int) -> bool:
        """Grow slot's table to cover [0, upto). False if pool exhausted
        (after reclaiming unreferenced prefix-cache blocks)."""
        need = self._blocks_needed(slot_idx, upto)
        if need > len(self._free_blocks) + self._evictable():
            return False
        for _ in range(need):
            blk = self._pop_block()
            self._block_refs[blk] = 1
            self._tables[slot_idx, self._nalloc[slot_idx]] = blk
            self._nalloc[slot_idx] += 1
        if need:
            self._dispatcher.invalidate_table(slot_idx)
        return True

    def _free_slot_blocks(self, slot_idx: int) -> None:
        n = self._nalloc[slot_idx]
        for b in (int(b) for b in self._tables[slot_idx, :n]):
            refs = self._block_refs.get(b, 1) - 1
            self._block_refs[b] = refs
            if self._prefix_cache.is_published(b):
                # published ref-0 blocks stay resident as prefix cache
                # until the allocator needs them (_pop_block eviction);
                # the cache mirrors the table refcount to know which
                self._prefix_cache.release(b)
            elif refs <= 0:
                self._free_blocks.append(b)
        self._tables[slot_idx, :] = 0
        self._nalloc[slot_idx] = 0
        self._dispatcher.invalidate_table(slot_idx)

    def _match_prefix(self, prompt: list) -> list:
        """Longest run of already-cached full prompt blocks, capped so at
        least ONE prompt token is left to prefill (its logits seed the
        first generated token). One radix-tree step per block: O(block)
        hashing per step, O(prompt) total — never re-tupling the whole
        prefix."""
        if not self.prefix_cache_enabled:
            return []
        matched = []
        bs = self.block_size
        cur = self._prefix_cache.cursor()
        for i in range((len(prompt) - 1) // bs):
            blk = cur.step(tuple(prompt[i * bs : (i + 1) * bs]))
            if blk is None:
                break
            matched.append(blk)
        return matched

    def _match_prefix_tiered(self, prompt: list) -> tuple[list, list]:
        """Tiered variant of :meth:`_match_prefix`: the walk continues
        THROUGH spilled nodes. Returns ``(matched, spilled)`` — resident
        block ids, then the digests of the spilled chain that extends
        them (restorable from the host tier), jointly capped at the same
        at-least-one-token-left bound. The spilled chain is contiguous:
        a resident node cannot sit below a spilled one (restores revive
        top-down), and the walk stops at the first gap either way."""
        matched: list = []
        spilled: list = []
        if not self.prefix_cache_enabled:
            return matched, spilled
        bs = self.block_size
        cur = self._prefix_cache.cursor()
        for i in range((len(prompt) - 1) // bs):
            step = cur.step_tiered(tuple(prompt[i * bs : (i + 1) * bs]))
            if step is None:
                break
            kind, val = step
            if kind == "res":
                if spilled:  # defensive: see docstring
                    break
                matched.append(val)
            else:
                spilled.append(val)
        return matched, spilled

    def _restore_spilled(
        self, slot_idx: int, prompt: list, base: int, spilled: list
    ) -> int:
        """Restore a spilled chain from the host tier into freshly
        popped blocks — dequantize + scatter, batched ``_RESTORE_BATCH``
        blocks per async jitted dispatch (overlapping any in-flight
        decode chunks) — and revive the radix nodes with the new
        blocks. ``base`` is the resident matched-block count (the chain
        extends it). Payloads are prefetched host-side first, stopping
        at the first miss/corrupt/failed one: that node is pruned
        (digest dropped tier-side too) and the remaining tokens fall
        back to recompute-prefill. Returns blocks restored; the caller
        advances ``prefill_pos`` past them. Block budget was already
        checked by _admit (restores consume the same ``need`` the
        availability check counted)."""
        bs = self.block_size
        t0 = time.monotonic()
        overlapped = self._dispatcher.in_flight > 0
        # phase 0 (network): any REMOTE run in the chain is fetched from
        # its source replica and imported into the local tier, promoting
        # the covered nodes to spilled. A failed/partial migration
        # leaves nodes remote, whose tier reads below MISS — so every
        # migration failure rides the same drop-spilled ->
        # recompute-prefill ladder as a lost local payload.
        remote = [
            d for d in spilled
            if self._prefix_cache.remote_source(d) is not None
        ]
        if remote:
            self._migrate_remote(slot_idx, remote)
        # phase 1 (host): prefetch + validate the chain's payloads —
        # all tier reads happen BEFORE any block pops, so eviction churn
        # from our own pops can't invalidate a payload we still need
        chain = []
        for digest in spilled:
            try:
                payload = self._kv_tier.get(digest)
            except Exception:  # noqa: BLE001 — any tier fault => recompute
                payload = None
            parsed = None
            if payload is not None:
                try:
                    parsed = unpack_kv_payload(payload)
                except ValueError:
                    parsed = None
            if parsed is None:
                # miss / corrupt: degrade to recompute-prefill from here.
                # Prune the dangling node (and its subtree's payloads) so
                # the next admission doesn't re-promise this restore.
                self.kv_restore_fallbacks += 1
                dropped, freed = self._prefix_cache.drop_spilled(digest)
                self._free_blocks.extend(freed)
                self._kv_tier.discard(digest)
                for d in dropped:
                    self._kv_tier.discard(d)
                slot_req = self.slots[slot_idx].req
                _events.emit(
                    "kv_tier", "restore_fallback", level="warn",
                    trace_id=(
                        _req_trace_id(slot_req)
                        if slot_req is not None else None
                    ),
                    slot=slot_idx, digest=digest[:16],
                    pruned=len(dropped),
                )
                break
            chain.append(parsed)
        if not chain:
            return 0
        # phase 2 (device): pop destination blocks, then scatter the
        # chain in _RESTORE_BATCH groups — one fixed-shape dispatch per
        # group, index lanes padded with scratch block 0
        blks = [self._pop_block() for _ in range(len(chain))]
        R = _RESTORE_BATCH
        for lo in range(0, len(chain), R):
            group = chain[lo : lo + R]
            idx = blks[lo : lo + R]
            pad = R - len(group)
            kq = np.stack([g[0] for g in group], axis=1)
            ks = np.stack([g[1] for g in group], axis=1)
            vq = np.stack([g[2] for g in group], axis=1)
            vs = np.stack([g[3] for g in group], axis=1)
            if pad:
                kq = np.pad(kq, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
                ks = np.pad(ks, ((0, 0), (0, pad), (0, 0), (0, 0)))
                vq = np.pad(vq, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
                vs = np.pad(vs, ((0, 0), (0, pad), (0, 0), (0, 0)))
            self.pool = self._restore_chain_jit(
                self.pool,
                jnp.asarray(idx + [0] * pad, jnp.int32),
                jnp.asarray(kq),
                jnp.asarray(ks),
                jnp.asarray(vq),
                jnp.asarray(vs),
            )
        # phase 3: revive the radix nodes and wire the slot table
        cur = self._prefix_cache.cursor()
        for i in range(base):
            # reposition after the resident prefix; publish on existing
            # nodes descends WITHOUT re-touching the LRU (the match walk
            # in _admit already stamped them once)
            cur.publish(
                tuple(prompt[i * bs : (i + 1) * bs]),
                int(self._tables[slot_idx, i]),
                0,
            )
        for n, blk in enumerate(blks):
            i = base + n
            self._block_refs[blk] = 1
            self._tables[slot_idx, i] = blk
            self._nalloc[slot_idx] += 1
            got = cur.publish(tuple(prompt[i * bs : (i + 1) * bs]), blk, 1)
            assert got == blk, "restore revived a node another block holds"
            self.kv_restore_hits += 1
        restored = len(blks)
        self._dispatcher.note_restores(restored, overlapped)
        self._dispatcher.invalidate_table(slot_idx)
        now = time.monotonic()
        if self._kv_restore_hist is not None:
            self._kv_restore_hist.observe(now - t0)
        # distributed trace + timeline: the restore belongs to the slot's
        # request (cold path — runs once per admission with a tier hit)
        req = self.slots[slot_idx].req
        trace = getattr(req, "_obs_trace", None) if req is not None else None
        if trace is not None:
            trace.event(f"kv_restore:{restored}", now)
        tl = self._timeline
        if tl is not None:
            tl.add(
                TRACK_TIER_RESTORE,
                f"restore x{restored}",
                t0,
                now,
                slot=slot_idx,
                blocks=restored,
                overlapped=overlapped,
                trace_id=trace.trace_id if trace is not None else None,
            )
        _events.emit(
            "kv_tier", "restore",
            trace_id=trace.trace_id if trace is not None else None,
            slot=slot_idx, blocks=restored, overlapped=overlapped,
            seconds=round(now - t0, 6),
        )
        return restored

    # -- KV migration (disaggregated prefill/decode, ISSUE 20) -------------
    def _mark_remote_chain(self, prompt: list, source: str) -> None:
        """Record that every full prompt block not already covered by
        the radix tree is fetchable from ``source``: a cursor walk that
        descends through resident/spilled nodes untouched and inserts
        REMOTE nodes past the frontier (``Cursor.publish_remote``)."""
        bs = self.block_size
        cur = self._prefix_cache.cursor()
        for i in range((len(prompt) - 1) // bs):
            cur.publish_remote(tuple(prompt[i * bs : (i + 1) * bs]), source)

    def _migrate_client(self) -> KVMigrationClient:
        if self._kv_client is None:
            self._kv_client = KVMigrationClient()
        return self._kv_client

    def _migrate_remote(self, slot_idx: int, remote: list) -> None:
        """Fetch the wire envelope covering a remote run (ONE pull for
        the whole run, leaf-addressed) and import it into the local
        tier, promoting covered nodes remote -> spilled. Failures leave
        the nodes remote — the caller's tier reads then miss and the
        ordinary fallback ladder recomputes. Never raises."""
        source = self._prefix_cache.remote_source(remote[0])
        t0 = time.monotonic()
        req = self.slots[slot_idx].req
        trace = getattr(req, "_obs_trace", None) if req is not None else None
        trace_id = trace.trace_id if trace is not None else None
        try:
            envelope = self._migrate_client().fetch(source, remote[-1])
            imported = set(import_chain(self._kv_tier, envelope))
        except Exception as e:  # noqa: BLE001 — any fault => recompute
            self.kv_migrate_failures += 1
            _events.emit(
                "kv_tier", "migrate_failed", level="warn",
                trace_id=trace_id, slot=slot_idx, source=source,
                digest=remote[-1][:16], blocks=len(remote),
                reason=type(e).__name__,
            )
            return
        promoted = 0
        for d in remote:
            # promote only the gap-free covered prefix: a node past a
            # gap is unrestorable (its ancestors would miss first)
            if d in imported and self._prefix_cache.promote_remote(d):
                promoted += 1
            else:
                break
        now = time.monotonic()
        self.kv_migrate_chains += 1
        self.kv_migrate_blocks += promoted
        self.kv_migrate_bytes += len(envelope)
        if self._kv_migrate_hist is not None:
            self._kv_migrate_hist.observe(now - t0)
        if trace is not None:
            trace.event(f"kv_migrate:{promoted}", now)
        tl = self._timeline
        if tl is not None:
            tl.add(
                TRACK_TIER_RESTORE, f"migrate x{promoted}", t0, now,
                slot=slot_idx, blocks=promoted, bytes=len(envelope),
                trace_id=trace_id,
            )
        _events.emit(
            "kv_tier", "migrate", trace_id=trace_id, slot=slot_idx,
            source=source, blocks=promoted, requested=len(remote),
            bytes=len(envelope), seconds=round(now - t0, 6),
        )

    def export_kv_chain(
        self, digest: str, timeout: float = 5.0
    ) -> Optional[bytes]:
        """Chain envelope for ``digest`` (the whole root->leaf run it
        names), for a peer replica's migration pull — the replica
        server's ``GET /kv/chain/<digest>``. Thread-safe: the request is
        mailboxed to the scheduler thread, the only one allowed to read
        the pool/cache/tier (with no scheduler running — tests, offline
        tools — it is served inline). None for unknown digests, with
        the tier off, or on timeout."""
        if self._kv_tier is None:
            return None
        if self._thread is not None and self._thread.is_alive():
            box: dict = {"done": threading.Event(), "envelope": None}
            self._kv_export_requests.put((digest, box))
            if not box["done"].wait(timeout):
                return None
            return box["envelope"]
        return self._serve_kv_export(digest)

    def _service_kv_exports(self) -> None:
        """Drain the export mailbox (scheduler thread, between
        iterations)."""
        while True:
            try:
                digest, box = self._kv_export_requests.get_nowait()
            except queue.Empty:
                return
            try:
                box["envelope"] = self._serve_kv_export(digest)
            except Exception:  # noqa: BLE001 — a failed export is a 404
                box["envelope"] = None
            finally:
                box["done"].set()

    def _serve_kv_export(self, digest: str) -> Optional[bytes]:
        """Build the envelope: resident chain blocks are gathered
        device->host (batched, same shape discipline as the spill path)
        and packed; spilled ones read from the tier. Serves the longest
        gap-free prefix — the importer promotes exactly what arrives.
        Scheduler thread (or no scheduler) only."""
        chain = self._prefix_cache.chain_to(digest)
        if not chain:
            return None
        resident = [(d, blk) for d, blk in chain if blk >= 0]
        payloads: dict[str, bytes] = {}
        R = _RESTORE_BATCH
        for lo in range(0, len(resident), R):
            group = resident[lo : lo + R]
            idx = [blk for _, blk in group] + [0] * (R - len(group))
            kq, ks, vq, vs = self._gather_chain_jit(
                self.pool, jnp.asarray(idx, jnp.int32)
            )
            kq, ks, vq, vs = jax.device_get((kq, ks, vq, vs))  # lint: allow(JIT502)
            for n, (d, _) in enumerate(group):
                payloads[d] = pack_kv_payload(
                    kq[:, n], ks[:, n], vq[:, n], vs[:, n]
                )
        blocks: list = []
        for d, blk in chain:
            payload = payloads.get(d) if blk >= 0 else self._kv_tier.get(d)
            if payload is None:
                break  # gap: nothing below it is restorable
            blocks.append((d, payload))
        if not blocks:
            return None
        self.kv_export_chains += 1
        _events.emit(
            "kv_tier", "migrate_export", digest=digest[:16],
            blocks=len(blocks),
        )
        return pack_chain_envelope(blocks)

    def _publish_prefix_blocks(self, slot_idx: int) -> None:
        """Make this slot's fully-written full prompt blocks matchable.
        Called after each prefill chunk; a block is publishable once
        prefill has passed its end (its K/V is final: later writes are
        all at higher positions). First writer wins — a concurrently
        computed duplicate stays private (the cursor descends through
        the first writer's node and our block is simply not inserted)."""
        if not self.prefix_cache_enabled:
            return
        slot = self.slots[slot_idx]
        bs = self.block_size
        n_full = min(slot.prefill_pos, len(slot.prompt)) // bs
        cur = self._prefix_cache.cursor()
        for i in range(n_full):
            blk = int(self._tables[slot_idx, i])
            cur.publish(
                tuple(slot.prompt[i * bs : (i + 1) * bs]),
                blk,
                self._block_refs.get(blk, 0),
            )

    def _decode_tables(self, include=None) -> jax.Array:
        """Block tables for a dispatch: slots outside ``include`` (default:
        all ready slots) get an all-zeros row so their garbage write lands
        in the scratch block instead of clobbering prefilled K/V."""
        t = self._tables.copy()
        for i, s in enumerate(self.slots):
            if include is not None:
                if i not in include:
                    t[i, :] = 0
            elif s.req is None or not s.ready:
                t[i, :] = 0
        return jnp.asarray(t)

    # -- scheduler ---------------------------------------------------------
    @staticmethod
    def _finish(req: Request) -> None:
        """Terminal wakeup: set done, then wake stream() waiters. done
        FIRST so a woken consumer observes the finished state."""
        req.done.set()
        req._notify()

    def _fail_outstanding(self, reason: str, drain_queue: bool = True) -> None:
        """Fail slot-resident requests (their K/V lives in the pool).
        ``drain_queue=False`` spares queued requests that were never
        admitted — after a cache loss they have no state to lose and a
        rebuilt pool can still serve them; only stop() drains the queue.

        The in-flight dispatch window is abandoned FIRST: its futures may
        be poisoned (async dispatch surfaces device errors at readback)
        and its chunks' requests are exactly the slot-resident ones
        failed below — nothing may read from or emit out of it after
        this point."""
        _events.emit(
            "engine", "fail_outstanding", level="error",
            reason=reason, drain_queue=drain_queue,
        )
        self._dispatcher.abandon()
        for i, slot in enumerate(self.slots):
            req = slot.req  # snapshot: a live scheduler may race us when
            if req is None:  # stop()'s join timed out on a wedged dispatch
                continue
            slot.req = None
            slot.ready = False
            self._free_slot_blocks(i)
            if req.done.is_set():
                continue  # completed concurrently — don't double-count
            req.error = reason
            self.requests_failed += 1
            if self.telemetry is not None:
                self.telemetry.on_finish(req, "failed")
            _events.emit(
                "engine", "request_failed", level="error",
                trace_id=_req_trace_id(req), reason=reason, slot=i,
                stage="decode",
            )
            self._finish(req)  # done LAST (see _emit)
        if not drain_queue:
            return
        for req in self._resume:
            req.error = reason
            self.requests_failed += 1
            if self.telemetry is not None:
                self.telemetry.on_finish(req, "failed")
            _events.emit(
                "engine", "request_failed", level="error",
                trace_id=_req_trace_id(req), reason=reason, stage="resume",
            )
            self._finish(req)  # done LAST (see _emit)
        self._resume.clear()
        while True:
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                break
            req.error = reason
            self.requests_failed += 1
            if self.telemetry is not None:
                self.telemetry.on_finish(req, "failed")
            _events.emit(
                "engine", "request_failed", level="error",
                trace_id=_req_trace_id(req), reason=reason, stage="queued",
            )
            self._finish(req)  # done LAST (see _emit)

    def _recover_pool_if_lost(self) -> None:
        """After a failed prefill/decode dispatch: the pool may have been
        donated into the failed call without the reassignment happening.
        Then in-flight K/V is unrecoverable — fail slot-resident requests
        and rebuild; queued requests are served from the fresh pool."""
        lost = False
        try:
            lost = any(a.is_deleted() for a in self.pool.values())
        except AttributeError:  # non-jax.Array leaves (tests with numpy)
            lost = False
        if lost:
            self._fail_outstanding(
                "kv pool lost in failed dispatch", drain_queue=False
            )
            self._reset_pool()

    def _reset_pool(self) -> None:
        """Fresh pool + allocator state (all failure paths share this —
        the invariant must not fork). The prefix cache indexes CONTENT
        of the lost pool, so it resets with it."""
        self.pool = self._fresh_pool()
        self._free_blocks = list(range(1, self.n_blocks))
        self._tables[:] = 0
        self._nalloc = [0] * self.max_slots
        self._prefix_cache.reset()
        if self._kv_tier is not None:
            # payloads are content-addressed, but the radix nodes that
            # map digests to matches died with the cache — drop them
            self._kv_tier.clear()
        self._block_refs.clear()
        # the keys array is an OUTPUT of the failed decode chain under
        # async dispatch — a poisoned future that would re-raise on the
        # next dispatch. Rebuild it; live slots were failed with the pool
        # and re-admissions reseed their rows at prefill completion.
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.prefill_chunk)

    @staticmethod
    def _pow2_buckets(limit: int, include_limit: bool = True) -> list[int]:
        """Power-of-two sizes up to ``limit`` (plus ``limit`` itself when
        ``include_limit`` and it is not one) — THE bucket enumeration the
        shape-keyed dispatch paths and prewarm() share; the
        no-new-compiles guarantee holds only while they agree.

        ``limit`` must be >= 1: the contract is every returned size is
        <= limit, and for limit < 1 there is no such bucket — returning
        [1] anyway (the old behavior) would hand callers an overshooting
        chunk shape (ADVICE r5)."""
        if limit < 1:
            raise ValueError(f"_pow2_buckets needs limit >= 1, got {limit}")
        out = [1]
        while out[-1] * 2 <= limit:
            out.append(out[-1] * 2)
        if include_limit and out[-1] != limit:
            out.append(limit)
        return out

    def _chunk_sizes(self) -> list[int]:
        sizes = [1]
        while sizes[-1] * 2 <= self.chunk_max:
            sizes.append(sizes[-1] * 2)
        return sizes

    def _pick_chunk(self, n: int) -> int:
        """Largest compiled chunk size <= n."""
        best = 1
        for k in self._chunk_sizes():
            if best < k <= n:
                best = k
        return best

    def _admit(self, slot_idx: int, req: Request) -> bool:
        """Assign a slot and allocate blocks for the prompt. The actual
        prefill happens chunk-by-chunk in the scheduler loop. Returns
        False (leaving the request queued) when the pool can't hold the
        prompt right now."""
        prompt = req.prompt_ids + req.tokens  # tokens: preempted resume
        if self._kv_tier is not None:
            matched, spilled = self._match_prefix_tiered(prompt)
            # disaggregated prefill: a kv_source hint promises the
            # uncovered prompt blocks at a peer replica — mark them
            # REMOTE so the restore path fetches their envelope instead
            # of recompute-prefilling (any failure falls back there)
            if req.kv_source and (
                len(matched) + len(spilled)
                < (len(prompt) - 1) // self.block_size
            ):
                self._mark_remote_chain(prompt, req.kv_source)
                matched, spilled = self._match_prefix_tiered(prompt)
        else:
            matched, spilled = self._match_prefix(prompt), []
        # spilled blocks are NOT subtracted from need: each restore pops
        # a fresh block, so they consume exactly the budget the
        # availability check counts for them
        need = math.ceil(len(prompt) / self.block_size) - len(matched)
        # availability must not count the matched blocks themselves: a
        # ref-0 cached block we are about to reference is no longer
        # evictable for the private-block pops
        avail = len(
            self._free_blocks
        ) + self._prefix_cache.evictable_excluding(matched)
        if need > avail:
            return False
        # commit: reference matched blocks FIRST so the private-block
        # pops below can never evict them
        for i, blk in enumerate(matched):
            self._block_refs[blk] = self._block_refs.get(blk, 0) + 1
            self._prefix_cache.ref(blk)
            self._tables[slot_idx, i] = blk
        self._nalloc[slot_idx] = len(matched)
        # restore the spilled extension of the matched chain (host->
        # device, async) before the private pops — restored blocks are
        # referenced, so the pops below can never evict them either
        restored = (
            self._restore_spilled(slot_idx, prompt, len(matched), spilled)
            if spilled
            else 0
        )
        ok = self._alloc(slot_idx, len(prompt))
        assert ok, "availability was checked above"
        self.prefix_hit_blocks += len(matched) + restored
        self.prefix_hit_tokens += (len(matched) + restored) * self.block_size
        self.recompute_tokens_saved += restored * self.block_size
        slot = self.slots[slot_idx]
        slot.gen += 1  # new occupant: stale in-flight chunks must not emit
        slot.req = req
        slot.prompt = prompt
        # skip straight past the cached prefix (resident matches plus
        # tier restores): its K/V is already in the pool; at least one
        # prompt token remains (_match_prefix cap)
        slot.prefill_pos = (len(matched) + restored) * self.block_size
        slot.ready = False
        slot.draft_ready = False
        slot.length = len(prompt)
        slot.remaining = req.max_new_tokens - len(req.tokens)
        slot.admitted_at = time.monotonic()
        self._sync_sampling_extras(slot_idx, req)
        if self.telemetry is not None:
            self.telemetry.on_admit(req)
        _events.emit(
            "engine", "admit", trace_id=_req_trace_id(req), slot=slot_idx,
            prompt_tokens=len(prompt),
            cached_blocks=len(matched) + restored,
        )
        return True

    def _sync_sampling_extras(self, slot_idx: int, req: Request) -> None:
        """Refresh this slot's device-side sampling extras (EOS
        suppression bound + logit bias row). Skipped entirely — no
        device dispatches — while neither the new request nor the slot's
        previous occupant used them, so plain requests never pay the
        admission round-trips."""
        uses_min = req.eos_id is not None and req.min_new_tokens > 0
        uses = uses_min or bool(req.logit_bias)
        if not uses and not self._extras_dirty[slot_idx]:
            return
        eos = req.eos_id if uses_min else -1
        # the device suppresses EOS while the WRITE position is below
        # this bound: sampled token number g is generated at position
        # len(prompt_ids)-2+g, and tokens 1..min_new must not be EOS
        # (absolute positions, so preemption-resume keeps the bound)
        min_until = (
            len(req.prompt_ids) + req.min_new_tokens - 1 if uses_min else 0
        )
        self._eos_ids = self._eos_ids.at[slot_idx].set(eos)
        self._min_until = self._min_until.at[slot_idx].set(min_until)
        self._logit_bias = self._logit_bias.at[slot_idx].set(
            self._bias_row(req)
        )
        self._extras_dirty[slot_idx] = uses

    def _bias_row(self, req: Request) -> np.ndarray:
        """The request's dense [vocab] additive-bias row — the ONE place
        logit_bias becomes an array (device rows and the host-side
        first-token sample must stay in lockstep)."""
        bias = np.zeros(self.cfg.vocab_size, np.float32)
        if req.logit_bias:
            for t, b in req.logit_bias.items():
                bias[t] = b
        return bias

    def _prefill_one_chunk(self, slot_idx: int) -> None:
        """Advance one slot's prefill by at most ``prefill_chunk`` tokens
        (ONE bounded dispatch). On the final chunk, sample the first
        generated token."""
        slot = self.slots[slot_idx]
        req = slot.req
        t = len(slot.prompt)
        offset = slot.prefill_pos
        remaining = t - offset
        c = (
            self.prefill_chunk
            if remaining >= self.prefill_chunk
            else self._bucket(remaining)
        )
        # the chunk's positions offset..offset+c-1 must stay inside the
        # slot's table span — an overshooting pad tail would clamp into
        # the prompt's last allocated block and corrupt its K/V. Shrink
        # by whole buckets, not to the raw span: an arbitrary-length
        # chunk would be a shape no one compiled (prewarm() enumerates
        # the bucket set and promises no mid-serving compiles).
        t_alloc = self.max_blocks * self.block_size
        # the slot's allocation always covers past the prefill offset
        # (admission allocated the whole prompt); _pow2_buckets would
        # raise for a non-positive span, so make the invariant explicit
        assert t_alloc > offset, (
            f"prefill offset {offset} outside allocated span {t_alloc}"
        )
        if c > t_alloc - offset:
            c = self._pow2_buckets(t_alloc - offset, include_limit=False)[-1]
        real = min(remaining, c)
        chunk = slot.prompt[offset : offset + real] + [0] * (c - real)
        table = jnp.asarray(self._tables[slot_idx])
        tl = self._timeline
        t_pf = time.monotonic() if tl is not None else 0.0
        logits, self.pool = self._prefill_step_jit(
            self.params,
            self.pool,
            table,
            jnp.asarray(chunk, jnp.int32),
            jnp.asarray(offset, jnp.int32),
        )
        if tl is not None:
            trace = getattr(req, "_obs_trace", None)
            tl.add(
                TRACK_PREFILL,
                f"prefill slot {slot_idx} @{offset}+{real}",
                t_pf,
                time.monotonic(),
                slot=slot_idx,
                offset=offset,
                tokens=real,
                trace_id=trace.trace_id if trace is not None else None,
            )
        slot.prefill_pos = offset + real
        self._publish_prefix_blocks(slot_idx)
        if self.telemetry is not None:
            self.telemetry.on_prefill_chunk(req, slot.prefill_pos)
        if slot.prefill_pos >= t:
            # prefill complete: first token from the last REAL position.
            # The slot's device row holds the BASE key; every sample key
            # is fold_in(base, position of the token sampled FROM). Here
            # that position is len(slot.prompt)-1 — for a fresh request
            # that's the last prompt token, and on preemption resume
            # (slot.prompt = prompt_ids + generated) it's the last
            # pre-preemption token, so the resumed stream re-derives
            # exactly the keys the uninterrupted run would have used.
            key = jax.random.PRNGKey(req.seed)
            sub = jax.random.fold_in(key, len(slot.prompt) - 1)
            self._keys = self._keys.at[slot_idx].set(key)
            lg = logits[real - 1]
            # the first generated token samples host-side, so the
            # device-side extras must be mirrored here
            if req.logit_bias:
                lg = lg + self._bias_row(req)
            # gen-so-far < min_new (NOT min_new >= 1: on preemption-
            # resume the request may already be past its minimum)
            if (
                req.eos_id is not None
                and len(req.tokens) < req.min_new_tokens
            ):
                lg = lg.at[req.eos_id].set(-jnp.inf)
            first = sample_logits(
                sub, lg, req.temperature, req.top_k, req.top_p
            )
            if self.draft_params is not None and not req.logit_bias:
                # every sampling config can ride the speculative path
                # (greedy matching, or Leviathan accept/resample against
                # the filtered target distribution). logit_bias slots
                # are spec-ineligible for their whole lifetime, so their
                # draft prefill would be dead work; min_new_tokens slots
                # become eligible later, so theirs pays off
                self._draft_prefill(slot_idx)
            slot.ready = True
            if self.telemetry is not None:
                self.telemetry.on_prefill_done(req)
            self._emit(slot_idx, int(first))
            # host is authoritative for this slot's carry row until its
            # first decode dispatch re-uploads it
            self._dispatcher.invalidate_state(slot_idx)

    def _draft_prefill(self, slot_idx: int) -> None:
        """Seed the slot's dense draft-cache row in ONE bucketed forward
        (shape-keyed jit: one compile per power-of-two prompt bucket).
        The draft is small, so a single full-prompt dispatch stays well
        under the target's per-chunk cost bound."""
        slot = self.slots[slot_idx]
        t = len(slot.prompt)
        c = 1
        while c < t:
            c *= 2
        c = min(c, self.max_len)
        toks = slot.prompt + [0] * (c - t)
        self._draft_cache = self._draft_prefill_jit(
            self.draft_params,
            self._draft_cache,
            jnp.asarray(toks, jnp.int32),
            jnp.asarray(slot_idx, jnp.int32),
        )
        slot.draft_ready = True

    def _reset_draft_cache(self) -> None:
        """After a dispatch failure that may have consumed the donated
        draft cache: rebuild it empty and stop speccing resident slots
        (they fall back to plain decode — losslessness never depended on
        draft state, so nothing else needs repair)."""
        if self.draft_params is None:
            return
        try:
            lost = any(
                hasattr(a, "is_deleted") and a.is_deleted()
                for a in (self._draft_cache["k"], self._draft_cache["v"])
            )
        except Exception:  # noqa: BLE001 — conservative: rebuild
            lost = True
        if lost:
            self._draft_cache = self._fresh_draft_cache()
            for s in self.slots:
                s.draft_ready = False

    def _preempt_youngest(self, keep: Optional[int] = None) -> bool:
        """Free the most recently admitted slot (ready OR mid-prefill),
        requeueing its request (recompute-style preemption: the generated
        prefix rides along as part of the next admission's prompt).
        ``keep`` protects one slot; returns False with nothing left to
        preempt. Since the pool always holds at least one max_len
        sequence (enforced at init), a lone resident can always grow —
        preemption cannot deadlock the allocator."""
        candidates = [
            (i, s)
            for i, s in enumerate(self.slots)
            if s.req is not None and i != keep
        ]
        if not candidates or (keep is None and len(candidates) <= 1):
            return False  # never preempt the only runner
        i, slot = max(candidates, key=lambda t: t[1].admitted_at)
        self._preempt(i)
        return True

    def _preempt(self, i: int) -> None:
        slot = self.slots[i]
        req = slot.req
        if req is None:
            return
        if self._kv_tier is not None:
            self._publish_preempt_chain(i)
        slot.req = None
        slot.ready = False
        self._free_slot_blocks(i)
        self._resume.append(req)
        self.requests_preempted += 1
        if self.telemetry is not None:
            self.telemetry.on_preempt(req)
        _events.emit(
            "engine", "preempt", level="warn",
            trace_id=_req_trace_id(req), slot=i,
            generated=len(req.tokens),
        )

    def _publish_preempt_chain(self, i: int) -> None:
        """Tiered preemption: publish the slot's fully-WRITTEN blocks
        covering prompt + generated tokens before the blocks are freed,
        so the chain stays matchable — under pressure it then spills to
        the host tier and the resume admission RESTORES it instead of
        re-prefilling the generated prefix (the recompute cost the
        pressure leg pays). K/V is final for positions [0, length-1)
        (the last emitted token's K/V is written by the step that
        generates its successor), and every _preempt call site reaches
        here with the dispatch window drained, so the blocks are
        settled. Gated on the tier: the untiered engine keeps its exact
        prior behavior (generated-suffix blocks were never published).

        Mid-prefill slots need nothing — their full prompt blocks are
        already published incrementally by _publish_prefix_blocks."""
        slot = self.slots[i]
        if not slot.ready or slot.req is None:
            return
        seq = slot.req.prompt_ids + slot.req.tokens
        bs = self.block_size
        n_full = (slot.length - 1) // bs
        cur = self._prefix_cache.cursor()
        for b in range(n_full):
            blk = int(self._tables[i, b])
            cur.publish(
                tuple(seq[b * bs : (b + 1) * bs]),
                blk,
                self._block_refs.get(blk, 0),
            )

    def _emit(self, slot_idx: int, token: int) -> None:
        slot = self.slots[slot_idx]
        req = slot.req
        req.tokens.append(token)
        req._notify()  # wake stream() consumers (event-driven delivery)
        self.tokens_generated += 1
        self._tok_rate.add(1)
        if self.telemetry is not None:
            self.telemetry.on_emit(req)
        slot.last_token = token
        slot.length += 1
        slot.remaining -= 1
        gen = len(req.tokens)
        finish = slot.remaining <= 0
        # EOS/stop never end generation inside the first min_new_tokens
        # (EOS is additionally suppressed device-side so the model keeps
        # producing real tokens there)
        if (
            req.eos_id is not None
            and token == req.eos_id
            and gen > req.min_new_tokens
        ):
            finish = True
        # checked even when max_new_tokens finishes on this same token —
        # a match ending here still strips (result() contract). A match
        # only counts when the WHOLE matched sequence lies past
        # min_new_tokens: a straddling match would strip result() below
        # the guaranteed minimum, so generation continues instead.
        if req.stop:
            for s in req.stop:
                if (
                    gen >= len(s)
                    and gen - len(s) >= req.min_new_tokens
                    and req.tokens[-len(s):] == s
                ):
                    req.result_len = gen - len(s)
                    finish = True
                    break
        if finish:
            slot.req = None
            slot.ready = False
            self._retire_slot(slot_idx)
            self.requests_completed += 1
            if self.telemetry is not None:
                self.telemetry.on_finish(req, "completed")
            # done LAST: result()/stats() callers wake on it and must see
            # the counters and the freed blocks already settled
            self._finish(req)

    def _retire_slot(self, slot_idx: int) -> None:
        """Release a finished slot's blocks — immediately when no decode
        chunk references it, otherwise deferred until the last in-flight
        chunk drains (the chunk's overshoot writes target these blocks;
        the slot stays un-admittable meanwhile — see slot_busy)."""
        if self._dispatcher.slot_busy(slot_idx):
            self._dispatcher.pending_free.add(slot_idx)
        else:
            self._free_slot_blocks(slot_idx)

    def _next_pending(self) -> Optional[Request]:
        if self._resume:
            return self._resume.pop(0)
        try:
            return self.pending.get_nowait()
        except queue.Empty:
            return None

    def _admit_pending(self) -> None:
        """Admit as many pending requests as there are free slots
        (admission only reserves blocks — prefill is incremental). A slot
        still referenced by in-flight decode chunks (a zombie: finished,
        but its blocks receive overshoot writes until the window drains)
        is skipped until the dispatcher releases it."""
        for i, slot in enumerate(self.slots):
            if slot.req is not None or self._dispatcher.slot_busy(i):
                continue
            req = self._next_pending()
            if req is None:
                break
            try:
                if not self._admit(i, req):
                    # pool full — keep it queued at the front
                    self._resume.insert(0, req)
                    break
            except Exception as e:  # noqa: BLE001 — surface per-request
                req.error = str(e)
                # _admit may have reserved blocks (and prefix-cache
                # refs) before raising — e.g. in the device work of
                # _sync_sampling_extras. Release them or the pool
                # shrinks permanently; idempotent when nothing was
                # reserved (_nalloc is 0).
                self._free_slot_blocks(i)
                self.slots[i].req = None
                self.requests_failed += 1
                if self.telemetry is not None:
                    self.telemetry.on_finish(req, "failed")
                _events.emit(
                    "engine", "request_failed", level="error",
                    trace_id=_req_trace_id(req), reason=str(e),
                    stage="admit", slot=i,
                )
                self._recover_pool_if_lost()
                self._finish(req)  # done LAST (see _emit)

    def _next_prefill_slot(self, prefilling: list[int]) -> int:
        """Rotating pick over prefilling slots: lowest index strictly
        above the previous pick, wrapping to the lowest — so high-index
        admissions make prefill progress under load instead of starving
        behind slot 0 (the old loop always took ``prefilling[0]``).
        Pinned by tests/test_engine_dispatch.py."""
        after = [i for i in prefilling if i > self._prefill_cursor]
        i = after[0] if after else prefilling[0]
        self._prefill_cursor = i
        return i

    def _spec_eligible(self, ready: list[int]) -> list[int]:
        """Slots riding this iteration's speculative round: draft cache
        seeded, far enough from max_len that a depth-R verification
        window fits, and using no per-slot sampling extras (the spec
        round samples without them — biased slots would commit unbiased
        tokens, and min-length slots could commit suppressed EOS; both
        take the plain path, which applies them). Truthiness: an empty
        logit_bias dict is a no-op and must not disqualify the slot."""
        if self.draft_params is None:
            return []
        # a depth-R dispatch can advance R*(k+1) tokens; its last verify
        # write lands at length-2 + R*(k+1), which must stay inside
        # max_len (R=1 reduces to length+k <= max_len)
        spec_span = self.spec_depth * (self.spec_k + 1)
        return [
            i
            for i in ready
            # greedy AND sampling (incl. top-k/top-p: the accept/resample
            # rule runs against the FILTERED target distribution —
            # lossless in distribution for any proposal distribution)
            if self.slots[i].draft_ready
            and self.slots[i].length + spec_span - 1 <= self.max_len
            and not self.slots[i].req.logit_bias
            and len(self.slots[i].req.tokens)
            >= self.slots[i].req.min_new_tokens
        ]

    def _dispatch_failed(self, e: Exception) -> None:
        """A decode dispatch or its readback died (async dispatch
        surfaces device errors at readback time). The pool and the device
        carry were donated into the failed chain and may be invalid:
        fail the WHOLE in-flight window (every chunk chains off the
        poisoned pool) rather than hang any caller, then rebuild a clean
        pool and keep serving new requests."""
        _events.emit(
            "engine", "poisoned_window", level="error", error=str(e),
            in_flight=self._dispatcher.in_flight,
        )
        self._fail_outstanding(f"decode failed: {e}", drain_queue=False)
        self._reset_pool()  # donated buffer is gone
        self._reset_draft_cache()

    def _note_iter(self, t_iter: float) -> None:
        """Close out one scheduler iteration: account busy time and, when
        a timeline capture is live, put the iteration on the host-sched
        lane (the async dispatch inside it overlaps the device lanes —
        that overlap is exactly what the profiler exists to show)."""
        now = time.monotonic()
        self._dispatcher.loop_busy_s += now - t_iter
        tl = self._timeline
        if tl is not None:
            tl.add(TRACK_HOST_SCHED, "iteration", t_iter, now)

    def _loop(self) -> None:
        """Scheduler iterations: admission, ONE bounded prefill chunk,
        spec-round interleaving, chunk sizing + block coverage (with the
        preemption ladder), then an ASYNC decode dispatch. The
        DecodeDispatcher (inference/dispatch.py) owns the in-flight
        window and device-resident carry; emit/EOS handling happens when
        entries drain — overlapping the newest chunk's device compute."""
        d = self._dispatcher
        while not self._stop.is_set():
            t_iter = time.monotonic()
            if not self._kv_export_requests.empty():
                self._service_kv_exports()
            self._admit_pending()
            prefilling = [
                i
                for i, s in enumerate(self.slots)
                if s.req is not None and not s.ready
            ]
            ready = [
                i for i, s in enumerate(self.slots) if s.req is not None and s.ready
            ]
            if not prefilling and not ready:
                if d.in_flight:
                    # nothing schedulable, but chunks are in flight —
                    # their readback is the only source of new work
                    # (zombie slots free, completions emit)
                    try:
                        d.drain(block=True)
                    except Exception as e:  # noqa: BLE001
                        self._dispatch_failed(e)
                    self._note_iter(t_iter)
                    continue
                # idle: wait for work
                try:
                    req = self.pending.get(timeout=0.05)
                except queue.Empty:
                    continue
                self._resume.insert(0, req)
                continue
            # ONE bounded prefill chunk per iteration (rotating over
            # prefilling slots), so admission never starves decode
            if prefilling:
                i = self._next_prefill_slot(prefilling)
                try:
                    self._prefill_one_chunk(i)
                except Exception as e:  # noqa: BLE001
                    slot = self.slots[i]
                    req = slot.req
                    slot.req = None
                    slot.ready = False
                    self._free_slot_blocks(i)
                    if req is not None:
                        req.error = str(e)
                        self.requests_failed += 1
                        if self.telemetry is not None:
                            self.telemetry.on_finish(req, "failed")
                        _events.emit(
                            "engine", "request_failed", level="error",
                            trace_id=_req_trace_id(req), reason=str(e),
                            stage="prefill", slot=i,
                        )
                    self._recover_pool_if_lost()
                    self._reset_draft_cache()  # draft prefill may have died
                    if req is not None:
                        self._finish(req)  # done LAST (see _emit)
                if not ready:
                    # nothing to decode yet — but finished in-flight
                    # chunks can retire while the next prompt prefills
                    try:
                        d.drain(block=False)
                    except Exception as e:  # noqa: BLE001
                        self._dispatch_failed(e)
                    self._note_iter(t_iter)
                    continue
            if not ready:
                continue
            # split ready slots into the SPECULATIVE group and the PLAIN
            # decode group; both dispatch in the same iteration so
            # neither starves — a slot that outgrows spec eligibility
            # (near max_len, monotone) simply finishes on the plain path
            spec_idx = self._spec_eligible(ready)
            if spec_idx and d.in_flight:
                # a spec round reads AND rewrites slot K/V and commits
                # host-side — it needs settled state, so the window
                # drains first; eligibility is then recomputed because
                # the drain advanced lengths and may finish slots
                try:
                    d.drain_all()
                except Exception as e:  # noqa: BLE001
                    self._dispatch_failed(e)
                    self._note_iter(t_iter)
                    continue
                ready = [
                    i
                    for i in ready
                    if self.slots[i].req is not None and self.slots[i].ready
                ]
                spec_idx = self._spec_eligible(ready)
            # Plain group: every ready non-spec slot that still has
            # tokens to produce BEYOND what in-flight chunks already
            # cover (a slot whose whole remainder is in flight will
            # finish when those chunks drain — dispatching for it would
            # be pure overshoot)
            plain = [
                i
                for i in ready
                if i not in spec_idx
                and self.slots[i].remaining - d.inflight_steps[i] >= 1
            ]
            # Plain chunk size: sized to the LONGEST effective remaining
            # want (rounded down to a compiled power of two) — clamping
            # to the shortest would put the whole batch back in the one-
            # round-trip-per-token regime whenever any short request is
            # co-resident. Slots that finish mid-chunk (EOS or
            # remaining=0) truncate host-side; the overshoot compute is
            # already paid by the static batch. In-flight steps count as
            # already-produced: the window must not inflate the want.
            if plain:
                want = max(
                    self.slots[i].remaining - d.inflight_steps[i]
                    for i in plain
                )
                room = min(
                    self.max_len
                    - (self.slots[i].length + d.inflight_steps[i])
                    for i in plain
                )
                k_steps = self._pick_chunk(max(1, min(want, room + 1)))
            else:
                k_steps = 1
            # grow every participating slot's table to cover this
            # iteration's writes; preempt youngest-first when the pool
            # runs dry
            plain_set = set(plain)
            restart = False
            for i in list(ready):
                s = self.slots[i]
                if s.req is None or not s.ready:
                    # preempted as a victim while an earlier slot in this
                    # pass grew its table — it no longer participates
                    ready.remove(i)
                    continue
                if i in spec_idx:
                    # verification writes reach position
                    # length-2 + depth*(k+1) (eligibility bounds it
                    # inside max_len); R=1 reduces to length+k
                    need_upto = (
                        s.length - 1 + self.spec_depth * (self.spec_k + 1)
                    )
                elif i in plain_set:
                    # writes never pass max_len-1 (the decode scan clamps
                    # its positions), so coverage past max_len is never
                    # needed — and would index past the table row.
                    # In-flight chunks write up to length+inflight first.
                    need_upto = min(
                        s.length + d.inflight_steps[i] + k_steps,
                        self.max_len,
                    )
                else:
                    continue  # remainder fully covered by the window
                while not self._alloc(i, need_upto):
                    if d.in_flight:
                        # in-flight chunks pin their slots' blocks (and
                        # may finish slots, freeing blocks): settle the
                        # window before preempting anyone, then rebuild
                        # the whole schedule from settled state
                        try:
                            d.drain_all()
                        except Exception as e:  # noqa: BLE001
                            self._dispatch_failed(e)
                        restart = True
                        break
                    if not self._preempt_youngest(keep=i):
                        # nothing else to evict: requeue this slot itself
                        # (a lone max_len resident always fits, so this
                        # only fires when prefilling peers hold the pool)
                        self._preempt(i)
                        break
                if restart:
                    break
                if s.req is None:  # got preempted itself
                    ready.remove(i)
            if restart:
                self._note_iter(t_iter)
                continue
            # liveness re-filter for BOTH groups: _preempt_youngest picks
            # by admitted_at, not index order, so a victim whose own
            # alloc turn already passed is still listed — the dispatch
            # must never see a req=None slot as live
            spec_idx = [
                i
                for i in spec_idx
                if self.slots[i].req is not None and self.slots[i].ready
            ]
            plain = [
                i
                for i in plain
                if self.slots[i].req is not None and self.slots[i].ready
            ]
            if spec_idx:
                self._run_spec_round(spec_idx)
                # the host committed tokens for these slots — it is
                # authoritative for their carry rows again
                for i in spec_idx:
                    d.invalidate_state(i)
                # spec commits may complete slots and free blocks; the
                # plain dispatch below rebuilds its views from live state
                plain = [
                    i
                    for i in plain
                    if self.slots[i].req is not None and self.slots[i].ready
                ]
            try:
                if plain:
                    plain_set = set(plain)
                    filters_on = any(
                        i in plain_set
                        and (s.req.top_k > 0 or s.req.top_p < 1.0)
                        for i, s in enumerate(self.slots)
                    )
                    # ASYNC: returns as soon as the futures exist — the
                    # device computes while the drain below does emit/EOS
                    # work for the previous chunk
                    d.dispatch(plain, k_steps, filters_on)
                # window full (or nothing new dispatched): block on the
                # OLDEST entry — the device is computing the newest one
                # meanwhile; otherwise consume only already-ready entries
                d.drain(block=d.full or not plain)
            except Exception as e:  # noqa: BLE001 — device errors (OOM, …)
                self._dispatch_failed(e)
            self._note_iter(t_iter)

    def _run_spec_round(self, spec_idx: list[int]) -> None:
        """One speculative round for ``spec_idx`` slots (others parked):
        the draft proposes ``spec_k`` tokens per slot, the target scores
        them in ONE paged verification block, and the longest matching
        prefix plus one corrected/bonus token commit — 1..k+1 tokens per
        dispatch. Commits come ONLY from the target's argmax choices, so
        the stream is exactly the plain greedy stream regardless of what
        the draft proposed (losslessness; asserted in
        tests/test_inference.py)."""
        spec_set = set(spec_idx)
        cur = jnp.asarray(
            [
                (s.last_token if i in spec_set else 0)
                for i, s in enumerate(self.slots)
            ],
            jnp.int32,
        )
        pos0_draft = jnp.asarray(
            [
                (s.length - 1 if i in spec_set else self.max_len)
                for i, s in enumerate(self.slots)
            ],
            jnp.int32,
        )
        pos0_verify = jnp.asarray(
            [
                (s.length - 1 if i in spec_set else 0)
                for i, s in enumerate(self.slots)
            ],
            jnp.int32,
        )
        temps = jnp.asarray(
            [
                (s.req.temperature if i in spec_set else 0.0)
                for i, s in enumerate(self.slots)
            ],
            jnp.float32,
        )
        top_ks = jnp.asarray(
            [
                (s.req.top_k if i in spec_set else 0)
                for i, s in enumerate(self.slots)
            ],
            jnp.int32,
        )
        top_ps = jnp.asarray(
            [
                (s.req.top_p if i in spec_set else 1.0)
                for i, s in enumerate(self.slots)
            ],
            jnp.float32,
        )
        filters_on = any(
            self.slots[i].req.temperature > 0
            and (
                self.slots[i].req.top_k > 0
                or self.slots[i].req.top_p < 1.0
            )
            for i in spec_idx
        )
        tl = self._timeline
        t_spec = time.monotonic() if tl is not None else 0.0
        try:
            # self._keys holds per-slot BASE keys (never advanced — see
            # decode_chunk): anchor this round's split chain at the
            # verify position so a replayed round re-derives the same
            # chain, and discard the advanced keys the jit returns
            round_keys = jax.vmap(jax.random.fold_in)(
                self._keys, pos0_verify
            )
            (
                self.pool,
                self._draft_cache,
                _,
                commit,
                n_commit,
            ) = self._spec_round_jit[filters_on](
                self.params,
                self.draft_params,
                self.pool,
                self._draft_cache,
                self._decode_tables(include=spec_set),
                cur,
                pos0_draft,
                pos0_verify,
                round_keys,
                temps,
                top_ks,
                top_ps,
                jnp.asarray(
                    [i in spec_set for i in range(self.max_slots)]
                ),
            )
            commit = np.asarray(jax.device_get(commit))  # [R, B, k+1]
            n_commit = np.asarray(jax.device_get(n_commit))  # [R, B]
        except Exception as e:  # noqa: BLE001 — device errors (OOM, …)
            # pool and draft cache were both donated into the failed call
            self._fail_outstanding(
                f"speculative round failed: {e}", drain_queue=False
            )
            self._reset_pool()
            self._reset_draft_cache()
            return
        if tl is not None:
            # one bar per draft/verify dispatch: with speculation on,
            # this IS the device-decode work (plain chunks never run for
            # these slots), so without it the profiler would show a
            # silent device under greedy spec traffic
            tl.add(
                TRACK_SPEC,
                f"spec round x{len(spec_idx)}",
                t_spec,
                time.monotonic(),
                slots=list(spec_idx),
                spec_k=self.spec_k,
                spec_depth=self.spec_depth,
                trace_ids=[
                    t.trace_id
                    for t in (
                        getattr(self.slots[i].req, "_obs_trace", None)
                        for i in spec_idx
                        if self.slots[i].req is not None
                    )
                    if t is not None
                ],
            )
        k = self.spec_k
        for i in spec_idx:
            for r in range(self.spec_depth):
                if self.slots[i].req is None:
                    # finished mid-dispatch (EOS / max_new): the device's
                    # later rounds for this slot are discarded speculation
                    break
                n = int(n_commit[r, i])
                # rounds/accepted/proposed all count REPLAYED slot-rounds
                # (ADVICE r5: counting dispatched device rounds skewed
                # committed_per_round low near end-of-generation — the
                # discarded tail rounds proposed nothing the host kept).
                # accepted/proposed measure the DRAFT-MATCH rate (the
                # number the operator tunes draft choice and SPEC_K by) —
                # raw n-1, not capped by how many tokens the request had
                # room to commit; spec_committed counts actual emits
                self.spec_rounds += 1
                self.spec_proposed += k
                self.spec_accepted += n - 1
                committed = 0
                for j in range(n):
                    if self.slots[i].req is None:
                        break  # hit EOS / max_new mid-commit
                    self._emit(i, int(commit[r, i, j]))
                    committed += 1
                self.spec_committed += committed
