"""Speculative decoding: draft-model proposals, target-model verification.

A small DRAFT model greedily proposes ``k`` tokens per round; the TARGET
model scores all of them in ONE ``decode_block`` dispatch (k+1 positions)
and the longest matching prefix is committed plus one corrected/bonus
token — so each target dispatch yields 1..k+1 tokens instead of 1.
Greedy speculative decoding is LOSSLESS: the committed stream is
token-for-token identical to greedy decoding with the target alone
(asserted in tests/test_inference.py), the draft only changes HOW FAST
tokens commit, never WHICH.

TPU-first cache handling: both models keep dense positional KV caches
and "rewind" after rejection is free — no copies, no bookkeeping.
Every decode WRITES a position's K/V before anything attends to it, so
a rejected proposal's stale cache entry is overwritten the moment the
corrected token is fed at that position (models/transformer.py
decode_tokens / decode_block are position-indexed for exactly this).

The round loop runs on host (acceptance length is data-dependent);
the per-round compute (draft scan + one verification block) is jitted.
No reference counterpart (the reference ships no serving stack).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer as tfm


@dataclass
class SpecStats:
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0  # draft proposals accepted (excl. corrected/bonus)
    committed: int = 0  # total tokens committed (incl. corrected/bonus)
    accept_hist: list = field(default_factory=list)  # per-round accept count

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.committed / self.rounds if self.rounds else 0.0


@partial(jax.jit, static_argnames=("cfg", "k"))
def _draft_propose(params, cache, cur, pos0, cfg, k):
    """Greedy-propose k tokens per sequence -> (proposals [B, k], cache).

    The scan runs k+1 steps: the extra step feeds the LAST proposal so
    its K/V is written to the draft cache too (otherwise a fully-
    accepted round would leave a permanent zero hole at that position
    that every later draft query attends); its own proposal is
    discarded."""

    def step(carry, j):
        cache, cur = carry
        logits, kv = tfm.decode_tokens(params, cache, cur, pos0 + j, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_cache = {
            "k": kv["k"], "v": kv["v"], "length": cache["length"],
        }
        return (new_cache, nxt), nxt

    (cache, _), props = jax.lax.scan(
        step, (cache, cur), jnp.arange(k + 1, dtype=jnp.int32)
    )
    return jnp.moveaxis(props, 0, 1)[:, :k], cache  # [B, k]


def filter_scaled_logits(logits, temperature, top_k=0, top_p=1.0):
    """Temperature-scale one logit row and mask it to the top-k/top-p
    keep set (-inf outside) — THE single implementation of the filter
    semantics: ``engine.sample_logits`` and the speculative-sampling
    target distribution must stay in lockstep or filtered requests
    would sample and verify against different distributions.

    ``top_k == 0`` and ``top_p >= 1`` disable their filters. Dynamic
    per-slot k/p: filters are computed by sorting rather than
    ``lax.top_k`` so k need not be a static constant."""
    logits = logits.astype(jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sorted_desc = jnp.sort(scaled)[::-1]
    # top-k: keep logits >= the k-th largest (k=0 -> keep all)
    kth = sorted_desc[jnp.clip(top_k - 1, 0, vocab - 1)]
    keep_k = jnp.where(top_k > 0, scaled >= kth, True)
    # top-p: keep tokens whose mass-before-them (sorted desc) is < top_p —
    # the shifted-cumsum form always keeps >= 1 token and is immune to
    # float32 cumsum never quite reaching top_p on a large vocab
    probs_desc = jax.nn.softmax(sorted_desc)
    shifted = jnp.cumsum(probs_desc) - probs_desc
    count = jnp.sum(shifted < top_p)
    p_threshold = sorted_desc[jnp.clip(count - 1, 0, vocab - 1)]
    keep_p = jnp.where(top_p < 1.0, scaled >= p_threshold, True)
    return jnp.where(keep_k & keep_p, scaled, -jnp.inf)


@partial(jax.jit, static_argnames=("cfg", "k"))
def _draft_propose_sampled(params, cache, cur, pos0, cfg, k, keys, temps):
    """Propose k tokens per sequence, SAMPLING rows with temps > 0
    (temperature-scaled categorical) and argmaxing the rest ->
    (proposals [B, k], draft probs [B, k, V], cache, keys). The probs
    are the draft's full temperature distribution per proposal position
    — what the Leviathan residual needs at rejection. Same k+1-step
    scan as :func:`_draft_propose` (the extra step seals the last
    proposal's K/V)."""
    safe_t = jnp.maximum(temps, 1e-6)[:, None]

    def step(carry, j):
        cache, cur, keys = carry
        logits, kv = tfm.decode_tokens(params, cache, cur, pos0 + j, cfg)
        probs = jax.nn.softmax(logits / safe_t, axis=-1)
        split = jax.vmap(jax.random.split)(keys)
        keys, subs = split[:, 0], split[:, 1]
        sampled = jax.vmap(
            lambda s, p: jax.random.categorical(
                s, jnp.log(jnp.maximum(p, 1e-30))
            )
        )(subs, probs).astype(jnp.int32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(temps > 0, sampled, greedy)
        new_cache = {
            "k": kv["k"], "v": kv["v"], "length": cache["length"],
        }
        return (new_cache, nxt, keys), (nxt, probs)

    (cache, _, keys), (props, probs) = jax.lax.scan(
        step, (cache, cur, keys), jnp.arange(k + 1, dtype=jnp.int32)
    )
    return (
        jnp.moveaxis(props, 0, 1)[:, :k],
        jnp.moveaxis(probs, 0, 1)[:, :k],
        cache,
        keys,
    )


def spec_accept_commit(
    props, d_probs, t_logits, temps, keys, top_ks=None, top_ps=None,
    use_filters=True,
):
    """Per-slot acceptance + correction for one speculative round ->
    ``(commit_tokens [B, k+1], n_commit [B], keys)``; the committed
    tokens for a slot are ``commit_tokens[i, :n_commit[i]]``.

    Greedy rows (``temps <= 0``): the classic exact rule — leading
    proposals matching the target's argmax commit, then the target's
    corrected/bonus token (bit-lossless vs sequential greedy decode in
    exact arithmetic).

    Stochastic rows: speculative SAMPLING (Leviathan et al. 2023) —
    proposal ``x_i`` accepts with prob ``min(1, p_t(x_i)/p_d(x_i))``;
    at the first rejection the corrected token resamples from the
    normalized residual ``max(p_t - p_d, 0)``; full acceptance samples
    the bonus from ``p_t`` at the last position. The committed stream
    is distributed EXACTLY as sequential temperature sampling from the
    target alone — pinned against a numpy reference and a Monte-Carlo
    marginal check in tests/test_speculative_sampling.py.

    ``top_ks``/``top_ps`` (per-slot, optional) make the target
    distribution the FILTERED one (:func:`filter_scaled_logits` — the
    same filter the plain path samples with): the Leviathan rule is
    valid for any proposal distribution, so the draft still proposes
    from its unfiltered temperature distribution and out-of-filter
    proposals simply auto-reject (p_t = 0). ``use_filters=False``
    (compile-time) skips the per-row vocab sort entirely — the caller
    compiles one variant per case, like the engine's decode chunks, so
    greedy/plain-temperature batches never pay for filters they don't
    use."""
    b, k = props.shape
    stoch = temps > 0
    if use_filters:
        if top_ks is None:
            top_ks = jnp.zeros((b,), jnp.int32)
        if top_ps is None:
            top_ps = jnp.ones((b,), jnp.float32)
        filtered = jax.vmap(  # over slots ...
            lambda rows, t, tk, tp: jax.vmap(  # ... then block positions
                lambda row: filter_scaled_logits(row, t, tk, tp)
            )(rows)
        )(t_logits, temps, top_ks, top_ps)
    else:
        filtered = t_logits.astype(jnp.float32) / jnp.maximum(
            temps, 1e-6
        )[:, None, None]
    t_probs = jax.nn.softmax(filtered, axis=-1)  # [B, k+1, V]
    greedy_choices = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    g_match = (props == greedy_choices[:, :k]).astype(jnp.int32)
    g_acc = jnp.sum(jnp.cumprod(g_match, axis=1), axis=1)
    p_t_prop = jnp.take_along_axis(
        t_probs[:, :k], props[..., None], axis=-1
    )[..., 0]
    p_d_prop = jnp.take_along_axis(d_probs, props[..., None], axis=-1)[..., 0]
    split = jax.vmap(jax.random.split)(keys)
    keys, sub_u = split[:, 0], split[:, 1]
    u = jax.vmap(lambda s: jax.random.uniform(s, (k,)))(sub_u)
    ok = (u * jnp.maximum(p_d_prop, 1e-30) < p_t_prop).astype(jnp.int32)
    s_acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)
    n_acc = jnp.where(stoch, s_acc, g_acc)  # [B] in 0..k
    # correction distribution at the rejection position (or bonus at k)
    t_at = jnp.take_along_axis(
        t_probs, n_acc[:, None, None], axis=1
    )[:, 0]  # [B, V] — t_probs has k+1 positions, n_acc <= k is valid
    d_at = jnp.take_along_axis(
        d_probs, jnp.minimum(n_acc, k - 1)[:, None, None], axis=1
    )[:, 0]
    residual = jnp.maximum(t_at - d_at, 0.0)
    rsum = jnp.sum(residual, axis=-1, keepdims=True)
    # identical-distribution rejection is probability-0; the numeric
    # guard falls back to p_t, which is the same limit
    corr_dist = jnp.where(
        (n_acc < k)[:, None] & (rsum[:, 0] > 1e-9)[:, None],
        residual / jnp.maximum(rsum, 1e-30),
        t_at,
    )
    split = jax.vmap(jax.random.split)(keys)
    keys, sub_c = split[:, 0], split[:, 1]
    sampled_corr = jax.vmap(
        lambda s, p: jax.random.categorical(
            s, jnp.log(jnp.maximum(p, 1e-30))
        )
    )(sub_c, corr_dist).astype(jnp.int32)
    greedy_corr = jnp.take_along_axis(
        greedy_choices, n_acc[:, None], axis=1
    )[:, 0]
    corr = jnp.where(stoch, sampled_corr, greedy_corr)
    padded = jnp.concatenate(
        [props, jnp.zeros((b, 1), props.dtype)], axis=1
    )
    commit = jnp.where(
        jnp.arange(k + 1)[None] == n_acc[:, None], corr[:, None], padded
    )
    return commit, n_acc + 1, keys


@partial(jax.jit, static_argnames=("cfg",))
def _verify(params, cache, block, positions, cfg):
    """Target scores the whole block -> (greedy choices [B, K], cache)."""
    logits, kv = tfm.decode_block(params, cache, block, positions, cfg)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv


def generate_speculative(
    target_params: dict,
    draft_params: dict,
    prompt: jax.Array,  # [B, T_prompt] int32
    target_cfg: tfm.TransformerConfig,
    draft_cfg: tfm.TransformerConfig,
    max_new_tokens: int,
    k: int = 4,
) -> tuple[jax.Array, SpecStats]:
    """Greedy speculative generation -> (tokens [B, max_new_tokens],
    stats). Output is exactly ``tfm.generate(target_params, prompt,
    target_cfg, max_new_tokens)`` (greedy losslessness)."""
    b, t_prompt = prompt.shape
    # Cache horizon bound (ADVICE r3): a FROZEN sequence (n >= max_new)
    # keeps riding draft/verify rounds while slower batchmates finish,
    # writing positions pos0..pos0+k every round at its frozen
    # pos0 = t_prompt + n - 1 <= t_prompt + max_new + k - 1 (commits can
    # overshoot max_new by up to k) — so the max write position is
    # t_prompt + max_new + 2k - 1, and the horizon must cover it. An
    # undersized horizon only survived because JAX drops out-of-bounds
    # scatters; under a clamping scatter mode the overflow would corrupt
    # the last cache row (tests/test_inference.py pins this bound).
    horizon = t_prompt + max_new_tokens + 2 * k
    # prefill BOTH models in one full-sequence forward each (big MXU
    # matmuls), seeding the caches from return_kv
    t_logits, (tk, tv) = tfm.forward(
        target_params, prompt, target_cfg, return_kv=True
    )
    d_logits, (dk, dv) = tfm.forward(
        draft_params, prompt, draft_cfg, return_kv=True
    )

    def seed(cfg, ks, vs):
        cache = tfm.init_kv_cache(cfg, b, horizon)
        return {
            "k": cache["k"].at[:, :, :t_prompt].set(ks),
            "v": cache["v"].at[:, :, :t_prompt].set(vs),
            "length": jnp.asarray(t_prompt, jnp.int32),
        }

    t_cache = seed(target_cfg, tk, tv)
    d_cache = seed(draft_cfg, dk, dv)

    out = np.zeros((b, max_new_tokens + k + 1), np.int64)
    out[:, 0] = np.asarray(jnp.argmax(t_logits[:, -1], axis=-1))
    n = np.ones((b,), np.int64)  # committed tokens per sequence
    stats = SpecStats()

    while int(n.min()) < max_new_tokens:
        cur = jnp.asarray(out[np.arange(b), n - 1], jnp.int32)  # last committed
        pos0 = jnp.asarray(t_prompt + n - 1, jnp.int32)  # its position
        props, d_cache = _draft_propose(
            draft_params, d_cache, cur, pos0, draft_cfg, k
        )
        # verification block: [last committed, prop_0..prop_{k-1}] at
        # positions pos0..pos0+k; choice[:, j] is the target's token for
        # position pos0+j+1 -> compare with prop_j; choice[:, k] is the
        # bonus token when everything matches
        block = jnp.concatenate([cur[:, None], props], axis=1)  # [B, k+1]
        positions = pos0[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        choices, t_kv = _verify(
            target_params, t_cache, block, positions, target_cfg
        )
        t_cache = {"k": t_kv["k"], "v": t_kv["v"], "length": t_cache["length"]}

        # one readback per round for both arrays — the accept/reject
        # decision is host-side by design; two np.asarray calls here
        # were two blocking transfers where one suffices
        props_h, choices_h = jax.device_get((props, choices))  # lint: allow(JIT502)
        match = props_h == choices_h[:, :k]  # [B, k]
        accepts = np.where(
            match.all(axis=1), k, match.argmin(axis=1)
        )  # accepted proposals per sequence (0..k)
        round_accepts = []
        for s in range(b):
            if n[s] >= max_new_tokens:
                # finished sequences freeze: no commits, no stats — and
                # crucially no growth past the out buffer / cache horizon
                round_accepts.append(-1)
                continue
            a = int(accepts[s])
            # committed this round: a accepted proposals + the target's
            # corrected (a<k) or bonus (a==k) token
            out[s, n[s] : n[s] + a] = props_h[s, :a]
            out[s, n[s] + a] = choices_h[s, a]
            n[s] += a + 1
            stats.accepted += a
            stats.committed += a + 1
            stats.proposed += k
            round_accepts.append(a)
        stats.rounds += 1
        stats.accept_hist.append(round_accepts)

    return jnp.asarray(out[:, :max_new_tokens], jnp.int32), stats
