"""Weight-only int8 quantization for serving.

Per-output-channel symmetric int8: each matmul weight [D_in, D_out] is
stored as int8 with a float32 scale per output column; the matmul
dequantizes on the fly (``x @ w_q * scale``), halving (vs bf16) or
quartering (vs f32) weight HBM traffic — decode is weight-bandwidth-bound,
so this translates ~directly into tokens/sec on HBM-limited configs.
Activations stay in the model dtype; no calibration needed for
weight-only. No reference counterpart (SURVEY.md §2.13: the reference
ships no model code).

Usage::

    from devspace_tpu.inference.quantization import quantize_params
    q_params = quantize_params(params)           # transformer param tree
    engine = InferenceEngine(q_params, cfg, ...) # drop-in: decode_tokens
                                                 # sees QuantizedLinear
                                                 # leaves transparently
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# scale floor shared with ops.paged_attention.quantize_kv — an all-zero
# vector quantizes to zeros with a tiny positive scale instead of NaNs
KV_SCALE_EPS = 1e-8

# transformer matmul leaves worth quantizing (norms/embeddings stay f32 —
# embeddings are gathers, not matmuls, and norms are tiny)
_MATMUL_LEAVES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)


@jax.tree_util.register_pytree_node_class
class QuantizedLinear:
    """int8 weight + per-output-channel f32 scale; behaves like the dense
    weight under ``@`` (dequantizing matmul)."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q  # int8 [D_in, D_out]
        self.scale = scale  # f32 [D_out]

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # what the dense weight would have been
        return jnp.bfloat16

    def __rmatmul__(self, x):
        # x @ w: do the contraction in the input dtype's MXU-friendly
        # form; int8 weights are upcast lane-wise by XLA, the scale is a
        # cheap per-column multiply on the [.., D_out] result.
        y = jax.lax.dot_general(
            x,
            self.q,
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * self.scale).astype(x.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QuantizedLinear(shape={tuple(self.q.shape)})"


def quantize_weight(w: jax.Array) -> QuantizedLinear:
    """Symmetric per-output-channel int8 quantization of a [D_in, D_out]
    (or [D_in, ...]) weight; scale chosen so max|w| per column maps to
    127."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=0)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedLinear(q, scale)


def quantize_params(params: dict) -> dict:
    """Quantize every matmul weight in a transformer param tree (see
    ``models.transformer.init_params`` for the layout); other leaves pass
    through untouched."""

    def walk(node, name=""):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v, name) for v in node)
        if name in _MATMUL_LEAVES and getattr(node, "ndim", 0) == 2:
            return quantize_weight(node)
        return node

    return walk(params)


def dequantize_params(params: dict):
    """Inverse (for checkpointing or debugging): expand QuantizedLinear
    leaves back to bf16 dense weights."""

    def leaf(x):
        if isinstance(x, QuantizedLinear):
            return (x.q.astype(jnp.float32) * x.scale).astype(jnp.bfloat16)
        return x

    return jax.tree_util.tree_map(
        leaf, params, is_leaf=lambda x: isinstance(x, QuantizedLinear)
    )


def quantize_kv_block(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric int8 quantization of one KV block ``[L, Hkv, bs, D]``
    for the host tier (inference/kv_tier.py): per-(layer, head, token)
    scale chosen so max|x| over the head dim D maps to 127 — the SAME
    convention as ``ops.paged_attention.quantize_kv``, so a spilled
    block from a float pool carries exactly the noise profile the int8
    pool already documents (~0.5%, greedy near-ties can flip). Runs on
    the spill path host-side (plain numpy, no device dispatch)."""
    x32 = np.asarray(x, np.float32)
    amax = np.max(np.abs(x32), axis=-1)  # [L, Hkv, bs]
    scale = np.maximum(amax, KV_SCALE_EPS) / 127.0
    q = np.clip(np.rint(x32 / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def dequantize_kv_block(
    q: np.ndarray, scale: np.ndarray, dtype=np.float32
) -> np.ndarray:
    """Host-side inverse of :func:`quantize_kv_block` (tests and
    debugging; the engine's restore path dequantizes device-side inside
    the jitted scatter to halve H2D traffic)."""
    return (q.astype(np.float32) * np.asarray(scale, np.float32)[..., None]).astype(
        dtype
    )


def quantization_error(params: dict) -> float:
    """Max relative per-leaf reconstruction error across quantized leaves
    (sanity metric; ~<1% for normal-ish weights)."""
    errs = []

    def walk(node, name=""):
        if isinstance(node, QuantizedLinear):
            raise ValueError(
                "quantization_error needs the DENSE params (the original "
                "weights are gone from a quantized tree, so the error "
                "cannot be measured from it)"
            )
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, k)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v, name)
        elif name in _MATMUL_LEAVES and getattr(node, "ndim", 0) == 2:
            ql = quantize_weight(node)
            w = node.astype(jnp.float32)
            deq = ql.q.astype(jnp.float32) * ql.scale
            errs.append(
                float(
                    jnp.linalg.norm(w - deq) / jnp.maximum(jnp.linalg.norm(w), 1e-9)
                )
            )

    walk(params)
    return max(errs) if errs else 0.0
