"""Inference package: paged-KV engine, dispatcher, prefix cache, tiers.

Exports resolve lazily (PEP 562): importing a pure-host submodule such
as :mod:`.prefix_cache` must not drag jax in — the serving stub replica
and the routing gateway import the prefix fingerprint helper at process
start, and a fleet of them would otherwise pay a jax import each.
``from devspace_tpu.inference import InferenceEngine`` still works
unchanged; the engine module loads on first attribute access.
"""

_EXPORTS = {
    "load_serving_params": ".checkpoint",
    "DecodeDispatcher": ".dispatch",
    "resolve_dispatch_depth": ".dispatch",
    "InferenceEngine": ".engine",
    "Request": ".engine",
    "SpecStats": ".speculative",
    "generate_speculative": ".speculative",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    return getattr(import_module(mod, __name__), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
