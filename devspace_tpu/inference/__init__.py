from .checkpoint import load_serving_params  # noqa: F401
from .dispatch import DecodeDispatcher, resolve_dispatch_depth  # noqa: F401
from .engine import InferenceEngine, Request  # noqa: F401
from .speculative import SpecStats, generate_speculative  # noqa: F401
