from .engine import InferenceEngine, Request  # noqa: F401
