"""Host-RAM (optionally disk-backed) tier for evicted KV blocks.

Under KV oversubscription the engine used to throw cached work away:
``RadixPrefixCache.pop_victim`` recycled a chain's pool blocks and the
K/V they held was simply gone — a later hit on the same prefix (or a
preempted request resuming) re-ran prefill from scratch. This module is
the tier below the HBM pool (ROADMAP item 2; Mooncake-style KV store,
RadixAttention-style chain reuse): before the engine recycles an evicted
chain's blocks it copies them device->host, int8-quantized per block
(``inference.quantization.quantize_kv_block``), and parks the payloads
here. A radix match that lands on a spilled chain then restores
host->device (one jitted scatter per block, async — it overlaps
in-flight decode chunks) instead of recomputing prefill.

Design mirrors ``sync/artifacts.py`` (the repo's content-addressed LRU
precedent):

- **Content-addressed**: keys are blake2b digests of the chain's token
  blocks (computed incrementally by the radix tree,
  ``prefix_cache.RadixPrefixCache(track_digests=True)``). A block's K/V
  is a pure function of its token chain and absolute position, so equal
  digests mean interchangeable payloads within an engine's lifetime.
- **LRU-by-bytes**: an ``OrderedDict`` holding packed payloads, evicted
  oldest-first when ``max_bytes`` overflows. With the disk level on
  (``"host+disk"``), RAM evictions overflow to digest-named files under
  their own byte budget instead of being dropped; reads promote back to
  RAM.
- **Checksummed**: every payload stores its own blake2b checksum, and
  ``get`` re-verifies before returning — a corrupted payload (bit rot,
  truncated file) is dropped and reported as a miss, never scattered
  into the pool. The engine falls back to recompute-prefill on any miss.

The engine's scheduler thread is the only mutator (no locks, like the
prefix cache); ``stats()`` reads are GIL-atomic ints for /healthz.
Dropped entries fire ``on_evict(digest)`` so the owner can prune the
radix tree's spilled nodes — a dangling spilled node would promise a
restore the tier can no longer honor.
"""

from __future__ import annotations

import hashlib
import os
import struct
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ..obs import events as _obs_events

_MAGIC = b"KVT1"
_CHECKSUM_SIZE = 16


def pack_kv_payload(
    kq: np.ndarray, ks: np.ndarray, vq: np.ndarray, vs: np.ndarray
) -> bytes:
    """Pack one spilled block — int8 K/V ``[L, Hkv, bs, D]`` plus their
    per-(layer, head, token) f32 scales ``[L, Hkv, bs]`` — into a
    self-describing byte string: magic, dims, then the four raw buffers
    in order. ~= bs * L * Hkv * (2D + 8) bytes, a ~2x (bf16) to ~3.6x
    (f32) shrink versus the resident block."""
    if kq.dtype != np.int8 or vq.dtype != np.int8:
        raise ValueError("quantized K/V must be int8")
    L, Hkv, bs, D = kq.shape
    parts = [
        _MAGIC,
        struct.pack("<4I", L, Hkv, bs, D),
        kq.tobytes(),
        np.ascontiguousarray(ks, np.float32).tobytes(),
        vq.tobytes(),
        np.ascontiguousarray(vs, np.float32).tobytes(),
    ]
    return b"".join(parts)


def unpack_kv_payload(
    buf: bytes,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Inverse of :func:`pack_kv_payload`. Raises ValueError on any
    structural mismatch (bad magic, short buffer) — the engine treats
    that as a miss and recomputes."""
    if buf[:4] != _MAGIC:
        raise ValueError("bad KV payload magic")
    L, Hkv, bs, D = struct.unpack_from("<4I", buf, 4)
    n_q, n_s = L * Hkv * bs * D, L * Hkv * bs
    want = 4 + 16 + 2 * n_q + 2 * 4 * n_s
    if len(buf) != want:
        raise ValueError(f"KV payload length {len(buf)} != expected {want}")
    off = 20
    kq = np.frombuffer(buf, np.int8, n_q, off).reshape(L, Hkv, bs, D)
    off += n_q
    ks = np.frombuffer(buf, np.float32, n_s, off).reshape(L, Hkv, bs)
    off += 4 * n_s
    vq = np.frombuffer(buf, np.int8, n_q, off).reshape(L, Hkv, bs, D)
    off += n_q
    vs = np.frombuffer(buf, np.float32, n_s, off).reshape(L, Hkv, bs)
    return kq, ks, vq, vs


def _checksum(payload: bytes) -> bytes:
    return hashlib.blake2b(payload, digest_size=_CHECKSUM_SIZE).digest()


class HostKVTier:
    """Byte-budgeted host store for spilled KV blocks. See module
    docstring for the design; the API is put/get/discard over digest
    keys plus ``stats()`` for the engine's observability surface."""

    def __init__(
        self,
        max_bytes: int = 256 << 20,
        disk_dir: Optional[str] = None,
        disk_max_bytes: int = 2 << 30,
    ):
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.disk_max_bytes = int(disk_max_bytes)
        # digest -> (payload, checksum); insertion/move order = LRU
        self._ram: "OrderedDict[str, tuple[bytes, bytes]]" = OrderedDict()
        self._ram_bytes = 0
        self._disk: "OrderedDict[str, int]" = OrderedDict()  # digest -> nbytes
        self._disk_bytes = 0
        # fired when an entry leaves the tier ENTIRELY (dropped from RAM
        # with no disk level, or aged off disk) — the engine prunes the
        # matching spilled radix node so matches never dangle
        self.on_evict: Optional[Callable[[str], None]] = None
        # optional span sink (obs/tracing.Tracer): attached by the engine
        # for the duration of a timeline capture so spill/restore I/O
        # shows up as real spans (digest, bytes, outcome) under whatever
        # trace context is active on the scheduler thread. None keeps
        # put/get at one attribute check of overhead.
        self.tracer = None
        self.puts = 0
        self.hits = 0
        self.misses = 0
        self.corrupt_dropped = 0
        self.evictions = 0

    # -- internals ---------------------------------------------------------
    def _path(self, digest: str) -> str:
        return os.path.join(self.disk_dir, f"{digest}.kv")

    def _drop(self, digest: str) -> None:
        """Entry left the tier entirely — tell the owner."""
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(digest)

    def _ram_evict_overflow(self) -> None:
        while self._ram_bytes > self.max_bytes and self._ram:
            digest, (payload, checksum) = self._ram.popitem(last=False)
            self._ram_bytes -= len(payload)
            if self.disk_dir is not None:
                self._disk_put(digest, payload, checksum)
            else:
                self._drop(digest)

    def _disk_put(self, digest: str, payload: bytes, checksum: bytes) -> None:
        try:
            os.makedirs(self.disk_dir, exist_ok=True)
            with open(self._path(digest), "wb") as f:
                f.write(checksum)
                f.write(payload)
        except OSError:
            self._drop(digest)  # disk refused it: gone for good
            return
        if digest in self._disk:
            self._disk_bytes -= self._disk.pop(digest)
        self._disk[digest] = _CHECKSUM_SIZE + len(payload)
        self._disk_bytes += self._disk[digest]
        while self._disk_bytes > self.disk_max_bytes and self._disk:
            old, nbytes = self._disk.popitem(last=False)
            self._disk_bytes -= nbytes
            self._disk_unlink(old)
            self._drop(old)

    def _disk_unlink(self, digest: str) -> None:
        try:
            os.unlink(self._path(digest))
        except OSError:
            pass

    def _disk_get(self, digest: str) -> Optional[bytes]:
        nbytes = self._disk.pop(digest, None)
        if nbytes is None:
            return None
        self._disk_bytes -= nbytes
        try:
            with open(self._path(digest), "rb") as f:
                buf = f.read()
        except OSError:
            buf = b""
        self._disk_unlink(digest)
        checksum, payload = buf[:_CHECKSUM_SIZE], buf[_CHECKSUM_SIZE:]
        if len(checksum) != _CHECKSUM_SIZE or _checksum(payload) != checksum:
            self.corrupt_dropped += 1
            _obs_events.emit(
                "kv_tier", "corrupt_drop", level="error",
                digest=digest[:16], tier="disk",
            )
            return None
        return payload

    # -- api ---------------------------------------------------------------
    def put(self, digest: str, payload: bytes) -> None:
        """Retain one spilled block. Re-putting an existing digest
        refreshes its LRU position (the payload is content-addressed —
        equal digests mean equal bytes, so the old copy is kept)."""
        if self.tracer is not None:
            with self.tracer.span(
                "kv_tier.put", digest=digest[:16], bytes=len(payload)
            ):
                self._put(digest, payload)
            return
        self._put(digest, payload)

    def _put(self, digest: str, payload: bytes) -> None:
        self.puts += 1
        if digest in self._ram:
            self._ram.move_to_end(digest)
            return
        if digest in self._disk:  # promote-by-rewrite: RAM is the hot level
            self._disk_bytes -= self._disk.pop(digest)
            self._disk_unlink(digest)
        self._ram[digest] = (payload, _checksum(payload))
        self._ram_bytes += len(payload)
        self._ram_evict_overflow()

    def get(self, digest: str) -> Optional[bytes]:
        """The payload for ``digest``, or None on miss. Integrity is
        re-verified on EVERY read; a checksum mismatch drops the entry
        and reports a miss — corrupted K/V is never handed back to be
        scattered into the pool."""
        if self.tracer is not None:
            with self.tracer.span(
                "kv_tier.get", digest=digest[:16]
            ) as sp:
                payload = self._get(digest)
                sp.attrs["hit"] = payload is not None
            return payload
        return self._get(digest)

    def _get(self, digest: str) -> Optional[bytes]:
        entry = self._ram.get(digest)
        if entry is not None:
            payload, checksum = entry
            if _checksum(payload) != checksum:
                del self._ram[digest]
                self._ram_bytes -= len(payload)
                self.corrupt_dropped += 1
                self.misses += 1
                _obs_events.emit(
                    "kv_tier", "corrupt_drop", level="error",
                    digest=digest[:16], tier="ram",
                )
                return None
            self._ram.move_to_end(digest)
            self.hits += 1
            return payload
        payload = self._disk_get(digest)
        if payload is not None:
            self.hits += 1
            # promote: recently-restored chains are likely to be hit again
            self._ram[digest] = (payload, _checksum(payload))
            self._ram_bytes += len(payload)
            self._ram_evict_overflow()
            return payload
        self.misses += 1
        return None

    def discard(self, digest: str) -> None:
        """Forget ``digest`` without firing ``on_evict`` — the owner
        already knows (it is the one discarding)."""
        entry = self._ram.pop(digest, None)
        if entry is not None:
            self._ram_bytes -= len(entry[0])
        nbytes = self._disk.pop(digest, None)
        if nbytes is not None:
            self._disk_bytes -= nbytes
            self._disk_unlink(digest)

    def clear(self) -> None:
        """Drop everything (the pool whose content this tier holds is
        gone — digests describe positions in a pool that no longer
        exists... content survives pool resets in principle, but the
        radix tree that maps digests to matches does not)."""
        self._ram.clear()
        self._ram_bytes = 0
        for digest in list(self._disk):
            self._disk_unlink(digest)
        self._disk.clear()
        self._disk_bytes = 0

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._ram) + len(self._disk)

    @property
    def resident_bytes(self) -> int:
        """Host RAM held right now (the gauge; disk bytes are separate)."""
        return self._ram_bytes

    def stats(self) -> dict:
        return {
            "entries": len(self._ram) + len(self._disk),
            "ram_entries": len(self._ram),
            "ram_bytes": self._ram_bytes,
            "disk_entries": len(self._disk),
            "disk_bytes": self._disk_bytes,
            "puts": self.puts,
            "hits": self.hits,
            "misses": self.misses,
            "corrupt_dropped": self.corrupt_dropped,
            "evictions": self.evictions,
        }


# -- KV migration wire format (disaggregated prefill/decode) --------------
#
# A chain envelope is the unit of KV migration between replicas: one
# self-describing byte string holding a contiguous run of packed KV
# blocks (each the exact ``pack_kv_payload`` bytes the host tier already
# stores — shapes and dtype ride in each block's own KVT1 header) plus
# enough redundancy to reject every transport failure cleanly:
#
#   magic     b"KVM1"
#   version   <H>  (skew -> WireFormatError, never a misparse)
#   chain     <H len><ascii>  the LEAF digest (names the whole chain)
#   count     <I>
#   blocks    count x [<H len><ascii digest> <16s checksum> <I len> payload]
#   trailer   16-byte blake2b over everything above
#
# The importer verifies the trailer, every per-block checksum, and each
# payload's KVT1 structure before anything touches the local tier — a
# truncated/bit-flipped/mis-versioned envelope raises WireFormatError
# and the decode engine falls back to recompute-prefill.

_WIRE_MAGIC = b"KVM1"
_WIRE_VERSION = 1


class WireFormatError(ValueError):
    """A chain envelope failed structural/integrity/version checks."""


def _validate_block_payload(digest: str, payload: bytes) -> None:
    """Structural check of one packed block (magic + dims-implied length)
    WITHOUT copying it out — shapes/dtype are declared by the KVT1
    header and must account for every byte."""
    if payload[:4] != _MAGIC:
        raise WireFormatError(f"block {digest[:16]}: bad payload magic")
    if len(payload) < 20:
        raise WireFormatError(f"block {digest[:16]}: truncated header")
    L, Hkv, bs, D = struct.unpack_from("<4I", payload, 4)
    n_q, n_s = L * Hkv * bs * D, L * Hkv * bs
    want = 4 + 16 + 2 * n_q + 2 * 4 * n_s
    if len(payload) != want:
        raise WireFormatError(
            f"block {digest[:16]}: payload length {len(payload)} != "
            f"{want} implied by dims ({L},{Hkv},{bs},{D})"
        )


def pack_chain_envelope(blocks: "list[tuple[str, bytes]]") -> bytes:
    """Pack an ordered (root->leaf) run of ``(digest, payload)`` blocks
    into one versioned wire envelope. The last digest names the chain."""
    if not blocks:
        raise ValueError("cannot pack an empty chain")
    leaf = blocks[-1][0].encode("ascii")
    parts = [
        _WIRE_MAGIC,
        struct.pack("<H", _WIRE_VERSION),
        struct.pack("<H", len(leaf)),
        leaf,
        struct.pack("<I", len(blocks)),
    ]
    for digest, payload in blocks:
        d = digest.encode("ascii")
        parts.append(struct.pack("<H", len(d)))
        parts.append(d)
        parts.append(_checksum(payload))
        parts.append(struct.pack("<I", len(payload)))
        parts.append(payload)
    body = b"".join(parts)
    return body + _checksum(body)


def unpack_chain_envelope(buf: bytes) -> "list[tuple[str, bytes]]":
    """Inverse of :func:`pack_chain_envelope`. Verifies the envelope
    trailer, per-block checksums and per-block KVT1 structure; raises
    :class:`WireFormatError` on ANY mismatch (truncation, bit flip,
    version skew) so a migration failure is always a clean rejection."""
    if len(buf) < 4 + 2 + 2 + 4 + _CHECKSUM_SIZE:
        raise WireFormatError("envelope too short")
    if buf[:4] != _WIRE_MAGIC:
        raise WireFormatError("bad envelope magic")
    body, trailer = buf[:-_CHECKSUM_SIZE], buf[-_CHECKSUM_SIZE:]
    if _checksum(body) != trailer:
        raise WireFormatError("envelope checksum mismatch")
    (version,) = struct.unpack_from("<H", buf, 4)
    if version != _WIRE_VERSION:
        raise WireFormatError(
            f"envelope version {version} != supported {_WIRE_VERSION}"
        )
    off = 6
    (dlen,) = struct.unpack_from("<H", buf, off)
    off += 2
    leaf = buf[off : off + dlen].decode("ascii")
    off += dlen
    (count,) = struct.unpack_from("<I", buf, off)
    off += 4
    blocks: list[tuple[str, bytes]] = []
    end = len(body)
    for _ in range(count):
        if off + 2 > end:
            raise WireFormatError("envelope truncated in block header")
        (dlen,) = struct.unpack_from("<H", buf, off)
        off += 2
        digest = buf[off : off + dlen].decode("ascii")
        off += dlen
        checksum = buf[off : off + _CHECKSUM_SIZE]
        off += _CHECKSUM_SIZE
        if off + 4 > end:
            raise WireFormatError("envelope truncated in block header")
        (plen,) = struct.unpack_from("<I", buf, off)
        off += 4
        if off + plen > end:
            raise WireFormatError("envelope truncated in block payload")
        payload = buf[off : off + plen]
        off += plen
        if _checksum(payload) != checksum:
            raise WireFormatError(f"block {digest[:16]}: checksum mismatch")
        _validate_block_payload(digest, payload)
        blocks.append((digest, payload))
    if off != end:
        raise WireFormatError("trailing bytes after last block")
    if not blocks or blocks[-1][0] != leaf:
        raise WireFormatError("leaf digest does not name the last block")
    return blocks


def export_chain(tier: HostKVTier, digests: "list[str]") -> Optional[bytes]:
    """Build a chain envelope from payloads the tier holds. Returns None
    when ANY digest misses (a partial chain is unrestorable below the
    gap — the caller serves what it can by trimming ``digests`` first)."""
    blocks: list[tuple[str, bytes]] = []
    for digest in digests:
        payload = tier.get(digest)
        if payload is None:
            return None
        blocks.append((digest, payload))
    if not blocks:
        return None
    return pack_chain_envelope(blocks)


def import_chain(tier: HostKVTier, buf: bytes) -> "list[str]":
    """Validate ``buf`` (raising :class:`WireFormatError`) and retain
    every block in the local tier. Returns the digests in chain order —
    the caller promotes the matching remote radix nodes to spilled."""
    blocks = unpack_chain_envelope(buf)
    for digest, payload in blocks:
        tier.put(digest, payload)
    return [digest for digest, _ in blocks]


class KVMigrateError(RuntimeError):
    """A chain fetch failed for a non-retryable reason (unknown digest
    at the source, migration disabled there)."""


class KVMigrationClient:
    """HTTP pull client for ``GET <source>/kv/chain/<digest>``, retried
    under the resilience :class:`~devspace_tpu.resilience.policy.RetryPolicy`
    (transient transport errors only — a 404 means the source no longer
    holds the chain and fails fast as :class:`KVMigrateError`). A custom
    ``fetch_fn(source, digest) -> bytes`` replaces the HTTP transport
    for in-process tests."""

    def __init__(
        self,
        retry=None,
        timeout_s: float = 5.0,
        fetch_fn=None,
    ):
        if retry is None:
            from ..resilience.policy import RetryPolicy

            retry = RetryPolicy(
                max_attempts=3,
                base_delay=0.05,
                max_delay=0.5,
                jitter=0.5,
                retry_on=(OSError,),
                seed=0,
            )
        self.retry = retry
        self.timeout_s = timeout_s
        self._fetch_fn = fetch_fn

    def _fetch_once(self, source: str, digest: str) -> bytes:
        if self._fetch_fn is not None:
            return self._fetch_fn(source, digest)
        import urllib.error
        import urllib.request

        url = f"{source.rstrip('/')}/kv/chain/{digest}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KVMigrateError(f"chain not held by source: {url}") from None
            raise OSError(f"kv fetch http {e.code}: {url}") from None

    def fetch(self, source: str, digest: str) -> bytes:
        """The chain envelope for ``digest`` from ``source``. Raises
        :class:`KVMigrateError` (gone at source) or the resilience
        layer's exhaustion error; the engine maps either to
        recompute-prefill."""
        return self.retry.execute(
            self._fetch_once,
            source,
            digest,
            describe=f"kv chain fetch {digest[:16]}",
            reraise=True,
        )


def resolve_kv_tier(kv_tier: Optional[str]) -> str:
    """Tier-mode resolution, mirroring ``resolve_dispatch_depth``: the
    explicit constructor arg wins, then the ``DEVSPACE_KV_TIER`` env knob,
    default off. Returns ``"off"``, ``"host"`` or ``"host+disk"``."""
    val = (
        str(kv_tier).strip().lower()
        if kv_tier is not None
        else os.environ.get("DEVSPACE_KV_TIER", "").strip().lower()
    )
    if val in ("", "off", "0", "false", "no", "none"):
        return "off"
    if val in ("host", "ram", "on", "true", "yes", "1"):
        return "host"
    if val in ("host+disk", "host_disk", "hostdisk", "disk"):
        return "host+disk"
    raise ValueError(
        f"kv_tier must be off|host|host+disk, got {kv_tier!r}"
    )
