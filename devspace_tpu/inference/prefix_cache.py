"""Radix-tree prefix cache for the paged-KV inference engine.

The engine's prefix cache used to be a flat ``OrderedDict`` keyed by
FULL token-prefix tuples. Correct, but two hot host-side paths scaled
with the whole cache instead of the work at hand (ADVICE r5):

- **match**: rebuilding and hashing a length-``i*block`` tuple for every
  matched block is O(L^2/block) hashing per admission on a long prompt;
- **evict**: descendant invalidation compared ``k2[:n] == key`` against
  EVERY cached key — O(cached_keys x key_length) on the scheduler
  thread per eviction.

This module replaces the flat map with a radix tree over token BLOCKS
(RadixAttention-style: SGLang / vLLM prefix sharing). Each node's edge
is one block's token tuple, so:

- **match is O(prompt)**: a cursor walks the tree one block at a time,
  hashing exactly ``block_size`` tokens per step (`Cursor.step`);
- **evict is O(evicted chain)**: parent->children links make descendant
  invalidation a walk of the evicted subtree, and the victim search is
  a lazy min-heap over evictable candidates instead of a scan of every
  key (`pop_victim`).

Semantics are EXACTLY those of the flat map — pinned by a randomized
trace-equivalence test against :class:`FlatPrefixCache` (the reference
port of the old engine code, kept for tests and the microbenchmark):

- a block is published under its content (the token chain from the
  root); first writer wins, duplicates stay private;
- matching touches the chain LRU-most-recent, publishing does not
  reorder existing entries;
- the eviction victim is the LEAST-RECENTLY-TOUCHED block with no table
  references, exactly the old ``OrderedDict`` scan order;
- evicting a mid-chain block unpublishes every descendant (a prefix
  chain is only matchable through its full ancestor line): ref-0
  descendants are freed immediately, in-use ones are unpublished so
  their table release frees them.

The cache owns no pool blocks — it maps block ids it is told about and
mirrors the engine's table refcounts via :meth:`ref`/:meth:`release`.
Everything here is plain host Python: no jax, no locks (the engine's
scheduler thread is the only caller).

**Tiered mode** (``track_digests=True``, used when the engine runs a
host KV tier — inference/kv_tier.py): nodes gain a third state beyond
resident and gone. ``pop_victim(collect_spill=...)`` transitions the
victim and its ref-0 descendants to **spilled** — they stay in the tree
(their blocks are recycled, ``blk = -1``) so the chain remains
matchable; ``Cursor.step_tiered`` keeps walking through them and
reports their content digests, which the engine uses to restore the K/V
from the host tier into fresh blocks (``Cursor.publish`` on a spilled
node *revives* it with the restored block). Each node's digest is the
incremental blake2b of its token chain from the root — the
content-address the tier stores payloads under. With
``track_digests=False`` (the default) no spilled node can ever exist
and every code path below is byte-identical to the untiered cache.

**Remote location** (disaggregated prefill/decode, ISSUE 20): a fourth
state beyond resident/spilled/gone. A *remote* node is in the tree with
``blk == -1`` like a spilled one, but its payload lives in ANOTHER
replica's KV tier (``Cursor.publish_remote`` records the source). The
engine's restore path first fetches the remote run's wire envelope from
the source and imports it into the local tier, *promoting* each covered
node remote -> spilled (``promote_remote``); from there the ordinary
spilled restore ladder applies — so every migration failure (fetch
error, checksum mismatch, version skew, source missing the chain)
degrades through the same drop-spilled -> recompute-prefill path, never
corruption.

    resident --pop_victim(collect_spill)--> spilled --publish--> resident
    resident --pop_victim()------------------------------------> gone
    spilled --drop_spilled / broken ancestor chain-------------> gone
    (absent) --publish_remote--> remote --promote_remote--> spilled
    remote --publish (recompute republish)-----------------> resident
    remote --drop_spilled / broken ancestor chain----------> gone
"""

from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict
from typing import Optional


def _chain_digest(parent_digest: str, edge: tuple) -> str:
    """Incremental content address: blake2b over the parent's digest and
    this block's token tuple — equal digests iff equal token chains from
    the root. O(block) per node, computed once at publish."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent_digest.encode("ascii"))
    h.update(",".join(map(str, edge)).encode("ascii"))
    return h.hexdigest()


def fingerprint_chain(token_ids, block_size: int) -> list:
    """Block-digest chain of a token prefix: the blake2b content
    addresses of each complete ``block_size`` block, chained from the
    root exactly as the radix tree computes them (``_chain_digest`` with
    the root anchor ``""``). Two prefixes share their first K digests
    iff they share their first ``K * block_size`` tokens — which is what
    lets a component that never sees another process's radix tree (the
    serving router's shadow index, the stub replica's prefix memory)
    still reason about cache overlap in the tree's own currency. The
    trailing partial block is excluded: it can never be a published
    cache entry. O(len(token_ids)) hashing."""
    if block_size < 1:
        raise ValueError("block_size must be >= 1")
    digest = ""
    chain = []
    for i in range(0, len(token_ids) - block_size + 1, block_size):
        digest = _chain_digest(digest, tuple(token_ids[i:i + block_size]))
        chain.append(digest)
    return chain


class _Node:
    """One published block: ``edge`` is the block's own token tuple (the
    child key under ``parent``), ``blk`` the pool block id, ``refs`` the
    mirrored table refcount, ``touch`` the LRU stamp (monotonic clock;
    larger = more recently matched/published). ``digest`` is the chain
    content address (tiered mode only, else None). State encoding:
    resident (``blk >= 0``, in ``_by_block``), spilled (``blk == -1``,
    in ``_spilled``, still in ``parent.children``), remote (``blk ==
    -1``, in ``_remote``, payload on another replica), gone (detached).
    ``live`` is the heap-validity flag: True only while resident."""

    __slots__ = (
        "edge", "parent", "children", "blk", "refs", "touch", "live", "digest",
    )

    def __init__(self, edge, parent, blk, refs, touch):
        self.edge = edge
        self.parent = parent
        self.children: dict = {}
        self.blk = blk
        self.refs = refs
        self.touch = touch
        self.live = True
        self.digest: Optional[str] = None


class Cursor:
    """Incremental walk from the root, one block per step — the unit of
    hashing is ONE block's token tuple, never the whole prefix."""

    __slots__ = ("_cache", "_node")

    def __init__(self, cache: "RadixPrefixCache"):
        self._cache = cache
        self._node = cache._root

    def step(self, edge: tuple) -> Optional[int]:
        """Match one block: descend by ``edge`` and return the resident
        block id (touching it LRU-most-recent), or None when the chain
        ends here — a SPILLED child also ends the resident walk (its
        K/V is host-side; use :meth:`step_tiered` to keep matching
        through it). O(len(edge)) hashing."""
        child = self._node.children.get(edge)
        if child is None or child.blk < 0:
            return None
        self._cache._touch(child)
        self._node = child
        return child.blk

    def step_tiered(self, edge: tuple) -> Optional[tuple[str, object]]:
        """Tiered match step: ``("res", blk)`` for a resident child
        (LRU-touched, like :meth:`step`), ``("spill", digest)`` for a
        spilled one (no touch — spilled nodes are outside the LRU; the
        engine restores the digest's payload into a fresh block and
        revives the node via :meth:`publish`), ``("remote", digest)``
        for a remote one (payload on another replica — the engine
        fetches its wire envelope and promotes it to spilled before
        restoring), None when the chain ends."""
        child = self._node.children.get(edge)
        if child is None:
            return None
        if child.blk < 0:
            self._node = child
            if child.digest in self._cache._remote:
                return ("remote", child.digest)
            return ("spill", child.digest)
        self._cache._touch(child)
        self._node = child
        return ("res", child.blk)

    def publish_remote(self, edge: tuple, source: str) -> Optional[str]:
        """Record that the NEXT block of this chain is held by another
        replica (``source`` is its base URL): descend by ``edge``,
        inserting a REMOTE node (``blk == -1``, payload fetchable from
        ``source``) when the chain ends here. Returns the node's chain
        digest. An existing child in ANY state is left untouched (a
        resident/spilled copy is strictly better than a remote promise;
        an existing remote node keeps its original source) — the cursor
        just descends. Tiered mode only."""
        cache = self._cache
        if not cache._track_digests:
            raise RuntimeError("publish_remote requires track_digests=True")
        child = self._node.children.get(edge)
        if child is not None:
            self._node = child
            return child.digest
        node = _Node(edge, self._node, -1, 0, 0)
        node.live = False
        node.digest = _chain_digest(self._node.digest or "", edge)
        self._node.children[edge] = node
        cache._remote[node.digest] = (node, source)
        self._node = node
        return node.digest

    def publish(self, edge: tuple, blk: int, refs: int) -> int:
        """Publish one block: descend by ``edge``, inserting a node for
        ``blk`` (with ``refs`` mirrored table references) when the chain
        ends here. Returns the RESIDENT block id — ``blk`` itself when
        inserted, the first writer's block when the content is already
        cached (the caller's copy stays private). Existing entries are
        NOT LRU-touched (publish never reorders, matching the flat
        map). Publishing onto a SPILLED node revives it with ``blk`` —
        the restore path (the tier's payload scattered into a fresh
        block) and the recompute-fallback republish both land here."""
        child = self._node.children.get(edge)
        if child is not None:
            if child.blk >= 0:
                self._node = child
                return child.blk
            cache = self._cache
            cache._clock += 1
            child.blk = blk
            child.refs = refs
            child.touch = cache._clock
            child.live = True
            cache._by_block[blk] = child
            cache._spilled.pop(child.digest, None)
            cache._remote.pop(child.digest, None)
            if refs == 0:
                cache._evictable += 1
                heapq.heappush(cache._heap, (child.touch, id(child), child))
            self._node = child
            return blk
        cache = self._cache
        cache._clock += 1
        node = _Node(edge, self._node, blk, refs, cache._clock)
        if cache._track_digests:
            node.digest = _chain_digest(self._node.digest or "", edge)
        self._node.children[edge] = node
        cache._by_block[blk] = node
        if refs == 0:
            cache._evictable += 1
            heapq.heappush(cache._heap, (node.touch, id(node), node))
        self._node = node
        return blk


class RadixPrefixCache:
    """Tree-structured published-block index. See module docstring.

    ``track_digests=True`` enables tiered mode: nodes carry chain
    content digests and eviction can SPILL chains (keep them matchable
    with their K/V parked host-side) instead of dropping them. Off by
    default — the engine turns it on only with a host tier attached, so
    the untiered engine pays zero digest hashing and behaves
    byte-identically to before."""

    def __init__(self, track_digests: bool = False):
        self._track_digests = bool(track_digests)
        self._root = _Node(None, None, -1, 0, 0)
        self._root.live = False  # never a victim
        self._root.digest = ""  # digest chain anchor
        self._by_block: dict[int, _Node] = {}
        # digest -> spilled node (tiered mode; empty otherwise)
        self._spilled: dict[str, _Node] = {}
        # digest -> (remote node, source URL): payload on another replica
        self._remote: dict[str, tuple[_Node, str]] = {}
        self._clock = 0
        # lazy min-heap of (touch, tiebreak, node) eviction candidates:
        # entries go stale when the node is re-touched, re-referenced or
        # evicted; pop_victim discards them on the way out. Only ref-0
        # nodes are ever pushed, so the heap never scans live traffic.
        self._heap: list = []
        self._evictable = 0

    # -- introspection ----------------------------------------------------
    def __len__(self) -> int:
        return len(self._by_block)

    def spilled_count(self) -> int:
        """Spilled (host-tier-backed) nodes currently matchable."""
        return len(self._spilled)

    def remote_count(self) -> int:
        """Remote (other-replica-backed) nodes currently matchable."""
        return len(self._remote)

    def remote_source(self, digest: str) -> Optional[str]:
        """The source URL a remote node's payload is fetchable from, or
        None when ``digest`` is not a remote node."""
        entry = self._remote.get(digest)
        return entry[1] if entry is not None else None

    def chain_to(self, digest: str) -> Optional[list[tuple[str, int]]]:
        """The root->leaf ``(digest, blk)`` line ending at the node whose
        chain digest is ``digest`` (``blk == -1`` for spilled/remote
        entries), or None when unknown. The KV export path uses this to
        serve a peer's migration pull. Resident leaves cost a scan of
        ``_by_block`` — no digest index is maintained because exports
        are rare (one per migration) and the hot paths stay lean."""
        node = self._spilled.get(digest)
        if node is None:
            entry = self._remote.get(digest)
            node = entry[0] if entry is not None else None
        if node is None:
            for n in self._by_block.values():
                if n.digest == digest:
                    node = n
                    break
        if node is None:
            return None
        chain: list[tuple[str, int]] = []
        while node is not None and node.parent is not None:
            chain.append((node.digest, node.blk))
            node = node.parent
        chain.reverse()
        return chain

    def promote_remote(self, digest: str) -> bool:
        """remote -> spilled: the payload for ``digest`` has been
        imported into the LOCAL tier (migration fetch succeeded), so the
        node is now restorable through the ordinary spilled ladder.
        Returns False for unknown digests."""
        entry = self._remote.pop(digest, None)
        if entry is None:
            return False
        self._spilled[digest] = entry[0]
        return True

    def is_published(self, blk: int) -> bool:
        return blk in self._by_block

    def evictable(self) -> int:
        """Published blocks with no table references — reclaimable. O(1)."""
        return self._evictable

    def evictable_excluding(self, blks) -> int:
        """Evictable count, not counting ``blks`` (an admission must not
        count the ref-0 cached blocks it is itself about to reference as
        evictable for its private pops). O(len(blks))."""
        n = self._evictable
        for b in blks:
            node = self._by_block.get(b)
            if node is not None and node.refs == 0:
                n -= 1
        return n

    # -- matching / publishing --------------------------------------------
    def cursor(self) -> Cursor:
        return Cursor(self)

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.touch = self._clock
        if node.refs == 0:
            heapq.heappush(self._heap, (node.touch, id(node), node))

    # -- refcount mirror ---------------------------------------------------
    def ref(self, blk: int) -> None:
        """A slot table now references published block ``blk``."""
        node = self._by_block[blk]
        node.refs += 1
        if node.refs == 1:
            self._evictable -= 1

    def release(self, blk: int) -> None:
        """A slot table dropped its reference to published block ``blk``.
        At ref 0 the block becomes an eviction candidate at its LAST
        TOUCH position (matching survives the referenced span — the flat
        map's move_to_end happened at match time, not release time)."""
        node = self._by_block[blk]
        node.refs -= 1
        if node.refs == 0:
            self._evictable += 1
            heapq.heappush(self._heap, (node.touch, id(node), node))

    # -- eviction ----------------------------------------------------------
    def pop_victim(
        self,
        collect_spill: Optional[list] = None,
        dropped: Optional[list] = None,
    ) -> tuple[int, list[int]]:
        """Reclaim the least-recently-touched ref-0 block for private
        reuse. Returns ``(victim_blk, freed)`` where ``freed`` lists the
        victim's ref-0 DESCENDANT blocks, unpublished along with it (the
        chain below an evicted block is unmatchable — ``freed`` goes
        straight back to the allocator's free list; in-use descendants
        are unpublished so their table release frees them). Cost is the
        heap pop plus a walk of the evicted subtree — never a scan of
        the whole cache. Raises RuntimeError when nothing is evictable.

        Tiered mode: with ``collect_spill`` a list (and digests
        tracked), the victim and its ref-0 descendants transition to
        SPILLED instead of gone — they stay in the tree, matchable
        through :meth:`Cursor.step_tiered` — and ``(digest, blk)`` pairs
        are appended for the engine to copy device->host BEFORE reusing
        the returned blocks. In-use descendants still go gone (their
        chain would need the evicted ancestors resident to match...
        they re-publish on their own), and any already-spilled node
        below a gone one is pruned — its digest is appended to
        ``dropped`` so the caller can discard the tier payload."""
        victim = None
        while self._heap:
            touch, _, node = heapq.heappop(self._heap)
            if node.live and node.refs == 0 and node.touch == touch:
                victim = node
                break
        if victim is None:
            raise RuntimeError("allocator invariant: no block available")
        spill = collect_spill is not None and self._track_digests
        freed: list[int] = []
        victim_blk = victim.blk  # _spill_node overwrites blk with -1
        if spill:
            collect_spill.append((victim.digest, victim.blk))
            self._spill_node(victim)
        else:
            del victim.parent.children[victim.edge]
            self._unpublish(victim)
        # (node, chain_ok): ok while every ancestor up to the victim is
        # itself spilled — a spilled node is restorable only through an
        # unbroken ancestor line
        stack = [(n, spill) for n in victim.children.values()]
        while stack:
            n, ok = stack.pop()
            if n.blk < 0:  # spilled/remote from an earlier transition
                if not ok:
                    self._spilled.pop(n.digest, None)
                    self._remote.pop(n.digest, None)
                    if dropped is not None:
                        dropped.append(n.digest)
                    del n.parent.children[n.edge]
                    n.live = False
                stack.extend((c, ok) for c in n.children.values())
                continue
            if ok and n.refs == 0:
                collect_spill.append((n.digest, n.blk))
                freed.append(n.blk)
                self._spill_node(n)
                stack.extend((c, True) for c in n.children.values())
            else:
                if spill:
                    # the victim stays in the tree, so gone descendants
                    # must detach explicitly (untiered eviction detaches
                    # the whole subtree at the victim)
                    del n.parent.children[n.edge]
                self._unpublish(n)
                if n.refs == 0:
                    freed.append(n.blk)
                stack.extend((c, False) for c in n.children.values())
        return victim_blk, freed

    def _unpublish(self, node: _Node) -> None:
        del self._by_block[node.blk]
        node.live = False
        if node.refs == 0:
            self._evictable -= 1

    def _spill_node(self, node: _Node) -> None:
        """resident -> spilled: out of ``_by_block`` and the eviction
        pool (its block is being recycled), but still in the tree and
        indexed by digest for restores. Only ref-0 nodes spill."""
        del self._by_block[node.blk]
        node.live = False
        self._evictable -= 1
        node.blk = -1
        self._spilled[node.digest] = node

    def drop_spilled(self, digest: str) -> tuple[list[str], list[int]]:
        """Prune a spilled node whose payload the host tier no longer
        holds (restore miss, corrupt payload, tier LRU eviction) — a
        dangling spilled node would promise restores forever. The whole
        subtree goes with it (nothing below is matchable without it).
        Returns ``(dropped_digests, freed_blocks)``: descendant spilled
        digests for the caller to discard from the tier, plus the
        blocks of any resident ref-0 descendants (defensive — the
        spill/restore protocol revives top-down, so resident nodes
        below a spilled one should not arise). Also prunes REMOTE
        nodes (a failed migration drops its promised chain the same
        way a tier miss drops a spilled one). No-op for unknown
        digests."""
        node = self._spilled.pop(digest, None)
        if node is None:
            entry = self._remote.pop(digest, None)
            node = entry[0] if entry is not None else None
        dropped: list[str] = []
        freed: list[int] = []
        if node is None:
            return dropped, freed
        del node.parent.children[node.edge]
        node.live = False
        stack = list(node.children.values())
        while stack:
            n = stack.pop()
            if n.blk < 0:
                self._spilled.pop(n.digest, None)
                self._remote.pop(n.digest, None)
                dropped.append(n.digest)
                n.live = False
            else:
                self._unpublish(n)
                if n.refs == 0:
                    freed.append(n.blk)
            stack.extend(n.children.values())
        return dropped, freed

    def reset(self) -> None:
        """Drop everything (the pool the blocks indexed is gone)."""
        self._root = _Node(None, None, -1, 0, 0)
        self._root.live = False
        self._root.digest = ""
        self._by_block.clear()
        self._spilled.clear()
        self._remote.clear()
        self._heap.clear()
        self._evictable = 0


class FlatPrefixCache:
    """The OLD flat-map implementation behind the same API — a faithful
    port of the pre-radix engine code (OrderedDict keyed by full token
    prefixes, linear victim scan, full-key descendant sweep). Kept as
    the REFERENCE MODEL: the randomized trace-equivalence test pins the
    radix cache to it, and the microbenchmark measures the speedup
    against it. Not used by the engine."""

    def __init__(self):
        self._map: "OrderedDict[tuple, int]" = OrderedDict()
        self._published: dict[int, tuple] = {}  # blk -> its key
        self._refs: dict[int, int] = {}

    def __len__(self) -> int:
        return len(self._published)

    def is_published(self, blk: int) -> bool:
        return blk in self._published

    def evictable(self) -> int:
        return sum(
            1 for b in self._published if self._refs.get(b, 0) == 0
        )

    def evictable_excluding(self, blks) -> int:
        excl = set(blks)
        return sum(
            1
            for b in self._published
            if self._refs.get(b, 0) == 0 and b not in excl
        )

    def cursor(self) -> "_FlatCursor":
        return _FlatCursor(self)

    def ref(self, blk: int) -> None:
        self._refs[blk] = self._refs.get(blk, 0) + 1

    def release(self, blk: int) -> None:
        self._refs[blk] = self._refs.get(blk, 0) - 1

    def pop_victim(self) -> tuple[int, list[int]]:
        victim = None
        for key, blk in self._map.items():  # LRU order: oldest first
            if self._refs.get(blk, 0) == 0:
                victim = (key, blk)
                break
        if victim is None:
            raise RuntimeError("allocator invariant: no block available")
        key, blk = victim
        del self._map[key]
        del self._published[blk]
        freed: list[int] = []
        n = len(key)
        for k2 in [k for k in self._map if len(k) > n and k[:n] == key]:
            b2 = self._map.pop(k2)
            del self._published[b2]
            if self._refs.get(b2, 0) == 0:
                freed.append(b2)
        return blk, freed

    def reset(self) -> None:
        self._map.clear()
        self._published.clear()
        self._refs.clear()


class _FlatCursor:
    """Full-prefix rehash per step — the O(L^2) shape being replaced."""

    __slots__ = ("_cache", "_prefix")

    def __init__(self, cache: FlatPrefixCache):
        self._cache = cache
        self._prefix: list = []

    def step(self, edge: tuple) -> Optional[int]:
        self._prefix.extend(edge)
        key = tuple(self._prefix)
        blk = self._cache._map.get(key)
        if blk is None:
            return None
        self._cache._map.move_to_end(key)  # LRU touch
        return blk

    def publish(self, edge: tuple, blk: int, refs: int) -> int:
        self._prefix.extend(edge)
        key = tuple(self._prefix)
        if blk in self._cache._published:
            return blk  # already matchable (e.g. matched at admission)
        existing = self._cache._map.get(key)
        if existing is not None:
            return existing  # another block already holds this content
        self._cache._map[key] = blk
        self._cache._published[blk] = key
        self._cache._refs[blk] = refs
        return blk


def microbench(
    n_entries: int = 10_000,
    prompt_tokens: int = 4096,
    block_size: int = 64,
    n_match: int = 30,
    n_evict: int = 50,
    seed: int = 0,
    include_flat: bool = False,
) -> dict:
    """Host-side cost of prefix-cache match and evict at serving scale:
    a cache of ``n_entries`` published blocks built from distinct
    ``prompt_tokens``-token prompts, then per-op mean microseconds for a
    full-prompt match walk and for a victim eviction (which invalidates
    the victim's whole descendant chain). ``include_flat=True`` also
    measures :class:`FlatPrefixCache` — the old flat-map implementation
    — for the speedup ratio pinned in tests/test_prefix_cache.py;
    bench.py reports the radix numbers as ``prefix_match_us`` /
    ``prefix_evict_us``. Pure host Python — no jax, no devices."""
    import random
    import time as _time

    rng = random.Random(seed)
    blocks_per = max(1, prompt_tokens // block_size)
    n_prompts = max(1, (n_entries + blocks_per - 1) // blocks_per)
    prompts = [
        [rng.randrange(1 << 15) for _ in range(blocks_per * block_size)]
        for _ in range(n_prompts)
    ]
    impls = [("radix", RadixPrefixCache)]
    if include_flat:
        impls.append(("flat", FlatPrefixCache))
    out: dict = {}
    for name, cls in impls:
        cache = cls()
        blk = 1
        for p in prompts:
            cur = cache.cursor()
            for i in range(blocks_per):
                cur.publish(
                    tuple(p[i * block_size : (i + 1) * block_size]), blk, 0
                )
                blk += 1
        t0 = _time.perf_counter()
        for j in range(n_match):
            p = prompts[j % n_prompts]
            cur = cache.cursor()
            for i in range((len(p) - 1) // block_size):
                if cur.step(tuple(p[i * block_size : (i + 1) * block_size])) is None:
                    break
        match_us = (_time.perf_counter() - t0) / n_match * 1e6
        n_e = min(n_evict, n_prompts)  # each evict retires a whole chain
        t0 = _time.perf_counter()
        for _ in range(n_e):
            cache.pop_victim()
        evict_us = (_time.perf_counter() - t0) / n_e * 1e6
        out[name] = {
            "entries": blocks_per * n_prompts,
            "match_us": round(match_us, 2),
            "evict_us": round(evict_us, 2),
        }
    return out
