"""Radix-tree prefix cache for the paged-KV inference engine.

The engine's prefix cache used to be a flat ``OrderedDict`` keyed by
FULL token-prefix tuples. Correct, but two hot host-side paths scaled
with the whole cache instead of the work at hand (ADVICE r5):

- **match**: rebuilding and hashing a length-``i*block`` tuple for every
  matched block is O(L^2/block) hashing per admission on a long prompt;
- **evict**: descendant invalidation compared ``k2[:n] == key`` against
  EVERY cached key — O(cached_keys x key_length) on the scheduler
  thread per eviction.

This module replaces the flat map with a radix tree over token BLOCKS
(RadixAttention-style: SGLang / vLLM prefix sharing). Each node's edge
is one block's token tuple, so:

- **match is O(prompt)**: a cursor walks the tree one block at a time,
  hashing exactly ``block_size`` tokens per step (`Cursor.step`);
- **evict is O(evicted chain)**: parent->children links make descendant
  invalidation a walk of the evicted subtree, and the victim search is
  a lazy min-heap over evictable candidates instead of a scan of every
  key (`pop_victim`).

Semantics are EXACTLY those of the flat map — pinned by a randomized
trace-equivalence test against :class:`FlatPrefixCache` (the reference
port of the old engine code, kept for tests and the microbenchmark):

- a block is published under its content (the token chain from the
  root); first writer wins, duplicates stay private;
- matching touches the chain LRU-most-recent, publishing does not
  reorder existing entries;
- the eviction victim is the LEAST-RECENTLY-TOUCHED block with no table
  references, exactly the old ``OrderedDict`` scan order;
- evicting a mid-chain block unpublishes every descendant (a prefix
  chain is only matchable through its full ancestor line): ref-0
  descendants are freed immediately, in-use ones are unpublished so
  their table release frees them.

The cache owns no pool blocks — it maps block ids it is told about and
mirrors the engine's table refcounts via :meth:`ref`/:meth:`release`.
Everything here is plain host Python: no jax, no locks (the engine's
scheduler thread is the only caller).
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from typing import Optional


class _Node:
    """One published block: ``edge`` is the block's own token tuple (the
    child key under ``parent``), ``blk`` the pool block id, ``refs`` the
    mirrored table refcount, ``touch`` the LRU stamp (monotonic clock;
    larger = more recently matched/published)."""

    __slots__ = ("edge", "parent", "children", "blk", "refs", "touch", "live")

    def __init__(self, edge, parent, blk, refs, touch):
        self.edge = edge
        self.parent = parent
        self.children: dict = {}
        self.blk = blk
        self.refs = refs
        self.touch = touch
        self.live = True


class Cursor:
    """Incremental walk from the root, one block per step — the unit of
    hashing is ONE block's token tuple, never the whole prefix."""

    __slots__ = ("_cache", "_node")

    def __init__(self, cache: "RadixPrefixCache"):
        self._cache = cache
        self._node = cache._root

    def step(self, edge: tuple) -> Optional[int]:
        """Match one block: descend by ``edge`` and return the resident
        block id (touching it LRU-most-recent), or None when the chain
        ends here. O(len(edge)) hashing."""
        child = self._node.children.get(edge)
        if child is None:
            return None
        self._cache._touch(child)
        self._node = child
        return child.blk

    def publish(self, edge: tuple, blk: int, refs: int) -> int:
        """Publish one block: descend by ``edge``, inserting a node for
        ``blk`` (with ``refs`` mirrored table references) when the chain
        ends here. Returns the RESIDENT block id — ``blk`` itself when
        inserted, the first writer's block when the content is already
        cached (the caller's copy stays private). Existing entries are
        NOT LRU-touched (publish never reorders, matching the flat
        map)."""
        child = self._node.children.get(edge)
        if child is not None:
            self._node = child
            return child.blk
        cache = self._cache
        cache._clock += 1
        node = _Node(edge, self._node, blk, refs, cache._clock)
        self._node.children[edge] = node
        cache._by_block[blk] = node
        if refs == 0:
            cache._evictable += 1
            heapq.heappush(cache._heap, (node.touch, id(node), node))
        self._node = node
        return blk


class RadixPrefixCache:
    """Tree-structured published-block index. See module docstring."""

    def __init__(self):
        self._root = _Node(None, None, -1, 0, 0)
        self._root.live = False  # never a victim
        self._by_block: dict[int, _Node] = {}
        self._clock = 0
        # lazy min-heap of (touch, tiebreak, node) eviction candidates:
        # entries go stale when the node is re-touched, re-referenced or
        # evicted; pop_victim discards them on the way out. Only ref-0
        # nodes are ever pushed, so the heap never scans live traffic.
        self._heap: list = []
        self._evictable = 0

    # -- introspection ----------------------------------------------------
    def __len__(self) -> int:
        return len(self._by_block)

    def is_published(self, blk: int) -> bool:
        return blk in self._by_block

    def evictable(self) -> int:
        """Published blocks with no table references — reclaimable. O(1)."""
        return self._evictable

    def evictable_excluding(self, blks) -> int:
        """Evictable count, not counting ``blks`` (an admission must not
        count the ref-0 cached blocks it is itself about to reference as
        evictable for its private pops). O(len(blks))."""
        n = self._evictable
        for b in blks:
            node = self._by_block.get(b)
            if node is not None and node.refs == 0:
                n -= 1
        return n

    # -- matching / publishing --------------------------------------------
    def cursor(self) -> Cursor:
        return Cursor(self)

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.touch = self._clock
        if node.refs == 0:
            heapq.heappush(self._heap, (node.touch, id(node), node))

    # -- refcount mirror ---------------------------------------------------
    def ref(self, blk: int) -> None:
        """A slot table now references published block ``blk``."""
        node = self._by_block[blk]
        node.refs += 1
        if node.refs == 1:
            self._evictable -= 1

    def release(self, blk: int) -> None:
        """A slot table dropped its reference to published block ``blk``.
        At ref 0 the block becomes an eviction candidate at its LAST
        TOUCH position (matching survives the referenced span — the flat
        map's move_to_end happened at match time, not release time)."""
        node = self._by_block[blk]
        node.refs -= 1
        if node.refs == 0:
            self._evictable += 1
            heapq.heappush(self._heap, (node.touch, id(node), node))

    # -- eviction ----------------------------------------------------------
    def pop_victim(self) -> tuple[int, list[int]]:
        """Reclaim the least-recently-touched ref-0 block for private
        reuse. Returns ``(victim_blk, freed)`` where ``freed`` lists the
        victim's ref-0 DESCENDANT blocks, unpublished along with it (the
        chain below an evicted block is unmatchable — ``freed`` goes
        straight back to the allocator's free list; in-use descendants
        are unpublished so their table release frees them). Cost is the
        heap pop plus a walk of the evicted subtree — never a scan of
        the whole cache. Raises RuntimeError when nothing is evictable."""
        victim = None
        while self._heap:
            touch, _, node = heapq.heappop(self._heap)
            if node.live and node.refs == 0 and node.touch == touch:
                victim = node
                break
        if victim is None:
            raise RuntimeError("allocator invariant: no block available")
        del victim.parent.children[victim.edge]
        self._unpublish(victim)
        freed: list[int] = []
        stack = list(victim.children.values())
        while stack:
            n = stack.pop()
            self._unpublish(n)
            if n.refs == 0:
                freed.append(n.blk)
            stack.extend(n.children.values())
        return victim.blk, freed

    def _unpublish(self, node: _Node) -> None:
        del self._by_block[node.blk]
        node.live = False
        if node.refs == 0:
            self._evictable -= 1

    def reset(self) -> None:
        """Drop everything (the pool the blocks indexed is gone)."""
        self._root = _Node(None, None, -1, 0, 0)
        self._root.live = False
        self._by_block.clear()
        self._heap.clear()
        self._evictable = 0


class FlatPrefixCache:
    """The OLD flat-map implementation behind the same API — a faithful
    port of the pre-radix engine code (OrderedDict keyed by full token
    prefixes, linear victim scan, full-key descendant sweep). Kept as
    the REFERENCE MODEL: the randomized trace-equivalence test pins the
    radix cache to it, and the microbenchmark measures the speedup
    against it. Not used by the engine."""

    def __init__(self):
        self._map: "OrderedDict[tuple, int]" = OrderedDict()
        self._published: dict[int, tuple] = {}  # blk -> its key
        self._refs: dict[int, int] = {}

    def __len__(self) -> int:
        return len(self._published)

    def is_published(self, blk: int) -> bool:
        return blk in self._published

    def evictable(self) -> int:
        return sum(
            1 for b in self._published if self._refs.get(b, 0) == 0
        )

    def evictable_excluding(self, blks) -> int:
        excl = set(blks)
        return sum(
            1
            for b in self._published
            if self._refs.get(b, 0) == 0 and b not in excl
        )

    def cursor(self) -> "_FlatCursor":
        return _FlatCursor(self)

    def ref(self, blk: int) -> None:
        self._refs[blk] = self._refs.get(blk, 0) + 1

    def release(self, blk: int) -> None:
        self._refs[blk] = self._refs.get(blk, 0) - 1

    def pop_victim(self) -> tuple[int, list[int]]:
        victim = None
        for key, blk in self._map.items():  # LRU order: oldest first
            if self._refs.get(blk, 0) == 0:
                victim = (key, blk)
                break
        if victim is None:
            raise RuntimeError("allocator invariant: no block available")
        key, blk = victim
        del self._map[key]
        del self._published[blk]
        freed: list[int] = []
        n = len(key)
        for k2 in [k for k in self._map if len(k) > n and k[:n] == key]:
            b2 = self._map.pop(k2)
            del self._published[b2]
            if self._refs.get(b2, 0) == 0:
                freed.append(b2)
        return blk, freed

    def reset(self) -> None:
        self._map.clear()
        self._published.clear()
        self._refs.clear()


class _FlatCursor:
    """Full-prefix rehash per step — the O(L^2) shape being replaced."""

    __slots__ = ("_cache", "_prefix")

    def __init__(self, cache: FlatPrefixCache):
        self._cache = cache
        self._prefix: list = []

    def step(self, edge: tuple) -> Optional[int]:
        self._prefix.extend(edge)
        key = tuple(self._prefix)
        blk = self._cache._map.get(key)
        if blk is None:
            return None
        self._cache._map.move_to_end(key)  # LRU touch
        return blk

    def publish(self, edge: tuple, blk: int, refs: int) -> int:
        self._prefix.extend(edge)
        key = tuple(self._prefix)
        if blk in self._cache._published:
            return blk  # already matchable (e.g. matched at admission)
        existing = self._cache._map.get(key)
        if existing is not None:
            return existing  # another block already holds this content
        self._cache._map[key] = blk
        self._cache._published[blk] = key
        self._cache._refs[blk] = refs
        return blk


def microbench(
    n_entries: int = 10_000,
    prompt_tokens: int = 4096,
    block_size: int = 64,
    n_match: int = 30,
    n_evict: int = 50,
    seed: int = 0,
    include_flat: bool = False,
) -> dict:
    """Host-side cost of prefix-cache match and evict at serving scale:
    a cache of ``n_entries`` published blocks built from distinct
    ``prompt_tokens``-token prompts, then per-op mean microseconds for a
    full-prompt match walk and for a victim eviction (which invalidates
    the victim's whole descendant chain). ``include_flat=True`` also
    measures :class:`FlatPrefixCache` — the old flat-map implementation
    — for the speedup ratio pinned in tests/test_prefix_cache.py;
    bench.py reports the radix numbers as ``prefix_match_us`` /
    ``prefix_evict_us``. Pure host Python — no jax, no devices."""
    import random
    import time as _time

    rng = random.Random(seed)
    blocks_per = max(1, prompt_tokens // block_size)
    n_prompts = max(1, (n_entries + blocks_per - 1) // blocks_per)
    prompts = [
        [rng.randrange(1 << 15) for _ in range(blocks_per * block_size)]
        for _ in range(n_prompts)
    ]
    impls = [("radix", RadixPrefixCache)]
    if include_flat:
        impls.append(("flat", FlatPrefixCache))
    out: dict = {}
    for name, cls in impls:
        cache = cls()
        blk = 1
        for p in prompts:
            cur = cache.cursor()
            for i in range(blocks_per):
                cur.publish(
                    tuple(p[i * block_size : (i + 1) * block_size]), blk, 0
                )
                blk += 1
        t0 = _time.perf_counter()
        for j in range(n_match):
            p = prompts[j % n_prompts]
            cur = cache.cursor()
            for i in range((len(p) - 1) // block_size):
                if cur.step(tuple(p[i * block_size : (i + 1) * block_size])) is None:
                    break
        match_us = (_time.perf_counter() - t0) / n_match * 1e6
        n_e = min(n_evict, n_prompts)  # each evict retires a whole chain
        t0 = _time.perf_counter()
        for _ in range(n_e):
            cache.pop_victim()
        evict_us = (_time.perf_counter() - t0) / n_e * 1e6
        out[name] = {
            "entries": blocks_per * n_prompts,
            "match_us": round(match_us, 2),
            "evict_us": round(evict_us, 2),
        }
    return out
