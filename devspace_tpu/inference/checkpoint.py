"""Serving-side checkpoint loading — the train -> serve seam.

Training writes step-managed Orbax checkpoints of the full train state
(``{"params", "opt_state", "step"}`` — training/checkpoint.py
``CheckpointManager``); serving needs only the params. This loader
restores the params SUBTREE alone (Orbax partial restore: the optimizer
state, ~2x the param bytes under Adam, is never materialized), places it
for the serving topology in the same restore (replicated on one chip, or
tensor-parallel per ``models.transformer.param_partition_spec`` — the
elastic cross-topology mechanism of
``training/checkpoint.py:sharded_template``, so a checkpoint saved on an
8-device training mesh serves on 1 chip or a different TP width), and
optionally int8 weight-quantizes for bandwidth-bound decode.

Reference parity: the reference's deploy engines consume the build
pipeline's image artifact (``/root/reference/pkg/devspace/deploy/deploy.go``
resolving images built by ``pkg/devspace/build``); here the artifact
crossing the train->serve seam is the checkpoint directory.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..training.checkpoint import list_step_dirs


def _resolve_step_dir(path: str, step: Optional[int]) -> tuple[str, Optional[int]]:
    """``path`` is either a training root full of ``step_NNNNNNNN`` dirs
    (pick ``step`` or the latest) or one checkpoint dir directly."""
    path = os.path.abspath(path)
    steps = list_step_dirs(path)
    if steps:
        if step is None:
            return steps[-1][1], steps[-1][0]
        for s, p in steps:
            if s == step:
                return p, s
        raise FileNotFoundError(
            f"no step_{step:08d} under {path} "
            f"(available steps: {[s for s, _ in steps]})"
        )
    if step is not None:
        raise FileNotFoundError(
            f"{path} contains no step_NNNNNNNN dirs to select step {step} from"
        )
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint at {path}")
    base = os.path.basename(path.rstrip(os.sep))
    found = (
        int(base[len("step_"):])
        if base.startswith("step_") and base[len("step_"):].isdigit()
        else None
    )
    return path, found


def _params_template(cfg, mesh, model_axis: str, device):
    """Abstract params tree (shapes/dtypes from the config — nothing
    materialized) with every leaf annotated with its serving placement.
    The explicit shardings are what make the restore elastic: Orbax reads
    the logical arrays and lays them out per the template instead of
    reproducing the training topology recorded in the checkpoint."""
    from ..models import transformer as tfm
    from ..training.checkpoint import sharded_template

    shapes = jax.eval_shape(lambda: tfm.init_params(cfg, jax.random.PRNGKey(0)))
    if mesh is not None:
        specs = tfm.param_partition_spec(cfg, model_axis=model_axis)
        return sharded_template(shapes, mesh, specs)
    sharding = jax.sharding.SingleDeviceSharding(device or jax.devices()[0])
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        shapes,
    )


def _is_train_state(path: str) -> bool:
    """Whether the checkpoint holds a full train state (restore the
    ``params`` subtree) or a bare params tree. Metadata-only — no array
    bytes are read. Unreadable metadata assumes the train-state layout
    (the common case; a bare tree then fails restore with a clear error)."""
    try:
        import orbax.checkpoint as ocp

        md = ocp.PyTreeCheckpointer().metadata(path)
        tree = md.item_metadata.tree
        return isinstance(tree, dict) and "params" in tree
    except Exception:  # noqa: BLE001 — metadata shape varies across versions
        return True


def load_serving_params(
    path: str,
    cfg,
    step: Optional[int] = None,
    mesh=None,
    model_axis: str = "model",
    device=None,
    quantize: Optional[str] = None,
) -> tuple[dict, Optional[int]]:
    """Restore serving params from a training checkpoint.

    ``path``: a training checkpoint root (``step_NNNNNNNN`` dirs — the
    latest, or ``step``, is chosen) or one checkpoint dir. Accepts both a
    full train state (params restored alone, optimizer state untouched)
    and a bare params tree. ``mesh`` shards the restore tensor-parallel;
    otherwise leaves land on ``device`` (default: the first device).
    ``quantize="int8"`` applies weight-only int8
    (inference/quantization.py) after restore. Returns ``(params, step)``
    with ``step`` None when the directory name carries no step number.
    """
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
    resolved, found_step = _resolve_step_dir(path, step)
    template = _params_template(cfg, mesh, model_axis, device)

    from ..training.checkpoint import restore_checkpoint

    try:
        if _is_train_state(resolved):
            params = restore_checkpoint(
                resolved, {"params": template}, partial=True
            )["params"]
        else:
            params = restore_checkpoint(resolved, template)
    except FileNotFoundError:
        raise
    except Exception as e:  # noqa: BLE001 — surface the seam, keep the cause
        raise ValueError(
            f"checkpoint at {resolved} does not match the serving config "
            f"(wrong model config, or not a params/train-state "
            f"checkpoint): {e}"
        ) from e
    if quantize == "int8":
        from .quantization import quantize_params

        params = quantize_params(params)
    return params, found_step
