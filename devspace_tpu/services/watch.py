"""Auto-reload watcher: poll-based glob watching with a callback.

Reference: pkg/devspace/watch/watch.go — 1s-poll doublestar-glob watcher
used by ``dev`` to watch chart paths / Dockerfiles / custom paths and
trigger a full redeploy (cmd/dev.go:283-301, 2s debounce after change).
"""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import Callable, Optional


class GlobWatcher:
    def __init__(
        self,
        patterns: list[str],
        callback: Callable[[list[str]], None],
        base_dir: str = ".",
        interval: float = 1.0,  # reference: watch.go poll interval
        debounce: float = 2.0,  # reference: cmd/dev.go:287-288
    ):
        self.patterns = patterns
        self.callback = callback
        self.base_dir = base_dir
        self.interval = interval
        self.debounce = debounce
        self._snapshot: dict[str, tuple[float, int]] = {}
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _scan(self) -> dict[str, tuple[float, int]]:
        out: dict[str, tuple[float, int]] = {}
        for pattern in self.patterns:
            for path in glob.glob(
                os.path.join(self.base_dir, pattern), recursive=True
            ):
                if os.path.isdir(path):
                    for dirpath, _, files in os.walk(path):
                        for f in files:
                            full = os.path.join(dirpath, f)
                            try:
                                st = os.stat(full)
                                out[full] = (st.st_mtime, st.st_size)
                            except OSError:
                                continue
                else:
                    try:
                        st = os.stat(path)
                        out[path] = (st.st_mtime, st.st_size)
                    except OSError:
                        continue
        return out

    def start(self) -> None:
        self._snapshot = self._scan()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            time.sleep(self.interval)
            current = self._scan()
            changed = [
                p
                for p in set(current) | set(self._snapshot)
                if current.get(p) != self._snapshot.get(p)
            ]
            if changed:
                # Debounce: wait for quiet, re-scan, then fire once.
                time.sleep(self.debounce)
                current = self._scan()
                self._snapshot = current
                if not self._stopped.is_set():
                    self.callback(sorted(changed))
            else:
                self._snapshot = current

    def stop(self) -> None:
        self._stopped.set()
