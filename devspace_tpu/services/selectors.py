"""Selector resolution: which pods does a dev-session service target?

Reference: pkg/devspace/services/{pod_selector.go, attach.go:76
getSelectorNamespaceLabelSelector} — precedence: explicit selector config >
inline labelSelector > fallback ``app=<first deployment>`` (the reference
falls back to ``release=<first helm deployment>``; our charts stamp
``app: <release>``). The TPU twist (SURVEY §7/L2): a selector resolves to
the *ordered* worker list of the slice, not one pod.
"""

from __future__ import annotations

from typing import Optional

from ..config import latest
from ..config.loader import get_default_namespace, get_selector
from ..resilience.policy import RetryPolicy


class SelectorError(Exception):
    pass


def _default_resolve_policy() -> RetryPolicy:
    """Pod resolution races pod churn (a slice restarting mid-resolve shows
    up as a transient connection error); retry those, never config errors."""
    return RetryPolicy(
        max_attempts=3,
        base_delay=0.2,
        max_delay=2.0,
        jitter=0.2,
        seed=0,
        retry_on=(ConnectionError, TimeoutError),
    )


def resolve_selector(
    config: latest.Config,
    selector_name: Optional[str] = None,
    label_selector: Optional[dict[str, str]] = None,
    namespace: Optional[str] = None,
    container: Optional[str] = None,
) -> tuple[str, dict[str, str], Optional[str]]:
    """Returns (namespace, label_selector, container_name)."""
    if selector_name:
        sel = get_selector(config, selector_name)
        if sel is None:
            raise SelectorError(f"unknown selector '{selector_name}'")
        return (
            namespace or sel.namespace or get_default_namespace(config),
            sel.label_selector or {},
            container or sel.container_name,
        )
    if label_selector:
        return (namespace or get_default_namespace(config), label_selector, container)
    # Fallback: first deployment's app label (reference: attach.go:120-124).
    if config.deployments:
        first = config.deployments[0].name
        if first:
            return (
                namespace
                or config.deployments[0].namespace
                or get_default_namespace(config),
                {"app": first},
                container,
            )
    raise SelectorError(
        "cannot resolve target pods: no selector, no labelSelector and no "
        "deployments configured"
    )


def resolve_workers(
    backend,
    config: latest.Config,
    selector_name: Optional[str] = None,
    label_selector: Optional[dict[str, str]] = None,
    namespace: Optional[str] = None,
    container: Optional[str] = None,
    timeout: float = 120.0,
    retry_policy: Optional[RetryPolicy] = None,
) -> tuple[list, str, Optional[str]]:
    """Resolve the ordered slice worker pods for a service.
    Returns (workers, namespace, container_name). Transient backend errors
    (connection drops, timeouts) are retried under ``retry_policy``;
    configuration errors (:class:`SelectorError`) are not."""
    ns, labels, cont = resolve_selector(
        config, selector_name, label_selector, namespace, container
    )
    expected = config.tpu.workers if config.tpu and config.tpu.workers else None
    policy = retry_policy or _default_resolve_policy()
    workers = policy.execute(
        backend.slice_workers,
        labels,
        namespace=ns,
        expected=expected,
        timeout=timeout,
        describe=f"resolve workers for {labels!r}",
        reraise=True,
    )
    return workers, ns, cont
