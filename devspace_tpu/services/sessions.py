"""Dev-session services: sync, port-forward, logs, attach, terminal.

Reference: pkg/devspace/services/{sync,port_forwarding,logs,attach,
terminal}.go — each service resolves its target pods, starts, and can be
stopped independently (SURVEY §7 design stance (c)). All of them fan out
across the slice workers; logs are multiplexed with a per-worker prefix
(SURVEY §7 step 7: "aggregated terminal/logs — worker-prefixed log mux").
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional

from ..config import latest
from ..kube.portforward import PortForwarder
from ..resilience.policy import IdleBackoff, RetryPolicy
from ..resilience.supervisor import format_ready_timeout
from ..sync.session import SyncOptions, SyncSession
from ..utils import log as logutil
from .selectors import resolve_workers

POD_WAIT_SYNC = 120.0  # reference: services/sync.go:70
POD_WAIT_PORTFORWARD = 120.0  # reference: services/port_forwarding.go:53
POD_WAIT_TERMINAL = 5.0  # reference: services/terminal.go:65
POD_WAIT_ATTACH = 60.0  # reference: services/attach.go:26
PORTFORWARD_READY_TIMEOUT = 20.0  # reference: port_forwarding.go:86-93


def start_sync(
    backend,
    config: latest.Config,
    base_dir: str = ".",
    logger: Optional[logutil.Logger] = None,
    verbose: bool = False,
    digest: bool = True,
) -> list[SyncSession]:
    """Start every dev.sync entry (reference: services/sync.go StartSync)."""
    import os

    log = logger or logutil.get_logger()
    sessions: list[SyncSession] = []
    for sc in (config.dev.sync if config.dev else None) or []:
        workers, ns, container = resolve_workers(
            backend,
            config,
            sc.selector,
            sc.label_selector,
            sc.namespace,
            sc.container_name,
            timeout=POD_WAIT_SYNC,
        )
        local = os.path.join(base_dir, sc.local_sub_path or ".")
        opts = SyncOptions(
            local_path=os.path.abspath(local),
            container_path=sc.container_path or "/app",
            exclude_paths=sc.exclude_paths or [],
            download_exclude_paths=sc.download_exclude_paths or [],
            upload_exclude_paths=sc.upload_exclude_paths or [],
            upload_limit_kbs=(
                sc.bandwidth_limits.upload if sc.bandwidth_limits else None
            ),
            download_limit_kbs=(
                sc.bandwidth_limits.download if sc.bandwidth_limits else None
            ),
            container=container,
            fan_out=sc.fan_out or "all",
            verbose=verbose,
            verify_interval=(
                sc.verify_interval if sc.verify_interval is not None else 30.0
            ),
            # off if either the CLI (--sync-digest off) or this sync
            # entry (digest: false) disables it
            digest_gating=digest and sc.digest is not False,
            status_path=os.path.join(
                base_dir, ".devspace", "logs", "sync-status.json"
            ),
        )
        mirror = logutil.get_file_logger("sync", root=os.path.join(base_dir, ".devspace"))
        session_logger = log
        log.add_mirror(mirror)
        session = SyncSession(backend, workers, opts, session_logger)
        session.start()
        sessions.append(session)
        log.done(
            "[sync] session ready: %s <-> %d worker(s):%s",
            opts.local_path,
            len(session.workers),
            opts.container_path,
        )
    return sessions


def start_port_forwarding(
    backend,
    config: latest.Config,
    logger: Optional[logutil.Logger] = None,
) -> list[PortForwarder]:
    """Start every dev.ports entry (reference:
    services/port_forwarding.go). TPU twist: ``workers: all`` forwards every
    worker, offsetting local ports by worker index (worker i reachable at
    localPort + i)."""
    log = logger or logutil.get_logger()
    forwarders: list[PortForwarder] = []
    for pc in (config.dev.ports if config.dev else None) or []:
        workers, ns, _ = resolve_workers(
            backend,
            config,
            pc.selector,
            pc.label_selector,
            pc.namespace,
            timeout=POD_WAIT_PORTFORWARD,
        )
        targets = workers if (pc.workers == "all") else workers[:1]
        for i, pod in enumerate(targets):
            ports = []
            for pm in pc.port_mappings or []:
                local = (pm.local_port or pm.remote_port or 0) + i
                remote = pm.remote_port or pm.local_port or 0
                ports.append((local, remote))
            fw = backend.portforward(
                pod,
                ports,
                namespace=ns,
                bind_address=(pc.port_mappings or [latest.PortMapping()])[0].bind_address
                or "127.0.0.1",
            )
            started = time.monotonic()
            fw.start()
            if not fw.ready.wait(PORTFORWARD_READY_TIMEOUT):
                # Same message shape as the supervisor's restart reporting
                # (resilience.supervisor.format_ready_timeout) so operators
                # grep one format for every not-ready-in-time failure.
                raise TimeoutError(
                    format_ready_timeout(
                        "port-forward",
                        f"worker {pod.name}",
                        time.monotonic() - started,
                        "ports " + ",".join(f"{lp}->{rp}" for lp, rp in ports),
                    )
                )
            forwarders.append(fw)
            for (lp, rp) in ports:
                log.done(
                    "[ports] %s:%d -> %s:%d", "127.0.0.1", lp, pod.name, rp
                )
    return forwarders


def _resolve_terminal_workers(backend, config, timeout: Optional[float] = None):
    """Shared terminal-target resolution (terminal, attach, enter --all):
    dev.terminal config decides selector/namespace/container; one site so
    the three commands can never target different pods."""
    tc = (config.dev.terminal if config.dev else None) or latest.TerminalConfig()
    if timeout is None:
        timeout = POD_WAIT_TERMINAL if not config.tpu else POD_WAIT_SYNC
    workers, ns, container = resolve_workers(
        backend,
        config,
        tc.selector,
        tc.label_selector,
        tc.namespace,
        tc.container_name,
        timeout=timeout,
    )
    return tc, workers, ns, container


def worker_prefix(pod) -> str:
    """One prefix convention for all slice-fan-out output (`logs`,
    `enter --all`): `[worker-N]` when the pod carries a TPU worker id,
    else the pod name."""
    wid = getattr(pod, "tpu_worker_id", None)
    return f"[worker-{wid}] " if wid is not None else f"[{getattr(pod, 'name', pod)}] "


def _default_logmux_policy() -> RetryPolicy:
    """Log streams drop whenever a pod restarts or the API server rotates
    the connection; reconnecting is cheap and the tail dedups nothing, so
    be generous with attempts but cap the wait. No jitter: one policy is
    shared across per-pod follow threads, and jitter would draw from the
    shared RNG in thread order — nondeterministic under chaos tests."""
    return RetryPolicy(
        max_attempts=5,
        base_delay=0.2,
        max_delay=5.0,
        jitter=0.0,
        seed=0,
        retry_on=(Exception,),
    )


class LogMux:
    """Worker-prefixed log streaming across the slice
    (replaces the reference's single-pod log follow). A dropped follow
    stream reconnects under ``retry_policy``; data on the new stream
    refills the attempt budget."""

    def __init__(
        self,
        backend,
        workers: list,
        namespace: str,
        container: Optional[str] = None,
        tail: Optional[int] = 100,
        out=None,
        logger: Optional[logutil.Logger] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.backend = backend
        self.workers = workers
        self.namespace = namespace
        self.container = container
        self.tail = tail
        self.out = out or sys.stdout
        self.log = logger or logutil.get_logger()
        self.retry_policy = retry_policy or _default_logmux_policy()
        self._threads: list[threading.Thread] = []
        self._stopped = threading.Event()
        self._write_lock = threading.Lock()
        # observability for tests/status: reconnects per pod name
        self.reconnects: dict[str, int] = {}

    def _prefix(self, pod) -> str:
        return worker_prefix(pod)

    def run_once(self) -> None:
        """Print the last `tail` lines of every worker (no follow)."""
        for pod in self.workers:
            prefix = self._prefix(pod)
            for line in self.backend.logs(
                pod, namespace=self.namespace, container=self.container, tail=self.tail
            ):
                with self._write_lock:
                    self.out.write(prefix + line.decode("utf-8", "replace") + "\n")
        if hasattr(self.out, "flush"):
            self.out.flush()

    def follow(self) -> None:
        for pod in self.workers:
            t = threading.Thread(target=self._follow_one, args=(pod,), daemon=True)
            t.start()
            self._threads.append(t)

    def _follow_one(self, pod) -> None:
        prefix = self._prefix(pod)
        name = getattr(pod, "name", str(pod))
        delays = self.retry_policy.delays()
        # Once lines have been printed, reconnects re-tail with 0 so a
        # mid-flight drop does not replay them; until then keep the
        # configured tail — the history was never shown.
        tail = self.tail
        got_any = False
        while not self._stopped.is_set():
            got_data = False
            try:
                for line in self.backend.logs(
                    pod,
                    namespace=self.namespace,
                    container=self.container,
                    tail=tail,
                    follow=True,
                ):
                    if self._stopped.is_set():
                        return
                    got_data = got_any = True
                    with self._write_lock:
                        self.out.write(prefix + line.decode("utf-8", "replace") + "\n")
                        if hasattr(self.out, "flush"):
                            self.out.flush()
                return  # clean EOF — pod gone for good, nothing to chase
            except Exception as e:  # noqa: BLE001 — stream dropped mid-follow
                if self._stopped.is_set():
                    return
                if got_data:
                    delays = self.retry_policy.delays()  # progress refills budget
                try:
                    delay = next(delays)
                except StopIteration:
                    self.log.warn(
                        "[logs] stream from %s ended (reconnect budget "
                        "exhausted): %s", name, e,
                    )
                    return
                self.reconnects[name] = self.reconnects.get(name, 0) + 1
                self.log.warn(
                    "[logs] stream from %s dropped, reconnecting in %.1fs: %s",
                    name, delay, e,
                )
                if got_any:
                    tail = 0
                if self._stopped.wait(delay):
                    return

    def stop(self) -> None:
        self._stopped.set()


def start_terminal(
    backend,
    config: latest.Config,
    command: Optional[list[str]] = None,
    worker_index: Optional[int] = None,
    stdin=None,
    stdout=None,
    logger: Optional[logutil.Logger] = None,
) -> int:
    """Interactive shell on one slice worker (reference:
    services/terminal.go StartTerminal; command precedence args > config >
    ``sh -c "bash || sh"``, terminal.go:29-33). Returns the exit code."""
    log = logger or logutil.get_logger()
    tc, workers, ns, container = _resolve_terminal_workers(backend, config)
    idx = worker_index if worker_index is not None else (tc.worker or 0)
    idx = max(0, min(idx, len(workers) - 1))
    pod = workers[idx]
    cmd = command or tc.command or ["sh", "-c", "bash || sh"]
    log.info("[terminal] opening shell on %s (worker %d)", pod.name, idx)
    use_tty = stdin is None and sys.stdin.isatty()
    proc = backend.exec_stream(pod, cmd, container=container, tty=use_tty)
    return _pump_terminal(proc, stdin=stdin, stdout=stdout, tty=use_tty)


def _pump_terminal(proc, stdin=None, stdout=None, tty: bool = False) -> int:
    """Bidirectional pump between the local terminal and the remote shell;
    raw-TTY passthrough when interactive (reference: pkg/util/terminal)."""
    stdout = stdout or sys.stdout
    stop = threading.Event()

    # Idle-adaptive polling (was a fixed timeout=0.2, waking 5x/s on
    # streams quiet for hours): the wait doubles while idle up to 1s and
    # snaps back to 50ms the moment data arrives, so interactive latency
    # is unchanged but an idle session barely wakes.
    def pump_out():
        idle = IdleBackoff(initial=0.05, maximum=1.0)
        while not stop.is_set():
            try:
                data = proc.stdout.read_available(timeout=idle.next_wait())
            except Exception:  # noqa: BLE001 — stream closed
                return
            if data:
                idle.reset()
                text = data.decode("utf-8", "replace")
                stdout.write(text)
                if hasattr(stdout, "flush"):
                    stdout.flush()

    def pump_err():
        idle = IdleBackoff(initial=0.05, maximum=1.0)
        while not stop.is_set():
            try:
                data = proc.stderr.read_available(timeout=idle.next_wait())
            except Exception:  # noqa: BLE001
                return
            if data:
                idle.reset()
                sys.stderr.write(data.decode("utf-8", "replace"))
                sys.stderr.flush()

    threads = [threading.Thread(target=pump_out, daemon=True)]
    if not tty:
        threads.append(threading.Thread(target=pump_err, daemon=True))
    for t in threads:
        t.start()

    raw_ctx = None
    if tty:
        raw_ctx = _raw_tty()
        raw_ctx.__enter__()
    try:
        import time as _time

        source = stdin if stdin is not None else sys.stdin.buffer

        # stdin forwarding runs on its own daemon thread: a blocked
        # readline() must never keep the session alive after the remote
        # command exits.
        def pump_in():
            while not stop.is_set() and proc.poll() is None:
                try:
                    data = source.read(1) if tty else source.readline()
                except (OSError, ValueError):
                    return
                if not data:
                    return  # stdin EOF
                if isinstance(data, str):
                    data = data.encode()
                try:
                    proc.write_stdin(data)
                except Exception:  # noqa: BLE001 — remote ended
                    return

        threading.Thread(target=pump_in, daemon=True).start()
        while proc.poll() is None and not stop.is_set():
            _time.sleep(0.05)
        _time.sleep(0.1)  # let the output pumps drain the tail
        rc = proc.poll()
        return rc if rc is not None else 0
    finally:
        stop.set()
        if raw_ctx is not None:
            raw_ctx.__exit__(None, None, None)
        proc.terminate()


def _raw_tty():
    import contextlib

    @contextlib.contextmanager
    def ctx():
        import termios
        import tty as ttymod

        fd = sys.stdin.fileno()
        old = termios.tcgetattr(fd)
        try:
            ttymod.setraw(fd)
            yield
        finally:
            termios.tcsetattr(fd, termios.TCSADRAIN, old)

    return ctx()


def start_attach(
    backend,
    config: latest.Config,
    worker_index: int = 0,
    stdout=None,
    logger: Optional[logutil.Logger] = None,
) -> int:
    """Attach to a worker's main process (reference: services/attach.go —
    the fallback when the terminal is disabled)."""
    _, workers, ns, container = _resolve_terminal_workers(
        backend, config, timeout=POD_WAIT_ATTACH
    )
    pod = workers[max(0, min(worker_index, len(workers) - 1))]
    proc = backend.attach_stream(pod, container=container)
    return _pump_terminal(proc, stdin=_EmptyStdin(), stdout=stdout, tty=False)


class _EmptyStdin:
    def readline(self):
        import time

        time.sleep(0.2)
        return b""

    def read(self, n):
        return b""


def broadcast_exec(
    backend,
    config,
    command: list[str],
    timeout: float = 300.0,
    logger=None,
) -> int:
    """Run ``command`` on EVERY slice worker concurrently, with worker-
    prefixed output (the N-worker generalization of `enter -- <cmd>`;
    SURVEY §7 hard part #3 — terminal UX across N workers). Targets the
    same pods/container as ``start_terminal`` (dev.terminal config).
    Returns the first non-zero exit code, else 0."""
    import concurrent.futures

    log = logger or logutil.get_logger()
    _, workers, ns, container = _resolve_terminal_workers(backend, config)

    def run(w):
        return backend.exec_buffered(
            w, command, namespace=ns, container=container, timeout=timeout
        )

    rc = 0
    with concurrent.futures.ThreadPoolExecutor(max_workers=len(workers)) as pool:
        futures = {pool.submit(run, w): w for w in workers}
        for fut in concurrent.futures.as_completed(futures):
            w = futures[fut]
            prefix = worker_prefix(w)
            try:
                out, err, code = fut.result()
            except Exception as e:  # noqa: BLE001 — report per worker
                log.error("%sexec failed: %s", prefix, e)
                rc = rc or 1
                continue
            for line in out.decode(errors="replace").splitlines():
                print(f"{prefix}{line}")
            for line in err.decode(errors="replace").splitlines():
                print(f"{prefix}{line}", file=sys.stderr)
            if code:
                log.error("%sexit code %d", prefix, code)
                rc = rc or code
    return rc
