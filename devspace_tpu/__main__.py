"""``python -m devspace_tpu`` entry point (reference: main.go -> cmd.Execute)."""

import sys

from .cli.main import main

if __name__ == "__main__":
    sys.exit(main())
