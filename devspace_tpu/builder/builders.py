"""Image builders: docker daemon, in-cluster kaniko, and a fake for tests.

Reference: builder/interface.go {Authenticate, BuildImage, PushImage};
builder/docker/docker.go; builder/kaniko/kaniko.go (pod spawn + context
upload over the sync engine + exec of /kaniko/executor).
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional

from ..sync.session import copy_to_container
from ..utils import log as logutil
from . import dockerclient
from .dockerclient import DockerClient, DockerError, load_docker_auths


class BuildError(Exception):
    pass


def apply_entrypoint_override(dockerfile_content: str, entrypoint: list[str]) -> str:
    """Rewrite/append ENTRYPOINT for dev-mode (reference:
    builder/util.go CreateTempDockerfile — the dev override keeps the
    container alive so sync/terminal can attach before the app starts)."""
    import json

    lines = dockerfile_content.splitlines()
    out = [
        ln
        for ln in lines
        if not re.match(r"^\s*(ENTRYPOINT|CMD)\b", ln, re.IGNORECASE)
    ]
    out.append("ENTRYPOINT " + json.dumps(entrypoint))
    return "\n".join(out) + "\n"


class DockerBuilder:
    """Local docker daemon build + push."""

    def __init__(
        self,
        client: Optional[DockerClient] = None,
        logger: Optional[logutil.Logger] = None,
    ):
        self.client = client or DockerClient()
        self.log = logger or logutil.get_logger()
        self._auths = load_docker_auths()

    def available(self) -> bool:
        return self.client.ping()

    def _auth_for(self, image: str) -> Optional[dict]:
        registry = dockerclient.registry_from_image(image)
        for key, auth in self._auths.items():
            if registry in key:
                return auth
        return None

    def authenticate(self, image: str) -> Optional[dict]:
        return self._auth_for(image)

    def build(
        self,
        image: str,
        tag: str,
        context_dir: str,
        dockerfile_path: str,
        entrypoint_override: Optional[list[str]] = None,
        build_args: Optional[dict[str, str]] = None,
        target: Optional[str] = None,
        network: Optional[str] = None,
    ) -> None:
        override: Optional[bytes] = None
        df_outside = None
        if entrypoint_override:
            with open(dockerfile_path, "r", encoding="utf-8") as fh:
                override = apply_entrypoint_override(
                    fh.read(), entrypoint_override
                ).encode()
        elif os.path.abspath(dockerfile_path) != os.path.abspath(
            os.path.join(context_dir, "Dockerfile")
        ):
            df_outside = dockerfile_path
        context = DockerClient.make_build_context(
            context_dir, dockerfile_path=df_outside, dockerfile_override=override
        )
        auth = self._auth_for(image)
        registry_auth = (
            {dockerclient.registry_from_image(image): auth} if auth else None
        )
        for line in self.client.build(
            context,
            f"{image}:{tag}",
            build_args=build_args,
            target=target,
            network=network,
            registry_auth=registry_auth,
        ):
            self.log.debug("[build] %s", line)

    def push(self, image: str, tag: str) -> None:
        for line in self.client.push(image, tag, auth=self._auth_for(image)):
            self.log.debug("[push] %s", line)


KANIKO_IMAGE = "gcr.io/kaniko-project/executor:latest"
KANIKO_CONTEXT_PATH = "/workspace"


class KanikoBuilder:
    """In-cluster build: a kaniko pod receives the context through the sync
    engine's one-shot upload, then runs /kaniko/executor
    (reference: builder/kaniko/kaniko.go:84-255)."""

    def __init__(
        self,
        backend,
        namespace: str = "default",
        pull_secret: Optional[str] = None,
        cache: bool = True,
        kaniko_image: str = KANIKO_IMAGE,
        logger: Optional[logutil.Logger] = None,
    ):
        self.backend = backend
        self.namespace = namespace
        self.pull_secret = pull_secret
        self.cache = cache
        self.kaniko_image = kaniko_image
        self.log = logger or logutil.get_logger()

    def authenticate(self, image: str) -> None:
        # Kaniko pushes from inside the cluster using the mounted pull
        # secret (reference: kaniko.go Authenticate creates the secret).
        return None

    def build(
        self,
        image: str,
        tag: str,
        context_dir: str,
        dockerfile_path: str,
        entrypoint_override: Optional[list[str]] = None,
        build_args: Optional[dict[str, str]] = None,
        target: Optional[str] = None,
        network: Optional[str] = None,
    ) -> None:
        import random
        import string

        suffix = "".join(random.choices(string.ascii_lowercase + string.digits, k=5))
        pod_name = f"devspace-kaniko-{suffix}"
        volumes = []
        mounts = []
        if self.pull_secret:
            volumes.append(
                {
                    "name": "registry-auth",
                    "secret": {
                        "secretName": self.pull_secret,
                        "items": [
                            {"key": ".dockerconfigjson", "path": "config.json"}
                        ],
                    },
                }
            )
            mounts.append({"name": "registry-auth", "mountPath": "/kaniko/.docker"})
        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": pod_name, "namespace": self.namespace},
            "spec": {
                "restartPolicy": "Never",
                "containers": [
                    {
                        "name": "kaniko",
                        "image": self.kaniko_image,
                        "command": ["sh", "-c", "sleep 7200"],
                        "volumeMounts": mounts,
                    }
                ],
                "volumes": volumes,
            },
        }
        self.backend.ensure_namespace(self.namespace)
        pod = self.backend.create_pod(manifest, namespace=self.namespace)
        try:
            self._wait_running(pod_name)
            # Upload build context (reference: kaniko.go:211-216 uses
            # sync.CopyToContainer).
            ctx_dest = f"{KANIKO_CONTEXT_PATH}/{suffix}"
            n = copy_to_container(
                self.backend, pod, context_dir, ctx_dest, logger=self.log
            )
            self.log.info("[kaniko] uploaded %d context entries", n)
            if entrypoint_override:
                with open(dockerfile_path, "r", encoding="utf-8") as fh:
                    content = apply_entrypoint_override(
                        fh.read(), entrypoint_override
                    )
                self._write_remote_file(pod, f"{ctx_dest}/Dockerfile", content)
            args = [
                "/kaniko/executor",
                f"--context={ctx_dest}",
                f"--dockerfile={ctx_dest}/Dockerfile",
                f"--destination={image}:{tag}",
            ]
            if self.cache:
                args.append("--cache=true")
            if target:
                args.append(f"--target={target}")
            for k, v in (build_args or {}).items():
                args.append(f"--build-arg={k}={v}")
            proc = self.backend.exec_stream(pod, args, container="kaniko")
            deadline = time.monotonic() + 1800
            while proc.poll() is None and time.monotonic() < deadline:
                try:
                    chunk = proc.stdout.read_available(timeout=0.5)
                    if chunk:
                        for ln in chunk.decode("utf-8", "replace").splitlines():
                            self.log.debug("[kaniko] %s", ln)
                except Exception:  # noqa: BLE001 — stream closed at exit
                    break
            rc = proc.wait(10)
            if rc != 0:
                err = proc.stderr.drain().decode("utf-8", "replace")
                raise BuildError(f"kaniko build failed (rc={rc}): {err[-2000:]}")
        finally:
            self.backend.delete_pod(pod_name, namespace=self.namespace)

    def _wait_running(self, pod_name: str, timeout: float = 300.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pod = self.backend.get_pod(pod_name, namespace=self.namespace)
            if pod is not None and pod.phase == "Running":
                return
            time.sleep(1.0)
        raise BuildError(f"kaniko pod {pod_name} not running after {timeout}s")

    def _write_remote_file(self, pod, path: str, content: str) -> None:
        import shlex

        # identity on a real cluster; maps into the pod dir on the fake
        # backend (same convention as the sync engine's remote dirs)
        path = self.backend.translate_path(pod, path)
        out, err, rc = self.backend.exec_buffered(
            pod,
            [
                "sh",
                "-c",
                f"printf '%s' {shlex.quote(content)} > {shlex.quote(path)}",
            ],
        )
        if rc != 0:
            raise BuildError(f"failed writing {path}: {err.decode('utf-8', 'replace')}")

    def push(self, image: str, tag: str) -> None:
        pass  # kaniko pushes as part of the build


class FakeBuilder:
    """Records builds; used by tests and environments without a daemon."""

    def __init__(self):
        self.builds: list[dict] = []
        self.pushes: list[tuple[str, str]] = []

    def authenticate(self, image: str) -> None:
        return None

    def build(self, image, tag, context_dir, dockerfile_path, **kwargs) -> None:
        self.builds.append(
            {
                "image": image,
                "tag": tag,
                "context": context_dir,
                "dockerfile": dockerfile_path,
                **kwargs,
            }
        )

    def push(self, image: str, tag: str) -> None:
        self.pushes.append((image, tag))
