"""Registry pull secrets.

Reference: pkg/devspace/registry/{registry,init}.go — for each image with
createPullSecret, resolve the registry from the image name, pull local
docker creds, and create a kubernetes.io/dockerconfigjson secret named
``devspace-auth-<registry>`` in every deployment namespace; the secret names
are later injected into charts (GetPullSecretNames).
"""

from __future__ import annotations

import base64
import json
import re
from typing import Optional

from ..config import latest
from ..utils import log as logutil
from .dockerclient import load_docker_auths, registry_from_image

SECRET_PREFIX = "devspace-auth-"


def secret_name(registry: str) -> str:
    """Reference: registry/registry.go:80 GetRegistryAuthSecretName."""
    slug = re.sub(r"[^a-z0-9-]", "-", registry.lower()).strip("-") or "registry"
    return SECRET_PREFIX + slug


def create_pull_secret(
    backend,
    namespace: str,
    registry: str,
    username: str,
    password: str,
    email: str = "noreply@devspace.tpu",
) -> str:
    auth = base64.b64encode(f"{username}:{password}".encode()).decode()
    docker_config = {
        "auths": {
            registry: {"username": username, "password": password, "email": email, "auth": auth}
        }
    }
    name = secret_name(registry)
    backend.apply(
        {
            "apiVersion": "v1",
            "kind": "Secret",
            "type": "kubernetes.io/dockerconfigjson",
            "metadata": {"name": name, "namespace": namespace},
            "data": {
                ".dockerconfigjson": base64.b64encode(
                    json.dumps(docker_config).encode()
                ).decode()
            },
        },
        namespace=namespace,
    )
    return name


def init_registries(
    backend,
    config: latest.Config,
    namespace: str,
    logger: Optional[logutil.Logger] = None,
) -> list[str]:
    """Create pull secrets for every image with createPullSecret in every
    deployment namespace (reference: registry/init.go InitRegistries).
    Returns the created secret names for chart injection."""
    log = logger or logutil.get_logger()
    auths = load_docker_auths()
    namespaces = {namespace}
    for d in config.deployments or []:
        if d.namespace:
            namespaces.add(d.namespace)
    created: list[str] = []
    for name, image_conf in (config.images or {}).items():
        if not image_conf.create_pull_secret or not image_conf.image:
            continue
        registry = registry_from_image(image_conf.image)
        cred = None
        for key, value in auths.items():
            if registry in key:
                cred = value
                break
        if cred is None or not cred.get("username"):
            log.warn(
                "[registry] no local docker credentials for %s — skipping pull secret",
                registry,
            )
            continue
        for ns in namespaces:
            backend.ensure_namespace(ns)
            sname = create_pull_secret(
                backend, ns, registry, cred["username"], cred.get("password", "")
            )
            if sname not in created:
                created.append(sname)
        log.done("[registry] pull secret ready for %s", registry)
    return created
