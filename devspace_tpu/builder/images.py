"""Per-image build orchestration with incremental skip cache.

Reference: pkg/devspace/image/build.go — BuildAll (24): for each configured
image, skip when dockerfile mtime + context hash match the generated cache
(shouldRebuild 189-238), otherwise random 7-char tag (86), authenticate ->
build -> push, dev-mode entrypoint override injection (146-158), record tag
in the cache (179-183); create_builder.go picks docker vs kaniko.
"""

from __future__ import annotations

import os
from typing import Optional

from ..config import latest
from ..config.generated import CacheConfig
from ..utils import log as logutil
from ..utils.hashutil import directory_hash
from ..utils.ignoreutil import get_ignore_rules
from ..utils.randutil import random_string
from .builders import KANIKO_IMAGE, DockerBuilder, FakeBuilder, KanikoBuilder


def create_builder(
    image_conf: latest.ImageConfig,
    backend=None,
    namespace: str = "default",
    pull_secret: Optional[str] = None,
    logger=None,
    prefer_fake: bool = False,
):
    """Pick the build engine (reference: image/create_builder.go):
    kaniko when configured, else local docker, else kaniko fallback when a
    backend exists, else the fake recorder."""
    build = image_conf.build
    if prefer_fake or getattr(backend, "is_fake", False):
        return FakeBuilder()
    if build and build.kaniko is not None and backend is not None:
        return KanikoBuilder(
            backend,
            namespace=(build.kaniko.namespace or namespace),
            pull_secret=build.kaniko.pull_secret or pull_secret,
            cache=build.kaniko.cache if build.kaniko.cache is not None else True,
            kaniko_image=build.kaniko.image or KANIKO_IMAGE,
            logger=logger,
        )
    docker = DockerBuilder(logger=logger)
    if docker.available():
        return docker
    if backend is not None and not (
        build and build.docker and build.docker.disable_fallback
    ):
        return KanikoBuilder(
            backend, namespace=namespace, pull_secret=pull_secret, logger=logger
        )
    raise RuntimeError(
        "no build engine available: docker daemon unreachable and no cluster "
        "backend for kaniko"
    )


def should_rebuild(
    name: str,
    image_conf: latest.ImageConfig,
    cache: CacheConfig,
    base_dir: str = ".",
) -> bool:
    """Dockerfile mtime + context hash vs cache
    (reference: image/build.go:189-238)."""
    dockerfile = os.path.join(base_dir, image_conf.dockerfile or "Dockerfile")
    context = os.path.join(base_dir, image_conf.context or ".")
    try:
        mtime = os.path.getmtime(dockerfile)
    except OSError:
        return True
    excludes = get_ignore_rules(os.path.join(context, ".dockerignore"))
    ctx_hash = directory_hash(context, excludes=excludes)
    unchanged = (
        cache.dockerfile_timestamps.get(name) == mtime
        and cache.dockerfile_context_hashes.get(name) == ctx_hash
        and name in cache.image_tags
    )
    if unchanged:
        return False
    cache.dockerfile_timestamps[name] = mtime
    cache.dockerfile_context_hashes[name] = ctx_hash
    return True


def build_all(
    config: latest.Config,
    cache: CacheConfig,
    backend=None,
    dev_mode: bool = False,
    force: bool = False,
    base_dir: str = ".",
    logger: Optional[logutil.Logger] = None,
    builder_factory=None,
) -> dict[str, str]:
    """Build every configured image; returns {name: full_ref_with_tag}
    for deploy-time injection (reference: image.BuildAll)."""
    log = logger or logutil.get_logger()
    image_tags: dict[str, str] = {}
    for name, image_conf in (config.images or {}).items():
        if image_conf.build and image_conf.build.disabled:
            continue
        if not force and not should_rebuild(name, image_conf, cache, base_dir):
            tag = cache.image_tags[name]
            image_tags[name] = f"{image_conf.image}:{tag}"
            log.info("[build] %s unchanged, keeping tag %s", name, tag)
            continue
        tag = image_conf.tag or random_string(7)
        entrypoint_override = None
        if dev_mode and config.dev and config.dev.override_images:
            for ov in config.dev.override_images:
                if ov.name == name and ov.entrypoint:
                    entrypoint_override = ov.entrypoint
        builder = (
            builder_factory(image_conf)
            if builder_factory
            else create_builder(image_conf, backend, logger=log)
        )
        opts = image_conf.build.options if image_conf.build else None
        log.info("[build] building %s:%s", image_conf.image, tag)
        builder.authenticate(image_conf.image)
        builder.build(
            image_conf.image,
            tag,
            context_dir=os.path.join(base_dir, image_conf.context or "."),
            dockerfile_path=os.path.join(
                base_dir, image_conf.dockerfile or "Dockerfile"
            ),
            entrypoint_override=entrypoint_override,
            build_args=(opts.build_args if opts else None),
            target=(opts.target if opts else None),
            network=(opts.network if opts else None),
        )
        if not image_conf.skip_push:
            builder.push(image_conf.image, tag)
        cache.image_tags[name] = tag
        image_tags[name] = f"{image_conf.image}:{tag}"
        log.done("[build] %s -> %s:%s", name, image_conf.image, tag)
    return image_tags
