"""Minimal Docker Engine API client over the unix socket, stdlib-only.

Reference: pkg/devspace/docker/client.go (docker client from env or
minikube's docker-env) + builder/docker/docker.go (build-context tar,
JSON progress stream, push with base64 auth). We speak the Engine REST API
directly: ping, build, tag, push.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import os
import socket
import subprocess
import tarfile
from typing import Iterator, Optional

from ..utils.ignoreutil import IgnoreMatcher

DEFAULT_SOCKET = "/var/run/docker.sock"


class DockerError(Exception):
    pass


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 600.0):
        super().__init__("localhost", timeout=timeout)
        self.socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self.socket_path)
        self.sock = sock


class DockerClient:
    def __init__(self, socket_path: Optional[str] = None, host: Optional[str] = None):
        env_host = host or os.environ.get("DOCKER_HOST", "")
        if env_host.startswith("unix://"):
            socket_path = env_host[len("unix://") :]
        self.socket_path = socket_path or DEFAULT_SOCKET

    def _conn(self, timeout: float = 600.0) -> _UnixHTTPConnection:
        return _UnixHTTPConnection(self.socket_path, timeout)

    def ping(self, timeout: float = 3.0) -> bool:
        try:
            conn = self._conn(timeout)
            conn.request("GET", "/_ping")
            resp = conn.getresponse()
            ok = resp.status == 200
            resp.read()
            conn.close()
            return ok
        except (OSError, http.client.HTTPException):
            return False

    # -- build -------------------------------------------------------------
    @staticmethod
    def make_build_context(
        context_dir: str,
        dockerfile_path: Optional[str] = None,
        dockerfile_override: Optional[bytes] = None,
    ) -> bytes:
        """Tar the build context honoring .dockerignore; a Dockerfile outside
        the context (or an entrypoint-overridden one) is spliced in as
        'Dockerfile' (reference: builder/docker/docker.go:56-120,
        builder/util.go OverwriteDockerfileInBuildContext)."""
        ignore = IgnoreMatcher.from_file(os.path.join(context_dir, ".dockerignore"))
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for root, dirs, files in os.walk(context_dir):
                for name in files:
                    full = os.path.join(root, name)
                    rel = os.path.relpath(full, context_dir).replace(os.sep, "/")
                    if rel != "Dockerfile" and ignore.matches(rel, False):
                        continue
                    if rel == "Dockerfile" and (dockerfile_override or dockerfile_path):
                        continue  # replaced below
                    try:
                        tf.add(full, arcname=rel, recursive=False)
                    except OSError:
                        continue
                dirs[:] = [
                    d
                    for d in dirs
                    if not ignore.matches(
                        os.path.relpath(os.path.join(root, d), context_dir).replace(
                            os.sep, "/"
                        ),
                        True,
                    )
                ]
            content = dockerfile_override
            if content is None and dockerfile_path:
                with open(dockerfile_path, "rb") as fh:
                    content = fh.read()
            if content is not None:
                ti = tarfile.TarInfo("Dockerfile")
                ti.size = len(content)
                tf.addfile(ti, io.BytesIO(content))
        return buf.getvalue()

    def build(
        self,
        context_tar: bytes,
        tag: str,
        build_args: Optional[dict[str, str]] = None,
        target: Optional[str] = None,
        network: Optional[str] = None,
        registry_auth: Optional[dict] = None,
    ) -> Iterator[str]:
        """POST /build; yields progress lines from the JSON stream."""
        import urllib.parse

        query = {"t": tag, "dockerfile": "Dockerfile"}
        if build_args:
            query["buildargs"] = json.dumps(build_args)
        if target:
            query["target"] = target
        if network:
            query["networkmode"] = network
        headers = {"Content-Type": "application/x-tar"}
        if registry_auth:
            headers["X-Registry-Config"] = base64.b64encode(
                json.dumps(registry_auth).encode()
            ).decode()
        conn = self._conn()
        conn.request(
            "POST",
            "/build?" + urllib.parse.urlencode(query),
            body=context_tar,
            headers=headers,
        )
        resp = conn.getresponse()
        try:
            yield from self._progress(resp, "build")
        finally:
            conn.close()

    def push(self, image: str, tag: str, auth: Optional[dict] = None) -> Iterator[str]:
        import urllib.parse

        headers = {
            "X-Registry-Auth": base64.b64encode(
                json.dumps(auth or {}).encode()
            ).decode()
        }
        conn = self._conn()
        conn.request(
            "POST",
            f"/images/{urllib.parse.quote(image, safe='')}/push?"
            + urllib.parse.urlencode({"tag": tag}),
            headers=headers,
        )
        resp = conn.getresponse()
        try:
            yield from self._progress(resp, "push")
        finally:
            conn.close()

    @staticmethod
    def _progress(resp, phase: str) -> Iterator[str]:
        if resp.status >= 400:
            raise DockerError(f"{phase} failed: {resp.status} {resp.read().decode('utf-8', 'replace')}")
        buf = b""
        while True:
            chunk = resp.read1(65536) if hasattr(resp, "read1") else resp.read(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if "errorDetail" in msg or "error" in msg:
                    detail = msg.get("errorDetail", {}).get("message") or msg.get("error")
                    raise DockerError(f"{phase} failed: {detail}")
                text = msg.get("stream") or msg.get("status") or ""
                if text.strip():
                    yield text.rstrip("\n")


# -- docker auth (reference: pkg/devspace/docker/{auth,config}.go) ----------
def load_docker_auths(config_path: Optional[str] = None) -> dict[str, dict]:
    """Parse ~/.docker/config.json auths into {registry: authconfig}."""
    path = config_path or os.path.join(
        os.environ.get("DOCKER_CONFIG", os.path.expanduser("~/.docker")),
        "config.json",
    )
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    out: dict[str, dict] = {}
    for registry, entry in (data.get("auths") or {}).items():
        auth = dict(entry)
        if auth.get("auth"):
            try:
                user, _, pw = base64.b64decode(auth["auth"]).decode().partition(":")
                auth["username"], auth["password"] = user, pw
            except Exception:  # noqa: BLE001 — malformed entry
                pass
        out[registry] = auth
    cred_store = data.get("credsStore")
    if cred_store and not out:
        out.update(_auths_from_credstore(cred_store))
    return out


def save_docker_auth(
    registry: str,
    username: str,
    password: str,
    config_path: Optional[str] = None,
) -> str:
    """Persist a registry login into ~/.docker/config.json auths
    (reference: pkg/devspace/docker/auth.go:34 Login with
    ConfigFile.Save). Returns the path written."""
    path = config_path or os.path.join(
        os.environ.get("DOCKER_CONFIG", os.path.expanduser("~/.docker")),
        "config.json",
    )
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        data = {}
    auths = data.setdefault("auths", {})
    auths[registry] = {
        "auth": base64.b64encode(f"{username}:{password}".encode()).decode()
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # credentials file: owner-only, like the docker CLI writes it
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
    os.chmod(path, 0o600)
    return path


def _auths_from_credstore(store: str) -> dict[str, dict]:
    """Query a docker credential helper (best effort)."""
    helper = f"docker-credential-{store}"
    try:
        listing = subprocess.run(
            [helper, "list"], capture_output=True, timeout=10, check=True
        )
        servers = json.loads(listing.stdout or b"{}")
    except (OSError, subprocess.SubprocessError, ValueError):
        return {}
    out: dict[str, dict] = {}
    for server in servers:
        try:
            got = subprocess.run(
                [helper, "get"],
                input=server.encode(),
                capture_output=True,
                timeout=10,
                check=True,
            )
            cred = json.loads(got.stdout)
            out[server] = {
                "username": cred.get("Username", ""),
                "password": cred.get("Secret", ""),
                "serveraddress": server,
            }
        except (OSError, subprocess.SubprocessError, ValueError):
            continue
    return out


def registry_from_image(image: str) -> str:
    """Registry host from an image name (reference: registry/util.go:9)."""
    first = image.split("/")[0]
    if "." in first or ":" in first or first == "localhost":
        return first
    return "docker.io"
