"""Project scaffolding: language detection, Dockerfile and chart generation.

Reference: pkg/devspace/generator/generator.go — clones the template repo,
detects the project language via enry over source files (GetLanguage,
generator.go:33/140+), copies the ``_base`` + ``<language>`` chart template
into the project (CreateChart, 83-108). Ours ships templates in-package
(no git clone, no network) and adds the JAX/TPU flavor: a project with JAX
imports gets the TPU Dockerfile and the TPU slice chart.
"""

from __future__ import annotations

import os
import re
import shutil
from collections import Counter
from typing import Optional

from ..utils import log as logutil

TEMPLATES_DIR = os.path.join(os.path.dirname(__file__), "templates")

_EXT_LANG = {
    ".py": "python",
    ".js": "node",
    ".mjs": "node",
    ".ts": "node",
    ".go": "go",
}

_JAX_IMPORT = re.compile(
    r"^\s*(?:import|from)\s+(?:jax|flax|optax|orbax)\b", re.MULTILINE
)


def detect_language(project_dir: str, max_files: int = 500) -> str:
    """Extension-count language detection with a JAX sniff: any Python file
    importing jax/flax/optax promotes the project to 'jax'."""
    counts: Counter[str] = Counter()
    jax_found = False
    scanned = 0
    for root, dirs, files in os.walk(project_dir):
        dirs[:] = [
            d
            for d in dirs
            if d not in (".git", "node_modules", "__pycache__", ".devspace", "venv")
        ]
        for name in files:
            ext = os.path.splitext(name)[1].lower()
            lang = _EXT_LANG.get(ext)
            if not lang:
                continue
            counts[lang] += 1
            scanned += 1
            if lang == "python" and not jax_found:
                try:
                    with open(
                        os.path.join(root, name), "r", encoding="utf-8", errors="ignore"
                    ) as fh:
                        if _JAX_IMPORT.search(fh.read(65536)):
                            jax_found = True
                except OSError:
                    pass
            if scanned >= max_files:
                break
        if scanned >= max_files:
            break
    if jax_found:
        return "jax"
    if not counts:
        return "python"
    return counts.most_common(1)[0][0]


def create_dockerfile(
    project_dir: str, language: str, logger: Optional[logutil.Logger] = None
) -> str:
    """Copy the language's Dockerfile template unless one exists."""
    log = logger or logutil.get_logger()
    dest = os.path.join(project_dir, "Dockerfile")
    if os.path.exists(dest):
        log.info("[init] keeping existing Dockerfile")
        return dest
    src = os.path.join(TEMPLATES_DIR, "dockerfiles", language, "Dockerfile")
    if not os.path.isfile(src):
        src = os.path.join(TEMPLATES_DIR, "dockerfiles", "python", "Dockerfile")
    shutil.copyfile(src, dest)
    log.done("[init] created Dockerfile (%s)", language)
    return dest


def create_chart(
    project_dir: str,
    language: str,
    logger: Optional[logutil.Logger] = None,
) -> str:
    """Copy the chart template (TPU slice chart for jax, plain chart
    otherwise) into ``<project>/chart`` (reference: CreateChart)."""
    log = logger or logutil.get_logger()
    dest = os.path.join(project_dir, "chart")
    if os.path.isdir(dest):
        log.info("[init] keeping existing chart/")
        return dest
    flavor = "chart-tpu" if language == "jax" else "chart-cpu"
    shutil.copytree(os.path.join(TEMPLATES_DIR, flavor), dest)
    log.done("[init] created chart/ (%s)", flavor)
    return dest
