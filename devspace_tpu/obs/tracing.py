"""Distributed tracing: real span model + engine timeline profiler.

ISSUE 8 tentpole. Three layers, smallest first:

- **SpanContext** — W3C Trace Context identity (128-bit ``trace_id``,
  64-bit ``span_id``) with ``traceparent`` encode/decode. The header is
  the ONLY thing that crosses a process boundary (HTTP request into
  serve.py, the sync session's remote-exec boundary), so the parse is
  strict: a malformed header yields ``None`` and the receiver starts a
  fresh trace rather than propagating garbage ids.

- **Tracer** — owns a thread-local context stack and a bounded ring of
  finished :class:`Span` records. Spans nest per thread; an explicit
  ``context=`` argument re-attaches a context captured in another
  thread (the sync fan-out pool) or another process (a parsed
  ``traceparent``). The clock and the id source are injectable so the
  golden parentage tests assert exact ids and durations.
  ``utils/trace.py`` keeps its old API as a shim over this layer: its
  ``span()`` delegates id/parent management here and mirrors the
  legacy dict shape into its own ring.

- **TimelineRecorder** — the on-demand engine profiler's event sink.
  While attached (``engine.start_timeline()`` / ``/debug/trace``),
  the serving loop's phases land on named Chrome-trace tracks —
  device decode chunks per window lane, host scheduling, readback
  waits, tier restores, prefill chunks — so the overlapped
  dispatcher's concurrency is *visually verifiable*: decode lanes and
  the host-sched lane overlap in wall time in ``chrome://tracing`` /
  Perfetto. Off (the default) it is a single ``is None`` check per
  hook site — nothing on the hot path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Iterator, Optional

# (name, kind, help) — lintable catalog (scripts/metrics_lint.py).
# trace_spans_dropped_total stays in utils/trace.py (its ring, its
# counter); these cover the new layer: span volume and timeline exports.
TRACING_METRIC_FAMILIES = (
    (
        "trace_spans_started_total",
        "counter",
        "Spans opened on the process-wide tracer",
        "sum",
    ),
    (
        "trace_timeline_exports_total",
        "counter",
        "Engine timeline captures rendered to Chrome-trace JSON",
        "sum",
    ),
)

_FLAG_SAMPLED = "01"


def new_trace_id(rand: Callable[[int], bytes] = os.urandom) -> str:
    """128-bit lowercase-hex trace id (W3C: all-zero is invalid)."""
    tid = rand(16).hex()
    return tid if int(tid, 16) else new_trace_id(rand)


def new_span_id(rand: Callable[[int], bytes] = os.urandom) -> str:
    """64-bit lowercase-hex span id (W3C: all-zero is invalid)."""
    sid = rand(8).hex()
    return sid if int(sid, 16) else new_span_id(rand)


def derive_span_id(parent_span_id: str, name: str) -> str:
    """Deterministic child span id — a pure function of (parent id,
    child name), so replays and the golden parentage tests get stable
    ids without threading an id source everywhere."""
    import hashlib

    return hashlib.blake2b(
        f"{parent_span_id}/{name}".encode(), digest_size=8
    ).hexdigest()


def _is_hex(s: str) -> bool:
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


class SpanContext:
    """Immutable (trace_id, span_id) identity pair."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"SpanContext({self.trace_id}, {self.span_id})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SpanContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id))

    def to_traceparent(self) -> str:
        """W3C header: ``00-<trace_id>-<span_id>-01``."""
        return f"00-{self.trace_id}-{self.span_id}-{_FLAG_SAMPLED}"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["SpanContext"]:
        """Strict W3C parse; ``None`` for anything malformed (the caller
        then starts a fresh trace — never propagate a bad id)."""
        if not header or not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if len(version) != 2 or not _is_hex(version) or version == "ff":
            return None
        if len(trace_id) != 32 or not _is_hex(trace_id) or not int(trace_id, 16):
            return None
        if len(span_id) != 16 or not _is_hex(span_id) or not int(span_id, 16):
            return None
        if len(flags) != 2 or not _is_hex(flags):
            return None
        if trace_id != trace_id.lower() or span_id != span_id.lower():
            return None
        return cls(trace_id, span_id)

    @classmethod
    def generate(cls, rand: Callable[[int], bytes] = os.urandom) -> "SpanContext":
        return cls(new_trace_id(rand), new_span_id(rand))


class Span:
    """One finished-or-running span. ``start`` is wall-clock seconds;
    ``duration_s`` is filled at close from the tracer's perf clock."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start",
        "duration_s", "track", "attrs", "ok", "error", "_t0",
    )

    def __init__(
        self,
        name: str,
        context: SpanContext,
        parent_id: Optional[str],
        start: float,
        track: str = "main",
        attrs: Optional[dict] = None,
    ):
        self.name = name
        self.trace_id = context.trace_id
        self.span_id = context.span_id
        self.parent_id = parent_id
        self.start = start
        self.duration_s: Optional[float] = None
        self.track = track
        self.attrs = attrs if attrs is not None else {}
        self.ok: Optional[bool] = None
        self.error: Optional[str] = None
        self._t0: float = 0.0

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_id,
            "start": self.start,
            "duration_s": self.duration_s,
            "track": self.track,
            "ok": self.ok,
        }
        if self.error:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class Tracer:
    """Thread-local span stack + bounded keep-newest ring of finished
    spans. One process-wide instance (:func:`get_tracer`); tests build
    private ones with deterministic clocks and id sources."""

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        perf: Callable[[], float] = time.perf_counter,
        ring: int = 2048,
        rand: Callable[[int], bytes] = os.urandom,
    ):
        self.clock = clock
        self.perf = perf
        self.rand = rand
        self._ring_size = ring
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.started = 0  # trace_spans_started_total
        self.dropped = 0

    # -- context -----------------------------------------------------------
    def _stack(self) -> list[SpanContext]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    def current_context(self) -> Optional[SpanContext]:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_traceparent(self) -> Optional[str]:
        ctx = self.current_context()
        return ctx.to_traceparent() if ctx else None

    @contextmanager
    def attach(self, context: Optional[SpanContext]) -> Iterator[None]:
        """Activate an externally-captured context on THIS thread without
        recording a span — the re-attachment primitive for thread pools
        (sync fan-out) and retry loops (resilience/policy.py). A None
        context is a no-op, so call sites don't need to branch."""
        if context is None:
            yield
            return
        stack = self._stack()
        stack.append(context)
        try:
            yield
        finally:
            stack.pop()

    # -- spans -------------------------------------------------------------
    def start_span(
        self,
        name: str,
        context: Optional[SpanContext] = None,
        track: str = "main",
        attrs: Optional[dict] = None,
        push: bool = True,
    ) -> Span:
        """Open a span and push its context; pair with :meth:`end_span`
        (use :meth:`span` unless the open/close sites are in different
        scopes, like the per-request serving lifecycle). ``push=False``
        creates a DETACHED span — not on any thread's stack — for spans
        that outlive their opening thread (a sync session's root);
        children attach its ``.context`` explicitly."""
        parent = context if context is not None else self.current_context()
        if parent is not None:
            ctx = SpanContext(parent.trace_id, new_span_id(self.rand))
            parent_id = parent.span_id
        else:
            ctx = SpanContext.generate(self.rand)
            parent_id = None
        sp = Span(name, ctx, parent_id, self.clock(), track=track, attrs=attrs)
        sp._t0 = self.perf()
        if push:
            self._stack().append(ctx)
        self.started += 1
        return sp

    def end_span(
        self, sp: Span, ok: bool = True, error: Optional[str] = None
    ) -> None:
        sp.duration_s = round(self.perf() - sp._t0, 6)
        sp.ok = ok
        sp.error = error
        stack = self._stack()
        if stack and stack[-1].span_id == sp.span_id:
            stack.pop()
        else:  # closed out of order (cross-thread end): scrub, don't leak
            for i in range(len(stack) - 1, -1, -1):
                if stack[i].span_id == sp.span_id:
                    del stack[i]
                    break
        self._record(sp)

    @contextmanager
    def span(
        self,
        name: str,
        context: Optional[SpanContext] = None,
        track: str = "main",
        **attrs: Any,
    ) -> Iterator[Span]:
        """Context-manager form; exceptions mark the span failed and
        propagate."""
        sp = self.start_span(name, context=context, track=track, attrs=attrs)
        try:
            yield sp
        except BaseException as e:
            self.end_span(sp, ok=False, error=f"{type(e).__name__}: {e}")
            raise
        else:
            self.end_span(sp, ok=True)

    def _record(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp)
            evicted = len(self._spans) - self._ring_size
            if evicted > 0:
                self.dropped += evicted
                del self._spans[:evicted]

    # -- views -------------------------------------------------------------
    def recent(self, limit: int = 50) -> list[Span]:
        with self._lock:
            return list(self._spans[-limit:])

    def find(self, trace_id: str) -> list[Span]:
        """All ring-resident spans of one trace, oldest first."""
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def current_traceparent() -> Optional[str]:
    """The default tracer's active context as a ``traceparent`` header
    (None outside any span) — what call sites inject at process/exec
    boundaries."""
    return _default_tracer.current_traceparent()


# -- engine timeline profiler ----------------------------------------------

# Canonical lane (Chrome ``tid``) names the serving-loop profiler emits.
# Device decode gets one lane per dispatch-window position so overlapping
# chunks render side by side instead of merging into one bar.
TRACK_HOST_SCHED = "host sched"
TRACK_READBACK = "readback wait"
TRACK_TIER_RESTORE = "tier restore"
TRACK_PREFILL = "prefill"
TRACK_SPEC = "spec round"
TRACK_REQUESTS = "serving"

TIMELINE_TRACKS = (
    TRACK_HOST_SCHED,
    TRACK_READBACK,
    TRACK_TIER_RESTORE,
    TRACK_PREFILL,
    TRACK_SPEC,
    TRACK_REQUESTS,
)


def device_decode_track(lane: int) -> str:
    """Lane name for a dispatch-window position (0..depth-1)."""
    return f"device decode/{int(lane)}"


class TimelineRecorder:
    """Bounded event sink for one capture window. ``add`` is called from
    the scheduler thread (and dispatch drains) with ``time.monotonic``
    endpoints; ``chrome()`` rebases onto the capture's wall-clock start.
    Appends are GIL-atomic list ops — no lock on the recording path."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = max_events
        self.events: list[dict] = []
        self.dropped = 0
        self._wall0 = time.time()
        self._mono0 = time.monotonic()

    def add(
        self, track: str, name: str, t0: float, t1: float, **args: Any
    ) -> None:
        """One complete event on ``track`` spanning monotonic [t0, t1]."""
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(
            {"track": track, "name": name, "t0": t0, "t1": t1, "args": args}
        )

    def chrome(self) -> dict:
        """Chrome-trace JSON object (``chrome://tracing`` / Perfetto).
        Every event lands on its named track (string ``tid``); a
        malformed track name is an exporter bug, rejected loudly."""
        events = []
        for e in self.events:
            track = e["track"]
            if not isinstance(track, str) or not track.strip():
                raise ValueError(
                    f"timeline event {e['name']!r} has an unnamed track"
                )
            events.append(
                {
                    "name": e["name"],
                    "cat": "engine",
                    "ph": "X",
                    "ts": (e["t0"] - self._mono0) * 1e6,
                    "dur": max(0.0, (e["t1"] - e["t0"]) * 1e6),
                    "pid": 1,
                    "tid": track,
                    "args": e["args"],
                }
            )
        # process/thread metadata so the lanes render with their names
        # in a stable order
        tracks = sorted({e["tid"] for e in events})
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "args": {"name": "devspace-tpu engine"},
            }
        ]
        for i, tr in enumerate(tracks):
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": tr,
                    "args": {"name": tr},
                }
            )
            meta.append(
                {"name": "thread_sort_index", "ph": "M", "pid": 1,
                 "tid": tr, "args": {"sort_index": i}}
            )
        global _timeline_exports
        _timeline_exports += 1
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {
                "capture_wall_start": self._wall0,
                "events": len(events),
                "dropped": self.dropped,
            },
        }

    def write_chrome(self, dest: str) -> int:
        doc = self.chrome()
        with open(dest, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        return len(doc["traceEvents"])


def lint_tracks(extra_depth: int = 8) -> list[str]:
    """Track-catalog lint (scripts/metrics_lint.py): every declared lane
    name must be nonempty and unique — a duplicated ``tid`` silently
    merges two semantic lanes in the Chrome UI; an empty one renders as
    an anonymous row. Checks the static catalog plus the dynamic decode
    lanes up to ``extra_depth``."""
    problems: list[str] = []
    names = list(TIMELINE_TRACKS) + [
        device_decode_track(i) for i in range(extra_depth)
    ]
    seen: set[str] = set()
    for n in names:
        if not isinstance(n, str) or not n.strip():
            problems.append(f"timeline track {n!r}: unnamed track")
            continue
        if n in seen:
            problems.append(f"timeline track {n!r}: duplicated track name")
        seen.add(n)
    return problems


_timeline_exports = 0


def _register_metrics() -> None:
    try:
        from .metrics import get_registry

        reg = get_registry()
        spans_name, _, spans_help, _agg = TRACING_METRIC_FAMILIES[0]
        exports_name, _, exports_help, _agg = TRACING_METRIC_FAMILIES[1]
        reg.register_callback(
            spans_name, "counter", spans_help,
            lambda: _default_tracer.started,
        )
        reg.register_callback(
            exports_name, "counter", exports_help,
            lambda: _timeline_exports,
        )
    except Exception:  # noqa: BLE001 — metrics are optional here
        pass


_register_metrics()
