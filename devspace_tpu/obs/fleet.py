"""Fleet federation math: parse, merge and re-render telemetry from
many processes (ISSUE 10).

The per-process observability stack (metrics ISSUE 6, traces ISSUE 8,
events/SLO ISSUE 9) ends at each process's ``/metrics``; the ROADMAP's
next steps — multi-replica routing, slice-wide SPMD sessions, closed-
loop autoscaling — all need *one* view over N of them. This module is
the pure-function half of that view (obs/collector.py owns the I/O):

- :func:`parse_exposition` — Prometheus text 0.0.4 back into a
  :meth:`Registry.snapshot`-shaped dict, reconstructing histograms from
  their ``_bucket``/``_sum``/``_count`` series. Strict: a truncated or
  garbage document raises :class:`ExpositionParseError` so the
  collector can count it and quarantine the target instead of
  federating nonsense.
- :func:`merge_snapshots` — the federation step. Counters sum.
  Histograms merge *exactly*, bucket-by-bucket (every latency histogram
  in the repo shares ``DEFAULT_LATENCY_BUCKETS``, so fleet-level
  TTFT/e2e SLOs evaluate over the merged distribution with the stock
  burn-rate engine — no quantile approximation). Gauges merge per the
  aggregation hint their family declares (the last element of every
  ``*_METRIC_FAMILIES`` tuple): ``sum`` for capacity/occupancy totals,
  ``max`` for worst-state signals like ``slo_status``, ``avg`` for
  already-averaged ratios, ``last`` for take-the-newest.
- :func:`stitch_chrome_trace` — cross-process trace stitching: span
  rings collected from each worker join on ``trace_id`` into one
  Chrome-trace JSON with a process lane per worker (spans carry wall-
  clock starts, so lanes line up to clock skew).

Dependency-free like the rest of obs/: the whole Prometheus wire format
round-trip stays ~200 lines instead of a client_golang port.
"""

from __future__ import annotations

import re
from typing import Iterable, Optional

from .metrics import render_snapshot  # noqa: F401  (re-exported: fleet render)

# The closed set of aggregation hints a metric family may declare.
FLEET_AGG_KINDS = ("sum", "max", "avg", "last")

# Fallback for families scraped off a target whose catalog this process
# does not know (version skew, third-party exporters). Summing is the
# Prometheus-federation default for fleet totals; merge notes name every
# family that fell back so the skew is visible, not silent.
DEFAULT_AGG = "sum"


def family_agg(fam) -> str:
    """Aggregation hint of one ``*_METRIC_FAMILIES`` entry — by
    convention the last element of the tuple."""
    hint = fam[-1]
    if hint not in FLEET_AGG_KINDS:
        raise ValueError(
            f"family {fam[0]!r} declares aggregation hint {hint!r}; "
            f"want one of {FLEET_AGG_KINDS}"
        )
    return hint


def aggregation_hints() -> dict[str, str]:
    """``{family_name: hint}`` over every catalog in the repo.

    Lazily imports each subsystem's catalog and tolerates import
    failures (the engine catalog pulls jax; a CPU-only collector box
    may not have it) — a missing catalog just means those families
    merge under :data:`DEFAULT_AGG` with a note.
    """
    hints: dict[str, str] = {}
    loaders = (
        ("devspace_tpu.inference.engine", "ENGINE_METRIC_FAMILIES"),
        ("devspace_tpu.obs.request_trace", "SERVING_METRIC_FAMILIES"),
        ("devspace_tpu.sync.session", "SYNC_METRIC_FAMILIES"),
        ("devspace_tpu.resilience.policy", "RESILIENCE_METRIC_FAMILIES"),
        ("devspace_tpu.utils.trace", "TRACE_METRIC_FAMILIES"),
        ("devspace_tpu.obs.tracing", "TRACING_METRIC_FAMILIES"),
        ("devspace_tpu.obs.events", "EVENTS_METRIC_FAMILIES"),
        ("devspace_tpu.obs.slo", "SLO_METRIC_FAMILIES"),
        ("devspace_tpu.obs.collector", "COLLECTOR_METRIC_FAMILIES"),
    )
    import importlib

    for mod_name, attr in loaders:
        try:
            catalog = getattr(importlib.import_module(mod_name), attr)
        except Exception:  # noqa: BLE001 — optional catalog (e.g. no jax)
            continue
        for fam in catalog:
            hints[fam[0]] = family_agg(fam)
    return hints


class ExpositionParseError(ValueError):
    """The scraped document is not well-formed Prometheus text 0.0.4."""


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(\{(.*)\})?"  # optional label block
    r"\s+(\S+)"  # value
    r"(\s+\S+)?\s*$"  # optional timestamp (ignored)
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def _parse_labels(block: str) -> dict:
    labels: dict[str, str] = {}
    pos = 0
    block = block.strip()
    while pos < len(block):
        m = _LABEL_RE.match(block, pos)
        if m is None:
            raise ExpositionParseError(f"bad label block: {block!r}")
        labels[m.group(1)] = _unescape(m.group(2))
        pos = m.end()
        if pos < len(block):
            if block[pos] != ",":
                raise ExpositionParseError(f"bad label block: {block!r}")
            pos += 1
    return labels


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    try:
        return float(s)
    except ValueError as e:
        raise ExpositionParseError(f"bad sample value {s!r}") from e


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def parse_exposition(text: str) -> dict:
    """Prometheus text 0.0.4 -> ``Registry.snapshot()``-shaped dict.

    Histograms are reconstructed from their ``_bucket``/``_sum``/
    ``_count`` series per label-set; a histogram missing any of the
    three, with a non-monotone cumulative sequence, or without a
    ``+Inf`` bucket raises — partial documents (a target dying mid-
    response) must quarantine the target, not corrupt the merge.
    """
    kinds: dict[str, str] = {}
    helps: dict[str, str] = {}
    # family -> labels_key -> scalar value  (non-histogram)
    scalars: dict[str, dict[tuple, tuple[dict, float]]] = {}
    # family -> labels_key -> {"buckets": {le: cum}, "sum": x, "count": n}
    hists: dict[str, dict[tuple, dict]] = {}

    def hist_family(sample_name: str) -> Optional[tuple[str, str]]:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if kinds.get(base) == "histogram":
                    return base, suffix
        return None

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                kind = parts[3].split()[0] if len(parts) > 3 else ""
                if kind not in ("counter", "gauge", "histogram", "untyped",
                                "summary"):
                    raise ExpositionParseError(f"bad TYPE line: {line!r}")
                kinds[parts[2]] = kind
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue  # other comments are legal and ignored
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionParseError(f"bad sample line: {line!r}")
        name, _, label_block, value_s = m.group(1), m.group(2), m.group(3), m.group(4)
        labels = _parse_labels(label_block) if label_block else {}
        value = _parse_value(value_s)
        hf = hist_family(name)
        if hf is not None:
            base, suffix = hf
            le = None
            if suffix == "_bucket":
                if "le" not in labels:
                    raise ExpositionParseError(
                        f"histogram bucket without le label: {line!r}"
                    )
                le = _parse_value(labels.pop("le"))
            key = _labels_key(labels)
            h = hists.setdefault(base, {}).setdefault(
                key, {"labels": labels, "buckets": {}, "sum": None,
                      "count": None}
            )
            if suffix == "_bucket":
                h["buckets"][le] = value
            elif suffix == "_sum":
                h["sum"] = value
            else:
                h["count"] = value
            continue
        key = _labels_key(labels)
        scalars.setdefault(name, {})[key] = (labels, value)

    out: dict[str, dict] = {}
    for name, by_key in scalars.items():
        kind = kinds.get(name)
        if kind in (None, "untyped"):
            kind = "counter" if name.endswith("_total") else "gauge"
        out[name] = {
            "kind": kind,
            "help": helps.get(name, ""),
            "samples": [by_key[k] for k in sorted(by_key)],
        }
    for name, by_key in hists.items():
        samples = []
        for key in sorted(by_key):
            h = by_key[key]
            if not h["buckets"] or h["sum"] is None or h["count"] is None:
                raise ExpositionParseError(
                    f"histogram {name}{dict(key)!r} is missing bucket/sum/"
                    "count series (truncated document?)"
                )
            edges = sorted(h["buckets"])
            if edges[-1] != float("inf"):
                raise ExpositionParseError(
                    f"histogram {name} has no +Inf bucket"
                )
            cums = [h["buckets"][le] for le in edges]
            if any(b < a for a, b in zip(cums, cums[1:])):
                raise ExpositionParseError(
                    f"histogram {name} buckets are not cumulative"
                )
            if cums[-1] != h["count"]:
                raise ExpositionParseError(
                    f"histogram {name}: +Inf bucket {cums[-1]} != "
                    f"count {h['count']}"
                )
            samples.append(
                (h["labels"],
                 {"buckets": list(zip(edges, cums)),
                  "sum": h["sum"], "count": h["count"]})
            )
        out[name] = {
            "kind": "histogram",
            "help": helps.get(name, ""),
            "samples": samples,
        }
    return out


def _merge_hist(acc: dict, val: dict) -> bool:
    """Bucket-wise exact merge of one histogram sample into ``acc``;
    False (and acc untouched) when the bucket edges differ."""
    if [le for le, _ in acc["buckets"]] != [le for le, _ in val["buckets"]]:
        return False
    acc["buckets"] = [
        (le, a + b)
        for (le, a), (_, b) in zip(acc["buckets"], val["buckets"])
    ]
    acc["sum"] += val["sum"]
    acc["count"] += val["count"]
    return True


def merge_snapshots(
    snapshots: Iterable[dict],
    hints: Optional[dict[str, str]] = None,
) -> tuple[dict, list[str]]:
    """Federate N ``Registry.snapshot()``-shaped dicts into one.

    ``snapshots`` iterate oldest-scrape-first: the ``last`` hint keeps
    the final value seen. Returns ``(merged, notes)`` where notes name
    every family that merged degraded (kind conflict, bucket-edge
    mismatch, unknown family defaulting to :data:`DEFAULT_AGG`) — the
    collector exposes them on ``/debug/fleet`` so skew is diagnosable.
    """
    hints = hints if hints is not None else aggregation_hints()
    merged: dict[str, dict] = {}
    # family -> labels_key -> list of values (for avg) / merged value
    notes: list[str] = []
    noted: set[str] = set()

    def note(msg: str) -> None:
        if msg not in noted:
            noted.add(msg)
            notes.append(msg)

    acc: dict[str, dict[tuple, list]] = {}
    for snap in snapshots:
        for name, fam in snap.items():
            kind = fam["kind"]
            cur = merged.get(name)
            if cur is None:
                merged[name] = {"kind": kind, "help": fam["help"],
                                "samples": []}
                acc[name] = {}
            elif cur["kind"] != kind:
                note(
                    f"{name}: kind conflict ({cur['kind']} vs {kind}); "
                    "dropping the divergent target's series"
                )
                continue
            agg = hints.get(name)
            if agg is None and kind == "gauge":
                note(f"{name}: no declared aggregation hint; using "
                     f"{DEFAULT_AGG}")
                agg = DEFAULT_AGG
            for labels, val in fam["samples"]:
                key = _labels_key(labels)
                slot = acc[name].get(key)
                if slot is None:
                    if kind == "histogram":
                        val = {"buckets": list(val["buckets"]),
                               "sum": val["sum"], "count": val["count"]}
                        acc[name][key] = [labels, val]
                    else:
                        acc[name][key] = [labels, [float(val)]]
                    continue
                if kind == "histogram":
                    if not _merge_hist(slot[1], val):
                        note(
                            f"{name}: bucket-edge mismatch; dropping the "
                            "divergent target's series"
                        )
                else:
                    slot[1].append(float(val))

    for name, by_key in acc.items():
        kind = merged[name]["kind"]
        agg = hints.get(name, DEFAULT_AGG)
        samples = []
        for key in sorted(by_key):
            labels, val = by_key[key]
            if kind == "histogram":
                samples.append((labels, val))
            elif kind == "counter" or agg == "sum":
                samples.append((labels, sum(val)))
            elif agg == "max":
                samples.append((labels, max(val)))
            elif agg == "avg":
                samples.append((labels, sum(val) / len(val)))
            else:  # "last" — snapshots iterate oldest-first
                samples.append((labels, val[-1]))
        merged[name]["samples"] = samples
    return merged, notes


# -- cross-process trace stitching ------------------------------------------
def stitch_chrome_trace(
    spans_by_process: dict[str, list[dict]],
    trace_id: Optional[str] = None,
) -> dict:
    """Join span rings from N processes into one Chrome-trace JSON.

    ``spans_by_process`` maps a process label (target name/URL) to its
    span dicts (:meth:`Span.to_dict` shape — wall-clock ``start``
    seconds + ``duration_s``). Each process gets its own ``pid`` lane
    with a ``process_name`` metadata row; tracks within a process
    become named ``tid`` rows. ``trace_id`` filters to one request's
    spans across every lane — the "where did my request go" view.
    Load the result in chrome://tracing or Perfetto.
    """
    events: list[dict] = []
    for pid, process in enumerate(sorted(spans_by_process), start=1):
        spans = spans_by_process[process] or []
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": process},
        })
        tids: dict[str, int] = {}
        for span in spans:
            track = str(span.get("track") or "spans")
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                events.append({
                    "ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": track},
                })
                events.append({
                    "ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_sort_index",
                    "args": {"sort_index": tid},
                })
            args = {
                "trace_id": span.get("trace_id"),
                "span_id": span.get("span_id"),
                "ok": span.get("ok", True),
            }
            if span.get("parent_span_id"):
                args["parent_span_id"] = span["parent_span_id"]
            if span.get("error"):
                args["error"] = span["error"]
            args.update(span.get("attrs") or {})
            events.append({
                "ph": "X", "pid": pid, "tid": tid,
                "name": span.get("name", "span"),
                "ts": float(span.get("start", 0.0)) * 1e6,
                "dur": max(0.0, float(span.get("duration_s", 0.0))) * 1e6,
                "cat": str(span.get("track") or "spans"),
                "args": args,
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "stitched": True,
            "processes": sorted(spans_by_process),
            **({"trace_id": trace_id} if trace_id else {}),
        },
    }
