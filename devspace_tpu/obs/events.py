"""Structured, trace-correlated events (ISSUE 9) — the third
observability pillar after metrics (ISSUE 6) and traces (ISSUE 8).

Metrics say *how much*, traces say *where time went*; events say *what
happened*: the discrete lifecycle edges the engine / dispatcher / KV
tier / sync session / supervisor already handle but until now only
printed or counted (admit, preempt, poisoned window, spill, quarantine,
circuit-open, ...). Each :class:`Event` is auto-stamped with the
current ``trace_id``/``span_id`` from the ISSUE 8 tracer so an operator
can pivot from "what happened" straight to the request trace and
timeline that explain it.

Design constraints, in order:

1. **One branch when nothing listens.** ``emit()`` reads the sink tuple
   once and returns immediately when it is empty — event call sites can
   live on scheduler-thread paths without a measurable tax (covered by
   the <=2% serving-bench overhead guard in bench.py).
2. **Sinks are dumb and swappable.** A sink is anything with a
   ``record(event)`` method. The bus stores them in an immutable tuple
   swapped under a lock, so ``emit`` never locks; a raising sink is
   counted (``events_dropped_total``) and never breaks the emitter.
3. **Background threads lack request context.** The tracer's context
   stack is thread-local and the scheduler / monitor threads never see
   the HTTP thread's stack, so call sites that know their request pass
   ``trace_id=`` explicitly; auto-stamping is the fallback, not the
   only path.
4. **Names are machine-checked.** ``EVENT_CATALOG`` is the closed set
   of (subsystem, name) pairs; scripts/metrics_lint.py enforces
   snake_case and known subsystems the same way it lints metric
   families, so a typo'd event name fails in CI, not in an incident.
"""

from __future__ import annotations

import io
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

from .metrics import get_registry
from .tracing import get_tracer

_LEVELS = ("debug", "info", "warn", "error")

# The known subsystems — an event outside this set fails the lint, and
# FlightRecorder rings are keyed by it.
EVENT_SUBSYSTEMS = (
    "cli",
    "dispatch",
    "engine",
    "fleet",
    "kv_tier",
    "resilience",
    "router",
    "serving",
    "slo",
    "supervisor",
    "sync",
)

# The closed event-name catalog: (subsystem, name, help). Linted by
# scripts/metrics_lint.py (snake_case names, known subsystem, unique
# pairs). Instrumentation sites emit ONLY names listed here.
EVENT_CATALOG = (
    ("cli", "log", "Leveled CLI log line routed through the event pipeline"),
    ("dispatch", "depth_change", "In-flight decode window count changed"),
    ("dispatch", "window_abandoned", "Queued dispatch windows dropped on abandon"),
    ("engine", "admit", "Request admitted to a decode slot"),
    ("engine", "preempt", "Lowest-priority slot preempted back to the queue"),
    ("engine", "poisoned_window", "Dispatched decode window raised; pool reset"),
    ("engine", "fail_outstanding", "Engine failing all outstanding requests"),
    ("engine", "request_failed", "One request failed (admission, prefill or decode)"),
    ("fleet", "scale_up", "Fleet manager adding replicas toward a higher target size"),
    ("fleet", "scale_down", "Fleet manager draining and removing surplus replicas"),
    ("fleet", "replica_started", "Fleet replica spawned and passed its readiness probe"),
    ("fleet", "replica_restarted", "Dead fleet replica restarted under the retry policy"),
    ("fleet", "replica_removed", "Fleet replica drained and terminated during scale-down"),
    ("kv_tier", "spill", "Evicted prefix blocks spilled to a lower KV tier"),
    ("kv_tier", "restore", "Spilled prefix blocks restored into the device pool"),
    ("kv_tier", "restore_fallback", "Tier restore failed; prefix recomputed"),
    ("kv_tier", "corrupt_drop", "Tier payload failed checksum and was dropped"),
    ("kv_tier", "migrate", "KV chain pulled from a peer replica and imported"),
    ("kv_tier", "migrate_failed", "KV chain pull failed; degrading to recompute-prefill"),
    ("kv_tier", "migrate_export", "KV chain envelope served to a peer replica"),
    ("resilience", "circuit_open", "Circuit breaker opened after repeated failures"),
    ("resilience", "circuit_close", "Circuit breaker closed after a probe success"),
    ("resilience", "retries_exhausted", "Retry policy gave up after max attempts"),
    ("router", "request_routed", "Gateway routed a request to a replica"),
    ("router", "spillover", "Request steered off its best prefix holder (it was hot)"),
    ("router", "request_rejected", "SLO-aware admission shed a request (breach band)"),
    ("router", "retry_rerouted", "Request rerouted after its replica failed before first byte"),
    ("router", "prefill_dispatched", "Two-phase placement: prompt prefilled on a separate replica"),
    ("router", "prefill_failed", "Phase-1 prefill call failed; degrading to unified placement"),
    ("serving", "drain_started", "Serving process entered drain mode (readyz 503, healthz live)"),
    ("serving", "drain_cleared", "Serving process left drain mode and readmits traffic"),
    ("slo", "warn", "SLO burn rate crossed the warn threshold"),
    ("slo", "breach", "SLO burn rate crossed the breach threshold"),
    ("slo", "recovered", "SLO returned to ok from warn/breach"),
    ("supervisor", "started", "Supervised service started"),
    ("supervisor", "died", "Supervised service died"),
    ("supervisor", "restarting", "Supervisor restarting a dead service"),
    ("supervisor", "restarted", "Supervised service restarted successfully"),
    ("supervisor", "degraded", "Service exceeded restart budget; running degraded"),
    ("supervisor", "budget_reset", "Service stayed healthy past its window; restart budget reset"),
    ("supervisor", "failed", "Supervised service failed permanently"),
    ("supervisor", "exited", "Supervised service exited cleanly"),
    ("supervisor", "stopped", "Supervisor stopped a service"),
    ("sync", "worker_quarantined", "Sync worker quarantined after repeated failures"),
    ("sync", "worker_revived", "Quarantined sync worker revived after probe"),
)

EVENTS_METRIC_FAMILIES = (
    ("events_emitted_total", "counter",
     "Structured events fanned out to at least one sink", "sum"),
    ("events_dropped_total", "counter",
     "Structured events a sink raised on (sink bug, full disk, ...)", "sum"),
)

# Keys owned by the envelope; attrs may not shadow them. "msg" stays an
# attr on purpose: utils/log.py writes {"time","level","msg",...} lines
# through this pipeline and downstream scrapers key on those three.
_RESERVED_KEYS = ("time", "level", "subsystem", "event", "trace_id", "span_id")


# Process-wide emission order. time.time() has finite resolution, so
# two events in one scheduler iteration often share a timestamp; the
# seq is the tie-break that keeps dump ordering stable (ISSUE 10).
_event_seq = itertools.count(1)


class Event:
    """One structured event. Immutable by convention; ``attrs`` is the
    free-form payload (small, JSON-serializable values only)."""

    __slots__ = ("ts", "level", "subsystem", "name", "attrs", "trace_id",
                 "span_id", "seq")

    def __init__(self, ts, level, subsystem, name, attrs=None,
                 trace_id=None, span_id=None, seq=None):
        self.ts = float(ts)
        self.level = level
        self.subsystem = subsystem
        self.name = name
        self.attrs = attrs or {}
        self.trace_id = trace_id
        self.span_id = span_id
        self.seq = int(seq) if seq is not None else next(_event_seq)

    def to_dict(self) -> dict:
        d = {
            "time": self.ts,
            "seq": self.seq,
            "level": self.level,
            "subsystem": self.subsystem,
            "event": self.name,
        }
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.span_id:
            d["span_id"] = self.span_id
        for k, v in self.attrs.items():
            if k not in _RESERVED_KEYS:
                d[k] = v
        return d

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"Event({self.subsystem}.{self.name} level={self.level} "
                f"trace={self.trace_id} {self.attrs!r})")


def make_event(subsystem: str, name: str, level: str = "info",
               attrs: Optional[dict] = None,
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None,
               clock: Callable[[], float] = time.time) -> Event:
    """Build a trace-stamped :class:`Event` without touching any bus —
    the constructor for sinks that originate their own events (the
    rebuilt utils/log.py FileLogger). When no explicit ids are given,
    stamps the calling thread's current tracer context."""
    if trace_id is None:
        ctx = get_tracer().current_context()
        if ctx is not None:
            trace_id, span_id = ctx.trace_id, ctx.span_id
    return Event(clock(), level, subsystem, name, attrs, trace_id, span_id)


class FlightRecorder:
    """Bounded per-subsystem ring of recent events, dumpable on demand
    (``/debug/events``, ``debug bundle``) or on failure (the engine dumps
    it when a dispatch window poisons). Cheap enough to leave attached
    in production: append to a deque under a short lock."""

    def __init__(self, per_subsystem: int = 256):
        self.per_subsystem = max(1, int(per_subsystem))
        self._rings: dict[str, deque] = {}
        self._lock = threading.Lock()

    def record(self, event: Event) -> None:
        with self._lock:
            ring = self._rings.get(event.subsystem)
            if ring is None:
                ring = self._rings[event.subsystem] = deque(
                    maxlen=self.per_subsystem
                )
            ring.append(event)

    def dump(self, subsystem: Optional[str] = None,
             limit: Optional[int] = None) -> list[Event]:
        """Recent events, oldest first, across all rings (or one
        subsystem's), trimmed to the newest ``limit``."""
        with self._lock:
            if subsystem is not None:
                events = list(self._rings.get(subsystem, ()))
            else:
                events = [e for ring in self._rings.values() for e in ring]
        # (ts, seq): equal timestamps are common (time.time() resolution
        # vs a tight scheduler loop) and a bare ts sort is only stable
        # WITHIN one ring — merging rings interleaved same-ts events in
        # ring-dict order. seq pins emission order across rings.
        events.sort(key=lambda e: (e.ts, e.seq))
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return events

    def dump_dicts(self, subsystem: Optional[str] = None,
                   limit: Optional[int] = None) -> list[dict]:
        return [e.to_dict() for e in self.dump(subsystem, limit)]

    def subsystems(self) -> list[str]:
        with self._lock:
            return sorted(self._rings)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()


class JsonlSink:
    """Append events to a JSONL file with the same 10 MB open-time
    rotation as the historical utils/log.py FileLogger (which is now a
    wrapper over this sink)."""

    MAX_BYTES = 10 * 1024 * 1024

    def __init__(self, path: str, max_bytes: int = MAX_BYTES):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            if os.path.getsize(path) > max_bytes:
                os.replace(path, path + ".old")
        except OSError:
            pass
        self._fh: Optional[io.TextIOBase] = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    @property
    def closed(self) -> bool:
        return self._fh is None or self._fh.closed

    def record(self, event: Event) -> None:
        line = json.dumps(event.to_dict(), default=str)
        with self._lock:
            if self._fh is None or self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()


class EventBus:
    """Fan-out point for structured events. ``emit`` is the API call
    sites use; sinks (FlightRecorder, JsonlSink, test lists) attach and
    detach at runtime. With no sinks attached, ``emit`` is one attribute
    read and one falsy branch — nothing is allocated."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self._sinks: tuple = ()
        self._lock = threading.Lock()
        self.emitted = 0  # GIL-atomic int counters, scraped via callback
        self.dropped = 0

    # -- sink management ----------------------------------------------------
    def add_sink(self, sink):
        """Attach ``sink`` (anything with ``record(event)``); returns it
        for `bus.add_sink(FlightRecorder())` one-liners."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks = self._sinks + (sink,)
        return sink

    def remove_sink(self, sink) -> None:
        with self._lock:
            self._sinks = tuple(s for s in self._sinks if s is not sink)

    @property
    def active(self) -> bool:
        return bool(self._sinks)

    # -- emission -----------------------------------------------------------
    def emit(self, subsystem: str, name: str, level: str = "info",
             trace_id: Optional[str] = None, span_id: Optional[str] = None,
             **attrs) -> Optional[Event]:
        sinks = self._sinks
        if not sinks:  # the one branch when nothing listens
            return None
        if trace_id is None:
            ctx = get_tracer().current_context()
            if ctx is not None:
                trace_id, span_id = ctx.trace_id, ctx.span_id
        ev = Event(self._clock(), level, subsystem, name, attrs,
                   trace_id, span_id)
        self.publish(ev, _sinks=sinks)
        return ev

    def publish(self, event: Event, _sinks: Optional[tuple] = None) -> None:
        """Fan a prebuilt event out to the attached sinks (the path for
        events originated elsewhere, e.g. FileLogger lines)."""
        sinks = self._sinks if _sinks is None else _sinks
        if not sinks:
            return
        self.emitted += 1
        for s in sinks:
            try:
                s.record(event)
            except Exception:
                self.dropped += 1


# -- process-wide default bus ------------------------------------------------
_default_bus = EventBus()


def get_bus() -> EventBus:
    return _default_bus


def emit(subsystem: str, name: str, level: str = "info",
         trace_id: Optional[str] = None, span_id: Optional[str] = None,
         **attrs) -> Optional[Event]:
    """Emit on the process-default bus. Call sites import this once and
    call it unconditionally; the no-sink case is one branch inside."""
    bus = _default_bus
    if not bus._sinks:
        return None
    return bus.emit(subsystem, name, level=level,
                    trace_id=trace_id, span_id=span_id, **attrs)


def add_sink(sink):
    return _default_bus.add_sink(sink)


def remove_sink(sink) -> None:
    _default_bus.remove_sink(sink)


def events_enabled(explicit: Optional[bool] = None) -> bool:
    """Event pipeline on/off resolution, mirroring ``metrics_enabled``:
    explicit arg wins, then ``DEVSPACE_ENGINE_EVENTS`` (``off``/``0``/
    ... disables), default ON. Gates whether serve.py / bench.py attach
    sinks — emit sites themselves stay unconditional and free."""
    if explicit is not None:
        return bool(explicit)
    env = os.environ.get("DEVSPACE_ENGINE_EVENTS", "").strip().lower()
    return env not in ("off", "0", "false", "no")


def lint_catalog() -> list[str]:
    """Catalog validity errors ([] when clean) — shared by
    scripts/metrics_lint.py and the unit tests. Checks: snake_case
    names, known subsystem, non-empty help, unique (subsystem, name)."""
    import re

    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    errors: list[str] = []
    seen: set = set()
    for entry in EVENT_CATALOG:
        if len(entry) != 3:
            errors.append(f"catalog entry {entry!r}: want (subsystem, name, help)")
            continue
        subsystem, name, help_ = entry
        if subsystem not in EVENT_SUBSYSTEMS:
            errors.append(f"{subsystem}.{name}: unknown subsystem {subsystem!r}")
        if not name_re.match(name or ""):
            errors.append(f"{subsystem}.{name}: event name not snake_case")
        if "-" in (name or "") or "-" in (subsystem or ""):
            errors.append(f"{subsystem}.{name}: kebab-case is not allowed")
        if not help_ or not str(help_).strip():
            errors.append(f"{subsystem}.{name}: empty help text")
        key = (subsystem, name)
        if key in seen:
            errors.append(f"{subsystem}.{name}: duplicate catalog entry")
        seen.add(key)
    return errors


def _register_metrics() -> None:
    reg = get_registry()
    emitted_name, _, emitted_help, _agg = EVENTS_METRIC_FAMILIES[0]
    dropped_name, _, dropped_help, _agg = EVENTS_METRIC_FAMILIES[1]
    reg.register_callback(
        emitted_name, "counter", emitted_help, lambda: _default_bus.emitted
    )
    reg.register_callback(
        dropped_name, "counter", dropped_help, lambda: _default_bus.dropped
    )


_register_metrics()
