"""Pull-based fleet telemetry collector (ISSUE 10).

One :class:`TelemetryCollector` scrapes ``/metrics``, ``/healthz``,
``/debug/events`` and ``/debug/spans`` from every target — serving
replicas, slice workers, anything speaking the serving example's
endpoints — and federates them with obs/fleet.py into a single fleet
snapshot: counters summed, gauges merged per their declared aggregation
hint, latency histograms merged bucket-exactly so the stock burn-rate
engine (obs/slo.py) evaluates fleet-level TTFT/e2e SLOs over the
*merged* distribution.

Degradation contract, in order:

1. **A dead target never fails the collector.** Scrapes run under the
   resilience RetryPolicy; an exhausted target flips its
   ``collector_target_up`` gauge to 0 and its staleness gauge keeps
   climbing, while its *last good* snapshot ages out of the merge.
2. **A lying target never corrupts the merge.** Garbage or truncated
   exposition text raises in the strict parser, increments
   ``collector_parse_errors_total`` and — after ``quarantine_after``
   consecutive parse failures — quarantines the target: still probed
   every round (cheap, so it can rejoin on a clean parse) but excluded
   from the fleet snapshot until then.
3. **Partial beats nothing.** ``/debug/events``, ``/healthz`` and
   ``/debug/spans`` are best-effort per round; only ``/metrics``
   participates in up/down accounting.

The aggregated signals are also exported in the autoscaling/v2
``metrics`` convention the deploy charts' ``values.autoscaling.objects``
consume (:meth:`TelemetryCollector.hpa_signals`) so a future autoscaler
reads them unchanged.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterable, Optional, Sequence, Union

from .fleet import (
    ExpositionParseError,
    aggregation_hints,
    merge_snapshots,
    parse_exposition,
    render_snapshot,
    stitch_chrome_trace,
)
from .metrics import Registry
from .slo import SLOEvaluator, default_serving_slos

# (name, kind, help, agg) — the collector's own families, linted like
# every other catalog by scripts/metrics_lint.py. Per-target gauges are
# labeled by target name; "last" on them because a fleet OF collectors
# federating each other should keep each collector's own per-target row,
# not sum health bits.
COLLECTOR_METRIC_FAMILIES = (
    ("collector_scrapes_total", "counter",
     "Target scrape attempts (one per target per round)", "sum"),
    ("collector_scrape_errors_total", "counter",
     "Scrapes that failed after retry-policy exhaustion", "sum"),
    ("collector_parse_errors_total", "counter",
     "Scraped documents rejected by the exposition parser", "sum"),
    ("collector_fleet_targets", "gauge",
     "Configured scrape targets", "sum"),
    ("collector_fleet_targets_up", "gauge",
     "Targets whose latest /metrics scrape succeeded", "sum"),
    ("collector_target_up", "gauge",
     "Per-target scrape health (1 up, 0 down)", "last"),
    ("collector_target_quarantined", "gauge",
     "Per-target quarantine state (1 = excluded from the merge)", "last"),
    ("collector_target_staleness_seconds", "gauge",
     "Seconds since the target's last successful /metrics scrape", "max"),
    ("collector_scrape_seconds", "histogram",
     "Latency of one full-fleet scrape round", "sum"),
)


def _default_fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


class TargetState:
    """Everything the collector remembers about one scrape target."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.up = False
        self.quarantined = False
        self.consecutive_parse_errors = 0
        self.last_attempt: Optional[float] = None
        self.last_ok: Optional[float] = None  # collector clock
        self.last_error: Optional[str] = None
        self.snapshot: Optional[dict] = None
        self.health: Optional[dict] = None
        self.events: list[dict] = []
        self.spans: list[dict] = []

    def status(self, now: float) -> dict:
        return {
            "target": self.name,
            "url": self.url,
            "up": self.up,
            "quarantined": self.quarantined,
            "staleness_s": (
                round(now - self.last_ok, 3) if self.last_ok is not None
                else None
            ),
            "last_error": self.last_error,
        }


def _target_name(url: str) -> str:
    parsed = urllib.parse.urlparse(url)
    return parsed.netloc or url


class TelemetryCollector:
    """Scrape N targets, federate them into one fleet snapshot.

    ``targets`` is a sequence of URLs or ``(name, url)`` pairs. All
    I/O is injectable: ``fetch(url, timeout) -> bytes`` for tests and
    benches, ``clock`` for deterministic staleness math.
    """

    def __init__(
        self,
        targets: Sequence[Union[str, tuple]],
        *,
        interval_s: float = 5.0,
        timeout_s: float = 2.0,
        retry_policy: Optional[RetryPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        fetch: Optional[Callable[[str, float], bytes]] = None,
        quarantine_after: int = 3,
        events_limit: int = 200,
        spans_limit: int = 512,
        slo_specs: Optional[Sequence] = None,
        hints: Optional[dict] = None,
    ):
        self.targets: list[TargetState] = []
        for t in targets:
            if isinstance(t, str):
                self.targets.append(TargetState(_target_name(t), t))
            else:
                name, url = t
                self.targets.append(TargetState(name, url))
        if len({t.name for t in self.targets}) != len(self.targets):
            raise ValueError("duplicate target names")
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.quarantine_after = max(1, int(quarantine_after))
        self.events_limit = int(events_limit)
        self.spans_limit = int(spans_limit)
        self._clock = clock
        self._fetch = fetch or _default_fetch
        # lazy import: resilience.policy imports back into obs, so a
        # top-level import here would be circular
        from ..resilience.policy import RetryPolicy

        # 2 quick attempts by default: a slow target must degrade to
        # staleness, not stall the whole round behind 5 backoffs
        self._retry = retry_policy or RetryPolicy(
            max_attempts=2, base_delay=0.05, max_delay=0.25,
            jitter=0.5, seed=0, retry_on=(OSError, urllib.error.URLError),
        )
        self._hints = dict(hints) if hints is not None else aggregation_hints()
        for fam in COLLECTOR_METRIC_FAMILIES:
            self._hints.setdefault(fam[0], fam[-1])
        self._lock = threading.Lock()
        self._notes: list[str] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

        self.registry = Registry()
        reg = self.registry
        fams = {f[0]: f for f in COLLECTOR_METRIC_FAMILIES}
        self._scrapes = reg.counter(
            "collector_scrapes_total", fams["collector_scrapes_total"][2])
        self._scrape_errors = reg.counter(
            "collector_scrape_errors_total",
            fams["collector_scrape_errors_total"][2])
        self._parse_errors = reg.counter(
            "collector_parse_errors_total",
            fams["collector_parse_errors_total"][2])
        self._scrape_hist = reg.histogram(
            "collector_scrape_seconds", fams["collector_scrape_seconds"][2])
        reg.register_callback(
            "collector_fleet_targets", "gauge",
            fams["collector_fleet_targets"][2], lambda: len(self.targets))
        reg.register_callback(
            "collector_fleet_targets_up", "gauge",
            fams["collector_fleet_targets_up"][2],
            lambda: sum(1 for t in self.targets if t.up))
        reg.register_callback(
            "collector_target_up", "gauge", fams["collector_target_up"][2],
            lambda: [({"target": t.name}, 1.0 if t.up else 0.0)
                     for t in self.targets],
            labels=("target",))
        reg.register_callback(
            "collector_target_quarantined", "gauge",
            fams["collector_target_quarantined"][2],
            lambda: [({"target": t.name}, 1.0 if t.quarantined else 0.0)
                     for t in self.targets],
            labels=("target",))
        reg.register_callback(
            "collector_target_staleness_seconds", "gauge",
            fams["collector_target_staleness_seconds"][2],
            self._staleness_samples, labels=("target",))

        # Fleet SLOs evaluate the MERGED distribution through the stock
        # burn-rate engine — same specs serve.py uses per process.
        specs = tuple(slo_specs) if slo_specs is not None \
            else default_serving_slos()
        self.slo = SLOEvaluator(specs, [self._merged_target_snapshot],
                                clock=clock)
        self.slo.register_metrics(reg)

    # -- discovery helpers ---------------------------------------------------
    @classmethod
    def from_replicas(cls, urls: Iterable[str], **kwargs):
        """Static serving-replica URL list (the ``--target`` CLI path)."""
        return cls(list(urls), **kwargs)

    def refresh(self, targets: Sequence[Union[str, tuple]]) -> None:
        """Replace the target set at runtime (ISSUE 18: the autoscaler
        adds/removes replicas and a restarted replica may come back on a
        new port) without rebuilding the collector. State is preserved
        per *name*: a surviving target keeps its quarantine, staleness
        and last-good-snapshot state (a URL change just repoints the
        same TargetState — the next scrape round re-probes it); new
        names start cold; dropped names are forgotten. The list is
        swapped atomically, so a concurrent ``scrape_once`` finishes
        its round over the old set and the gauge callbacks pick up the
        new one on their next read."""
        by_name = {t.name: t for t in self.targets}
        fresh: list[TargetState] = []
        for t in targets:
            if isinstance(t, str):
                name, url = _target_name(t), t
            else:
                name, url = t
            state = by_name.get(name)
            if state is not None:
                state.url = url.rstrip("/")
            else:
                state = TargetState(name, url)
            fresh.append(state)
        if len({t.name for t in fresh}) != len(fresh):
            raise ValueError("duplicate target names")
        self.targets = fresh

    @classmethod
    def from_workers(
        cls,
        backend,
        config,
        *,
        port: int = 8000,
        selector_name: Optional[str] = None,
        namespace: Optional[str] = None,
        timeout: float = 120.0,
        retry_policy: Optional[RetryPolicy] = None,
        **kwargs,
    ):
        """Discover targets by resolving the slice's worker pods through
        the same selector layer ``devspace-tpu exec/sync`` fan out over
        — each Running worker becomes ``http://<podIP>:<port>``."""
        from ..services.selectors import resolve_workers

        workers, _ns, _cont = resolve_workers(
            backend, config, selector_name=selector_name,
            namespace=namespace, timeout=timeout, retry_policy=retry_policy,
        )
        targets = []
        for pod in workers:
            host = pod.raw.get("status", {}).get("podIP") or pod.name
            targets.append((pod.name, f"http://{host}:{port}"))
        return cls(targets, **kwargs)

    # -- scraping ------------------------------------------------------------
    def _staleness_samples(self):
        now = self._clock()
        return [
            ({"target": t.name},
             max(0.0, now - t.last_ok) if t.last_ok is not None
             else float("inf"))
            for t in self.targets
        ]

    def _get(self, state: TargetState, path: str) -> bytes:
        return self._retry.execute(
            self._fetch, state.url + path, self.timeout_s,
            describe=f"scrape {state.name}{path}", reraise=True,
        )

    def _scrape_target(self, state: TargetState) -> None:
        now = self._clock()
        state.last_attempt = now
        self._scrapes.inc()
        try:
            text = self._get(state, "/metrics").decode("utf-8", "replace")
        except Exception as e:  # noqa: BLE001 — any fetch failure = down
            state.up = False
            state.last_error = f"fetch: {e}"
            self._scrape_errors.inc()
            return
        try:
            snap = parse_exposition(text)
        except ExpositionParseError as e:
            state.up = False
            state.last_error = f"parse: {e}"
            self._parse_errors.inc()
            state.consecutive_parse_errors += 1
            if state.consecutive_parse_errors >= self.quarantine_after:
                if not state.quarantined:
                    state.quarantined = True
                # a quarantined target keeps its stale snapshot OUT of
                # the merge until a clean parse readmits it
                state.snapshot = None
            return
        state.consecutive_parse_errors = 0
        if state.quarantined:
            state.quarantined = False
        state.up = True
        state.last_ok = self._clock()
        state.last_error = None
        state.snapshot = snap
        # best-effort sidecars: partial evidence beats a failed round
        try:
            body = self._get(
                state, f"/debug/events?limit={self.events_limit}")
            state.events = json.loads(body).get("events") or []
        except Exception:  # noqa: BLE001
            pass
        try:
            state.health = json.loads(self._get(state, "/healthz"))
        except Exception:  # noqa: BLE001
            pass
        try:
            body = self._get(
                state, f"/debug/spans?limit={self.spans_limit}")
            state.spans = json.loads(body).get("spans") or []
        except Exception:  # noqa: BLE001
            pass

    def scrape_once(self) -> None:
        """One full round over every target. Never raises."""
        t0 = self._clock()
        for state in self.targets:
            self._scrape_target(state)
        self._scrape_hist.observe(max(0.0, self._clock() - t0))
        self.slo.evaluate()

    # -- federation ----------------------------------------------------------
    def _merged_target_snapshot(self) -> dict:
        """Merge of the target snapshots only (no collector self-metrics)
        — the source the fleet SLO evaluator reads."""
        contributing = sorted(
            (t for t in self.targets
             if t.snapshot is not None and not t.quarantined),
            key=lambda t: t.last_ok or 0.0,
        )
        merged, notes = merge_snapshots(
            [t.snapshot for t in contributing], self._hints
        )
        with self._lock:
            self._notes = notes
        return merged

    def fleet_snapshot(self) -> dict:
        """The federated fleet snapshot: merged target families plus the
        collector's own (scrape health, staleness, fleet SLO state)."""
        merged, notes = merge_snapshots(
            [self._merged_target_snapshot(), self.registry.snapshot()],
            self._hints,
        )
        with self._lock:
            self._notes = sorted(set(self._notes) | set(notes))
        return merged

    def merge_notes(self) -> list[str]:
        with self._lock:
            return list(self._notes)

    def render_metrics(self) -> str:
        """Prometheus text 0.0.4 of the fleet snapshot (``/metrics`` of
        ``devspace-tpu collector serve``)."""
        return render_snapshot(self.fleet_snapshot())

    def merged_events(self, limit: int = 200,
                      subsystem: Optional[str] = None) -> list[dict]:
        """Events from every target, stamped with their origin and
        ordered by ``(time, seq)`` — the same stable tie-break the
        per-process FlightRecorder dump uses."""
        out = []
        for t in self.targets:
            for e in t.events:
                if subsystem and e.get("subsystem") != subsystem:
                    continue
                d = dict(e)
                d["target"] = t.name
                out.append(d)
        out.sort(key=lambda e: (e.get("time", 0.0), e.get("seq", 0)))
        return out[-limit:] if limit and limit > 0 else out

    def stitched_trace(self, trace_id: Optional[str] = None) -> dict:
        """One Chrome trace over every target's span ring — a process
        lane per target, joined on ``trace_id`` when given."""
        return stitch_chrome_trace(
            {t.name: t.spans for t in self.targets}, trace_id
        )

    def fleet_status(self) -> dict:
        """The ``/debug/fleet`` document: per-target matrix, fleet SLO
        table, merge notes and the HPA-convention signal export."""
        now = self._clock()
        snap = self.fleet_snapshot()

        def val(name, default=None):
            fam = snap.get(name)
            if not fam or not fam["samples"]:
                return default
            return sum(v for _l, v in fam["samples"]
                       if not isinstance(v, dict))

        matrix = []
        for t in self.targets:
            row = t.status(now)
            s = t.snapshot or {}

            def tval(name):
                fam = s.get(name)
                if not fam or not fam["samples"]:
                    return None
                return fam["samples"][0][1]

            row.update({
                "tok_s": tval("engine_tokens_per_sec_10s"),
                "active_slots": tval("engine_active_slots"),
                "max_slots": tval("engine_max_slots"),
                "queued": tval("engine_queued_requests"),
                "occupancy": tval("engine_dispatch_depth_occupancy"),
            })
            if t.health and isinstance(t.health.get("slo"), dict):
                row["slo"] = t.health["slo"].get("status")
            matrix.append(row)
        return {
            "targets": matrix,
            "fleet": {
                "targets": len(self.targets),
                "up": sum(1 for t in self.targets if t.up),
                "quarantined": sum(
                    1 for t in self.targets if t.quarantined),
                "tok_s": val("engine_tokens_per_sec_10s"),
                "active_slots": val("engine_active_slots"),
                "max_slots": val("engine_max_slots"),
                "queued": val("engine_queued_requests"),
            },
            "slo": self.slo.to_dict(),
            "notes": self.merge_notes(),
            "hpa": {"metrics": self.hpa_signals()},
        }

    def hpa_signals(self) -> list[dict]:
        """Aggregated signals as autoscaling/v2 ``metrics`` entries —
        the exact shape ``values.autoscaling.objects`` carries in the
        deploy charts (chart.py ``_derive_autoscaling``), so an
        autoscaler templated on that convention consumes fleet signals
        unchanged. ``averageValue`` is the current per-replica average
        (the quantity v2 Pods metrics target)."""
        up = max(1, sum(1 for t in self.targets if t.up))
        snap = self._merged_target_snapshot()

        def total(name):
            fam = snap.get(name)
            if not fam:
                return None
            vals = [v for _l, v in fam["samples"]
                    if not isinstance(v, dict)]
            return sum(vals) if vals else None

        out = []
        for name in (
            "engine_dispatch_depth_occupancy",
            "engine_queued_requests",
            "engine_tokens_per_sec_10s",
        ):
            fleet_value = total(name)
            if fleet_value is None:
                continue
            # "avg"-merged gauges already hold the per-replica average
            # after the hint merge; sum-merged ones are fleet totals.
            if self._hints.get(name) != "avg":
                fleet_value = fleet_value / up
            out.append({
                "type": "Pods",
                "pods": {
                    "metric": {"name": name},
                    "target": {
                        "type": "AverageValue",
                        "averageValue": round(fleet_value, 4),
                    },
                },
            })
        return out

    # -- background loop -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.scrape_once()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(
            target=loop, name="telemetry-collector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def make_http_server(collector: TelemetryCollector, host: str = "127.0.0.1",
                     port: int = 9090):
    """The federated endpoint (``devspace-tpu collector serve``):

    - ``/metrics`` — the merged fleet exposition (Prometheus 0.0.4)
    - ``/healthz`` — collector liveness + up/total target counts
    - ``/debug/fleet`` — per-target matrix, fleet SLO table, merge
      notes, HPA-convention signals
    - ``/debug/events`` — merged recent events from every target
      (same document shape as a replica's, so ``top`` reuses its
      renderer; rows gain a ``target`` key)
    - ``/debug/trace`` — stitched Chrome trace (``?trace_id=`` filters
      to one request across every process lane)

    Returns an unstarted ``ThreadingHTTPServer``; the caller owns
    ``serve_forever``/``shutdown`` (and the collector's scrape loop).
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: N802 — quiet
            pass

        def _json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server API
            from urllib.parse import parse_qs

            path, _, query = self.path.partition("?")
            qs = parse_qs(query)
            if path == "/metrics":
                body = collector.render_metrics().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                up = sum(1 for t in collector.targets if t.up)
                self._json(200, {
                    "ok": True,
                    "role": "collector",
                    "targets": len(collector.targets),
                    "up": up,
                    "slo": collector.slo.to_dict(),
                })
            elif path == "/debug/fleet":
                self._json(200, collector.fleet_status())
            elif path == "/debug/events":
                try:
                    limit = int(qs.get("limit", ["200"])[0])
                except ValueError:
                    self._json(400, {"error": "limit must be an integer"})
                    return
                subsystem = qs.get("subsystem", [None])[0]
                self._json(200, {
                    "events_enabled": True,
                    "subsystems": sorted(
                        {e.get("subsystem") for t in collector.targets
                         for e in t.events if e.get("subsystem")}
                    ),
                    "events": collector.merged_events(limit, subsystem),
                })
            elif path == "/debug/trace":
                trace_id = qs.get("trace_id", [None])[0]
                self._json(200, collector.stitched_trace(trace_id))
            else:
                self._json(404, {"error": "not found"})

    return ThreadingHTTPServer((host, port), Handler)
