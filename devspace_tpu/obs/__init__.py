"""Unified telemetry (ISSUE 6): metrics registry + per-request traces.

- :mod:`devspace_tpu.obs.metrics` — dependency-free Counter / Gauge /
  Histogram registry with labeled families, callback (pull) metrics and
  Prometheus text-exposition rendering.
- :mod:`devspace_tpu.obs.request_trace` — per-request serving lifecycle
  recorder producing TTFT / TPOT / queue-wait / prefill / e2e
  histograms and a bounded ring of recent request traces.

Every serving subsystem registers its counters here as metric families;
the existing ``stats()`` dicts stay byte-compatible (they and the
registry are two views over the same counters).
"""

from .events import (
    EVENT_CATALOG,
    EVENT_SUBSYSTEMS,
    EVENTS_METRIC_FAMILIES,
    Event,
    EventBus,
    FlightRecorder,
    JsonlSink,
    add_sink,
    emit,
    events_enabled,
    get_bus,
    make_event,
    remove_sink,
)
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    WindowedRate,
    get_registry,
    metrics_enabled,
)
from .request_trace import (
    SERVING_METRIC_FAMILIES,
    RequestTrace,
    ServingTelemetry,
)
from .slo import (
    SLO_METRIC_FAMILIES,
    SLOEvaluator,
    SLOSpec,
    SLOStatus,
    default_serving_slos,
)
from .tracing import (
    TIMELINE_TRACKS,
    TRACING_METRIC_FAMILIES,
    Span,
    SpanContext,
    TimelineRecorder,
    Tracer,
    current_traceparent,
    get_tracer,
)

__all__ = [
    "EVENT_CATALOG",
    "EVENT_SUBSYSTEMS",
    "EVENTS_METRIC_FAMILIES",
    "Event",
    "EventBus",
    "FlightRecorder",
    "JsonlSink",
    "add_sink",
    "emit",
    "events_enabled",
    "get_bus",
    "make_event",
    "remove_sink",
    "SLO_METRIC_FAMILIES",
    "SLOEvaluator",
    "SLOSpec",
    "SLOStatus",
    "default_serving_slos",
    "TIMELINE_TRACKS",
    "TRACING_METRIC_FAMILIES",
    "Span",
    "SpanContext",
    "TimelineRecorder",
    "Tracer",
    "current_traceparent",
    "get_tracer",
    "DEFAULT_LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "WindowedRate",
    "get_registry",
    "metrics_enabled",
    "SERVING_METRIC_FAMILIES",
    "RequestTrace",
    "ServingTelemetry",
]
