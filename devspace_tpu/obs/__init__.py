"""Unified telemetry (ISSUE 6): metrics registry + per-request traces.

- :mod:`devspace_tpu.obs.metrics` — dependency-free Counter / Gauge /
  Histogram registry with labeled families, callback (pull) metrics and
  Prometheus text-exposition rendering.
- :mod:`devspace_tpu.obs.request_trace` — per-request serving lifecycle
  recorder producing TTFT / TPOT / queue-wait / prefill / e2e
  histograms and a bounded ring of recent request traces.
- :mod:`devspace_tpu.obs.fleet` — exposition parse/merge: counters
  summed, gauges per aggregation hints, histograms merged
  bucket-exactly; cross-process Chrome-trace stitching.
- :mod:`devspace_tpu.obs.collector` — the pull-based fleet collector
  behind ``devspace-tpu collector serve`` (ISSUE 10).

Every serving subsystem registers its counters here as metric families;
the existing ``stats()`` dicts stay byte-compatible (they and the
registry are two views over the same counters).
"""

from .events import (
    EVENT_CATALOG,
    EVENT_SUBSYSTEMS,
    EVENTS_METRIC_FAMILIES,
    Event,
    EventBus,
    FlightRecorder,
    JsonlSink,
    add_sink,
    emit,
    events_enabled,
    get_bus,
    make_event,
    remove_sink,
)
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    WindowedRate,
    get_registry,
    metrics_enabled,
)
from .request_trace import (
    SERVING_METRIC_FAMILIES,
    RequestTrace,
    ServingTelemetry,
)
from .slo import (
    SLO_METRIC_FAMILIES,
    SLOEvaluator,
    SLOSpec,
    SLOStatus,
    default_serving_slos,
)
from .tracing import (
    TIMELINE_TRACKS,
    TRACING_METRIC_FAMILIES,
    Span,
    SpanContext,
    TimelineRecorder,
    Tracer,
    current_traceparent,
    get_tracer,
)

# fleet federation last: collector pulls in every catalog above (and
# resilience.policy, which imports back into this package)
from .collector import (  # noqa: E402
    COLLECTOR_METRIC_FAMILIES,
    TelemetryCollector,
    make_http_server,
)
from .fleet import (  # noqa: E402
    FLEET_AGG_KINDS,
    ExpositionParseError,
    aggregation_hints,
    family_agg,
    merge_snapshots,
    parse_exposition,
    stitch_chrome_trace,
)

__all__ = [
    "COLLECTOR_METRIC_FAMILIES",
    "TelemetryCollector",
    "make_http_server",
    "FLEET_AGG_KINDS",
    "ExpositionParseError",
    "aggregation_hints",
    "family_agg",
    "merge_snapshots",
    "parse_exposition",
    "stitch_chrome_trace",
    "EVENT_CATALOG",
    "EVENT_SUBSYSTEMS",
    "EVENTS_METRIC_FAMILIES",
    "Event",
    "EventBus",
    "FlightRecorder",
    "JsonlSink",
    "add_sink",
    "emit",
    "events_enabled",
    "get_bus",
    "make_event",
    "remove_sink",
    "SLO_METRIC_FAMILIES",
    "SLOEvaluator",
    "SLOSpec",
    "SLOStatus",
    "default_serving_slos",
    "TIMELINE_TRACKS",
    "TRACING_METRIC_FAMILIES",
    "Span",
    "SpanContext",
    "TimelineRecorder",
    "Tracer",
    "current_traceparent",
    "get_tracer",
    "DEFAULT_LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "WindowedRate",
    "get_registry",
    "metrics_enabled",
    "SERVING_METRIC_FAMILIES",
    "RequestTrace",
    "ServingTelemetry",
]
