"""Per-request serving lifecycle traces + the latency histograms.

`ServingTelemetry` is the engine's observer (ISSUE 6 tentpole): the
scheduler calls its ``on_*`` hooks at each lifecycle transition
(enqueue -> admit -> prefill chunks -> first token -> decode ->
finish/preempt/fail) and it derives the latency distributions a serving
operator actually pages on:

- ``queue_wait_seconds``  enqueue -> first admission
- ``prefill_seconds``     first admission -> prefill complete
- ``ttft_seconds``        enqueue -> first generated token
- ``tpot_seconds``        mean inter-token time after the first token,
                          observed once per completed request
- ``request_e2e_seconds`` enqueue -> completion

plus ``requests_finished_total{outcome}``. A bounded ring of recent
:class:`RequestTrace` objects backs ``/debug/requests`` on the serving
example and exports as JSONL or through the Chrome-trace writer shared
with ``utils/trace.py``.

Hot-path discipline: ``on_emit`` runs once per generated token and does
a clock read plus three attribute writes — no locks, no allocation
(events are only appended for state TRANSITIONS, never per token).
Histogram observes happen at transition points only. The clock is
injectable so tests assert hand-computed TTFT/TPOT values exactly.

Thread model: hooks are called by the scheduler thread (and ``on_submit``
by client threads); readers (``/debug/requests``, scrapes) see
GIL-atomic field reads. Traces attach to the Request object itself
(``req._obs_trace``) so preemption/re-admission naturally continues the
same trace.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Optional

from .metrics import Registry
from .tracing import SpanContext, derive_span_id, new_trace_id

# (name, kind, help) — the lintable catalog (scripts/metrics_lint.py);
# ServingTelemetry registers EXACTLY these so spec and registration
# cannot drift.
SERVING_METRIC_FAMILIES = (
    (
        "ttft_seconds",
        "histogram",
        "Time from request enqueue to its first generated token",
        "sum",
    ),
    (
        "tpot_seconds",
        "histogram",
        "Mean time per output token after the first, per completed request",
        "sum",
    ),
    (
        "queue_wait_seconds",
        "histogram",
        "Time from request enqueue to its first slot admission",
        "sum",
    ),
    (
        "prefill_seconds",
        "histogram",
        "Time from first admission to prefill completion (chunked prefill)",
        "sum",
    ),
    (
        "request_e2e_seconds",
        "histogram",
        "Time from request enqueue to completion",
        "sum",
    ),
    (
        "requests_finished_total",
        "counter",
        "Terminal request outcomes by kind (completed/failed)",
        "sum",
    ),
)

_MAX_EVENTS = 64  # per-trace event cap (preempt/re-admit churn bound)


class RequestTrace:
    """One request's lifecycle record: a bounded event list (name,
    t_monotonic) plus the timestamps the derived latencies need."""

    __slots__ = (
        "id", "prompt_len", "max_new_tokens", "events", "t_wall_enqueue",
        "t_enqueue", "t_admit", "t_prefill_done", "t_first", "t_last",
        "n_tokens", "preemptions", "outcome",
        "trace_id", "span_id", "parent_span_id",
    )

    def __init__(self, rid: int, prompt_len: int, max_new_tokens: int, now: float):
        self.id = rid
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.events: list[tuple[str, float]] = []
        self.t_wall_enqueue = time.time()
        self.t_enqueue = now
        self.t_admit: Optional[float] = None
        self.t_prefill_done: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.n_tokens = 0
        self.preemptions = 0
        self.outcome: Optional[str] = None
        # distributed-trace identity (ISSUE 8): set by ServingTelemetry
        # from the request's inbound traceparent (or freshly minted)
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_span_id: Optional[str] = None

    def event(self, name: str, t: float) -> None:
        if len(self.events) < _MAX_EVENTS:
            self.events.append((name, t))

    # -- derived latencies -------------------------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_enqueue

    @property
    def prefill_s(self) -> Optional[float]:
        if self.t_prefill_done is None or self.t_admit is None:
            return None
        return self.t_prefill_done - self.t_admit

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return self.t_first - self.t_enqueue

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean inter-token time after the first token; needs >= 2."""
        if self.t_first is None or self.t_last is None or self.n_tokens < 2:
            return None
        return (self.t_last - self.t_first) / (self.n_tokens - 1)

    def e2e_s(self, t_end: float) -> float:
        return t_end - self.t_enqueue

    def to_dict(self) -> dict:
        end = self.events[-1][1] if self.events else self.t_enqueue

        def r(v):
            return round(v, 6) if v is not None else None

        return {
            "id": self.id,
            "trace_id": self.trace_id,
            "prompt_len": self.prompt_len,
            "max_new_tokens": self.max_new_tokens,
            "tokens_generated": self.n_tokens,
            "preemptions": self.preemptions,
            "outcome": self.outcome,  # None while in flight
            "queue_wait_s": r(self.queue_wait_s),
            "prefill_s": r(self.prefill_s),
            "ttft_s": r(self.ttft_s),
            "tpot_s": r(self.tpot_s),
            "e2e_s": r(self.e2e_s(end)) if self.outcome else None,
            "events": [
                (name, round(t - self.t_enqueue, 6)) for name, t in self.events
            ],
        }

    def to_spans(self) -> list[dict]:
        """utils/trace.py-shaped span dicts (one per lifecycle phase) so
        the existing Chrome-trace writer renders request timelines.
        Monotonic offsets are rebased onto the wall-clock enqueue time."""

        def wall(t_mono: float) -> float:
            return self.t_wall_enqueue + (t_mono - self.t_enqueue)

        spans = []

        root_sid = self.span_id or (
            derive_span_id(self.trace_id or "", f"request-{self.id}")
        )

        def phase(name, t0, t1, **attrs):
            if t0 is None or t1 is None:
                return
            spans.append(
                {
                    "name": name,
                    "parent": f"request-{self.id}",
                    "thread": "serving",
                    "start": wall(t0),
                    "duration_s": round(t1 - t0, 6),
                    "request_id": self.id,
                    "trace_id": self.trace_id,
                    # deterministic child ids: pure function of the root
                    # span id and the phase name (golden-testable)
                    "span_id": derive_span_id(root_sid, name),
                    "parent_span_id": root_sid,
                    "ok": self.outcome != "failed",
                    **attrs,
                }
            )

        end = self.events[-1][1] if self.events else self.t_enqueue
        phase("queue_wait", self.t_enqueue, self.t_admit)
        phase("prefill", self.t_admit, self.t_prefill_done)
        phase(
            "decode", self.t_first, self.t_last, tokens=self.n_tokens
        )
        spans.append(
            {
                "name": f"request-{self.id}",
                "parent": None,
                "thread": "serving",
                "start": self.t_wall_enqueue,
                "duration_s": round(end - self.t_enqueue, 6),
                "request_id": self.id,
                "trace_id": self.trace_id,
                "span_id": root_sid,
                "parent_span_id": self.parent_span_id,
                "outcome": self.outcome,
                "tokens": self.n_tokens,
                "ok": self.outcome != "failed",
            }
        )
        return spans


class ServingTelemetry:
    """The engine's lifecycle observer: owns a metrics Registry (or
    shares one passed in), the latency histograms and the bounded ring
    of recent request traces. One instance per engine."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        clock=time.monotonic,
        ring: int = 256,
    ):
        self.registry = registry if registry is not None else Registry()
        self.clock = clock
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._ring: deque[RequestTrace] = deque(maxlen=ring)
        by_name = {name: (kind, help_) for name, kind, help_, _agg in SERVING_METRIC_FAMILIES}

        def hist(name):
            return self.registry.histogram(name, by_name[name][1])

        self.ttft = hist("ttft_seconds")
        self.tpot = hist("tpot_seconds")
        self.queue_wait = hist("queue_wait_seconds")
        self.prefill = hist("prefill_seconds")
        self.e2e = hist("request_e2e_seconds")
        self.finished = self.registry.counter(
            "requests_finished_total",
            by_name["requests_finished_total"][1],
            labels=("outcome",),
        )

    # -- lifecycle hooks (scheduler thread; on_submit: client threads) -----
    def on_submit(self, req) -> None:
        now = self.clock()
        trace = RequestTrace(
            next(self._ids), len(req.prompt_ids), req.max_new_tokens, now
        )
        # join the caller's distributed trace when it sent a valid
        # traceparent (serve.py forwards the HTTP header onto the
        # Request); otherwise this request roots a fresh trace. The
        # request's own span id is derived, not random, so replays and
        # golden tests see stable ids.
        ctx = SpanContext.from_traceparent(getattr(req, "traceparent", None))
        trace.trace_id = ctx.trace_id if ctx else new_trace_id()
        trace.parent_span_id = ctx.span_id if ctx else None
        trace.span_id = derive_span_id(trace.trace_id, f"request-{trace.id}")
        trace.event("enqueue", now)
        req._obs_trace = trace
        with self._lock:
            self._ring.append(trace)

    def on_admit(self, req) -> None:
        t = getattr(req, "_obs_trace", None)
        if t is None:
            return
        now = self.clock()
        if t.t_admit is None:  # first admission only (resume re-admits)
            t.t_admit = now
            qw = t.queue_wait_s
            if qw is not None:
                self.queue_wait.observe(qw)
        t.event("admit", now)

    def on_prefill_chunk(self, req, pos: int) -> None:
        t = getattr(req, "_obs_trace", None)
        if t is None:
            return
        t.event(f"prefill_chunk:{pos}", self.clock())

    def on_prefill_done(self, req) -> None:
        t = getattr(req, "_obs_trace", None)
        if t is None:
            return
        now = self.clock()
        if t.t_prefill_done is None:
            t.t_prefill_done = now
            pf = t.prefill_s
            if pf is not None:
                self.prefill.observe(pf)
        t.event("prefill_done", now)

    def on_emit(self, req) -> None:
        # HOT PATH: once per generated token — clock read + field writes,
        # no locks, no event append
        t = getattr(req, "_obs_trace", None)
        if t is None:
            return
        now = self.clock()
        if t.t_first is None:
            t.t_first = now
            t.event("first_token", now)
            # exemplar links e.g. the p99 TTFT bucket to its trace
            self.ttft.observe(now - t.t_enqueue, exemplar=t.trace_id)
        t.t_last = now
        t.n_tokens += 1

    def on_preempt(self, req) -> None:
        t = getattr(req, "_obs_trace", None)
        if t is None:
            return
        t.preemptions += 1
        t.event("preempt", self.clock())

    def on_finish(self, req, outcome: str) -> None:
        """Terminal transition (``completed`` | ``failed``). Idempotent:
        the failure ladder and stop() can both reach a request — the
        first terminal event wins, mirroring the engine's own
        ``req.done.is_set()`` double-count guards."""
        t = getattr(req, "_obs_trace", None)
        if t is None or t.outcome is not None:
            return
        now = self.clock()
        t.outcome = outcome
        t.event(outcome, now)
        self.finished.labels(outcome=outcome).inc()
        if outcome == "completed":
            self.e2e.observe(t.e2e_s(now), exemplar=t.trace_id)
            tp = t.tpot_s
            if tp is not None:
                self.tpot.observe(tp)

    # -- views -------------------------------------------------------------
    def recent(self, limit: int = 50) -> list[dict]:
        """Newest-last dicts of the most recent traces (finished and
        in-flight)."""
        with self._lock:
            traces = list(self._ring)[-limit:]
        return [t.to_dict() for t in traces]

    def export_jsonl(self, path: str) -> int:
        """Write the ring as JSONL (one trace per line); returns count."""
        rows = self.recent(limit=self._ring.maxlen or 256)
        with open(path, "w", encoding="utf-8") as fh:
            for row in rows:
                fh.write(json.dumps(row) + "\n")
        return len(rows)

    def recent_spans(
        self, limit: int = 512, trace_id: Optional[str] = None
    ) -> list[dict]:
        """Lifecycle-phase span dicts for the newest requests (newest
        last) — the per-process feed the fleet collector stitches into
        one cross-worker Chrome trace. Starts are wall-clock and the
        dicts carry the distributed ``trace_id``, so lanes from N
        replicas line up on one timeline."""
        with self._lock:
            traces = list(self._ring)
        spans = [s for t in traces for s in t.to_spans()]
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        for s in spans:
            s.setdefault("track", s.get("thread") or "serving")
        return spans[-max(0, limit):]

    def export_chrome(self, dest: str) -> int:
        """Chrome-trace (chrome://tracing / Perfetto) export of the
        recent-request ring through the shared span writer."""
        from ..utils import trace as trace_mod

        with self._lock:
            traces = list(self._ring)
        spans = [s for t in traces for s in t.to_spans()]
        return trace_mod.write_chrome(spans, dest)
