"""Declarative SLOs with multi-window burn-rate evaluation (ISSUE 9).

An :class:`SLOSpec` names an objective over metric families that
already exist (``Registry.snapshot()`` is the only data source — no new
bookkeeping in hot paths): a latency percentile via histogram buckets
(TTFT p99), an error-rate / availability target via counter deltas, or
a throughput floor via gauge samples. The :class:`SLOEvaluator` keeps a
timestamped ring of snapshots and computes, per spec, the **bad-event
fraction over a short and a long window**; dividing by the error budget
(``1 - objective``) gives the *burn rate* — 1.0 means burning exactly
the budget, 10 means the budget is gone in a tenth of the window.

Statuses follow the multi-window discipline from the SRE workbook: a
spec is ``breach`` only when BOTH windows burn above the breach
threshold (the long window proves it is significant, the short window
proves it is still happening — and lets ``/readyz`` recover as soon as
the short window slides past the incident), ``warn`` when both exceed
the warn threshold, else ``ok``. Status transitions emit ``slo.warn`` /
``slo.breach`` / ``slo.recovered`` events on the default bus.

Everything takes an injectable ``clock`` so the burn math is pinned by
golden tests (tests/test_obs_slo.py) without sleeping.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

from . import events as _events
from .metrics import Registry, _validate_name

SLO_METRIC_FAMILIES = (
    ("slo_status", "gauge",
     "SLO state per objective: 0 ok, 1 warn, 2 breach", "max"),
    ("slo_burn_ratio", "gauge",
     "Error-budget burn rate per SLO and window "
     "(1.0 = burning exactly the budget)", "max"),
)

_STATUS_ORDER = {"ok": 0, "warn": 1, "breach": 2}


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective. ``kind`` selects the bad-fraction
    source:

    - ``latency``: fraction of ``histogram`` observations above
      ``threshold_s`` within the window (bucket-resolution: the
      threshold snaps up to the nearest bucket edge).
    - ``error_rate``: ``sum(bad counters) / sum(total counters)`` delta
      within the window.
    - ``throughput_floor``: fraction of evaluation samples where
      ``gauge < floor`` while the ``activity`` gauges sum > 0 (an idle
      engine is not a breach).
    """

    name: str
    kind: str  # "latency" | "error_rate" | "throughput_floor"
    objective: float  # target good fraction, e.g. 0.99
    # latency
    histogram: Optional[str] = None
    threshold_s: Optional[float] = None
    # error_rate
    bad: Sequence[str] = ()
    total: Sequence[str] = ()
    # throughput_floor
    gauge: Optional[str] = None
    floor: Optional[float] = None
    activity: Sequence[str] = ()
    # windows + thresholds
    short_window_s: float = 300.0
    long_window_s: float = 3600.0
    warn_burn: float = 1.0
    breach_burn: float = 6.0
    min_events: int = 1  # below this many window events: no data -> ok

    def __post_init__(self):
        _validate_name(self.name)
        if self.kind not in ("latency", "error_rate", "throughput_floor"):
            raise ValueError(f"{self.name}: unknown SLO kind {self.kind!r}")
        if not (0.0 < self.objective < 1.0):
            raise ValueError(f"{self.name}: objective must be in (0, 1)")
        if self.kind == "latency" and (
            not self.histogram or self.threshold_s is None
        ):
            raise ValueError(f"{self.name}: latency needs histogram+threshold_s")
        if self.kind == "error_rate" and (not self.bad or not self.total):
            raise ValueError(f"{self.name}: error_rate needs bad+total counters")
        if self.kind == "throughput_floor" and (
            not self.gauge or self.floor is None
        ):
            raise ValueError(f"{self.name}: throughput_floor needs gauge+floor")
        if self.short_window_s <= 0 or self.long_window_s < self.short_window_s:
            raise ValueError(f"{self.name}: want 0 < short <= long window")

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "kind": self.kind,
            "objective": self.objective,
            "short_window_s": self.short_window_s,
            "long_window_s": self.long_window_s,
            "warn_burn": self.warn_burn,
            "breach_burn": self.breach_burn,
        }
        if self.kind == "latency":
            d["histogram"] = self.histogram
            d["threshold_s"] = self.threshold_s
        elif self.kind == "error_rate":
            d["bad"] = list(self.bad)
            d["total"] = list(self.total)
        else:
            d["gauge"] = self.gauge
            d["floor"] = self.floor
        return d


@dataclass
class SLOStatus:
    """One spec's evaluation result."""

    name: str
    status: str  # ok | warn | breach
    burn_short: float
    burn_long: float
    bad_short: float = 0.0
    total_short: float = 0.0
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "status": self.status,
            "burn_short": round(self.burn_short, 4),
            "burn_long": round(self.burn_long, 4),
            "bad_short": self.bad_short,
            "total_short": self.total_short,
            **self.detail,
        }


def _sum_counter(snap: dict, names: Sequence[str]) -> float:
    total = 0.0
    for name in names:
        fam = snap.get(name)
        if not fam:
            continue
        for _labels, val in fam.get("samples", ()):
            if isinstance(val, (int, float)):
                total += float(val)
    return total


def _hist_good_total(snap: dict, name: str, threshold: float):
    """(observations <= threshold, total observations) summed across the
    family's label sets, at bucket resolution (threshold snaps up to the
    nearest ``le`` edge)."""
    fam = snap.get(name)
    good = total = 0.0
    if not fam:
        return good, total
    for _labels, val in fam.get("samples", ()):
        if not isinstance(val, dict):
            continue
        buckets = val.get("buckets") or ()
        cum_at_threshold = 0.0
        for le, cum in buckets:
            if le >= threshold or math.isinf(le):
                cum_at_threshold = cum
                break
        good += cum_at_threshold
        total += float(val.get("count", 0))
    return good, total


def _sum_gauge(snap: dict, names: Sequence[str]) -> float:
    return _sum_counter(snap, names)  # same shape: scalar samples


class SLOEvaluator:
    """Evaluates a set of :class:`SLOSpec` over registry snapshots.

    ``sources`` is one callable — or a list of callables — returning
    :meth:`Registry.snapshot` dicts (the serving example passes both the
    engine's private registry and the process default registry; merged
    left-to-right). Call :meth:`evaluate` periodically (serve.py runs it
    on a background thread every ``DEVSPACE_SLO_INTERVAL_S``); between
    calls, :meth:`statuses` / :meth:`ready` / :meth:`to_dict` serve the
    last result without recomputing.
    """

    def __init__(
        self,
        specs: Sequence[SLOSpec],
        sources,
        clock: Callable[[], float] = time.monotonic,
        bus: Optional[_events.EventBus] = None,
    ):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in {names}")
        self.specs = tuple(specs)
        if callable(sources):
            sources = [sources]
        self._sources = list(sources)
        self._clock = clock
        self._bus = bus  # None -> default bus at emit time
        self._lock = threading.Lock()
        self._history: deque = deque()  # (ts, {spec.name: extracted})
        self._last: list[SLOStatus] = []
        self._last_ts: Optional[float] = None
        self._horizon = max(
            (s.long_window_s for s in self.specs), default=3600.0
        )

    # -- snapshot extraction ------------------------------------------------
    def _collect(self) -> dict:
        merged: dict = {}
        for src in self._sources:
            try:
                merged.update(src() or {})
            except Exception:
                continue  # a dead source degrades to "no data", not a crash
        return merged

    def _extract(self, snap: dict) -> dict:
        out = {}
        for spec in self.specs:
            if spec.kind == "latency":
                good, total = _hist_good_total(
                    snap, spec.histogram, spec.threshold_s
                )
                out[spec.name] = (total - good, total)  # cumulative (bad, total)
            elif spec.kind == "error_rate":
                out[spec.name] = (
                    _sum_counter(snap, spec.bad),
                    _sum_counter(snap, spec.total),
                )
            else:  # throughput_floor: instantaneous (value, active?)
                value = _sum_gauge(snap, [spec.gauge])
                active = (
                    True
                    if not spec.activity
                    else _sum_gauge(snap, spec.activity) > 0
                )
                out[spec.name] = (value, active)
        return out

    # -- window math --------------------------------------------------------
    def _baseline(self, cutoff: float):
        """Latest history entry at or before ``cutoff`` (else the oldest
        one) — the subtrahend for cumulative deltas over a window."""
        base = None
        for ts, vals in self._history:
            if ts <= cutoff:
                base = (ts, vals)
            else:
                break
        if base is None and self._history:
            base = self._history[0]
        return base

    def _window_bad_frac(self, spec: SLOSpec, now: float, window: float,
                         current: dict):
        """(bad_fraction, bad, total) over the trailing ``window``."""
        if spec.kind == "throughput_floor":
            samples = [
                vals[spec.name]
                for ts, vals in self._history
                if ts > now - window and spec.name in vals
            ]
            active = [(v, a) for v, a in samples if a]
            if not active:
                return 0.0, 0.0, 0.0
            bad = sum(1.0 for v, _a in active if v < spec.floor)
            return bad / len(active), bad, float(len(active))
        cur_bad, cur_total = current[spec.name]
        base = self._baseline(now - window)
        base_bad = base_total = 0.0
        if base is not None and spec.name in base[1]:
            base_bad, base_total = base[1][spec.name]
        d_bad = max(0.0, cur_bad - base_bad)
        d_total = max(0.0, cur_total - base_total)
        if d_total < spec.min_events:
            return 0.0, d_bad, d_total
        return min(1.0, d_bad / d_total), d_bad, d_total

    # -- evaluation ---------------------------------------------------------
    def evaluate(self) -> list[SLOStatus]:
        now = self._clock()
        current = self._extract(self._collect())
        with self._lock:
            self._history.append((now, current))
            horizon = now - self._horizon - 1.0
            # keep one entry older than the horizon as the long baseline
            while len(self._history) > 1 and self._history[1][0] <= horizon:
                self._history.popleft()
            prev = {s.name: s.status for s in self._last}
            statuses = []
            for spec in self.specs:
                frac_s, bad_s, total_s = self._window_bad_frac(
                    spec, now, spec.short_window_s, current
                )
                frac_l, _bad_l, _total_l = self._window_bad_frac(
                    spec, now, spec.long_window_s, current
                )
                burn_s = frac_s / spec.budget
                burn_l = frac_l / spec.budget
                gating = min(burn_s, burn_l)
                if gating >= spec.breach_burn:
                    status = "breach"
                elif gating >= spec.warn_burn:
                    status = "warn"
                else:
                    status = "ok"
                statuses.append(SLOStatus(
                    name=spec.name,
                    status=status,
                    burn_short=burn_s,
                    burn_long=burn_l,
                    bad_short=bad_s,
                    total_short=total_s,
                    detail={"objective": spec.objective, "kind": spec.kind},
                ))
            self._last = statuses
            self._last_ts = now
        for st in statuses:
            before = prev.get(st.name, "ok")
            if st.status == before:
                continue
            bus = self._bus or _events.get_bus()
            name = "recovered" if st.status == "ok" else st.status
            level = {"ok": "info", "warn": "warn", "breach": "error"}[st.status]
            bus.emit(
                "slo", name, level=level, slo=st.name,
                burn_short=round(st.burn_short, 4),
                burn_long=round(st.burn_long, 4), was=before,
            )
        return statuses

    # -- read side ----------------------------------------------------------
    def statuses(self) -> list[SLOStatus]:
        with self._lock:
            return list(self._last)

    def ready(self) -> bool:
        """False iff any spec is in ``breach`` as of the last
        evaluation — the ``/readyz`` signal (True before the first
        evaluation: never block startup on missing data)."""
        with self._lock:
            return all(s.status != "breach" for s in self._last)

    def worst(self) -> str:
        with self._lock:
            if not self._last:
                return "ok"
            return max(
                (s.status for s in self._last), key=_STATUS_ORDER.__getitem__
            )

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "ready": all(s.status != "breach" for s in self._last),
                "status": (
                    max((s.status for s in self._last),
                        key=_STATUS_ORDER.__getitem__)
                    if self._last else "ok"
                ),
                "evaluated_at": self._last_ts,
                "slos": [s.to_dict() for s in self._last],
            }

    def register_metrics(self, registry: Registry) -> None:
        """Expose per-SLO status + burn gauges on ``registry`` via
        pull callbacks (no bookkeeping beyond the last evaluation)."""
        status_name, _, status_help, _agg = SLO_METRIC_FAMILIES[0]
        burn_name, _, burn_help, _agg = SLO_METRIC_FAMILIES[1]

        def _status_samples():
            return [
                ({"slo": s.name}, float(_STATUS_ORDER[s.status]))
                for s in self.statuses()
            ]

        def _burn_samples():
            out = []
            for s in self.statuses():
                out.append(({"slo": s.name, "window": "short"}, s.burn_short))
                out.append(({"slo": s.name, "window": "long"}, s.burn_long))
            return out

        registry.register_callback(
            status_name, "gauge", status_help, _status_samples, labels=("slo",)
        )
        registry.register_callback(
            burn_name, "gauge", burn_help, _burn_samples,
            labels=("slo", "window"),
        )


def default_serving_slos(
    ttft_threshold_s: float = 1.0,
    tok_s_floor: float = 0.5,
    short_window_s: float = 300.0,
    long_window_s: float = 3600.0,
) -> tuple[SLOSpec, ...]:
    """The four stock serving objectives over families that already
    exist: TTFT p99 (request_trace's ``ttft_seconds``), error rate and
    availability (engine request counters), and a tok/s floor that only
    counts samples taken under load (idle != breach). serve.py builds
    these from env knobs (``DEVSPACE_SLO_*``)."""
    return (
        SLOSpec(
            name="ttft_p99", kind="latency", objective=0.99,
            histogram="ttft_seconds", threshold_s=ttft_threshold_s,
            short_window_s=short_window_s, long_window_s=long_window_s,
        ),
        SLOSpec(
            name="error_rate", kind="error_rate", objective=0.99,
            bad=("engine_requests_failed_total",),
            total=("engine_requests_failed_total",
                   "engine_requests_completed_total"),
            short_window_s=short_window_s, long_window_s=long_window_s,
        ),
        SLOSpec(
            name="availability", kind="error_rate", objective=0.999,
            bad=("engine_requests_failed_total",),
            total=("engine_requests_failed_total",
                   "engine_requests_completed_total"),
            short_window_s=long_window_s,
            long_window_s=long_window_s * 4,
            warn_burn=1.0, breach_burn=14.4,
        ),
        SLOSpec(
            name="tok_s_floor", kind="throughput_floor", objective=0.9,
            gauge="engine_tokens_per_sec_10s", floor=tok_s_floor,
            activity=("engine_active_slots", "engine_queued_requests"),
            short_window_s=short_window_s, long_window_s=long_window_s,
        ),
    )
