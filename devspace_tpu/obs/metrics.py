"""Dependency-free metrics registry with Prometheus text exposition.

The measurement substrate for the serving stack (ISSUE 6): Counter /
Gauge / Histogram primitives, labeled families, pull-style callback
metrics, and a renderer for the Prometheus text exposition format 0.0.4
(`/metrics` on the serving example, `devspace-tpu status serving`).

Design constraints, in order:

1. **Dependency-free.** No prometheus_client; the whole wire format is
   ~60 lines and the repo must not grow a runtime dependency for it.
2. **Two views, one truth.** Existing subsystems keep their plain-int
   counters (engine.stats(), sync session.stats, dispatcher counters) as
   the single mutation site; the registry exposes them through
   *callback* metrics that read the same memory at scrape time. No
   double bookkeeping in hot paths, no drift, no double-count risk.
3. **Thread-safe where mutated.** Direct Counter/Gauge/Histogram
   mutation takes a per-metric lock (histogram observes come from the
   scheduler thread while HTTP scrapes render concurrently). Callback
   metrics are lock-free by construction — they read GIL-atomic ints.
4. **Naming conventions are machine-checked** (scripts/metrics_lint.py):
   snake_case, counters end ``_total``, histograms carry a unit suffix
   (``_seconds``/``_bytes``). The registry itself validates the name
   charset at registration so a typo fails at import, not at scrape.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Fixed log-spaced latency buckets (seconds): sub-ms ... 60s. Shared by
# every latency histogram so dashboards can overlay TTFT/TPOT/queue-wait
# without per-metric bucket gymnastics.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _validate_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"invalid metric name {name!r} (want snake_case)")
    return name


def _fmt(v) -> str:
    """Prometheus sample value: integers without a trailing .0, +Inf for
    infinity, repr() floats otherwise (exact round-trip)."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s: str) -> str:
    return (
        str(s).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count. ``inc(n)`` with n >= 0 only."""

    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A value that can go up and down."""

    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` buckets at render, like
    Prometheus client libraries). Buckets are per-family and immutable."""

    kind = "histogram"

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        b = sorted(float(x) for x in buckets)
        if not b or any(
            b2 <= b1 for b1, b2 in zip(b, b[1:])
        ):
            raise ValueError(f"need strictly increasing buckets, got {buckets}")
        self.buckets = tuple(b)
        self._lock = threading.Lock()
        # counts[i] = observations in (buckets[i-1], buckets[i]];
        # counts[-1] = observations above the last finite bucket
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # bucket index -> (value, trace_id, wall_ts); last writer wins
        self._exemplars: dict[int, tuple[float, str, float]] = {}

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        """Record ``v``. ``exemplar`` (a trace_id) attaches an OpenMetrics
        exemplar to the bucket the observation lands in — last writer
        wins per bucket — linking e.g. a p99 TTFT bucket straight to the
        distributed trace that produced it (``render_openmetrics``).
        Without an exemplar the hot path is unchanged."""
        v = float(v)
        i = 0
        for i, edge in enumerate(self.buckets):  # noqa: B007
            if v <= edge:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar:
                self._exemplars[i] = (v, str(exemplar), time.time())

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative_count)...], "sum": s, "count": n,
        "exemplars": [...]}`` with the implicit +Inf bucket last;
        ``exemplars`` aligns with ``buckets`` — ``(value, trace_id,
        wall_ts)`` or None per bucket."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
            ex = dict(self._exemplars)
        out, cum = [], 0
        for edge, c in zip(self.buckets, counts):
            cum += c
            out.append((edge, cum))
        out.append((math.inf, n))
        exemplars = [ex.get(i) for i in range(len(self.buckets) + 1)]
        return {"buckets": out, "sum": s, "count": n, "exemplars": exemplars}

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One registered metric name: its kind, help text, label schema and
    children (one child per distinct label-value tuple; the unlabeled
    family has a single child keyed ``()``)."""

    def __init__(self, name, kind, help_, labelnames, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self.callback: Optional[Callable] = None
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _make_child(self):
        if self.kind == "histogram":
            return Histogram(self.buckets or DEFAULT_LATENCY_BUCKETS)
        return _KINDS[self.kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != schema "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def samples(self) -> list[tuple[dict, object]]:
        """``[(labels_dict, child_or_value)]``. Callback families call
        their fn at collect time; it returns a scalar (unlabeled) or an
        iterable of ``(labels_dict, value)``."""
        if self.callback is not None:
            got = self.callback()
            if isinstance(got, (int, float)):
                return [({}, float(got))]
            return [(dict(lb), float(v)) for lb, v in got]
        with self._lock:
            items = list(self._children.items())
        return [
            (dict(zip(self.labelnames, key)), child) for key, child in items
        ]


class Registry:
    """A namespace of metric families. Registration is idempotent for
    same-(name, kind) direct metrics (you get the existing family back);
    ``register_callback`` REPLACES an existing callback of the same name
    — the bridge for per-instance sources (latest instance wins)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, name, kind, help_, labels, buckets=None):
        _validate_name(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}"
                    )
                return fam
            fam = _Family(name, kind, help_, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help_, labels: Sequence[str] = ()):
        fam = self._get_or_create(name, "counter", help_, labels)
        return fam if fam.labelnames else fam.labels()

    def gauge(self, name, help_, labels: Sequence[str] = ()):
        fam = self._get_or_create(name, "gauge", help_, labels)
        return fam if fam.labelnames else fam.labels()

    def histogram(
        self,
        name,
        help_,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ):
        fam = self._get_or_create(name, "histogram", help_, labels, buckets)
        return fam if fam.labelnames else fam.labels()

    def register_callback(
        self, name, kind, help_, fn: Callable, labels: Sequence[str] = ()
    ) -> None:
        """Pull-style metric: ``fn`` is called at collect time and returns
        a scalar, or — for labeled families — an iterable of
        ``(labels_dict, value)``. Re-registering a name replaces the
        callback (per-instance bridges re-bind on instance churn)."""
        if kind not in ("counter", "gauge"):
            raise ValueError(f"callback metrics must be counter/gauge, not {kind}")
        _validate_name(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None and fam.callback is None:
                raise ValueError(
                    f"metric {name!r} already registered as a direct metric"
                )
            fam = _Family(name, kind, help_, labels)
            fam.callback = fn
            self._families[name] = fam

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._families)

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    # -- collection --------------------------------------------------------
    def snapshot(self) -> dict:
        """``{name: {"kind", "help", "samples": [(labels, value_or_hist)]}}``
        where histogram values are :meth:`Histogram.snapshot` dicts."""
        out = {}
        for fam in self.families():
            samples = []
            for labels, child in fam.samples():
                if isinstance(child, Histogram):
                    samples.append((labels, child.snapshot()))
                elif isinstance(child, (Counter, Gauge)):
                    samples.append((labels, child.value))
                else:
                    samples.append((labels, child))
            out[fam.name] = {
                "kind": fam.kind,
                "help": fam.help,
                "samples": samples,
            }
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        return render_snapshot(self.snapshot())

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition — same families as
        :meth:`render` plus histogram bucket exemplars
        (``... # {trace_id="..."} value ts``), which is the one thing
        the 0.0.4 format cannot carry. Served on ``/metrics`` content
        negotiation by the serving example."""
        lines: list[str] = []
        for name, fam in sorted(self.snapshot().items()):
            kind = fam["kind"]
            # OpenMetrics: a counter family is named WITHOUT the _total
            # suffix; its sample keeps it.
            fam_name = (
                name[: -len("_total")]
                if kind == "counter" and name.endswith("_total")
                else name
            )
            lines.append(f"# TYPE {fam_name} {kind}")
            lines.append(f"# HELP {fam_name} {_escape_help(fam['help'])}")
            for labels, val in fam["samples"]:
                if kind == "histogram":
                    exemplars = val.get("exemplars") or [None] * len(
                        val["buckets"]
                    )
                    for (le, cum), ex in zip(val["buckets"], exemplars):
                        lb = dict(labels)
                        lb["le"] = _fmt(le)
                        line = f"{name}_bucket{_label_str(lb)} {cum}"
                        if ex is not None:
                            ev, tid, ts = ex
                            line += (
                                f' # {{trace_id="{_escape_label_value(tid)}"}}'
                                f" {_fmt(ev)} {_fmt(ts)}"
                            )
                        lines.append(line)
                    lines.append(
                        f"{name}_sum{_label_str(labels)} {_fmt(val['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_label_str(labels)} {val['count']}"
                    )
                else:
                    lines.append(f"{name}{_label_str(labels)} {_fmt(val)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def render_snapshot(snap: dict) -> str:
    """Prometheus text exposition 0.0.4 from a :meth:`Registry.snapshot`
    -shaped dict. Module-level so the fleet collector can render a
    *merged* snapshot that never lived in a Registry (obs/fleet.py)."""
    lines: list[str] = []
    for name, fam in sorted(snap.items()):
        lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        for labels, val in fam["samples"]:
            if fam["kind"] == "histogram":
                for le, cum in val["buckets"]:
                    lb = dict(labels)
                    lb["le"] = _fmt(le)
                    lines.append(f"{name}_bucket{_label_str(lb)} {cum}")
                lines.append(
                    f"{name}_sum{_label_str(labels)} {_fmt(val['sum'])}"
                )
                lines.append(
                    f"{name}_count{_label_str(labels)} {val['count']}"
                )
            else:
                lines.append(f"{name}{_label_str(labels)} {_fmt(val)}")
    return "\n".join(lines) + ("\n" if lines else "")


class WindowedRate:
    """Events-per-second over a sliding ~``window_s`` window, from
    1-second buckets — the fix for ``tokens_per_sec`` being a lifetime
    average that goes stale after idle periods (ISSUE 6 satellite).

    ``add`` is the hot path (once per emitted token): one clock read, one
    modulo, one locked add. ``rate`` sums buckets stamped within the
    window and divides by the *covered* window length — ``min(window,
    elapsed since the first add)`` — so a cold start no longer
    under-reports by dividing a partial window's count by the full
    window (ISSUE 9 satellite); it still decays to 0 within ``window_s``
    of traffic stopping (the lifetime average never does)."""

    def __init__(self, window_s: float = 10.0, clock=time.monotonic):
        self.window = max(1, int(window_s))
        self._clock = clock
        self._n = self.window + 1  # +1: current partial second
        self._counts = [0.0] * self._n
        self._stamps = [-1] * self._n
        self._first: Optional[float] = None  # clock time of the first add
        self._lock = threading.Lock()

    def add(self, n: float = 1.0) -> None:
        now = self._clock()
        t = int(now)
        i = t % self._n
        with self._lock:
            if self._first is None:
                self._first = now
            if self._stamps[i] != t:
                self._stamps[i] = t
                self._counts[i] = 0.0
            self._counts[i] += n

    def rate(self) -> float:
        now = self._clock()
        t = int(now)
        lo = t - self.window
        with self._lock:
            if self._first is None:
                return 0.0
            total = sum(
                c
                for c, s in zip(self._counts, self._stamps)
                if lo < s <= t
            )
            covered = min(float(self.window), max(1.0, now - self._first))
        return total / covered


# -- process-wide default registry ------------------------------------------
# Engines get a PRIVATE registry each (tests build many engines per
# process; private registries keep their families from colliding). The
# default registry carries process-wide sources: sync sessions,
# resilience counters, the span-trace ring.
_default_registry = Registry()


def get_registry() -> Registry:
    return _default_registry


def metrics_enabled(explicit: Optional[bool] = None) -> bool:
    """Engine metrics on/off resolution, mirroring the
    ``DEVSPACE_ENGINE_OVERLAP`` pattern: explicit constructor arg wins,
    then the ``DEVSPACE_ENGINE_METRICS`` env knob (``off``/``0``/...
    disables), default ON — this is the bench A/B escape hatch for the
    <= 2% overhead guard (bench.py)."""
    if explicit is not None:
        return bool(explicit)
    env = os.environ.get("DEVSPACE_ENGINE_METRICS", "").strip().lower()
    return env not in ("off", "0", "false", "no")
