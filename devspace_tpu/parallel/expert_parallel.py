"""Expert parallelism: Mixture-of-Experts with all-to-all dispatch.

GShard/Switch-style MoE laid out TPU-first: experts are sharded over a
mesh axis (conventionally the ``data`` axis — ep-over-dp, the standard
TPU recipe), tokens stay batch-sharded on the same axis, and routing
moves tokens to their expert's device with a pair of ``jax.lax.all_to_all``
collectives that ride ICI. Inside each device the expert FFNs run as one
batched einsum over the local expert dim, keeping the MXU busy with a
single large contraction instead of E small ones.

Routing is capacity-based top-k (k=1 -> Switch, k=2 -> GShard): each
expert accepts at most ``capacity`` tokens per device per step; overflow
tokens fall through the residual connection (their combine weight is
zero). Static shapes throughout — capacity is computed from the static
token count, so the whole layer is jit/scan-friendly.

The reference (hoatle/devspace) contains no ML parallelism at all
(SURVEY.md §2.13); this module is part of the TPU-native framework's
first-class parallelism layer alongside data/tensor/pipeline/sequence.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def swiglu(h):
    """SwiGLU expert activation for fused gate+up projections: ``h``
    [..., 2F] (gate | up concatenated on the last dim) -> [..., F].
    Lets Mixtral-style experts ride the same single batched einsum as
    plain-MLP experts."""
    f = h.shape[-1] // 2
    return jax.nn.silu(h[..., :f]) * h[..., f:]


def init_moe_params(
    key,
    dim: int,
    ffn_dim: int,
    num_experts: int,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> dict:
    """Pytree params: router ``w_gate`` [D, E] (kept float32 — routing
    logits are precision-sensitive) and stacked expert FFNs ``w_up``
    [E, D, F], ``w_down`` [E, F, D]."""
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w_gate": jax.random.normal(k1, (dim, num_experts), jnp.float32) * scale,
        "w_up": (
            jax.random.normal(k2, (num_experts, dim, ffn_dim), jnp.float32) * scale
        ).astype(dtype),
        "w_down": (
            jax.random.normal(k3, (num_experts, ffn_dim, dim), jnp.float32) * scale
        ).astype(dtype),
    }


def moe_param_spec(axis: str = "data") -> dict:
    """PartitionSpec tree matching ``init_moe_params``: experts sharded
    over ``axis``, router replicated."""
    return {
        "w_gate": P(),
        "w_up": P(axis, None, None),
        "w_down": P(axis, None, None),
    }


def shard_moe_params(params: dict, mesh: Mesh, axis: str = "data") -> dict:
    return jax.tree.map(
        lambda w, spec: jax.device_put(w, NamedSharding(mesh, spec)),
        params,
        moe_param_spec(axis),
    )


def expert_capacity(
    tokens_per_device: int, num_experts: int, capacity_factor: float, k: int
) -> int:
    """Per-expert, per-source-device slot count (static)."""
    return max(1, math.ceil(capacity_factor * k * tokens_per_device / num_experts))


def _route(probs, k: int, capacity: int):
    """Capacity-based top-k routing (all static shapes).

    probs: [T, E] router probabilities. Returns (dispatch [T, E, C] 0/1,
    combine [T, E, C] floats, aux_loss scalar). Tokens beyond an expert's
    capacity are dropped (combine row = 0 -> residual passthrough).
    """
    T, E = probs.shape
    remaining = probs
    counts = jnp.zeros((E,), jnp.int32)  # slots used per expert so far
    dispatch = jnp.zeros((T, E, capacity), jnp.bool_)
    gates = []  # per-choice kept gate values [T]
    onehots = []  # per-choice expert one-hot [T, E]
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)  # [T, E]
        gate = jnp.sum(remaining * onehot, axis=-1)  # [T]
        # position of each token within its chosen expert's queue:
        # tokens earlier in the batch (and earlier choices) get priority.
        pos_matrix = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :].astype(
            probs.dtype
        )
        pos = jnp.sum(pos_matrix * onehot, axis=-1).astype(jnp.int32)  # [T]
        keep = pos < capacity
        slot = jax.nn.one_hot(
            jnp.where(keep, pos, capacity), capacity, dtype=jnp.float32
        )  # [T, C] (overflow rows all-zero)
        dispatch = dispatch | (
            (onehot[:, :, None] * slot[:, None, :]) > 0.5
        )
        counts = counts + jnp.sum(
            onehot * keep[:, None].astype(probs.dtype), axis=0
        ).astype(jnp.int32)
        gates.append(jnp.where(keep, gate, 0.0))
        onehots.append(onehot)
        remaining = remaining * (1.0 - onehot)
    # normalize kept gates across choices (GShard top-2 normalization)
    gate_stack = jnp.stack(gates, axis=0)  # [k, T]
    denom = jnp.sum(gate_stack, axis=0, keepdims=True)
    gate_stack = gate_stack / jnp.maximum(denom, 1e-9)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    for c in range(k):
        choice_disp = (
            onehots[c][:, :, None] * dispatch.astype(probs.dtype)
        )  # this choice's slots
        combine = combine + gate_stack[c][:, None, None] * choice_disp
    # Switch load-balancing aux loss on the primary assignment:
    # E * sum_e fraction_dispatched_e * mean_prob_e (1.0 when balanced).
    frac = jnp.mean(onehots[0], axis=0)  # [E]
    mean_prob = jnp.mean(probs, axis=0)  # [E]
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_ffn(
    mesh: Mesh,
    axis: str = "data",
    k: int = 1,
    capacity_factor: float = 1.25,
    activation: Callable = jax.nn.gelu,
):
    """Build the expert-parallel MoE FFN.

    Returns ``f(x, params) -> (y, aux_loss)`` where x is [T, D] with T
    sharded over ``axis`` and params as ``init_moe_params`` sharded per
    ``moe_param_spec`` (E over ``axis``). Per shard:

      route -> dispatch einsum -> all_to_all (tokens to their expert's
      device) -> batched expert FFN -> all_to_all back -> combine einsum

    aux_loss is the Switch load-balancing loss, psum-averaged over the
    axis; add ``aux_weight * aux_loss`` (typically 1e-2) to the train loss.
    """
    n_shards = mesh.shape[axis]

    def local_fn(x, params):
        T, D = x.shape  # local tokens
        E = params["w_gate"].shape[1]  # global expert count
        assert E % n_shards == 0, f"experts {E} not divisible by axis {n_shards}"
        capacity = expert_capacity(T, E, capacity_factor, k)
        logits = jnp.einsum(
            "td,de->te", x.astype(jnp.float32), params["w_gate"]
        )
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, aux = _route(probs, k, capacity)
        # [T, E, C] x [T, D] -> [E, C, D]: gather each expert's tokens
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(x.dtype), x
        )
        # tokens to their expert's device: split E, concat C.
        # [E, C, D] -> [E/n, n*C, D]; dim 1 is now (source_shard, slot).
        expert_in = jax.lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=1, tiled=True
        )
        w_up, w_down = params["w_up"], params["w_down"]  # local [E/n, D, F]
        h = activation(
            jnp.einsum(
                "ecd,edf->ecf", expert_in, w_up,
                preferred_element_type=jnp.float32,
            )
        ).astype(x.dtype)
        expert_out = jnp.einsum(
            "ecf,efd->ecd", h, w_down, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        # route results back to the tokens' home devices
        expert_out = jax.lax.all_to_all(
            expert_out, axis, split_axis=1, concat_axis=0, tiled=True
        )
        y = jnp.einsum(
            "tec,ecd->td", combine.astype(x.dtype), expert_out
        )
        return y, jax.lax.pmean(aux, axis)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis, None), moe_param_spec(axis)),
        out_specs=(P(axis, None), P()),
        check_vma=False,
    )


def moe_ffn_reference(x, params, k: int = 1, capacity_factor: float = 1.25,
                      activation: Callable = jax.nn.gelu):
    """Single-device reference semantics (no mesh) for testing: identical
    routing and capacity rules, experts applied densely."""
    T, D = x.shape
    E = params["w_gate"].shape[1]
    capacity = expert_capacity(T, E, capacity_factor, k)
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32), params["w_gate"])
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _route(probs, k, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    h = activation(
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_up"],
                   preferred_element_type=jnp.float32)
    ).astype(x.dtype)
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["w_down"], preferred_element_type=jnp.float32
    ).astype(x.dtype)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return y, aux
