"""Pipeline parallelism over a ``pipe`` mesh axis.

GPipe-style schedule expressed the TPU way: every device holds one stage's
params (sharded on ``pipe``), microbatches flow through a
``jax.lax.scan`` over time steps, and activations hop to the next stage
with ``jax.lax.ppermute`` (ICI neighbor transfer). With S stages and M
microbatches the scan runs M + S - 1 ticks; device s computes on ticks
s..s+M-1 — idle ticks multiply by a 0/1 mask instead of branching, which
keeps the loop a single fused XLA while-op (no data-dependent control
flow under jit).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable,
    axis: str = "pipe",
    params_spec: tuple = (),
    xs_spec: tuple = (),
):
    """Build ``f(stage_params, x_microbatches) -> y_microbatches``.

    ``stage_params``: pytree whose leaves have a leading stage dim S,
    sharded over ``axis`` (each device sees its own stage's slice).
    ``x_microbatches``: [M, mb, ...] replicated along ``axis``; returns
    [M, mb, ...] outputs of the final stage (replicated along ``axis``).
    ``stage_fn(params_one_stage, x) -> y`` must map activations to
    activations of the same shape (classic homogeneous-stage pipeline).

    Composition with tp/dp in the same mesh: ``params_spec`` shards the
    dims AFTER each param leaf's leading stage dim (e.g. ``("model",)``
    keeps stage weights row-sharded inside the stages — stage_fn then
    owns the tensor-parallel psum), and ``xs_spec`` shards the dims after
    the microbatch dim of ``xs`` (e.g. ``("data",)`` keeps microbatches
    data-sharded end to end). Without these, weights/activations arrive
    replicated over those axes. ``params_spec`` may also be a pytree of
    per-leaf tuples matching ``stage_params`` for mixed-rank leaves
    (e.g. ``{"w": ("model",), "b": (None,)}`` so a [S, d, d] weight is
    row-sharded while its [S, d] bias stays replicated).
    """
    n_stages = mesh.shape[axis]

    def local_fn(params, xs):
        # params leaves arrive with leading dim 1 (this device's stage).
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        total = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            outputs, prev_act = carry
            # Stage 0 feeds microbatch t (while t < M); later stages use
            # the activation passed from the previous stage.
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(stage == 0, xs[mb_idx], prev_act)
            y = stage_fn(params, x_in)
            # Validity: stage s works on tick t iff s <= t < s + M.
            valid = jnp.logical_and(stage <= t, t < stage + n_micro)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # Last stage stores its result for microbatch t - (S-1).
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_last = stage == n_stages - 1
            store = jnp.logical_and(is_last, t >= n_stages - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(store, y, outputs[out_idx]),
                out_idx,
                axis=0,
            )
            # Activations hop to the next stage.
            nxt = jax.lax.ppermute(y, axis, perm)
            return (outputs, nxt), None

        outputs = jnp.zeros_like(xs)
        prev = jnp.zeros_like(xs[0])
        (outputs, _), _ = jax.lax.scan(tick, (outputs, prev), jnp.arange(total))
        # Only the last stage holds real outputs; broadcast via all_gather
        # (ppermute forbids multicast from one source).
        gathered = jax.lax.all_gather(outputs, axis)
        return gathered[n_stages - 1]

    if isinstance(params_spec, tuple):
        params_in_spec = P(axis, *params_spec)
    else:  # pytree of per-leaf dim tuples (prefix pytree for shard_map)
        params_in_spec = jax.tree_util.tree_map(
            lambda leaf_spec: P(axis, *leaf_spec),
            params_spec,
            is_leaf=lambda x: isinstance(x, tuple),
        )
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(params_in_spec, P(None, *xs_spec)),
        out_specs=P(None, *xs_spec),
        check_vma=False,
    )


def stack_stage_params(param_list):
    """Stack per-stage pytrees into the leading-stage-dim layout that
    pipeline_apply expects (shard the result over the pipe axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


# ---------------------------------------------------------------------------
# 1F1B pipeline training for the transformer (heterogeneous end-to-end)
# ---------------------------------------------------------------------------
# VERDICT r1 next #4: a REAL model — embedding -> n_stages groups of
# transformer layers (stage-sharded over `pipe`) -> final-norm + LM head —
# trained with the one-forward-one-backward schedule, not GPipe-via-grad.
#
# Schedule (PipeDream-flush / non-interleaved 1F1B), mapped onto a global
# tick clock so the whole thing is ONE lax.scan under shard_map:
#   stage s runs forward  f at tick  tau = s + 2f                (f < M)
#   stage s runs backward b at tick  tau = 2S - 1 - s + 2b       (b < M)
# F and B ticks have opposite parity per device, so each tick a device
# does exactly one of {F, B, idle} — selected with lax.cond (the branches
# contain no collectives; the ppermute hops run unconditionally each tick,
# carrying zeros when nothing was produced — the receiver only reads a
# channel on the tick the schedule says a real value arrives).
#
# Why embed/head are replicated, not stages: they are not in the
# steady-state loop. Embedding is a gather (computed by stage 0's F tick);
# head+loss run inside the LAST stage's B tick — that is what makes the
# schedule 1F1B: microbatch m's backward starts the tick after its forward
# leaves the last stage, bounding stored activations at S - s microbatches
# per device (ring buffer) instead of GPipe's M.
#
# Backward recomputes the stage forward (activation recomputation): the
# ring stores only stage INPUTS; jax.vjp re-runs the K-layer group on the
# B tick. Grads: stage grads stay sharded over `pipe`; embed/head grads
# are nonzero on one stage and psum'd over `pipe` to all.


def transformer_stage_params(params: dict, n_stages: int) -> dict:
    """Split standard transformer params (models.transformer.init_params)
    into the pipeline layout: {"embed", "stages" [S, K, ...], "final_norm",
    "lm_head"} with K = n_layers / n_stages."""
    n_layers = len(params["layers"])
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    k = n_layers // n_stages
    groups = [
        stack_stage_params(params["layers"][s * k : (s + 1) * k])
        for s in range(n_stages)
    ]
    return {
        "embed": params["embed"],
        "stages": stack_stage_params(groups),  # [S, K, ...]
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def transformer_unstage_params(stage_params: dict) -> dict:
    """Inverse of transformer_stage_params."""
    stages = stage_params["stages"]
    s = jax.tree_util.tree_leaves(stages)[0].shape[0]
    k = jax.tree_util.tree_leaves(stages)[0].shape[1]
    layers = []
    for si in range(s):
        for ki in range(k):
            layers.append(
                jax.tree_util.tree_map(lambda p: p[si, ki], stages)
            )
    return {
        "embed": stage_params["embed"],
        "layers": layers,
        "final_norm": stage_params["final_norm"],
        "lm_head": stage_params["lm_head"],
    }


def pipeline_param_specs(axis: str = "pipe", tp_axis: str = None) -> dict:
    """PartitionSpec tree for the staged-transformer layout. Stage groups
    are sharded over the pipe axis; with ``tp_axis`` the per-layer weights
    are ADDITIONALLY Megatron-sharded over the model axis (columns for
    qkv/gate/up, rows for o/down — leaves are [S, K, d_in, d_out])."""
    if tp_axis is None:
        return {
            "embed": P(),
            "stages": P(axis),
            "final_norm": P(),
            "lm_head": P(),
        }
    return {
        "embed": P(),
        "stages": {
            "wq": P(axis, None, None, tp_axis),
            "wk": P(axis, None, None, tp_axis),
            "wv": P(axis, None, None, tp_axis),
            "wo": P(axis, None, tp_axis, None),
            "w_gate": P(axis, None, None, tp_axis),
            "w_up": P(axis, None, None, tp_axis),
            "w_down": P(axis, None, tp_axis, None),
            "attn_norm": P(axis, None, None),
            "ffn_norm": P(axis, None, None),
        },
        "final_norm": P(),
        "lm_head": P(),
    }


def _tp_layer_setup(cfg, tp: int, tp_axis):
    """Per-shard cfg + layer_apply hooks for Megatron-TP stages — the ONE
    place the tensor-parallel boundary wiring lives (shared by the plain
    and interleaved 1F1B schedules)."""
    if tp_axis is None:
        return cfg, {}
    import dataclasses

    from .tensor_parallel import copy_fwd_psum_bwd, psum_fwd_copy_bwd

    local_cfg = dataclasses.replace(
        cfg,
        n_heads=cfg.n_heads // tp,
        n_kv_heads=cfg.n_kv_heads // tp,
        ffn_dim=cfg.ffn_dim // tp,
        head_dim_override=cfg.head_dim,
    )
    layer_kwargs = dict(
        pre_block=lambda x: copy_fwd_psum_bwd(x, tp_axis),
        post_block=lambda x: psum_fwd_copy_bwd(x, tp_axis),
    )
    return local_cfg, layer_kwargs


def _check_tp_divisibility(cfg, tp: int) -> None:
    if cfg.n_heads % tp or cfg.n_kv_heads % tp or cfg.ffn_dim % tp:
        raise ValueError(
            f"heads/kv/ffn ({cfg.n_heads}/{cfg.n_kv_heads}/{cfg.ffn_dim}) "
            f"not divisible by tp={tp}"
        )


def _reduce_pipeline_grads(
    loss_sum, g_embed, g_head, g_stages, axis, data_axis, m_total
):
    """Shared grad epilogue: loss lives on the last stage, embed grad on
    stage 0, head grads on the last stage — psum over pipe replicates the
    totals; stage grads stay pipe-sharded; everything pmeans over data."""
    loss = jax.lax.psum(loss_sum, axis) / m_total
    g_embed = jax.lax.psum(g_embed, axis) / m_total
    g_head = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis) / m_total, g_head
    )
    g_stages = jax.tree_util.tree_map(lambda g: g / m_total, g_stages)
    if data_axis is not None:
        loss = jax.lax.pmean(loss, data_axis)
        g_embed = jax.lax.pmean(g_embed, data_axis)
        g_head = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, data_axis), g_head
        )
        g_stages = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, data_axis), g_stages
        )
    return loss, g_embed, g_head, g_stages


def pipeline_lm_loss_and_grads(
    mesh: Mesh,
    cfg,
    n_microbatches: int,
    axis: str = "pipe",
    data_axis: str = None,
    tp_axis: str = None,
):
    """Build ``f(stage_params, tokens) -> (loss, grads)`` running the
    transformer forward+backward under the 1F1B schedule.

    ``tokens``: [M, mb, T+1] int32 (next-token LM: inputs are [:, :, :-1],
    targets [:, :, 1:]); M must equal ``n_microbatches``. ``stage_params``
    from transformer_stage_params, sharded over ``axis``. With
    ``data_axis`` set, the microbatch dim (mb) is additionally sharded
    over that mesh axis (PP x DP); loss/grads are psum'd accordingly.
    With ``tp_axis`` set, each stage's weights are Megatron-sharded over
    the model axis and the stage math runs head/ffn-parallel with the
    f/g boundary ops (PP x DP x TP in ONE program — VERDICT r2 next #2);
    the f/g custom-vjp pair keeps the 1F1B backward's jax.vjp from ever
    transposing a raw psum. Returns the mean loss over all microbatches
    and a grads pytree shaped like stage_params."""
    from ..models.transformer import (
        layer_apply,
        rms_norm,
        rope_frequencies,
    )
    from ..ops.losses import fused_cross_entropy

    n_stages = mesh.shape[axis]
    m_total = n_microbatches
    tp = mesh.shape[tp_axis] if tp_axis else 1
    _check_tp_divisibility(cfg, tp)
    local_cfg, layer_kwargs = _tp_layer_setup(cfg, tp, tp_axis)

    def local_fn(stage_params, tokens):
        stage = jax.lax.axis_index(axis)
        stages = jax.tree_util.tree_map(lambda p: p[0], stage_params["stages"])
        embed = stage_params["embed"]
        final_norm = stage_params["final_norm"]
        lm_head = stage_params["lm_head"]
        inputs = tokens[:, :, :-1]  # [M, mb, T]
        targets = tokens[:, :, 1:]
        m, mb, t = inputs.shape
        cos, sin = rope_frequencies(cfg, jnp.arange(t))
        is_first = stage == 0
        is_last = stage == n_stages - 1
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        # Tensor parallelism reuses layer_apply (the single source of
        # truth for the layer math) with the per-shard cfg + Megatron
        # f/g boundary hooks from _tp_layer_setup (activations enter
        # sharded blocks via f = copy-fwd/psum-bwd, leave via
        # g = psum-fwd/copy-bwd, so h stays replicated over tp).
        def stage_forward(stages_, x):
            def one(h, layer):
                h, _ = layer_apply(
                    h, layer, local_cfg, cos, sin, **layer_kwargs
                )
                return h, None

            h, _ = jax.lax.scan(one, x, stages_)
            return h

        def head_loss(head, y, target):
            h = rms_norm(y, head["final_norm"], cfg.norm_eps)
            logits = (h @ head["lm_head"]).astype(jnp.float32)
            b_, t_, v_ = logits.shape
            losses = fused_cross_entropy(
                logits.reshape(b_ * t_, v_), target.reshape(-1)
            )
            return jnp.mean(losses)

        head = {"final_norm": final_norm, "lm_head": lm_head}
        act_shape = (mb, t, cfg.dim)
        zero_act = jnp.zeros(act_shape, cfg.dtype)

        def tick(carry, tau):
            (fwd_in, bwd_in, ring, f_cnt, b_cnt, g_stages, g_embed, g_head,
             loss_sum) = carry
            do_f = jnp.logical_and(tau == stage + 2 * f_cnt, f_cnt < m)
            do_b = jnp.logical_and(
                tau == 2 * n_stages - 1 - stage + 2 * b_cnt, b_cnt < m
            )

            # ---- forward tick -------------------------------------------
            def f_branch(args):
                fwd_in, ring, f_cnt = args
                mb_idx = jnp.clip(f_cnt, 0, m - 1)
                x0 = embed[inputs[mb_idx]].astype(cfg.dtype)  # [mb, T, D]
                x_in = jnp.where(is_first, x0, fwd_in)
                y = stage_forward(stages, x_in)
                ring = jax.lax.dynamic_update_index_in_dim(
                    ring, x_in, jnp.mod(f_cnt, n_stages), axis=0
                )
                return y, ring, f_cnt + 1

            def f_skip(args):
                fwd_in, ring, f_cnt = args
                return zero_act, ring, f_cnt

            y_out, ring, f_cnt = jax.lax.cond(
                do_f, f_branch, f_skip, (fwd_in, ring, f_cnt)
            )

            # ---- backward tick ------------------------------------------
            def b_branch(args):
                bwd_in, b_cnt, g_stages, g_embed, g_head, loss_sum = args
                mb_idx = jnp.clip(b_cnt, 0, m - 1)
                x_stored = ring[jnp.mod(b_cnt, n_stages)]
                y_st, vjp_fn = jax.vjp(stage_forward, stages, x_stored)

                # last stage: seed from head+loss (computed HERE — that is
                # the 1F1B property); other stages: seed from the grad hop
                def seed_last(_):
                    (loss, (dhead, dy)) = jax.value_and_grad(
                        head_loss, argnums=(0, 1)
                    )(head, y_st, targets[mb_idx])
                    return dy.astype(cfg.dtype), dhead, loss

                def seed_mid(_):
                    zero_head = jax.tree_util.tree_map(jnp.zeros_like, head)
                    return bwd_in, zero_head, jnp.zeros((), jnp.float32)

                dy, dhead, loss = jax.lax.cond(is_last, seed_last, seed_mid, None)
                dstages, dx = vjp_fn(dy)
                g_stages = jax.tree_util.tree_map(
                    jnp.add, g_stages, dstages
                )
                g_head = jax.tree_util.tree_map(jnp.add, g_head, dhead)

                # stage 0 owns the embedding backward (vjp of the gather)
                def embed_grad(_):
                    _, evjp = jax.vjp(
                        lambda e: e[inputs[mb_idx]].astype(cfg.dtype), embed
                    )
                    return evjp(dx)[0]

                g_embed = g_embed + jax.lax.cond(
                    is_first, embed_grad, lambda _: jnp.zeros_like(g_embed), None
                )
                return bwd_in, b_cnt + 1, g_stages, g_embed, g_head, \
                    loss_sum + loss, dx

            def b_skip(args):
                bwd_in, b_cnt, g_stages, g_embed, g_head, loss_sum = args
                return bwd_in, b_cnt, g_stages, g_embed, g_head, loss_sum, \
                    zero_act

            bwd_in, b_cnt, g_stages, g_embed, g_head, loss_sum, dx_out = (
                jax.lax.cond(
                    do_b,
                    b_branch,
                    b_skip,
                    (bwd_in, b_cnt, g_stages, g_embed, g_head, loss_sum),
                )
            )

            # ---- hops (unconditional: collectives can't live in cond) ---
            fwd_in = jax.lax.ppermute(y_out, axis, fwd_perm)
            bwd_in = jax.lax.ppermute(dx_out, axis, bwd_perm)
            return (
                fwd_in, bwd_in, ring, f_cnt, b_cnt, g_stages, g_embed,
                g_head, loss_sum,
            ), None

        ring0 = jnp.zeros((n_stages,) + act_shape, cfg.dtype)
        g_stages0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), stages
        )
        g_head0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), head
        )
        carry0 = (
            zero_act, zero_act, ring0, jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32), g_stages0,
            jnp.zeros_like(embed, jnp.float32), g_head0,
            jnp.zeros((), jnp.float32),
        )
        total_ticks = 2 * (m + n_stages - 1)
        (carry, _) = jax.lax.scan(
            tick, carry0, jnp.arange(total_ticks, dtype=jnp.int32)
        )
        (_, _, _, _, _, g_stages, g_embed, g_head, loss_sum) = carry

        loss, g_embed, g_head, g_stages = _reduce_pipeline_grads(
            loss_sum, g_embed, g_head, g_stages, axis, data_axis, m_total
        )
        grads = {
            "embed": g_embed,
            "stages": jax.tree_util.tree_map(lambda g: g[None], g_stages),
            "final_norm": g_head["final_norm"],
            "lm_head": g_head["lm_head"],
        }
        return loss, grads

    param_specs = pipeline_param_specs(axis, tp_axis)
    tok_spec = P(None, data_axis) if data_axis else P()
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, tok_spec),
        out_specs=(P(), param_specs),
        check_vma=False,
    )


def transformer_interleaved_stage_params(
    params: dict, n_stages: int, n_chunks: int
) -> dict:
    """Split transformer params into the INTERLEAVED layout: virtual
    stage p = v * S + s holds layers [p*K, (p+1)*K); leaves are
    [V, S, K, ...] so sharding dim 1 over `pipe` hands device s its V
    chunks {v*S+s} (Megatron virtual-pipeline assignment)."""
    n_layers = len(params["layers"])
    total = n_stages * n_chunks
    if n_layers % total:
        raise ValueError(
            f"{n_layers} layers not divisible by {n_stages} stages x "
            f"{n_chunks} chunks"
        )
    k = n_layers // total
    chunks = []
    for v in range(n_chunks):
        per_stage = []
        for s in range(n_stages):
            p = v * n_stages + s
            per_stage.append(
                stack_stage_params(params["layers"][p * k : (p + 1) * k])
            )
        chunks.append(stack_stage_params(per_stage))  # [S, K, ...]
    return {
        "embed": params["embed"],
        "stages": stack_stage_params(chunks),  # [V, S, K, ...]
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def transformer_uninterleave_params(stage_params: dict) -> dict:
    """Inverse of transformer_interleaved_stage_params."""
    stages = stage_params["stages"]
    leaf = jax.tree_util.tree_leaves(stages)[0]
    v_n, s_n, k_n = leaf.shape[0], leaf.shape[1], leaf.shape[2]
    layers = []
    for p in range(v_n * s_n):
        v, s = p // s_n, p % s_n
        for ki in range(k_n):
            layers.append(
                jax.tree_util.tree_map(lambda x: x[v, s, ki], stages)
            )
    return {
        "embed": stage_params["embed"],
        "layers": layers,
        "final_norm": stage_params["final_norm"],
        "lm_head": stage_params["lm_head"],
    }


def interleaved_pipeline_lm_loss_and_grads(
    mesh: Mesh,
    cfg,
    n_microbatches: int,
    n_chunks: int,
    axis: str = "pipe",
    data_axis: str = None,
    tp_axis: str = None,
):
    """Interleaved (virtual-stage) 1F1B — ``f(stage_params, tokens) ->
    (loss, grads)`` with ``stage_params`` from
    transformer_interleaved_stage_params. Same math as the non-
    interleaved schedule, ~V-fold smaller pipeline bubble (see
    parallel/interleaved.py for the schedule construction). Composes
    with ``data_axis`` (microbatch sharding) and ``tp_axis`` (Megatron
    tensor parallelism inside every chunk) like the non-interleaved
    version."""
    from ..models.transformer import (
        layer_apply,
        rms_norm,
        rope_frequencies,
    )
    from ..ops.losses import fused_cross_entropy
    from .interleaved import OP_B, OP_F, build_interleaved_schedule

    n_stages = mesh.shape[axis]
    sched = build_interleaved_schedule(n_stages, n_chunks, n_microbatches)
    m_total = n_microbatches
    tp = mesh.shape[tp_axis] if tp_axis else 1
    _check_tp_divisibility(cfg, tp)
    local_cfg, layer_kwargs = _tp_layer_setup(cfg, tp, tp_axis)

    # schedule tables as device-resident constants
    t_op = jnp.asarray(sched.op)
    t_chunk = jnp.asarray(sched.chunk)
    t_mb = jnp.asarray(sched.mb)
    t_slot = jnp.asarray(sched.slot)
    t_recv_f_c = jnp.asarray(sched.recv_f_chunk)
    t_recv_f_s = jnp.asarray(sched.recv_f_slot)
    t_recv_b_c = jnp.asarray(sched.recv_b_chunk)
    t_recv_b_s = jnp.asarray(sched.recv_b_slot)

    def local_fn(stage_params, tokens):
        stage = jax.lax.axis_index(axis)
        # [V, 1, K, ...] local -> [V, K, ...]
        stages = jax.tree_util.tree_map(
            lambda p: p[:, 0], stage_params["stages"]
        )
        embed = stage_params["embed"]
        head = {
            "final_norm": stage_params["final_norm"],
            "lm_head": stage_params["lm_head"],
        }
        inputs = tokens[:, :, :-1]  # [M, mb, T]
        targets = tokens[:, :, 1:]
        m, mb, t = inputs.shape
        cos, sin = rope_frequencies(cfg, jnp.arange(t))

        def chunk_forward(chunk_params, x):
            def one(h, layer):
                h, _ = layer_apply(
                    h, layer, local_cfg, cos, sin, **layer_kwargs
                )
                return h, None

            h, _ = jax.lax.scan(one, x, chunk_params)
            return h

        def head_loss(head_, y, target):
            h = rms_norm(y, head_["final_norm"], cfg.norm_eps)
            logits = (h @ head_["lm_head"]).astype(jnp.float32)
            b_, t_, v_ = logits.shape
            losses = fused_cross_entropy(
                logits.reshape(b_ * t_, v_), target.reshape(-1)
            )
            return jnp.mean(losses)

        act_shape = (mb, t, cfg.dim)
        zero_act = jnp.zeros(act_shape, cfg.dtype)
        V = n_chunks

        def tick(carry, tau):
            (fwd_in, bwd_in, in_buf, gin_buf, ring, g_stages, g_embed,
             g_head, loss_sum) = carry
            op = t_op[tau, stage]
            c = t_chunk[tau, stage]
            mbi = t_mb[tau, stage]
            slot = t_slot[tau, stage]
            # route arrivals (trash chunk-slot V when nothing arrives)
            rf_c = t_recv_f_c[tau, stage]
            rb_c = t_recv_b_c[tau, stage]
            in_buf = in_buf.at[
                jnp.where(rf_c >= 0, rf_c, V), t_recv_f_s[tau, stage]
            ].set(fwd_in)
            gin_buf = gin_buf.at[
                jnp.where(rb_c >= 0, rb_c, V), t_recv_b_s[tau, stage]
            ].set(bwd_in)
            f_slot = jnp.mod(mbi, sched.f_depth)
            b_slot = jnp.mod(mbi, sched.b_depth)

            chunk_params = jax.tree_util.tree_map(lambda p: p[c], stages)
            is_p0 = jnp.logical_and(c == 0, stage == 0)
            is_last = jnp.logical_and(c == V - 1, stage == n_stages - 1)

            def f_branch(args):
                ring, = args
                x0 = embed[inputs[mbi]].astype(cfg.dtype)
                x_in = jnp.where(is_p0, x0, in_buf[c, f_slot])
                y = chunk_forward(chunk_params, x_in)
                ring = ring.at[c, slot].set(x_in)
                return y, ring

            def f_skip(args):
                ring, = args
                return zero_act, ring

            y_out, ring = jax.lax.cond(op == OP_F, f_branch, f_skip, (ring,))

            def b_branch(args):
                g_stages, g_embed, g_head, loss_sum = args
                x_stored = ring[c, slot]
                y_st, vjp_fn = jax.vjp(chunk_forward, chunk_params, x_stored)

                def seed_last(_):
                    (loss, (dhead, dy)) = jax.value_and_grad(
                        head_loss, argnums=(0, 1)
                    )(head, y_st, targets[mbi])
                    return dy.astype(cfg.dtype), dhead, loss

                def seed_mid(_):
                    zero_head = jax.tree_util.tree_map(jnp.zeros_like, head)
                    return (
                        gin_buf[c, b_slot],
                        zero_head,
                        jnp.zeros((), jnp.float32),
                    )

                dy, dhead, loss = jax.lax.cond(
                    is_last, seed_last, seed_mid, None
                )
                dchunk, dx = vjp_fn(dy)
                g_stages = jax.tree_util.tree_map(
                    lambda g, d: g.at[c].add(d), g_stages, dchunk
                )
                g_head = jax.tree_util.tree_map(jnp.add, g_head, dhead)

                def embed_grad(_):
                    _, evjp = jax.vjp(
                        lambda e: e[inputs[mbi]].astype(cfg.dtype), embed
                    )
                    return evjp(dx)[0]

                g_embed = g_embed + jax.lax.cond(
                    is_p0, embed_grad, lambda _: jnp.zeros_like(g_embed), None
                )
                return g_stages, g_embed, g_head, loss_sum + loss, dx

            def b_skip(args):
                g_stages, g_embed, g_head, loss_sum = args
                return g_stages, g_embed, g_head, loss_sum, zero_act

            g_stages, g_embed, g_head, loss_sum, dx_out = jax.lax.cond(
                op == OP_B,
                b_branch,
                b_skip,
                (g_stages, g_embed, g_head, loss_sum),
            )

            fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
            fwd_in = jax.lax.ppermute(y_out, axis, fwd_perm)
            bwd_in = jax.lax.ppermute(dx_out, axis, bwd_perm)
            return (
                fwd_in, bwd_in, in_buf, gin_buf, ring, g_stages, g_embed,
                g_head, loss_sum,
            ), None

        g_stages0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), stages
        )
        g_head0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), head
        )
        carry0 = (
            zero_act,
            zero_act,
            jnp.zeros((V + 1, sched.f_depth) + act_shape, cfg.dtype),
            jnp.zeros((V + 1, sched.b_depth) + act_shape, cfg.dtype),
            jnp.zeros((V, sched.ring_depth) + act_shape, cfg.dtype),
            g_stages0,
            jnp.zeros_like(embed, jnp.float32),
            g_head0,
            jnp.zeros((), jnp.float32),
        )
        (carry, _) = jax.lax.scan(
            tick, carry0, jnp.arange(sched.total_ticks, dtype=jnp.int32)
        )
        (_, _, _, _, _, g_stages, g_embed, g_head, loss_sum) = carry

        loss, g_embed, g_head, g_stages = _reduce_pipeline_grads(
            loss_sum, g_embed, g_head, g_stages, axis, data_axis, m_total
        )
        grads = {
            "embed": g_embed,
            "stages": jax.tree_util.tree_map(lambda g: g[:, None], g_stages),
            "final_norm": g_head["final_norm"],
            "lm_head": g_head["lm_head"],
        }
        return loss, grads

    param_specs = interleaved_param_specs(axis, tp_axis)
    tok_spec = P(None, data_axis) if data_axis else P()
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, tok_spec),
        out_specs=(P(), param_specs),
        check_vma=False,
    )


def interleaved_param_specs(axis: str = "pipe", tp_axis: str = None) -> dict:
    """Specs for the interleaved layout: derived from
    pipeline_param_specs by prefixing the chunk dim (leaves are
    [V, S, K, ...], device dim is 1) — one source of truth for the
    per-weight shardings."""
    base = pipeline_param_specs(axis, tp_axis)

    def prefix(spec: P) -> P:
        return P(None, *spec)

    stages = base["stages"]
    return {
        **base,
        "stages": prefix(stages)
        if isinstance(stages, P)
        else jax.tree_util.tree_map(
            prefix, stages, is_leaf=lambda x: isinstance(x, P)
        ),
    }


def make_pipeline_lm_train_step(
    mesh: Mesh,
    cfg,
    optimizer,
    n_microbatches: int,
    axis: str = "pipe",
    data_axis: str = None,
    tp_axis: str = None,
    donate: bool = True,
):
    """1F1B pipeline-parallel LM train step: ``step(state, tokens) ->
    (state, loss)`` with state = {params (stage layout), opt_state, step}.
    ``tokens`` [M, mb, T+1]. Loss and grads are mathematically identical
    to the non-pipelined ``make_lm_train_step`` on the unstaged params
    (equivalence is asserted in tests/test_parallel.py). ``data_axis``/
    ``tp_axis`` compose pp with dp/tp on the same mesh (Megatron-style
    pp x dp x tp in one jitted program)."""
    loss_and_grads = pipeline_lm_loss_and_grads(
        mesh, cfg, n_microbatches, axis=axis, data_axis=data_axis,
        tp_axis=tp_axis,
    )
    return _pp_train_step(
        loss_and_grads,
        pipeline_param_specs(axis, tp_axis),
        mesh,
        optimizer,
        data_axis=data_axis,
        donate=donate,
    )


def make_interleaved_pipeline_lm_train_step(
    mesh: Mesh,
    cfg,
    optimizer,
    n_microbatches: int,
    n_chunks: int,
    axis: str = "pipe",
    data_axis: str = None,
    tp_axis: str = None,
    donate: bool = True,
):
    """Interleaved (virtual-stage) 1F1B train step: ``step(state, tokens)
    -> (state, loss)`` with state = {params (interleaved stage layout,
    from transformer_interleaved_stage_params), opt_state, step} —
    the full-step counterpart of ``make_pipeline_lm_train_step`` with a
    ~V-fold smaller pipeline bubble (parallel/interleaved.py; the
    schedule hits Megatron's 2*(S-1) chunk-tick bound when
    n_microbatches is a multiple of the stage count). Optimizer moments
    mirror the chunked stage layout and shard via
    ``interleaved_param_specs``; the state is donated so params/moments
    update in place."""
    loss_and_grads = interleaved_pipeline_lm_loss_and_grads(
        mesh, cfg, n_microbatches, n_chunks, axis=axis,
        data_axis=data_axis, tp_axis=tp_axis,
    )
    return _pp_train_step(
        loss_and_grads,
        interleaved_param_specs(axis, tp_axis),
        mesh,
        optimizer,
        data_axis=data_axis,
        donate=donate,
    )


def _pp_train_step(
    loss_and_grads, param_specs, mesh, optimizer, data_axis, donate
):
    """Shared train-step tail for both pipeline layouts: optimizer
    update + lazily-built jit with sharded opt-state and donation."""
    import optax
    from jax.sharding import NamedSharding

    def step_fn(state, tokens):
        loss, grads = loss_and_grads(state["params"], tokens)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return {
            **state,
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    params_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    repl = NamedSharding(mesh, P())
    tok_spec = NamedSharding(mesh, P(None, data_axis) if data_axis else P())
    # Optimizer moments mirror the stage params and get the SAME stage
    # sharding (replicating would cost ~2x the model per device; leaving
    # them unspecified makes jit compile twice). Structure is known only
    # at call time -> lazy jit, built once.
    cache: dict = {}

    def call(state, tokens):
        if "jit" not in cache:
            from ..training.trainer import opt_state_partition_spec

            opt_sharding = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                opt_state_partition_spec(state["opt_state"], param_specs),
                is_leaf=lambda s: isinstance(s, P),
            )
            out_state_sharding = {
                "params": params_sharding,
                "opt_state": opt_sharding,
                "step": repl,
            }
            # in: opt_state unconstrained — donated args cannot be
            # resharded, and callers may init moments replicated OR
            # already sharded. out: pinned, so from step 1 on the
            # moments LIVE at their params' shardings.
            in_state_sharding = {
                "params": params_sharding,
                "opt_state": None,
                "step": repl,
            }
            cache["jit"] = jax.jit(
                step_fn,
                in_shardings=(in_state_sharding, tok_spec),
                out_shardings=(out_state_sharding, repl),
                donate_argnums=(0,) if donate else (),
            )
        return cache["jit"](state, tokens)

    return call
