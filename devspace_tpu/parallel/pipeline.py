"""Pipeline parallelism over a ``pipe`` mesh axis.

GPipe-style schedule expressed the TPU way: every device holds one stage's
params (sharded on ``pipe``), microbatches flow through a
``jax.lax.scan`` over time steps, and activations hop to the next stage
with ``jax.lax.ppermute`` (ICI neighbor transfer). With S stages and M
microbatches the scan runs M + S - 1 ticks; device s computes on ticks
s..s+M-1 — idle ticks multiply by a 0/1 mask instead of branching, which
keeps the loop a single fused XLA while-op (no data-dependent control
flow under jit).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable,
    axis: str = "pipe",
    params_spec: tuple = (),
    xs_spec: tuple = (),
):
    """Build ``f(stage_params, x_microbatches) -> y_microbatches``.

    ``stage_params``: pytree whose leaves have a leading stage dim S,
    sharded over ``axis`` (each device sees its own stage's slice).
    ``x_microbatches``: [M, mb, ...] replicated along ``axis``; returns
    [M, mb, ...] outputs of the final stage (replicated along ``axis``).
    ``stage_fn(params_one_stage, x) -> y`` must map activations to
    activations of the same shape (classic homogeneous-stage pipeline).

    Composition with tp/dp in the same mesh: ``params_spec`` shards the
    dims AFTER each param leaf's leading stage dim (e.g. ``("model",)``
    keeps stage weights row-sharded inside the stages — stage_fn then
    owns the tensor-parallel psum), and ``xs_spec`` shards the dims after
    the microbatch dim of ``xs`` (e.g. ``("data",)`` keeps microbatches
    data-sharded end to end). Without these, weights/activations arrive
    replicated over those axes. ``params_spec`` may also be a pytree of
    per-leaf tuples matching ``stage_params`` for mixed-rank leaves
    (e.g. ``{"w": ("model",), "b": (None,)}`` so a [S, d, d] weight is
    row-sharded while its [S, d] bias stays replicated).
    """
    n_stages = mesh.shape[axis]

    def local_fn(params, xs):
        # params leaves arrive with leading dim 1 (this device's stage).
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        total = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            outputs, prev_act = carry
            # Stage 0 feeds microbatch t (while t < M); later stages use
            # the activation passed from the previous stage.
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(stage == 0, xs[mb_idx], prev_act)
            y = stage_fn(params, x_in)
            # Validity: stage s works on tick t iff s <= t < s + M.
            valid = jnp.logical_and(stage <= t, t < stage + n_micro)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # Last stage stores its result for microbatch t - (S-1).
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_last = stage == n_stages - 1
            store = jnp.logical_and(is_last, t >= n_stages - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(store, y, outputs[out_idx]),
                out_idx,
                axis=0,
            )
            # Activations hop to the next stage.
            nxt = jax.lax.ppermute(y, axis, perm)
            return (outputs, nxt), None

        outputs = jnp.zeros_like(xs)
        prev = jnp.zeros_like(xs[0])
        (outputs, _), _ = jax.lax.scan(tick, (outputs, prev), jnp.arange(total))
        # Only the last stage holds real outputs; broadcast via all_gather
        # (ppermute forbids multicast from one source).
        gathered = jax.lax.all_gather(outputs, axis)
        return gathered[n_stages - 1]

    if isinstance(params_spec, tuple):
        params_in_spec = P(axis, *params_spec)
    else:  # pytree of per-leaf dim tuples (prefix pytree for shard_map)
        params_in_spec = jax.tree_util.tree_map(
            lambda leaf_spec: P(axis, *leaf_spec),
            params_spec,
            is_leaf=lambda x: isinstance(x, tuple),
        )
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(params_in_spec, P(None, *xs_spec)),
        out_specs=P(None, *xs_spec),
        check_vma=False,
    )


def stack_stage_params(param_list):
    """Stack per-stage pytrees into the leading-stage-dim layout that
    pipeline_apply expects (shard the result over the pipe axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)
