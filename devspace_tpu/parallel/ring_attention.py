"""Ring attention: sequence parallelism for long context.

The query sequence stays sharded over the ``seq`` mesh axis; key/value
blocks rotate around the ring with ``jax.lax.ppermute`` while each device
accumulates its queries' attention with an online-softmax (flash-style
running max / sum / weighted-value accumulators). After S steps (S = ring
size) every query block has attended to the full sequence, with peak
memory O(seq/S) per device and the K/V transfers riding ICI neighbor
links — the canonical TPU long-context layout.

Causal masking uses global block offsets so the result matches full
(unsharded) causal attention exactly.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_offset, kv_offset, scale, causal):
    """Scores for one (q_block, kv_block) pair + masking.
    q: [B, Tq, H, D], k/v: [B, Tkv, H, D] -> (scores [B,H,Tq,Tkv], v)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        tq, tkv = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(tq)[:, None]
        kv_pos = kv_offset + jnp.arange(tkv)[None, :]
        mask = q_pos >= kv_pos
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    return scores


def _online_update(acc, row_max, row_sum, scores, v_blk):
    """One flash-style online-softmax accumulation step (shared by the
    ring hop and the within-hop kv sub-blocking)."""
    blk_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(row_max, blk_max)
    # Guard fully-masked rows (new_max = -inf) against NaNs.
    safe_max = jnp.where(new_max <= NEG_INF / 2, 0.0, new_max)
    correction = jnp.exp(row_max - safe_max)
    correction = jnp.where(row_max <= NEG_INF / 2, 0.0, correction)
    probs = jnp.exp(scores - safe_max[..., None])
    probs = jnp.where(scores <= NEG_INF / 2, 0.0, probs)
    acc = acc * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", probs, v_blk, preferred_element_type=jnp.float32
    )
    row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
    return acc, new_max, row_sum


def ring_attention(
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    block_size: Optional[int] = 512,
):
    """Build ``f(q, k, v) -> out`` with q/k/v [B, T, H, D] sharded on T
    over ``axis``; out is sharded the same way. ``batch_axis``/``head_axis``
    optionally co-shard B and H (composing sequence parallelism with data
    and tensor parallelism in one mesh).

    ``block_size`` bounds the within-hop working set (flash-within-ring):
    each arriving K/V block is consumed in kv sub-blocks of this size with
    the same online-softmax accumulators, so the materialized score tile
    is [B, H, t_local, block_size] instead of [B, H, t_local, t_local] —
    at 32k tokens over an 8-ring that is the difference between a
    512-wide tile and a 4k×4k (~1 GiB f32 per hop) intermediate. ``None``
    disables sub-blocking."""
    ring = mesh.shape[axis]
    io_spec = P(batch_axis, axis, head_axis, None)

    def local_fn(q, k, v):
        idx = jax.lax.axis_index(axis)
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
        t_local = q.shape[1]
        q_offset = idx * t_local
        blk = block_size if block_size and block_size < t_local else None
        if blk is not None and t_local % blk:
            # Degrade gracefully to the largest divisor of t_local so the
            # memory bound holds instead of cliffing to a whole-block
            # [t_local, t_local] tile; warn if only a degenerate divisor
            # exists (tiny blocks = long scan, so fall back instead).
            d = blk
            while t_local % d:
                d -= 1
            if d >= max(16, blk // 4):
                blk = d
            else:
                import warnings

                warnings.warn(
                    f"ring_attention: t_local={t_local} has no usable "
                    f"divisor near block_size={blk}; falling back to a "
                    f"whole-block [{t_local},{t_local}] score tile",
                    stacklevel=2,
                )
                blk = None

        b, tq, h, d = q.shape
        acc = jnp.zeros((b, h, tq, d), jnp.float32)
        row_max = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        row_sum = jnp.zeros((b, h, tq), jnp.float32)

        def step(carry, step_idx):
            k_blk, v_blk, acc, row_max, row_sum = carry
            kv_idx = (idx - step_idx) % ring  # whose block we hold now
            kv_offset = kv_idx * t_local
            if blk is None:
                scores = _block_attend(
                    q, k_blk, v_blk, q_offset, kv_offset, scale, causal
                )
                acc, row_max, row_sum = _online_update(
                    acc, row_max, row_sum, scores, v_blk
                )
            else:
                # flash-within-ring: consume this hop's K/V in sub-blocks
                n_sub = t_local // blk
                k_sub = k_blk.reshape(b, n_sub, blk, h, d)
                v_sub = v_blk.reshape(b, n_sub, blk, h, d)

                def sub_step(carry, sub):
                    acc, row_max, row_sum = carry
                    k_s, v_s, sub_idx = sub
                    scores = _block_attend(
                        q, k_s, v_s, q_offset, kv_offset + sub_idx * blk,
                        scale, causal,
                    )
                    return _online_update(acc, row_max, row_sum, scores, v_s), None

                (acc, row_max, row_sum), _ = jax.lax.scan(
                    sub_step,
                    (acc, row_max, row_sum),
                    (
                        jnp.moveaxis(k_sub, 1, 0),
                        jnp.moveaxis(v_sub, 1, 0),
                        jnp.arange(n_sub),
                    ),
                )
            # Rotate K/V to the next device; ICI-neighbor transfer.
            perm = [(i, (i + 1) % ring) for i in range(ring)]
            k_next = jax.lax.ppermute(k_blk, axis, perm)
            v_next = jax.lax.ppermute(v_blk, axis, perm)
            return (k_next, v_next, acc, row_max, row_sum), None

        (k_fin, v_fin, acc, row_max, row_sum), _ = jax.lax.scan(
            step, (k, v, acc, row_max, row_sum), jnp.arange(ring)
        )
        denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
        out = acc / denom[..., None]
        return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(io_spec, io_spec, io_spec),
        out_specs=io_spec,
        check_vma=False,
    )


def full_attention(q, k, v, causal: bool = True):
    """Unsharded reference attention (tests compare ring against this)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tkv = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tkv)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)
