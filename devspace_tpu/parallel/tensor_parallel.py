"""Tensor parallelism over a ``model`` mesh axis.

Megatron-style column/row parallel linear layers expressed with shard_map
+ explicit collectives: y = (x @ W1_col) -> activation -> (@ W2_row) with a
single psum at the block output, so the pair costs one all-reduce like the
standard TP MLP. Weights live sharded (never materialized fully), which is
what makes 7B+ layers fit per-chip HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# -- Megatron f/g boundary ops ---------------------------------------------
# The classic pair that makes a column->row parallel block differentiable
# INSIDE manual (shard_map) code without ever transposing a raw psum:
#   f = copy_fwd_psum_bwd : marks the block INPUT. Forward is identity;
#       backward all-reduces the partial input-grads each model shard
#       produced through its weight shard.
#   g = psum_fwd_copy_bwd : marks the block OUTPUT. Forward all-reduces
#       the partial outputs; backward passes the (replicated) cotangent
#       through unchanged.
# Used by the pipeline's tensor-parallel stages (parallel/pipeline.py),
# where the 1F1B backward runs jax.vjp over per-device code.


from functools import lru_cache


@lru_cache(maxsize=None)
def _f_op(axis: str):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (jax.lax.psum(ct, axis),)

    f.defvjp(fwd, bwd)
    return f


@lru_cache(maxsize=None)
def _g_op(axis: str):
    @jax.custom_vjp
    def g(x):
        return jax.lax.psum(x, axis)

    def fwd(x):
        return jax.lax.psum(x, axis), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


def copy_fwd_psum_bwd(x, axis: str):
    return _f_op(axis)(x)


def psum_fwd_copy_bwd(x, axis: str):
    return _g_op(axis)(x)


def shard_columnwise(w: jax.Array, mesh: Mesh, axis: str = "model") -> jax.Array:
    """Shard the output (last) dim of a weight over the model axis."""
    return jax.device_put(w, NamedSharding(mesh, P(None, axis)))


def shard_rowwise(w: jax.Array, mesh: Mesh, axis: str = "model") -> jax.Array:
    """Shard the input (first) dim of a weight over the model axis."""
    return jax.device_put(w, NamedSharding(mesh, P(axis, None)))


def tp_mlp(
    mesh: Mesh,
    axis: str = "model",
    activation: Callable = jax.nn.gelu,
):
    """Build the canonical TP MLP block: column-parallel up-projection,
    row-parallel down-projection, one psum.

    Returns ``f(x, w_up, w_down) -> y`` where ``w_up`` is sharded
    columnwise [D, F/axis], ``w_down`` rowwise [F/axis, D]; x and y are
    replicated along the model axis (shard x over data/seq axes outside).
    """

    def block(x, w_up, w_down):
        h = activation(
            jnp.einsum("...d,df->...f", x, w_up, preferred_element_type=jnp.float32)
        ).astype(x.dtype)
        partial_out = jnp.einsum(
            "...f,fd->...d", h, w_down, preferred_element_type=jnp.float32
        )
        return jax.lax.psum(partial_out, axis).astype(x.dtype)

    return jax.shard_map(
        block,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )


def tp_attention_projections(mesh: Mesh, axis: str = "model"):
    """Head-parallel attention projections: QKV column-parallel (heads
    sharded), output row-parallel with one psum — attention itself runs
    per-shard on local heads.

    Returns ``f(x, wq, wk, wv, wo, attn_fn) -> y`` with weights sharded on
    the head dimension. ``attn_fn(q, k, v) -> ctx`` operates on local
    heads: [..., H_local * Dh]."""

    def block(x, wq, wk, wv, wo, attn_fn):
        q = jnp.einsum("...d,dh->...h", x, wq)
        k = jnp.einsum("...d,dh->...h", x, wk)
        v = jnp.einsum("...d,dh->...h", x, wv)
        ctx = attn_fn(q, k, v)
        out = jnp.einsum("...h,hd->...d", ctx, wo, preferred_element_type=jnp.float32)
        return jax.lax.psum(out, axis).astype(x.dtype)

    def wrapper(x, wq, wk, wv, wo, attn_fn):
        return jax.shard_map(
            partial(block, attn_fn=attn_fn),
            mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P(None, axis), P(axis, None)),
            out_specs=P(),
            check_vma=False,
        )(x, wq, wk, wv, wo)

    return wrapper
