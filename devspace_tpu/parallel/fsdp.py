"""FSDP (fully-sharded data parallel / ZeRO-3) the XLA way.

No hand-rolled gather/scatter machinery: parameters AND optimizer state are
sharded over the ``data`` mesh axis via per-leaf PartitionSpecs, the batch
is sharded over the same axis, and GSPMD materializes the all-gather of
each weight right before its matmul and the reduce-scatter of its gradient
right after — the same schedule hand-written FSDP implementations build,
but derived by the partitioner and overlapped with compute by the XLA
latency-hiding scheduler. Peak per-device memory drops from O(params) to
O(params / data) plus one transiently-gathered layer.

The reference (a Go k8s dev CLI) has no parallelism of any kind
(SURVEY §2.13); this module is part of the TPU compute layer the north
star's scaffolded workloads ride on, alongside data/tensor/pipeline/
sequence/expert parallelism in this package.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def fsdp_leaf_spec(shape, axis: str, axis_size: int, min_size: int = 1024) -> P:
    """Spec for one param: shard the largest divisible dim over ``axis``.

    Ties go to the earliest largest dim. Tiny leaves (< min_size elements —
    biases, norm scales) and leaves with no divisible dim stay replicated;
    gathering them costs more than storing them.
    """
    if not shape:
        return P()
    n = 1
    for d in shape:
        n *= d
    if n < min_size:
        return P()
    best = None
    for i, d in enumerate(shape):
        if d % axis_size == 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    spec: list = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def fsdp_spec(params: Any, mesh: Mesh, axis: str = "data", min_size: int = 1024):
    """PartitionSpec tree mirroring ``params`` for FSDP over ``axis``."""
    size = mesh.shape[axis]
    return jax.tree_util.tree_map(
        lambda p: fsdp_leaf_spec(jnp.shape(p), axis, size, min_size), params
    )


def shard_params(
    params: Any,
    mesh: Mesh,
    axis: str = "data",
    min_size: int = 1024,
    spec: Any = None,
):
    """Device-put ``params`` with their FSDP shardings (frees the
    replicated copies once the sharded arrays are committed). ``spec``
    overrides the derived spec tree when the caller already computed it."""
    if spec is None:
        spec = fsdp_spec(params, mesh, axis, min_size)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, spec
    )


def _sharding_tree(tree_spec, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        tree_spec,
        is_leaf=lambda s: isinstance(s, P),
    )


def opt_state_spec(
    opt_state: Any, axis: str, axis_size: int, min_size: int = 1024
):
    """Spec tree for optimizer state, leaf-by-leaf with the same rule as
    the params: adam mu/nu and momentum mirror param shapes so they land on
    the identical sharding; scalar counters come out replicated."""
    return jax.tree_util.tree_map(
        lambda l: fsdp_leaf_spec(jnp.shape(l), axis, axis_size, min_size),
        opt_state,
    )


def make_fsdp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    params: Any,
    axis: str = "data",
    min_size: int = 1024,
    donate: bool = True,
):
    """Build ``(step, sharded_params, sharded_opt_state)``.

    ``loss_fn(params, batch) -> scalar``. The returned jitted
    ``step(params, opt_state, batch) -> (params, opt_state, loss)`` holds
    params and opt state sharded over ``axis`` (ZeRO-3); the batch is
    sharded over the same axis, so each device computes grads for its
    shard of the data against transiently-gathered full weights.
    """
    p_spec = fsdp_spec(params, mesh, axis, min_size)
    sharded_params = shard_params(params, mesh, spec=p_spec)
    opt_state = optimizer.init(sharded_params)
    o_spec = opt_state_spec(opt_state, axis, mesh.shape[axis], min_size)

    p_shardings = _sharding_tree(p_spec, mesh)
    o_shardings = _sharding_tree(o_spec, mesh)
    batch_sharding = NamedSharding(mesh, P(axis))

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # Keep grads in the params' sharding so optax updates stay sharded
        # (reduce-scatter rather than all-reduce comes out of GSPMD here).
        grads = jax.lax.with_sharding_constraint(grads, p_shardings)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jitted = jax.jit(
        step,
        in_shardings=(p_shardings, o_shardings, batch_sharding),
        out_shardings=(p_shardings, o_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0, 1) if donate else (),
    )
    return jitted, sharded_params, opt_state
