"""Interleaved (virtual-stage) 1F1B pipeline schedule.

Megatron-LM's interleaved schedule (Narayanan et al. 2021, "Efficient
large-scale language model training on GPU clusters"): each of the S
pipeline devices holds V model CHUNKS instead of one contiguous stage —
virtual stage p (of P = S*V) lives on device p % S, so every
stage-to-stage hop is still a ring +1 ppermute, and the pipeline
fill/drain bubble shrinks ~V-fold because a device starts computing its
first chunk after 1/V of the old fill time.

TPU-first formulation: rather than per-rank imperative op lists (the
GPU-framework shape of this schedule), the whole schedule is compiled to
STATIC per-tick tables (numpy [T, S]: op, chunk, microbatch, ring slot,
receive routing). The train step is then ONE lax.scan whose body indexes
the tables with the device's stage id — no data-dependent control flow,
exactly like the non-interleaved schedule in pipeline.py, just
table-driven instead of closed-form.

The builder generates Megatron's exact static per-device op order —
warmup of ``2*(S-s-1) + (V-1)*S`` forwards on device s, then strict
F,B,F,B 1F1B alternation, with chunk-cycling in groups of S
microbatches (forward ascending chunks, backward descending) — and then
TICK-SIMULATES it under the real lockstep constraints (F needs the
upstream activation a tick earlier, B the downstream grad a tick
earlier, one op per device per tick, in-order microbatches per virtual
stage): each device executes the head of its queue when ready, else
idles. The simulation realizes Megatron's bubble exactly: 2*(S-1)
chunk-ticks total — V-fold smaller than non-interleaved 1F1B's
2*(S-1)*V, i.e. a bubble fraction of (S-1)/(M*V + S-1) — asserted
across an (S, V, M) grid in tests/test_parallel.py. (An earlier greedy
backward-first list scheduler landed ~30-70% above this bound; the
warmup depth is the part greedy choice cannot discover.) Buffer depths
(activation stash per chunk, in-flight hops per edge) are derived from
the schedule afterwards and become static array sizes in the executor.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

OP_IDLE, OP_F, OP_B = 0, 1, 2


@dataclass(frozen=True)
class InterleavedSchedule:
    n_stages: int  # S devices
    n_chunks: int  # V chunks per device
    n_micro: int  # M microbatches
    total_ticks: int
    ring_depth: int  # max in-flight microbatches per (device, chunk)
    f_depth: int  # received-activation buffer slots per chunk (fwd edges)
    b_depth: int  # received-gradient buffer slots per chunk (bwd edges)
    # all [T, S] int32 tables
    op: np.ndarray  # OP_IDLE / OP_F / OP_B
    chunk: np.ndarray  # local chunk the op runs on
    mb: np.ndarray  # microbatch index of the op
    slot: np.ndarray  # activation-ring slot (F stores, B loads)
    recv_f_chunk: np.ndarray  # chunk to store the arriving fwd act (-1 none)
    recv_f_slot: np.ndarray
    recv_b_chunk: np.ndarray  # chunk to store the arriving grad (-1 none)
    recv_b_slot: np.ndarray

    @property
    def bubble_fraction(self) -> float:
        busy = 2 * self.n_micro * self.n_chunks  # per device
        return 1.0 - busy / (self.total_ticks or 1)


def _device_op_order(S: int, V: int, M: int, s: int) -> list:
    """Megatron's static op sequence for device ``s``: warmup forwards,
    then strict F,B alternation until forwards run out, then the
    backward drain. Forward order cycles chunks in groups of S
    microbatches ascending; backward mirrors it with chunks descending.
    Microbatches stay in-order per virtual stage by construction (the
    executor's ring/buffer slot math relies on it)."""
    groups = [range(g0, min(g0 + S, M)) for g0 in range(0, M, S)]
    fwd = [
        (v, m) for grp in groups for v in range(V) for m in grp
    ]
    bwd = [
        (v, m)
        for grp in groups
        for v in reversed(range(V))
        for m in grp
    ]
    # Warmup depth is the schedule's load-bearing constant: deep enough
    # that the steady state never starves (the first grad arrives just
    # as warmup ends on every device), shallow enough that in-flight
    # activations stay bounded.
    warmup = min(2 * (S - s - 1) + (V - 1) * S, len(fwd))
    queue = [(OP_F, v, m) for v, m in fwd[:warmup]]
    fi, bi = warmup, 0
    while fi < len(fwd) or bi < len(bwd):
        if fi < len(fwd):
            queue.append((OP_F, *fwd[fi]))
            fi += 1
        if bi < len(bwd):
            queue.append((OP_B, *bwd[bi]))
            bi += 1
    return queue


def build_interleaved_schedule(
    n_stages: int, n_chunks: int, n_micro: int
) -> InterleavedSchedule:
    S, V, M = n_stages, n_chunks, n_micro
    P = S * V
    f_done: dict[tuple[int, int], int] = {}  # (p, m) -> tick
    b_done: dict[tuple[int, int], int] = {}

    def f_ready(p: int, m: int, tau: int) -> bool:
        if m > 0 and (p, m - 1) not in f_done:
            return False  # in-order per stage (buffer slots rely on it)
        if p > 0 and f_done.get((p - 1, m), tau) >= tau:
            return False
        return True

    def b_ready(p: int, m: int, tau: int) -> bool:
        if m > 0 and (p, m - 1) not in b_done:
            return False
        if p == P - 1:
            if f_done.get((p, m), tau) >= tau:
                return False
        elif b_done.get((p + 1, m), tau) >= tau:
            return False
        return True

    ops: list[list[tuple[int, int, int]]] = []  # per tick: [(op,p,m)] per dev
    tau = 0
    if M % S == 0:
        # Megatron static order: realizes the exact 2*(S-1) bubble, but
        # its warmup symmetry needs full chunk-cycling groups (S | M —
        # Megatron-LM imposes the same divisibility requirement)
        queues = [_device_op_order(S, V, M, s) for s in range(S)]
        heads = [0] * S
        while any(heads[s] < len(queues[s]) for s in range(S)):
            tick_ops: list[tuple[int, int, int]] = [(OP_IDLE, 0, 0)] * S
            # select against the PREVIOUS ticks' state for every device
            # (readiness uses `>= tau`), then commit — ops chosen this
            # tick cannot feed each other within the tick
            for s in range(S):
                if heads[s] >= len(queues[s]):
                    continue
                op, v, m = queues[s][heads[s]]
                p = v * S + s
                ready = (
                    f_ready(p, m, tau) if op == OP_F else b_ready(p, m, tau)
                )
                if ready:
                    tick_ops[s] = (op, p, m)
            scheduled = False
            for s in range(S):
                op, p, m = tick_ops[s]
                if op == OP_F:
                    f_done[(p, m)] = tau
                elif op == OP_B:
                    b_done[(p, m)] = tau
                else:
                    continue
                heads[s] += 1
                scheduled = True
            if not scheduled:
                # an all-idle tick can never recover (readiness depends
                # only on ticks < tau): a genuine deadlock, which for
                # the divisible static order would be a builder bug
                raise RuntimeError(
                    f"interleaved schedule deadlocked at tick {tau} "
                    f"(S={S}, V={V}, M={M})"
                )
            ops.append(tick_ops)
            tau += 1
    else:
        # ragged microbatch count: greedy earliest-tick list scheduler
        # (backward-first with chunk-cycling forwards) — valid for ANY
        # (S, V, M), lands within a few ticks of the bound
        while len(f_done) + len(b_done) < 2 * P * M:
            tick_ops = [(OP_IDLE, 0, 0)] * S
            scheduled = False
            for s in range(S):
                best = None
                b_cands = []
                for v in range(V):
                    p = v * S + s
                    for m in range(M):
                        if (p, m) not in b_done and b_ready(p, m, tau):
                            b_cands.append(((m // S, -v, m), (OP_B, p, m)))
                            break
                if b_cands:
                    best = min(b_cands)[1]
                else:
                    f_cands = []
                    for v in range(V):
                        p = v * S + s
                        for m in range(M):
                            if (p, m) not in f_done and f_ready(p, m, tau):
                                f_cands.append(
                                    ((m // S, v, m), (OP_F, p, m))
                                )
                                break
                    if f_cands:
                        best = min(f_cands)[1]
                if best is not None:
                    tick_ops[s] = best
                    scheduled = True
            for s in range(S):
                op, p, m = tick_ops[s]
                if op == OP_F:
                    f_done[(p, m)] = tau
                elif op == OP_B:
                    b_done[(p, m)] = tau
            if not scheduled:
                raise RuntimeError(
                    f"interleaved schedule deadlocked at tick {tau} "
                    f"(S={S}, V={V}, M={M})"
                )
            ops.append(tick_ops)
            tau += 1

    total = len(ops)
    # activation-ring depth: max in-flight (F done, B pending) per stage
    ring_depth = 1
    for p in range(P):
        events = []
        for m in range(M):
            events.append((f_done[(p, m)], 1))
            events.append((b_done[(p, m)], -1))
        events.sort()
        cur = 0
        for _, delta in events:
            cur += delta
            ring_depth = max(ring_depth, cur)
    # received-buffer depths, PER DIRECTION: max outstanding activations
    # on any forward edge (produced at p, not yet consumed at p+1) and
    # max outstanding grads on any backward edge — a combined counter
    # would over-allocate the (typically depth-1) backward buffer
    def _edge_depth(produce, consume) -> int:
        depth = 1
        for p in range(P - 1):
            events = []
            for m in range(M):
                events.append((produce(p, m), 1))
                events.append((consume(p, m), -1))
            events.sort()
            cur = 0
            for _, delta in events:
                cur += delta
                depth = max(depth, cur)
        return depth

    f_depth = _edge_depth(
        lambda p, m: f_done[(p, m)], lambda p, m: f_done[(p + 1, m)]
    )
    b_depth = _edge_depth(
        lambda p, m: b_done[(p + 1, m)], lambda p, m: b_done[(p, m)]
    )

    op_t = np.zeros((total, S), np.int32)
    chunk_t = np.zeros((total, S), np.int32)
    mb_t = np.zeros((total, S), np.int32)
    slot_t = np.zeros((total, S), np.int32)
    recv_f_c = np.full((total, S), -1, np.int32)
    recv_f_s = np.zeros((total, S), np.int32)
    recv_b_c = np.full((total, S), -1, np.int32)
    recv_b_s = np.zeros((total, S), np.int32)
    for tau, tick_ops in enumerate(ops):
        for s in range(S):
            op, p, m = tick_ops[s]
            op_t[tau, s] = op
            if op == OP_IDLE:
                continue
            chunk_t[tau, s] = p // S
            mb_t[tau, s] = m
            slot_t[tau, s] = m % ring_depth
            if op == OP_F and p + 1 < P and tau + 1 < total:
                recv_f_c[tau + 1, (s + 1) % S] = (p + 1) // S
                recv_f_s[tau + 1, (s + 1) % S] = m % f_depth
            if op == OP_B and p > 0 and tau + 1 < total:
                recv_b_c[tau + 1, (s - 1) % S] = (p - 1) // S
                recv_b_s[tau + 1, (s - 1) % S] = m % b_depth
    return InterleavedSchedule(
        n_stages=S,
        n_chunks=V,
        n_micro=M,
        total_ticks=total,
        ring_depth=ring_depth,
        f_depth=f_depth,
        b_depth=b_depth,
        op=op_t,
        chunk=chunk_t,
        mb=mb_t,
        slot=slot_t,
        recv_f_chunk=recv_f_c,
        recv_f_slot=recv_f_s,
        recv_b_chunk=recv_b_c,
        recv_b_slot=recv_b_s,
    )
