"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second canonical long-context layout next to ring attention
(ring_attention.py). Activations flow through the network sharded on the
sequence axis ([B, T/P, H, D]); for attention each device needs full
sequence but only some heads, so a tiled ``jax.lax.all_to_all`` re-shards
from sequence-parallel to head-parallel ([B, T, H/P, D]), exact local
attention runs per head group, and a second all-to-all restores sequence
sharding. Two collectives per attention vs ring's P ppermute steps:
Ulysses wins when heads >= ring size and the all-to-all fits ICI;
ring wins at extreme sequence lengths (memory stays O(T/P) throughout).
Both are exposed so the scaffolded workloads can pick per topology.

The reference has no sequence dimension at all (SURVEY §5.7) — this is
north-star TPU compute-layer work, not reference parity.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import full_attention


def ulysses_attention(
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    batch_axis: Optional[str] = None,
    attn_fn: Optional[Callable] = None,
):
    """Build ``f(q, k, v) -> out`` with q/k/v [B, T, H, D] sharded on T
    over ``axis``; out is sharded the same way. H must be divisible by the
    axis size. ``attn_fn(q, k, v, causal)`` defaults to exact full
    attention and may be swapped for the flash kernel on real shapes."""
    n = mesh.shape[axis]
    attend = attn_fn or full_attention
    io_spec = P(batch_axis, axis, None, None)

    def local_fn(q, k, v):
        if q.shape[2] % n:
            raise ValueError(
                f"ulysses needs heads ({q.shape[2]}) divisible by the "
                f"'{axis}' axis size ({n})"
            )
        # [B, T/P, H, D] -> [B, T, H/P, D]: split heads, gather sequence.
        to_heads = lambda x: jax.lax.all_to_all(
            x, axis, split_axis=2, concat_axis=1, tiled=True
        )
        out = attend(to_heads(q), to_heads(k), to_heads(v), causal=causal)
        # [B, T, H/P, D] -> [B, T/P, H, D]: split sequence, gather heads.
        return jax.lax.all_to_all(
            out, axis, split_axis=1, concat_axis=2, tiled=True
        )

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(io_spec, io_spec, io_spec),
        out_specs=io_spec,
        check_vma=False,
    )
