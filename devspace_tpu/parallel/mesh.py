"""Device mesh construction for TPU slices.

TPU-first design: all parallelism in this package is expressed as shardings
over a named `jax.sharding.Mesh` (axes like data/model/seq/pipe); XLA
inserts the collectives, which ride ICI inside a slice and DCN across
slices (scaling-book recipe). The CLI side of the framework wires
TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / JAX_COORDINATOR_ADDRESS into the
pods (deploy/chart.py); :func:`multihost_initialize` consumes them here.
"""

from __future__ import annotations

import math
import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def mesh_shape_for(
    n_devices: int, axes: dict[str, int]
) -> dict[str, int]:
    """Resolve -1 entries: the leftover device count goes to the (single)
    -1 axis. ``axes`` preserves insertion order. Axis sizes must be
    integers >= 1 (or the one -1 wildcard) — a zero/negative axis would
    otherwise surface as a baffling reshape error deep in mesh build."""
    known = 1
    wildcard = None
    for name, size in axes.items():
        if size == -1:
            if wildcard is not None:
                raise ValueError("only one mesh axis may be -1")
            wildcard = name
        elif not isinstance(size, int) or isinstance(size, bool) or size < 1:
            raise ValueError(
                f"mesh axis {name!r} must be a positive integer or -1 "
                f"(got {size!r})"
            )
        else:
            known *= size
    if wildcard is not None:
        if n_devices % known:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes ({known})"
            )
        axes = {**axes, wildcard: n_devices // known}
    total = math.prod(axes.values())
    if total != n_devices:
        raise ValueError(
            f"mesh {axes} needs {total} devices but {n_devices} are available"
        )
    return axes


def create_mesh(
    axes: Optional[dict[str, int]] = None, devices=None
) -> Mesh:
    """Create a named mesh. Default: all devices on one ``data`` axis.

    ``axes`` maps axis name -> size, one size may be -1 (inferred), e.g.
    ``{"data": -1, "model": 2}`` on 8 devices -> data=4, model=2.
    Device order follows ``jax.devices()`` which on TPU enumerates in
    ICI-topology order — adjacent mesh coordinates are ICI neighbors, so
    collectives over the innermost axis stay on the fastest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = mesh_shape_for(len(devices), dict(axes or {"data": -1}))
    dev_array = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def multihost_initialize(logger=None) -> bool:
    """Initialize jax.distributed from the env our charts wire into TPU
    slice pods (JAX_COORDINATOR_ADDRESS, TPU_WORKER_ID, JAX_NUM_PROCESSES).
    No-op (returns False) outside a multi-host slice."""
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if not coordinator or n <= 1:
        return False
    pid = int(os.environ.get("TPU_WORKER_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=n, process_id=pid
    )
    if logger:
        logger.info(
            "[jax] distributed init: process %d/%d via %s", pid, n, coordinator
        )
    return True
