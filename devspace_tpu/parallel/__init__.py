from .mesh import create_mesh, mesh_shape_for  # noqa: F401
