from .mesh import create_mesh, mesh_shape_for  # noqa: F401
from .fsdp import fsdp_spec, make_fsdp_train_step, shard_params  # noqa: F401
from .sequence_parallel import ulysses_attention  # noqa: F401
