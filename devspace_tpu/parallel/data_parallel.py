"""Data parallelism: sharding-annotated jit, XLA inserts the collectives.

TPU-first: no hand-written allreduce. The batch is sharded over the
``data`` mesh axis, params/opt-state are replicated, and the SPMD
partitioner emits the gradient psum over ICI (the scaling-book recipe:
pick a mesh, annotate shardings, let XLA insert collectives). This is the
compute-side counterpart of the north star's "jax.lax.psum over ICI"
example — expressed at the jit boundary rather than inside the loss.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place a host batch with leading dim sharded over the data axis."""
    shard = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, shard), batch)


def make_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    data_axis: str = "data",
    param_spec: P | None = None,
    donate: bool = True,
    compute_dtype=None,
):
    """Build a jitted data-parallel train step.

    ``loss_fn(params, batch) -> scalar loss`` (or ``(loss, aux)`` with
    ``has_aux`` inferred from a tuple return at trace time is NOT done —
    pass aux via the loss closure if needed). ``param_spec`` defaults to
    fully replicated; pass a PartitionSpec tree for sharded params (e.g.
    FSDP-style sharding over the data axis).
    """
    param_sharding = NamedSharding(mesh, param_spec or P())
    batch_sharding = NamedSharding(mesh, P(data_axis))

    def step(params, opt_state, batch):
        if compute_dtype is not None:
            cast = lambda t: (
                t.astype(compute_dtype)
                if isinstance(t, jax.Array) and jnp.issubdtype(t.dtype, jnp.floating)
                else t
            )
            compute_params = jax.tree_util.tree_map(cast, params)
        else:
            compute_params = params
        loss, grads = jax.value_and_grad(loss_fn)(compute_params, batch)
        if compute_dtype is not None:
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), grads, params
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_sharding, param_sharding, batch_sharding),
        out_shardings=(param_sharding, param_sharding, NamedSharding(mesh, P())),
        donate_argnums=(0, 1) if donate else (),
    )


def make_eval_step(
    apply_fn: Callable, mesh: Mesh, data_axis: str = "data"
):
    batch_sharding = NamedSharding(mesh, P(data_axis))
    return jax.jit(
        apply_fn,
        in_shardings=(NamedSharding(mesh, P()), batch_sharding),
        out_shardings=batch_sharding,
    )


def psum_mean_loss(loss_fn: Callable, axis: str = "data") -> Callable:
    """Explicit-collective flavor for shard_map-based steps: per-shard mean
    loss averaged across the axis with jax.lax.pmean (the north star's
    literal 'psum over ICI' form). Use under shard_map; under plain jit
    with shardings the implicit version in make_train_step is preferred."""

    def wrapped(params, batch):
        loss = loss_fn(params, batch)
        return jax.lax.pmean(loss, axis)

    return wrapped
