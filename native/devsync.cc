// libdevsync — native fast path for the sync engine's local filesystem scans.
//
// The reference implementation (hoatle/devspace, pkg/devspace/sync) is a Go
// binary whose local walks are compiled code; this library keeps the
// Python framework's hot loops (initial-sync snapshot, downstream compare,
// build-context hashing — SURVEY §2.2/§2.5) at native speed. The Python
// side (devspace_tpu/utils/native.py) loads it via ctypes and falls back to
// pure Python when the library is absent.
//
// C ABI, one call: ds_walk(root, prune_csv, follow_symlinks) returns a
// malloc'd NUL-terminated buffer of lines
//   relpath\tsize\tmtime_sec\tmtime_ns\trawmode_oct\tuid\tgid\tis_symlink\n
// (relpath '/'-separated; rawmode octal st_mode incl. file type bits, so
// the Python layer derives is_dir like parse_stat_line does).
// prune_csv: comma-separated directory *names* to skip entirely (fast-path
// for excludes like .git, node_modules; full gitignore semantics stay in
// Python). Free with ds_free.

#include <dirent.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>

#include <set>
#include <string>
#include <utility>
#include <vector>

namespace {

struct Output {
  char* buf = nullptr;
  size_t len = 0;
  size_t cap = 0;

  void ensure(size_t extra) {
    if (len + extra + 1 <= cap) return;
    size_t want = (cap ? cap * 2 : 1 << 16);
    while (want < len + extra + 1) want *= 2;
    buf = static_cast<char*>(realloc(buf, want));
    cap = want;
  }

  void append_line(const std::string& rel, const struct stat& st,
                   bool is_symlink) {
    // The symlink flag rides as its own column: a followed symlink-to-dir
    // is both a directory (stat) and a link (lstat), and the exclusive
    // file-type bits of st_mode cannot express that.
    char meta[160];
    int n = snprintf(meta, sizeof meta,
                     "\t%lld\t%lld\t%lld\t%o\t%u\t%u\t%d\n",
                     S_ISDIR(st.st_mode) ? 0LL
                                         : static_cast<long long>(st.st_size),
                     static_cast<long long>(st.st_mtim.tv_sec),
                     static_cast<long long>(st.st_mtim.tv_nsec),
                     static_cast<unsigned>(st.st_mode),
                     static_cast<unsigned>(st.st_uid),
                     static_cast<unsigned>(st.st_gid), is_symlink ? 1 : 0);
    ensure(rel.size() + static_cast<size_t>(n));
    memcpy(buf + len, rel.data(), rel.size());
    len += rel.size();
    memcpy(buf + len, meta, static_cast<size_t>(n));
    len += static_cast<size_t>(n);
  }
};

bool pruned(const std::vector<std::string>& prune, const char* name) {
  for (const auto& p : prune)
    if (p == name) return true;
  return false;
}

}  // namespace

extern "C" {

// ABI version so the Python loader can refuse a stale build.
uint64_t ds_abi_version() { return 1; }

char* ds_walk(const char* root, const char* prune_csv, int follow_symlinks) {
  std::vector<std::string> prune;
  if (prune_csv && *prune_csv) {
    const char* p = prune_csv;
    while (*p) {
      const char* comma = strchr(p, ',');
      size_t n = comma ? static_cast<size_t>(comma - p) : strlen(p);
      if (n) prune.emplace_back(p, n);
      p += n + (comma ? 1 : 0);
    }
  }

  Output out;
  // (dev, ino) of visited directories — symlink cycle guard, mirrors
  // walk_local_tree's seen_dirs set.
  std::set<std::pair<uint64_t, uint64_t>> seen;
  // stack of (abs_path, rel_path)
  std::vector<std::pair<std::string, std::string>> stack;
  stack.emplace_back(root, "");

  while (!stack.empty()) {
    auto [dir, rel_dir] = std::move(stack.back());
    stack.pop_back();

    DIR* d = opendir(dir.c_str());
    if (!d) continue;
    struct dirent* ent;
    while ((ent = readdir(d)) != nullptr) {
      const char* name = ent->d_name;
      if (name[0] == '.' && (name[1] == 0 || (name[1] == '.' && name[2] == 0)))
        continue;
      std::string abs = dir;
      if (abs.empty() || abs.back() != '/') abs += '/';
      abs += name;
      std::string rel = rel_dir.empty() ? name : rel_dir + "/" + name;

      struct stat lst;
      if (lstat(abs.c_str(), &lst) != 0) continue;
      bool is_symlink = S_ISLNK(lst.st_mode);
      struct stat st = lst;
      if (is_symlink && follow_symlinks) {
        if (stat(abs.c_str(), &st) != 0) continue;  // dangling link
      }

      if (S_ISDIR(st.st_mode)) {
        if (pruned(prune, name)) continue;
        out.append_line(rel, st, is_symlink);
        auto key = std::make_pair(static_cast<uint64_t>(st.st_dev),
                                  static_cast<uint64_t>(st.st_ino));
        if (seen.insert(key).second) stack.emplace_back(abs, rel);
      } else {
        out.append_line(rel, st, is_symlink);
      }
    }
    closedir(d);
  }

  out.ensure(0);
  out.buf[out.len] = 0;
  return out.buf;
}

void ds_free(char* p) { free(p); }

}  // extern "C"
