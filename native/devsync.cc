// libdevsync — native fast path for the sync engine's local filesystem scans.
//
// The reference implementation (hoatle/devspace, pkg/devspace/sync) is a Go
// binary whose local walks are compiled code; this library keeps the
// Python framework's hot loops (initial-sync snapshot, downstream compare,
// build-context hashing — SURVEY §2.2/§2.5) at native speed. The Python
// side (devspace_tpu/utils/native.py) loads it via ctypes and falls back to
// pure Python when the library is absent.
//
// C ABI, one call: ds_walk(root, prune_csv, follow_symlinks) returns a
// malloc'd NUL-terminated buffer of lines
//   relpath\tsize\tmtime_sec\tmtime_ns\trawmode_oct\tuid\tgid\tis_symlink\n
// (relpath '/'-separated; rawmode octal st_mode incl. file type bits, so
// the Python layer derives is_dir like parse_stat_line does).
// prune_csv: comma-separated directory *names* to skip entirely (fast-path
// for excludes like .git, node_modules; full gitignore semantics stay in
// Python). Free with ds_free.

#include <dirent.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>

#include <set>
#include <string>
#include <utility>
#include <vector>

namespace {

struct Output {
  char* buf = nullptr;
  size_t len = 0;
  size_t cap = 0;

  void ensure(size_t extra) {
    if (len + extra + 1 <= cap) return;
    size_t want = (cap ? cap * 2 : 1 << 16);
    while (want < len + extra + 1) want *= 2;
    buf = static_cast<char*>(realloc(buf, want));
    cap = want;
  }

  void append_line(const std::string& rel, const struct stat& st,
                   bool is_symlink) {
    // The symlink flag rides as its own column: a followed symlink-to-dir
    // is both a directory (stat) and a link (lstat), and the exclusive
    // file-type bits of st_mode cannot express that.
    char meta[160];
    int n = snprintf(meta, sizeof meta,
                     "\t%lld\t%lld\t%lld\t%o\t%u\t%u\t%d\n",
                     S_ISDIR(st.st_mode) ? 0LL
                                         : static_cast<long long>(st.st_size),
                     static_cast<long long>(st.st_mtim.tv_sec),
                     static_cast<long long>(st.st_mtim.tv_nsec),
                     static_cast<unsigned>(st.st_mode),
                     static_cast<unsigned>(st.st_uid),
                     static_cast<unsigned>(st.st_gid), is_symlink ? 1 : 0);
    ensure(rel.size() + static_cast<size_t>(n));
    memcpy(buf + len, rel.data(), rel.size());
    len += rel.size();
    memcpy(buf + len, meta, static_cast<size_t>(n));
    len += static_cast<size_t>(n);
  }
};

bool pruned(const std::vector<std::string>& prune, const char* name) {
  for (const auto& p : prune)
    if (p == name) return true;
  return false;
}

// --- tar assembly (ds_pack) -------------------------------------------------
// The initial-sync upstream batch packs thousands of small files; CPython's
// tarfile spends ~70us per member on TarInfo/header bookkeeping, an order
// of magnitude over the actual I/O (measured in docs/PERF.md). The packer
// emits an UNCOMPRESSED GNU-format tar — gzip stays in Python (zlib is C
// already), and the format matches what tarfile reads on the remote side.

void raw_append(Output& out, const char* data, size_t n) {
  out.ensure(n);
  memcpy(out.buf + out.len, data, n);
  out.len += n;
}

// Does ``value`` fit a ``len``-byte octal header field (len-1 digits)?
// Overflow must abort the whole pack (caller falls back to Python's PAX
// writer) — a truncated size field would silently misalign every
// following member.
bool fits_octal(unsigned long long value, size_t len) {
  unsigned long long limit = 1;
  for (size_t i = 0; i + 1 < len; i++) limit *= 8;
  return value < limit;
}

void pack_octal(char* field, size_t len, unsigned long long value) {
  // via scratch: silences -Wformat-truncation (callers pre-check with
  // fits_octal; this is belt-and-suspenders)
  char tmp[32];
  int n = snprintf(tmp, sizeof tmp, "%0*llo", static_cast<int>(len - 1), value);
  memcpy(field, tmp, static_cast<size_t>(n) < len ? n + 1 : len);
}

void tar_header(Output& out, const std::string& name, unsigned long long mode,
                unsigned long long uid, unsigned long long gid,
                unsigned long long size, unsigned long long mtime,
                char typeflag) {
  char hdr[512];
  memset(hdr, 0, sizeof hdr);
  size_t nlen = name.size();
  memcpy(hdr, name.data(), nlen < 100 ? nlen : 100);
  pack_octal(hdr + 100, 8, mode);
  pack_octal(hdr + 108, 8, uid);
  pack_octal(hdr + 116, 8, gid);
  pack_octal(hdr + 124, 12, size);
  pack_octal(hdr + 136, 12, mtime);
  memset(hdr + 148, ' ', 8);  // checksum computed over spaces
  hdr[156] = typeflag;
  memcpy(hdr + 257, "ustar  ", 8);  // GNU magic+version ("ustar  \0")
  unsigned sum = 0;
  for (size_t i = 0; i < sizeof hdr; i++) sum += static_cast<unsigned char>(hdr[i]);
  char chk[16];
  snprintf(chk, sizeof chk, "%06o", sum);
  memcpy(hdr + 148, chk, 7);  // "dddddd\0"
  hdr[155] = ' ';  // canonical terminator: NUL then space
  raw_append(out, hdr, sizeof hdr);
}

void tar_pad(Output& out, size_t written) {
  static const char zeros[512] = {0};
  size_t rem = written % 512;
  if (rem) raw_append(out, zeros, 512 - rem);
}

// GNU @LongLink extension for member names that don't fit the 100-byte
// header field (what tarfile's GNU writer emits; its reader consumes it).
void tar_name(Output& out, const std::string& name, unsigned long long mtime) {
  if (name.size() < 100) return;
  tar_header(out, "././@LongLink", 0644, 0, 0, name.size() + 1, mtime, 'L');
  raw_append(out, name.c_str(), name.size() + 1);
  tar_pad(out, name.size() + 1);
}

}  // namespace

extern "C" {

// ABI version so the Python loader can refuse a stale build.
uint64_t ds_abi_version() { return 2; }

// Pack local files into an uncompressed GNU tar. ``entries`` is
// newline-separated records ``relpath\tis_dir\tmode\tuid\tgid\tmtime``
// (mode/uid/gid decimal, -1 = "use/derive the local default": files take
// st_mode&07777 and uid/gid 0 — exactly the Python builder's TarInfo
// defaults in sync/shell.py build_tar; dirs take 0755). Entries whose
// stat/open fails are skipped (raced concurrent delete, same as the
// Python path). Returns a malloc'd buffer (*out_len bytes; free with
// ds_free), or null on allocation/argument failure.
char* ds_pack(const char* root, const char* entries, uint64_t* out_len) {
  if (!root || !entries || !out_len) return nullptr;
  Output out;
  const char* p = entries;
  std::string root_s(root);
  if (!root_s.empty() && root_s.back() != '/') root_s += '/';
  std::vector<char> iobuf(1 << 16);
  while (*p) {
    const char* nl = strchr(p, '\n');
    size_t linelen = nl ? static_cast<size_t>(nl - p) : strlen(p);
    std::string line(p, linelen);
    p += linelen + (nl ? 1 : 0);
    // split 6 tab fields
    std::vector<std::string> f;
    size_t start = 0;
    for (size_t i = 0; i <= line.size(); i++) {
      if (i == line.size() || line[i] == '\t') {
        f.emplace_back(line, start, i - start);
        start = i + 1;
      }
    }
    if (f.size() != 6 || f[0].empty()) continue;
    const std::string& name = f[0];
    bool is_dir = f[1] == "1";
    long long mode = atoll(f[2].c_str());
    long long uid = atoll(f[3].c_str());
    long long gid = atoll(f[4].c_str());
    long long mtime = atoll(f[5].c_str());
    // any value the fixed octal fields can't carry (>=8GiB files,
    // uid/gid > 2097151, pre-1970 or far-future mtimes) aborts the
    // native pack — Python's PAX writer handles those fine
    if (mtime < 0 || !fits_octal(static_cast<unsigned long long>(mtime), 12) ||
        (uid >= 0 && !fits_octal(static_cast<unsigned long long>(uid), 8)) ||
        (gid >= 0 && !fits_octal(static_cast<unsigned long long>(gid), 8)) ||
        (mode >= 0 && !fits_octal(static_cast<unsigned long long>(mode), 8))) {
      free(out.buf);
      return nullptr;
    }
    if (is_dir) {
      std::string dname = name + "/";
      tar_name(out, dname, static_cast<unsigned long long>(mtime));
      tar_header(out, dname,
                 static_cast<unsigned long long>(mode >= 0 ? mode : 0755),
                 static_cast<unsigned long long>(uid >= 0 ? uid : 0),
                 static_cast<unsigned long long>(gid >= 0 ? gid : 0), 0,
                 static_cast<unsigned long long>(mtime), '5');
      continue;
    }
    std::string abs = root_s + name;
    struct stat st;
    if (stat(abs.c_str(), &st) != 0 || !S_ISREG(st.st_mode)) continue;
    unsigned long long size = static_cast<unsigned long long>(st.st_size);
    if (!fits_octal(size, 12) || st.st_mtim.tv_sec < 0 ||
        !fits_octal(static_cast<unsigned long long>(st.st_mtim.tv_sec), 12)) {
      free(out.buf);
      return nullptr;
    }
    FILE* fh = fopen(abs.c_str(), "rb");
    if (!fh) continue;
    tar_name(out, name, static_cast<unsigned long long>(st.st_mtim.tv_sec));
    tar_header(out, name,
               static_cast<unsigned long long>(
                   mode >= 0 ? mode : (st.st_mode & 07777)),
               static_cast<unsigned long long>(uid >= 0 ? uid : 0),
               static_cast<unsigned long long>(gid >= 0 ? gid : 0), size,
               static_cast<unsigned long long>(st.st_mtim.tv_sec), '0');
    unsigned long long copied = 0;
    while (copied < size) {
      size_t want = iobuf.size();
      if (size - copied < want) want = static_cast<size_t>(size - copied);
      size_t got = fread(iobuf.data(), 1, want, fh);
      if (got == 0) break;  // shrank underneath us: zero-fill the promise
      raw_append(out, iobuf.data(), got);
      copied += got;
    }
    fclose(fh);
    if (copied < size) {
      // header promised `size` bytes — keep the stream well-formed
      static const char zeros[512] = {0};
      while (copied < size) {
        unsigned long long want = size - copied;
        if (want > sizeof zeros) want = sizeof zeros;
        raw_append(out, zeros, static_cast<size_t>(want));
        copied += want;
      }
    }
    tar_pad(out, static_cast<size_t>(size));
  }
  // end-of-archive: two zero blocks
  static const char zeros[1024] = {0};
  raw_append(out, zeros, sizeof zeros);
  out.ensure(0);
  out.buf[out.len] = 0;
  *out_len = out.len;
  return out.buf;
}

char* ds_walk(const char* root, const char* prune_csv, int follow_symlinks) {
  std::vector<std::string> prune;
  if (prune_csv && *prune_csv) {
    const char* p = prune_csv;
    while (*p) {
      const char* comma = strchr(p, ',');
      size_t n = comma ? static_cast<size_t>(comma - p) : strlen(p);
      if (n) prune.emplace_back(p, n);
      p += n + (comma ? 1 : 0);
    }
  }

  Output out;
  // (dev, ino) of visited directories — symlink cycle guard, mirrors
  // walk_local_tree's seen_dirs set.
  std::set<std::pair<uint64_t, uint64_t>> seen;
  // stack of (abs_path, rel_path)
  std::vector<std::pair<std::string, std::string>> stack;
  stack.emplace_back(root, "");

  while (!stack.empty()) {
    auto [dir, rel_dir] = std::move(stack.back());
    stack.pop_back();

    DIR* d = opendir(dir.c_str());
    if (!d) continue;
    struct dirent* ent;
    while ((ent = readdir(d)) != nullptr) {
      const char* name = ent->d_name;
      if (name[0] == '.' && (name[1] == 0 || (name[1] == '.' && name[2] == 0)))
        continue;
      std::string abs = dir;
      if (abs.empty() || abs.back() != '/') abs += '/';
      abs += name;
      std::string rel = rel_dir.empty() ? name : rel_dir + "/" + name;

      struct stat lst;
      if (lstat(abs.c_str(), &lst) != 0) continue;
      bool is_symlink = S_ISLNK(lst.st_mode);
      struct stat st = lst;
      if (is_symlink && follow_symlinks) {
        if (stat(abs.c_str(), &st) != 0) continue;  // dangling link
      }

      if (S_ISDIR(st.st_mode)) {
        if (pruned(prune, name)) continue;
        out.append_line(rel, st, is_symlink);
        auto key = std::make_pair(static_cast<uint64_t>(st.st_dev),
                                  static_cast<uint64_t>(st.st_ino));
        if (seen.insert(key).second) stack.emplace_back(abs, rel);
      } else {
        out.append_line(rel, st, is_symlink);
      }
    }
    closedir(d);
  }

  out.ensure(0);
  out.buf[out.len] = 0;
  return out.buf;
}

void ds_free(char* p) { free(p); }

}  // extern "C"
