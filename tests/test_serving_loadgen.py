"""Loadgen tests: trace determinism, workload shapes, outcome accounting.

The determinism test is the replay contract: ``trace_json`` must be
byte-stable for a given spec, because chaos runs are bisected by
replaying the exact same traffic.
"""

import threading

import pytest

from devspace_tpu.serving import (
    LoadGenerator,
    ReplicaFleet,
    ReplicaSpec,
    TraceSpec,
    generate_trace,
)
from devspace_tpu.serving.loadgen import OUTCOMES, LoadReport, RequestOutcome, trace_json
from devspace_tpu.serving.stub import token_at


# -- determinism -------------------------------------------------------------
@pytest.mark.parametrize("kind", ["poisson", "chat", "bursty"])
def test_trace_byte_stable_per_seed(kind):
    spec = TraceSpec(kind=kind, seed=42, duration_s=2.0, rate_rps=10)
    again = TraceSpec(kind=kind, seed=42, duration_s=2.0, rate_rps=10)
    assert trace_json(spec) == trace_json(again)
    # a different seed must actually change the trace
    assert trace_json(spec) != trace_json(
        TraceSpec(kind=kind, seed=43, duration_s=2.0, rate_rps=10)
    )


def test_unknown_kind_rejected():
    with pytest.raises(ValueError, match="unknown trace kind"):
        generate_trace(TraceSpec(kind="sawtooth"))


# -- workload shapes ---------------------------------------------------------
def test_poisson_trace_sorted_and_bounded():
    spec = TraceSpec(kind="poisson", seed=1, duration_s=3.0, rate_rps=20)
    trace = generate_trace(spec)
    assert trace, "a 3s/20rps trace must produce events"
    ats = [e["at"] for e in trace]
    assert ats == sorted(ats)
    assert all(0 <= t < spec.duration_s for t in ats)
    lo, hi = spec.prompt_len
    assert all(lo <= len(e["prompt_ids"]) <= hi for e in trace)
    assert {e["sampled"] for e in trace} == {True, False}


def test_chat_sessions_share_growing_prefix():
    trace = generate_trace(
        TraceSpec(kind="chat", seed=3, duration_s=2.0, rate_rps=5,
                  turns=(3, 3))
    )
    sessions = {}
    for e in trace:
        sessions.setdefault(e["session"], []).append(e)
    multi = [v for v in sessions.values() if len(v) > 1]
    assert multi, "chat trace must contain multi-turn sessions"
    for turns in multi:
        turns.sort(key=lambda e: e["at"])
        for prev, nxt in zip(turns, turns[1:]):
            prev_prompt = prev["prompt_ids"]
            # next turn = previous prompt + previous turn's full reply
            reply = [token_at(prev_prompt, i)
                     for i in range(prev["max_new_tokens"])]
            assert nxt["prompt_ids"] == prev_prompt + reply


def test_bursty_trace_denser_in_bursts():
    spec = TraceSpec(kind="bursty", seed=9, duration_s=8.0, rate_rps=10,
                     burst_on_s=1.0, burst_off_s=1.0, burst_multiplier=4.0)
    trace = generate_trace(spec)
    period = spec.burst_on_s + spec.burst_off_s
    on = sum(1 for e in trace if (e["at"] % period) < spec.burst_on_s)
    off = len(trace) - on
    assert on > 2 * off, f"burst phase must dominate: on={on} off={off}"


# -- report accounting -------------------------------------------------------
def test_report_counts_and_quantiles():
    rep = LoadReport(outcomes=[
        RequestOutcome(id=0, outcome="completed", latency_s=0.1),
        RequestOutcome(id=1, outcome="completed", latency_s=0.3),
        RequestOutcome(id=2, outcome="retried", latency_s=0.5, attempts=2),
        RequestOutcome(id=3, outcome="failed", latency_s=9.0),
    ], wall_s=1.0)
    counts = rep.counts()
    assert set(counts) == set(OUTCOMES)
    assert counts["completed"] == 2 and counts["retried"] == 1
    assert sum(counts.values()) == 4
    # failed latencies are excluded from the served-latency quantiles
    assert rep.latency_quantile(1.0) == 0.5
    d = rep.to_dict()
    assert d["requests"] == 4 and d["counts"]["failed"] == 1


def test_no_targets_resolves_as_failed():
    gen = LoadGenerator(lambda: {}, max_attempts=2, hang_timeout_s=2)
    trace = generate_trace(
        TraceSpec(seed=0, duration_s=0.2, rate_rps=20))
    report = gen.run(trace, speed=10.0)
    assert len(report.outcomes) == len(trace)
    assert report.counts()["failed"] == len(trace)


# -- live replay against a stub replica -------------------------------------
def test_replay_verifies_streams_live():
    fleet = ReplicaFleet(
        spec=ReplicaSpec(env={"STUB_TOKEN_DELAY_S": "0.001"}),
        replicas=1, poll_interval=0.1)
    fleet.start()
    try:
        trace = generate_trace(
            TraceSpec(seed=7, duration_s=0.6, rate_rps=25))
        gen = LoadGenerator(fleet.targets, request_timeout_s=5,
                            hang_timeout_s=10)
        report = gen.run(trace, speed=2.0)
        counts = report.counts()
        assert len(report.outcomes) == len(trace)
        assert counts["completed"] == len(trace), counts
        assert counts["corrupted"] == 0 and counts["hung"] == 0
        assert all(o.tokens == trace[i]["max_new_tokens"]
                   for i, o in enumerate(report.outcomes))
    finally:
        fleet.stop()


def test_corruption_is_detected_not_papered_over():
    # a target that streams WRONG tokens must yield outcome=corrupted:
    # the verifier compares against token_at, so a lying replica can't
    # hide behind a well-formed stream
    import http.server
    import json as _json
    import threading as _threading

    class LyingHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: N802
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.end_headers()
            for tok in (1, 2, 3):
                self.wfile.write(
                    _json.dumps({"token": tok}).encode() + b"\n")
            self.wfile.write(_json.dumps({"done": True}).encode() + b"\n")

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), LyingHandler)
    th = _threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        gen = LoadGenerator(lambda: {"liar": url}, hang_timeout_s=5)
        trace = generate_trace(TraceSpec(seed=2, duration_s=0.2, rate_rps=10))
        report = gen.run(trace, speed=10.0)
        assert report.counts()["corrupted"] == len(trace)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_truncated_stream_is_death_not_corruption():
    # a replica killed mid-stream surfaces as EOF (close-delimited body)
    # or a half-written line, never as a socket error — the verifier must
    # classify a correct-prefix truncation as a death (retryable), and
    # reserve `corrupted` for wrong content. With every target
    # truncating, requests end `failed`; corrupted stays zero.
    import http.server
    import json as _json
    import threading as _threading

    from devspace_tpu.serving.stub import token_at

    class TruncatingHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: N802
            pass

        def do_POST(self):  # noqa: N802
            body = _json.loads(
                self.rfile.read(int(self.headers.get("Content-Length", 0))))
            self.send_response(200)
            self.end_headers()
            # two CORRECT tokens, then a half-written third line and a
            # dropped connection — no done marker ever arrives
            for i in range(2):
                self.wfile.write(_json.dumps(
                    {"token": token_at(body["prompt_ids"], i)}
                ).encode() + b"\n")
            self.wfile.write(b'{"tok')
            self.wfile.flush()
            self.connection.close()

    httpd = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), TruncatingHandler)
    th = _threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        gen = LoadGenerator(lambda: {"trunc": url}, hang_timeout_s=5)
        trace = generate_trace(TraceSpec(
            seed=3, duration_s=0.2, rate_rps=10,
            max_new_tokens=(4, 8)))
        report = gen.run(trace, speed=10.0)
        counts = report.counts()
        assert counts["corrupted"] == 0, counts
        assert counts["hung"] == 0, counts
        assert counts["failed"] == len(trace), counts
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- recorded-trace replay (disagg satellite) --------------------------------
def test_recorded_trace_file_replays_byte_stable(tmp_path):
    """``kind="file:<path>.jsonl"`` replays recorded traffic: arrivals
    re-based so the earliest is 0, prompt/tenant carried through, and
    trace_json byte-stable (same file in, same trace out)."""
    path = tmp_path / "prod.jsonl"
    path.write_text(
        '{"timestamp": 1000.5, "prompt": [1, 2, 3], "tenant": "acme"}\n'
        "\n"  # blank lines are skipped
        '{"timestamp": 1000.0, "prompt_ids": [4, 5], "max_new_tokens": 3,'
        ' "sampled": true, "session": 7}\n'
        '{"at": 1001.2, "prompt": [6]}\n'
    )
    spec = TraceSpec(kind=f"file:{path}")
    trace = generate_trace(spec)
    assert [e["at"] for e in trace] == [0.0, 0.5, 1.2]
    assert trace[0] == {"id": 1, "at": 0.0, "prompt_ids": [4, 5],
                        "max_new_tokens": 3, "sampled": True,
                        "session": 7, "tenant": ""}
    assert trace[1]["prompt_ids"] == [1, 2, 3]
    assert trace[1]["tenant"] == "acme"
    assert trace[1]["max_new_tokens"] == 16  # default when unrecorded
    assert trace_json(spec) == trace_json(TraceSpec(kind=f"file:{path}"))


def test_recorded_trace_rejects_bad_records(tmp_path):
    import pytest as _pytest

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"timestamp": 0.0}\n')  # no prompt at all
    with _pytest.raises(ValueError, match="bad.jsonl:1: bad trace record"):
        generate_trace(TraceSpec(kind=f"file:{bad}"))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("\n\n")
    with _pytest.raises(ValueError, match="empty trace file"):
        generate_trace(TraceSpec(kind=f"file:{empty}"))
