"""Rule-engine lint subsystem: registry, reporters, SARIF, CLI semantics.

Pins the ISSUE acceptance criteria: exit codes (0 clean / 1 errors /
warnings pass unless --strict), byte-stable sorted JSON, SARIF 2.1.0
structure, the shared topology parser at both call sites, mesh axis-size
validation, Dockerfile rules, and a zero-finding self-lint of the
generator template charts.
"""

import json
import os

import pytest

from devspace_tpu.cli.main import main
from devspace_tpu.config import latest
from devspace_tpu.utils import log as logutil
from devspace_tpu.utils.fsutil import write_file
from devspace_tpu.utils.topology import parse_topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEMPLATES = os.path.join(REPO, "devspace_tpu", "generator", "templates")


@pytest.fixture
def project(tmp_path, monkeypatch):
    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_FAKE_BACKEND", str(tmp_path / "cluster"))
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    write_file(str(proj / "train.py"), "import jax\nprint('step 0')\n")
    logutil.set_logger(logutil.StdoutLogger())
    return proj


# -- registry ---------------------------------------------------------------


def test_registry_rules_well_formed():
    from devspace_tpu.lint import REGISTRY, SEVERITIES

    packs = {
        "manifest",
        "tpu",
        "hygiene",
        "sharding",
        "image",
        "hotpath",
        "concurrency",
        "obs",
    }
    assert len(REGISTRY) >= 15  # manifest + tpu + sharding + image packs
    for rule_id, r in REGISTRY.items():
        assert r.id == rule_id
        assert r.severity in SEVERITIES
        assert r.category in packs
        assert r.description
    # every pack is represented
    cats = {r.category for r in REGISTRY.values()}
    assert packs <= cats


def test_duplicate_rule_id_rejected():
    from devspace_tpu.lint import rule

    with pytest.raises(ValueError, match="duplicate"):

        @rule("DS101", severity="error", category="manifest", description="x")
        def clash(ctx):
            return ()

    with pytest.raises(ValueError, match="severity"):

        @rule("ZZ999", severity="fatal", category="manifest", description="x")
        def bad_sev(ctx):
            return ()


def test_findings_carry_rule_metadata():
    from devspace_tpu.lint import ERROR, WARNING, lint_docs

    docs = [
        {"kind": "Service", "metadata": {"name": "Bad_Name"}},
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {"containers": [{"name": "c", "image": "nginx"}]},
        },
    ]
    findings = lint_docs(docs)
    by_rule = {f.rule_id for f in findings}
    assert "DS101" in by_rule  # missing apiVersion / bad name
    assert "DS150" in by_rule  # untagged image -> hygiene warning
    for f in findings:
        assert f.severity == (WARNING if f.rule_id == "DS150" else ERROR)
        assert f.message


def test_legacy_shim_excludes_new_hygiene_warnings():
    """validate_manifests must stay byte-compatible: the new DS150
    untagged-image warning is engine-only."""
    from devspace_tpu.deploy.lint import validate_manifests
    from devspace_tpu.lint import lint_docs

    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p"},
        "spec": {"containers": [{"name": "c", "image": "nginx:latest"}]},
    }
    assert validate_manifests([pod]) == []
    assert any(f.rule_id == "DS150" for f in lint_docs([pod]))


# -- reporters --------------------------------------------------------------


def _sample_findings():
    from devspace_tpu.lint import lint_docs

    return lint_docs(
        [
            {"kind": "Service", "metadata": {"name": "Bad_Name"}},
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web"},
                "spec": {
                    "template": {
                        "spec": {"containers": [{"name": "c"}]},
                    }
                },
            },
        ],
        artifact="chart",
    )


def test_json_report_stable_and_sorted():
    from devspace_tpu.lint import reporters

    findings = _sample_findings()
    out1 = reporters.to_json(findings)
    out2 = reporters.to_json(list(reversed(findings)))
    assert out1 == out2  # insertion order must not leak into output
    payload = json.loads(out1)
    keys = [
        (f["artifact"], f["location"], f["rule"], f["message"])
        for f in payload["findings"]
    ]
    assert keys == sorted(keys)
    assert payload["summary"]["error"] >= 2


# The structural core of the SARIF 2.1.0 schema (oasis-tcs/sarif-spec),
# inlined because tests run offline. Covers everything a code-scanning
# consumer requires: version/runs, tool.driver with named rules, results
# with ruleId + message.text + a valid level.
SARIF_CORE_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string", "format": "uri"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "version": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                            "properties": {
                                                "id": {"type": "string"},
                                                "shortDescription": {
                                                    "type": "object",
                                                    "required": ["text"],
                                                },
                                                "defaultConfiguration": {
                                                    "type": "object",
                                                    "properties": {
                                                        "level": {
                                                            "enum": [
                                                                "none",
                                                                "note",
                                                                "warning",
                                                                "error",
                                                            ]
                                                        }
                                                    },
                                                },
                                            },
                                        },
                                    },
                                },
                            }
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "message"],
                            "properties": {
                                "ruleId": {"type": "string"},
                                "ruleIndex": {"type": "integer", "minimum": 0},
                                "level": {
                                    "enum": ["none", "note", "warning", "error"]
                                },
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                    "properties": {
                                        "text": {"type": "string"}
                                    },
                                },
                                "locations": {"type": "array"},
                            },
                        },
                    },
                },
            },
        },
    },
}


def test_sarif_output_validates_against_2_1_0_schema():
    import jsonschema

    from devspace_tpu.lint import reporters

    findings = _sample_findings()
    sarif = reporters.to_sarif(findings)
    jsonschema.validate(sarif, SARIF_CORE_SCHEMA)
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "devspace-tpu-lint"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(rule_ids)
    for result in run["results"]:
        # ruleIndex must point at the result's own rule
        assert rule_ids[result["ruleIndex"]] == result["ruleId"]
        assert result["message"]["text"]
    # severities map onto SARIF's level vocabulary
    levels = {r["level"] for r in run["results"]}
    assert levels <= {"error", "warning", "note"}
    # round-trips through the serializer deterministically
    assert reporters.to_sarif_json(findings) == reporters.to_sarif_json(
        list(reversed(findings))
    )


# -- shared topology parser (satellite: dedupe) -----------------------------


def test_parse_topology_products_and_rejections():
    assert parse_topology("4x4") == 16
    assert parse_topology("2x2x2") == 8
    assert parse_topology("8") == 8
    assert parse_topology("2X4") == 8  # case-insensitive
    for bad in ("", "2xbogus", "x4", "4x", "0x4", "-2x4", "4x0x2"):
        with pytest.raises(ValueError):
            parse_topology(bad)


def test_topology_parser_at_lint_call_site():
    from devspace_tpu.deploy.lint import lint_tpu_consistency

    tpu = latest.TPUConfig(workers=2, chips_per_worker=4, topology="0x4")
    issues = lint_tpu_consistency([], tpu)
    assert any("unparseable topology '0x4'" in i for i in issues)
    # a parseable-but-wrong product still reports the product mismatch
    tpu = latest.TPUConfig(workers=2, chips_per_worker=1, topology="4x4")
    issues = lint_tpu_consistency([], tpu)
    assert any("topology 4x4 has 16" in i for i in issues)


def test_topology_parser_at_analyze_call_site(tmp_path):
    from devspace_tpu.analyze.analyze import analyze_tpu_slice
    from devspace_tpu.kube.fake import FakeCluster

    fc = FakeCluster(str(tmp_path))
    env = {"TPU_WORKER_HOSTNAMES": "app-0.app,app-1.app"}
    for i in range(2):
        fc.add_pod(f"app-{i}", labels={"app": "app"}, worker_id=i, env=env)
    cfg = latest.new()
    cfg.deployments = [latest.DeploymentConfig(name="app")]
    cfg.tpu = latest.TPUConfig(workers=2, topology="0x4", chips_per_worker=4)
    probs = analyze_tpu_slice(fc, cfg, "default")
    assert any("unparseable topology '0x4'" in p for p in probs)


# -- mesh axis validation (satellite) ---------------------------------------


def test_mesh_shape_for_rejects_bad_axis_sizes():
    from devspace_tpu.parallel.mesh import mesh_shape_for

    # boundary: 1 is the smallest legal size; -1 is the wildcard
    assert mesh_shape_for(8, {"data": 8, "model": 1}) == {"data": 8, "model": 1}
    assert mesh_shape_for(8, {"data": -1}) == {"data": 8}
    for bad in (0, -2, 2.0, "2", True):
        with pytest.raises(ValueError, match="positive integer"):
            mesh_shape_for(8, {"data": bad, "model": 2})
    with pytest.raises(ValueError, match="only one"):
        mesh_shape_for(8, {"data": -1, "model": -1})


# -- Dockerfile rules -------------------------------------------------------


def test_dockerfile_rules_tpu_flavor():
    from devspace_tpu.lint import lint_dockerfile

    fs = lint_dockerfile(
        "FROM nvidia/cuda:12.2.0-runtime\nRUN pip install torch\n",
        tpu_flavor=True,
    )
    ids = {f.rule_id for f in fs}
    assert {"IMG401", "IMG402", "IMG403"} <= ids

    # continuation-aware: the jax[tpu] install spans lines
    ok = (
        "FROM python:3.12-slim\n"
        "RUN pip install \\\n"
        '    "jax[tpu]" -f https://storage.googleapis.com/libtpu-releases/index.html\n'
        'CMD ["python", "train.py"]\n'
    )
    assert lint_dockerfile(ok, tpu_flavor=True) == []

    # non-python entrypoint on a TPU image is a warning, not an error
    fs = lint_dockerfile(
        "FROM python:3.12-slim\nENV JAX_PLATFORMS=tpu\nCMD [\"./run.sh\"]\n",
        tpu_flavor=True,
    )
    assert [f.rule_id for f in fs] == ["IMG404"]
    assert all(f.severity == "warning" for f in fs)

    # cpu flavor: only the universal checks apply
    assert lint_dockerfile("FROM golang:1.22\nCMD [\"/app\"]\n") == []
    fs = lint_dockerfile("FROM golang:1.22\n")
    assert [f.rule_id for f in fs] == ["IMG403"]


def test_template_dockerfiles_lint_clean():
    df_dir = os.path.join(TEMPLATES, "dockerfiles")
    from devspace_tpu.lint import lint_dockerfile

    for flavor in sorted(os.listdir(df_dir)):
        path = os.path.join(df_dir, flavor, "Dockerfile")
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8") as fh:
            findings = lint_dockerfile(
                fh.read(), path=path, tpu_flavor=(flavor == "jax")
            )
        assert findings == [], f"{flavor}: {[f.message for f in findings]}"


# -- self-lint: generator charts render clean (satellite) -------------------


def _chart_tpu_context(name, workers):
    hostnames = ",".join(f"{name}-{i}.{name}" for i in range(workers))
    return {
        "accelerator": "v5litepod-16" if workers > 1 else "",
        "topology": "4x4" if workers > 1 else "",
        "workers": workers,
        "chipsPerWorker": 4 if workers > 1 else 1,
        "runtimeVersion": "",
        "workerHostnames": hostnames,
        "coordinatorAddress": f"{name}-0.{name}:8476",
    }


def test_self_lint_template_charts_zero_findings():
    from devspace_tpu.lint import lint_chart_findings

    tpu = latest.TPUConfig(
        accelerator="v5litepod-16", topology="4x4", workers=4, chips_per_worker=4
    )
    findings = lint_chart_findings(
        os.path.join(TEMPLATES, "chart-tpu"),
        release_name="self",
        values={"image": "registry.local/self:ci"},
        tpu=tpu,
        extra_context={
            "images": {},
            "pullSecrets": [],
            "tpu": _chart_tpu_context("self", 4),
        },
    )
    assert findings == [], [f.legacy() for f in findings]
    findings = lint_chart_findings(
        os.path.join(TEMPLATES, "chart-cpu"),
        release_name="self",
        values={"image": "registry.local/self:ci"},
        extra_context={
            "images": {},
            "pullSecrets": [],
            "tpu": _chart_tpu_context("self", 1),
        },
    )
    assert findings == [], [f.legacy() for f in findings]


def test_lint_self_script_passes():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_self.py")],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)
    assert sarif["version"] == "2.1.0"
    # the repo's own charts must produce no ERROR results
    for run in sarif["runs"]:
        assert all(r["level"] != "error" for r in run["results"])


# -- CLI exit-code semantics (satellite) ------------------------------------


def test_cli_exit_codes_clean_errors_warnings_strict(project, tmp_path):
    assert main(["init"]) == 0
    assert main(["lint"]) == 0

    # warning-only chart: untagged image -> 0 normally, 1 under --strict
    chart = tmp_path / "warnchart"
    (chart / "templates").mkdir(parents=True)
    (chart / "chart.yaml").write_text("name: warnchart\nversion: 0.1.0\n")
    (chart / "templates" / "p.yaml").write_text(
        "apiVersion: v1\nkind: Pod\nmetadata:\n  name: p\nspec:\n"
        "  containers:\n  - name: c\n    image: nginx\n"
    )
    assert main(["lint", "--chart", str(chart)]) == 0
    assert main(["lint", "--chart", str(chart), "--strict"]) == 1

    # error chart: 1 regardless of strictness
    (chart / "templates" / "p.yaml").write_text(
        "apiVersion: v1\nkind: Pod\nmetadata:\n  name: UPPER\n"
    )
    assert main(["lint", "--chart", str(chart)]) == 1


def test_cli_json_output_is_stable(project, capsys):
    assert main(["init"]) == 0
    assert main(["lint", "--format", "json"]) == 0
    out1 = capsys.readouterr().out
    assert main(["lint", "--format", "json"]) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    payload = json.loads(out1)
    assert payload["summary"] == {"error": 0, "info": 0, "warning": 0}


def test_cli_sarif_format(project, capsys):
    import jsonschema

    assert main(["init"]) == 0
    # break the chart so results are non-empty
    sts = project / "chart" / "templates" / "statefulset.yaml"
    text = sts.read_text().replace("${{ tpu.workers }}", "1")
    sts.write_text(text)
    assert main(["lint", "--format", "sarif"]) == 1
    sarif = json.loads(capsys.readouterr().out)
    jsonschema.validate(sarif, SARIF_CORE_SCHEMA)
    results = sarif["runs"][0]["results"]
    assert any(r["ruleId"] == "TPU203" for r in results)


# -- deploy preflight -------------------------------------------------------


def test_deploy_preflight_blocks_errors_and_skip_lint_bypasses(project):
    assert main(["init"]) == 0
    sts = project / "chart" / "templates" / "statefulset.yaml"
    text = sts.read_text().replace("${{ tpu.workers }}", "1")
    sts.write_text(text)
    assert main(["deploy"]) == 1  # lint errors abort before anything applies
    assert main(["deploy", "--skip-lint"]) == 0
