import io
import os
import time

import pytest

from devspace_tpu.utils import hashutil, log as logutil
from devspace_tpu.utils.dockerfile import get_ports
from devspace_tpu.utils.fsutil import walk_files, write_file
from devspace_tpu.utils.ignoreutil import IgnoreMatcher
from devspace_tpu.utils.randutil import random_string


def test_random_string():
    s = random_string(7)
    assert len(s) == 7 and s.isalnum() and s == s.lower()
    assert random_string(7) != random_string(7) or True  # non-deterministic


def test_directory_hash_changes_on_edit(tmp_path):
    write_file(str(tmp_path / "a.txt"), "hello")
    write_file(str(tmp_path / "sub" / "b.txt"), "world")
    h1 = hashutil.directory_hash(str(tmp_path))
    h1b = hashutil.directory_hash(str(tmp_path))
    assert h1 == h1b
    time.sleep(0.01)
    write_file(str(tmp_path / "a.txt"), "hello2")
    assert hashutil.directory_hash(str(tmp_path)) != h1


def test_directory_hash_excludes(tmp_path):
    write_file(str(tmp_path / "a.txt"), "hello")
    write_file(str(tmp_path / "node_modules" / "x.js"), "junk")
    h1 = hashutil.directory_hash(str(tmp_path), excludes=["node_modules/"])
    write_file(str(tmp_path / "node_modules" / "y.js"), "more junk")
    assert hashutil.directory_hash(str(tmp_path), excludes=["node_modules/"]) == h1


def test_walk_files_prunes_ignored(tmp_path):
    write_file(str(tmp_path / "keep.py"), "x")
    write_file(str(tmp_path / "skip" / "deep" / "f.txt"), "x")
    rels = [r for r, _, _ in walk_files(str(tmp_path), IgnoreMatcher(["skip/"]))]
    assert rels == ["keep.py"]


def test_dockerfile_ports(tmp_path):
    df = tmp_path / "Dockerfile"
    df.write_text("FROM python:3.12\nEXPOSE 8080 9000/tcp\nexpose 3000\n")
    assert get_ports(str(df)) == [8080, 9000, 3000]


def test_logger_levels_and_mirror(tmp_path):
    stream = io.StringIO()
    lg = logutil.StdoutLogger(level="info", stream=stream)
    fl = logutil.FileLogger(str(tmp_path / "logs" / "t.log"))
    lg.add_mirror(fl)
    lg.debug("hidden")
    lg.info("shown %d", 42)
    lg.done("finished")
    out = stream.getvalue()
    assert "hidden" not in out and "shown 42" in out and "finished" in out
    fl.close()
    content = (tmp_path / "logs" / "t.log").read_text()
    assert "shown 42" in content and "hidden" in content  # file logs debug too


def test_logger_fatal_raises():
    lg = logutil.StdoutLogger(stream=io.StringIO())
    with pytest.raises(logutil.FatalError):
        lg.fatal("boom")


def test_print_table():
    stream = io.StringIO()
    lg = logutil.StdoutLogger(stream=stream)
    lg.print_table(["NAME", "STATUS"], [["app", "Running"], ["db", "Pending"]])
    lines = stream.getvalue().splitlines()
    assert lines[0].startswith("NAME") and "STATUS" in lines[0]
    assert "Running" in lines[1]


def test_trace_spans(tmp_path):
    """Span nesting, error capture, file sink, chrome export."""
    from devspace_tpu.utils import trace

    trace.enable(str(tmp_path))
    try:
        with trace.span("outer", phase="test") as s:
            s["extra"] = 1
            with trace.span("inner"):
                pass
        try:
            with trace.span("failing"):
                raise ValueError("boom")
        except ValueError:
            pass
    finally:
        trace.disable()

    spans = trace.load(str(tmp_path))
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent"] == "outer"
    assert by_name["outer"]["parent"] is None
    assert by_name["outer"]["extra"] == 1
    assert by_name["outer"]["ok"] and by_name["inner"]["ok"]
    assert not by_name["failing"]["ok"]
    assert "boom" in by_name["failing"]["error"]
    assert all(s["duration_s"] >= 0 for s in spans)

    dest = tmp_path / "chrome.json"
    n = trace.export_chrome(str(tmp_path), str(dest))
    assert n == 3
    import json

    data = json.loads(dest.read_text())
    assert {e["name"] for e in data["traceEvents"]} == {"outer", "inner", "failing"}


def test_file_logger_rotation(tmp_path):
    """Oversized logs rotate to .old on open (reference: sync/util.go:305-340)."""
    from devspace_tpu.utils import log as logutil

    path = tmp_path / "logs" / "sync.log"
    path.parent.mkdir()
    path.write_text("x" * 64)
    old_max = logutil.FileLogger.MAX_BYTES
    logutil.FileLogger.MAX_BYTES = 16
    try:
        fl = logutil.FileLogger(str(path))
        fl.info("fresh entry")
        fl.close()
    finally:
        logutil.FileLogger.MAX_BYTES = old_max
    assert (tmp_path / "logs" / "sync.log.old").read_text() == "x" * 64
    assert "fresh entry" in path.read_text()
