import os

import pytest
import yaml

from devspace_tpu.config import latest, versions
from devspace_tpu.config.generated import GeneratedConfig
from devspace_tpu.config.loader import ConfigLoader, find_root, get_selector
from devspace_tpu.config.merge import merge, split
from devspace_tpu.config.structs import ConfigError, from_dict, to_dict
from devspace_tpu.config.variables import resolve_vars


LATEST_YAML = """
version: tpu/v1
cluster:
  namespace: myns
tpu:
  accelerator: v5litepod-16
  workers: 4
images:
  default:
    image: gcr.io/proj/app
deployments:
  - name: app
    chart:
      path: ./chart
dev:
  selectors:
    - name: default
      labelSelector:
        app: myapp
  sync:
    - selector: default
      containerPath: /app
      excludePaths: ["node_modules/"]
  ports:
    - selector: default
      portMappings:
        - localPort: 8888
          remotePort: 8888
"""


def test_parse_latest():
    cfg = versions.parse(yaml.safe_load(LATEST_YAML))
    assert cfg.version == latest.VERSION
    assert cfg.tpu.workers == 4
    assert cfg.images["default"].image == "gcr.io/proj/app"
    assert cfg.dev.sync[0].container_path == "/app"
    assert get_selector(cfg, "default").label_selector == {"app": "myapp"}


def test_unknown_key_rejected():
    data = yaml.safe_load(LATEST_YAML)
    data["bogus"] = 1
    with pytest.raises(ConfigError, match="bogus"):
        versions.parse(data)


def test_missing_version_rejected():
    with pytest.raises(ConfigError, match="version"):
        versions.parse({"cluster": {}})


def test_upgrade_chain_v1alpha1():
    old = yaml.safe_load(
        """
version: tpu/v1alpha1
deployments:
  - name: app
    autoReload: true
    chart: {path: ./chart}
sync:
  - selector: default
    containerPath: /app
ports:
  - selector: default
    localPort: 8080
    remotePort: 80
terminal:
  command: ["bash"]
"""
    )
    cfg = versions.parse(old)
    assert cfg.version == latest.VERSION
    assert cfg.dev.sync[0].container_path == "/app"
    assert cfg.dev.ports[0].port_mappings[0].local_port == 8080
    assert cfg.dev.terminal.command == ["bash"]
    assert cfg.dev.auto_reload.deployments == ["app"]
    assert cfg.deployments[0].chart.path == "./chart"


def test_roundtrip_to_dict():
    cfg = versions.parse(yaml.safe_load(LATEST_YAML))
    tree = to_dict(cfg)
    cfg2 = from_dict(latest.Config, tree)
    assert to_dict(cfg2) == tree


def test_merge_semantics():
    base = {"a": {"x": 1, "y": 2}, "list": [1, 2], "keep": "v"}
    override = {"a": {"y": 3}, "list": [9]}
    out = merge(base, override)
    assert out == {"a": {"x": 1, "y": 3}, "list": [9], "keep": "v"}
    # split is the inverse for the contributed parts
    assert split(out, override) == {"a": {"x": 1}, "keep": "v"}


def test_var_resolution(monkeypatch):
    tree = {"image": "gcr.io/${project}/app:${tag}", "ns": "${project}"}
    monkeypatch.setenv("DEVSPACE_VAR_PROJECT", "envproj")
    cache = {"tag": "v1"}
    out = resolve_vars(tree, cache, interactive=False)
    assert out == {"image": "gcr.io/envproj/app:v1", "ns": "envproj"}


def test_var_noninteractive_default(monkeypatch):
    monkeypatch.delenv("DEVSPACE_VAR_NAME", raising=False)
    cache = {}
    out = resolve_vars({"v": "${name}"}, cache, interactive=False)
    assert out == {"v": ""}
    assert "name" in cache  # answer cached for next load


def test_loader_end_to_end(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text(LATEST_YAML)
    loader = ConfigLoader(str(root))
    cfg = loader.load(interactive=False)
    assert cfg.cluster.namespace == "myns"
    # root discovery from a nested dir
    nested = root / "src" / "deep"
    nested.mkdir(parents=True)
    assert find_root(str(nested)) == str(root)


def test_loader_overrides(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text(LATEST_YAML)
    (root / ".devspace" / "overrides.yaml").write_text(
        "cluster:\n  namespace: overridden\n"
    )
    cfg = ConfigLoader(str(root)).load(interactive=False)
    assert cfg.cluster.namespace == "overridden"


def test_loader_multi_config(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / "base.yaml").write_text(LATEST_YAML)
    (root / ".devspace" / "configs.yaml").write_text(
        """
default:
  config: {path: base.yaml}
staging:
  config: {path: base.yaml}
  overrides:
    - config:
        cluster: {namespace: staging}
  vars:
    - name: tag
      default: stable
"""
    )
    loader = ConfigLoader(str(root))
    cfg = loader.load("staging", interactive=False)
    assert cfg.cluster.namespace == "staging"
    assert loader.generated.active_config == "staging"


def test_validation_errors(tmp_path):
    bad = yaml.safe_load(LATEST_YAML)
    bad["dev"]["sync"][0]["selector"] = "nope"
    root = tmp_path / "p"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text(yaml.safe_dump(bad))
    with pytest.raises(ConfigError, match="unknown selector"):
        ConfigLoader(str(root)).load(interactive=False)


def test_generated_cache_roundtrip(tmp_path):
    gc = GeneratedConfig(str(tmp_path))
    cache = gc.get_cache(dev_mode=True)
    cache.image_tags["default"] = "abc1234"
    cache.dockerfile_context_hashes["default"] = "deadbeef"
    gc.get_active().vars["tag"] = "v1"
    gc.save()
    gc2 = GeneratedConfig.load(str(tmp_path))
    assert gc2.get_cache(True).image_tags["default"] == "abc1234"
    assert gc2.get_active().vars["tag"] == "v1"
    assert gc2.get_cache(False).image_tags == {}


def test_save_preserves_var_placeholders(tmp_path, monkeypatch):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text(
        "version: tpu/v1\ncluster:\n  namespace: ${project}-ns\n"
    )
    monkeypatch.setenv("DEVSPACE_VAR_PROJECT", "secretproj")
    loader = ConfigLoader(str(root))
    cfg = loader.load(interactive=False)
    assert cfg.cluster.namespace == "secretproj-ns"
    cfg.tpu = latest.TPUConfig(workers=2)  # a real edit
    loader.save(cfg)
    saved = (root / ".devspace" / "config.yaml").read_text()
    assert "${project}-ns" in saved and "secretproj" not in saved
    assert "workers: 2" in saved


def test_save_multi_config_writes_referenced_file(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / "base.yaml").write_text(LATEST_YAML)
    (root / ".devspace" / "configs.yaml").write_text(
        "default:\n  config: {path: base.yaml}\n"
    )
    loader = ConfigLoader(str(root))
    cfg = loader.load(interactive=False)
    cfg.cluster.namespace = "edited"
    loader.save(cfg)
    assert "edited" in (root / "base.yaml").read_text()
    assert not (root / ".devspace" / "config.yaml").exists()
    # and the edit is visible on reload
    assert ConfigLoader(str(root)).load(interactive=False).cluster.namespace == "edited"


def test_stale_active_config_falls_back(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / "base.yaml").write_text(LATEST_YAML)
    (root / ".devspace" / "configs.yaml").write_text(
        "default:\n  config: {path: base.yaml}\n"
    )
    gc = GeneratedConfig(str(root))
    gc.active_config = "deleted-config"
    gc.save()
    cfg = ConfigLoader(str(root)).load(interactive=False)  # must not raise
    assert cfg.cluster.namespace == "myns"


def test_noninteractive_var_with_pattern_errors(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / "base.yaml").write_text(LATEST_YAML.replace("myns", "${env}"))
    (root / ".devspace" / "configs.yaml").write_text(
        """
default:
  config: {path: base.yaml}
  vars:
    - name: env
      regexPattern: "^(dev|prod)$"
"""
    )
    with pytest.raises(ValueError, match="pattern"):
        ConfigLoader(str(root)).load(interactive=False)


def test_terminal_selector_validated(tmp_path):
    bad = yaml.safe_load(LATEST_YAML)
    bad["dev"]["terminal"] = {"selector": "nope"}
    root = tmp_path / "p"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text(yaml.safe_dump(bad))
    with pytest.raises(ConfigError, match="terminal.*unknown selector"):
        ConfigLoader(str(root)).load(interactive=False)


def test_corrupt_generated_yaml_degrades(tmp_path):
    d = tmp_path / ".devspace"
    d.mkdir()
    (d / "generated.yaml").write_text("configs:\n  default:\n")  # null cache
    gc = GeneratedConfig.load(str(tmp_path))
    assert gc.get_active() is not None
    (d / "generated.yaml").write_text("{{{{not yaml")
    gc = GeneratedConfig.load(str(tmp_path))
    assert gc.active_config == "default"


def test_save_does_not_bake_defaults(tmp_path):
    root = tmp_path / "proj"
    (root / ".devspace").mkdir(parents=True)
    (root / ".devspace" / "config.yaml").write_text("version: tpu/v1\n")
    loader = ConfigLoader(str(root))
    cfg = loader.load(interactive=False)
    loader.save(cfg)
    saved = yaml.safe_load((root / ".devspace" / "config.yaml").read_text())
    assert "cluster" not in saved
