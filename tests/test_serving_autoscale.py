"""Autoscaler decision-table goldens.

Every expectation here is hand-computed from the HPA formula
``desired = ceil(current * value / target)`` plus the tolerance band and
the stabilization-window rules documented in
devspace_tpu/serving/autoscale.py. The clock is injected, so the table
is exact — no sleeps, no wall time.
"""

import pytest

from devspace_tpu.serving import Autoscaler, AutoscalerConfig
from devspace_tpu.serving.autoscale import AutoscaleLoop, signal_values


def sig(value, name="occ"):
    return [{
        "type": "Pods",
        "pods": {
            "metric": {"name": name},
            "target": {"type": "AverageValue", "averageValue": value},
        },
    }]


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make(clock, *, target=0.5, tol=0.1, down=5.0, up=0.0,
         lo=1, hi=4, name="occ"):
    return Autoscaler(
        AutoscalerConfig(
            min_replicas=lo, max_replicas=hi, targets={name: target},
            tolerance=tol, scale_up_stabilization_s=up,
            scale_down_stabilization_s=down,
        ),
        clock=clock,
    )


# -- config / parsing --------------------------------------------------------
def test_config_validation():
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=0).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(targets={}).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(targets={"m": 0}).validate()


def test_signal_values_parses_hpa_entries():
    entries = sig(0.8) + [
        {"type": "Resource", "resource": {}},           # not a Pods entry
        {"type": "Pods", "pods": {"metric": {"name": "bad"},
                                  "target": {"type": "Utilization"}}},
        {"type": "Pods", "pods": {"metric": {"name": "nan"},
                                  "target": {"type": "AverageValue",
                                             "averageValue": "x"}}},
    ]
    assert signal_values(entries) == {"occ": 0.8}
    assert signal_values([]) == {}
    assert signal_values(None) == {}


# -- golden decision table ---------------------------------------------------
def test_golden_scale_up_is_immediate():
    clk = Clock()
    a = make(clk)
    # value 1.0 vs target 0.5 at current=2: ceil(2*2.0) = 4
    d = a.evaluate(sig(1.0), 2)
    assert (d.desired, d.recommendation) == (4, 4)
    # value 0.6 vs target 0.5 at current=1: ratio 1.2 outside the 10%
    # band -> ceil(1*1.2) = 2
    d = make(Clock()).evaluate(sig(0.6), 1)
    assert d.desired == 2


def test_golden_tolerance_band_holds():
    # |ratio - 1| <= 0.1 votes for the current count
    for value in (0.45, 0.5, 0.55):
        d = make(Clock()).evaluate(sig(value), 3)
        assert d.desired == 3, f"value={value} must hold at 3"


def test_golden_clamps():
    # ratio 10 at current=2 wants 20; max_replicas clamps to 4
    assert make(Clock()).evaluate(sig(5.0), 2).desired == 4
    # ratio ~0 wants 1 but min_replicas=2 clamps (window observed)
    clk = Clock()
    a = make(clk, lo=2, down=1.0)
    a.evaluate(sig(0.01), 3)
    clk.t = 2.0
    assert a.evaluate(sig(0.01), 3).desired == 2


def test_golden_no_signals_holds_steady():
    d = make(Clock()).evaluate([], 3)
    assert d.desired == 3
    assert d.reason == "no signals"


def test_golden_scale_down_waits_for_observed_window():
    clk = Clock()
    a = make(clk, down=5.0)
    # t=0: quiet sample, but the 5s window predates history -> HOLD
    assert a.evaluate(sig(0.0), 2).desired == 2
    # t=3: still inside the unobserved window -> HOLD
    clk.t = 3.0
    assert a.evaluate(sig(0.0), 2).desired == 2
    # t=5.5: a full 5s of low recommendations observed -> scale down
    clk.t = 5.5
    assert a.evaluate(sig(0.0), 2).desired == 1


def test_golden_spike_pins_scale_down_until_window_clears():
    clk = Clock()
    a = make(clk, down=5.0)
    a.evaluate(sig(0.0), 2)            # t=0   rec 1
    clk.t = 2.0
    d = a.evaluate(sig(2.0), 2)        # t=2   spike: rec 4, scale up
    assert d.desired == 4
    clk.t = 4.0
    # quiet again, but the t=2 spike is inside [−1, 4] -> hold at 4
    assert a.evaluate(sig(0.0), 4).desired == 4
    clk.t = 6.9
    # spike rec stood until t=4 (recommendations hold until the next
    # sample), so window [1.9, 6.9] still saw it -> hold
    assert a.evaluate(sig(0.0), 4).desired == 4
    clk.t = 9.1
    # window [4.1, 9.1]: standing rec at window start is t=4's quiet 1
    # and everything after is quiet -> scale down
    assert a.evaluate(sig(0.0), 4).desired == 1


def test_golden_scale_up_stabilization_takes_window_min():
    clk = Clock()
    a = make(clk, up=3.0, down=10.0)
    a.evaluate(sig(0.5), 2)            # t=0 rec 2 (in band)
    clk.t = 1.0
    # raw rec 4, but min over the up-window {2 (standing), 4} = 2
    d = a.evaluate(sig(1.0), 2)
    assert (d.recommendation, d.desired) == (4, 2)
    clk.t = 4.0
    # window [1, 4] now only holds high recs -> up goes through
    d = a.evaluate(sig(1.0), 2)
    assert d.desired == 4


def test_golden_multiple_metrics_most_pressured_wins():
    clk = Clock()
    a = Autoscaler(
        AutoscalerConfig(
            min_replicas=1, max_replicas=8,
            targets={"occ": 0.5, "queue": 2.0},
            scale_down_stabilization_s=5.0,
        ),
        clock=clk,
    )
    signals = sig(0.5, "occ") + sig(8.0, "queue")
    # occ votes hold (ratio 1); queue ratio 4 at current=2 votes 8
    d = a.evaluate(signals, 2)
    assert d.desired == 8
    assert "queue" in d.reason


# -- the closed loop (fakes: no sockets) ------------------------------------
class FakeFleet:
    def __init__(self):
        self.desired = 2
        self.scale_calls = []
        self._targets = {"replica-0": "http://x:1", "replica-1": "http://x:2"}

    def targets(self):
        return dict(self._targets)

    def scale_to(self, n, reason=""):
        self.scale_calls.append((n, reason))
        self.desired = n


class FakeCollector:
    def __init__(self, signals):
        self.signals = signals
        self.refreshed = []

    def refresh(self, targets):
        self.refreshed.append(list(targets))

    def hpa_signals(self):
        return self.signals


def test_loop_tick_refreshes_targets_and_applies_decision():
    fleet = FakeFleet()
    coll = FakeCollector(sig(2.0))  # heavy pressure
    loop = AutoscaleLoop(fleet, coll, AutoscalerConfig(
        min_replicas=1, max_replicas=6, targets={"occ": 0.5},
        scale_down_stabilization_s=5.0))
    decision = loop.tick()
    # the collector was re-pointed at the fleet's current replica set
    assert coll.refreshed == [sorted(fleet.targets().items())]
    # ceil(2 * 4.0) = 8, clamped to 6, applied through scale_to
    assert decision.desired == 6
    assert fleet.scale_calls == [(6, decision.reason)]
    assert loop.decisions[-1] is decision


def test_loop_tick_no_change_means_no_scale_call():
    fleet = FakeFleet()
    coll = FakeCollector(sig(0.5))  # exactly on target
    loop = AutoscaleLoop(fleet, coll, AutoscalerConfig(
        min_replicas=1, max_replicas=6, targets={"occ": 0.5}))
    loop.tick()
    assert fleet.scale_calls == []
