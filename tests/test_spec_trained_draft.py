"""Trained-draft speculative decoding (VERDICT r4 next #3): train a
micro target + smaller draft on the learnable Markov corpus via the
actual pair-training pipeline (scripts/train_draft_pair.py), restore both
through the train->serve seam, and show the draft GENUINELY predicts the
target — engine acceptance far above the random floor — while staying
lossless."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
)

from devspace_tpu.inference import InferenceEngine
from devspace_tpu.models import transformer as tfm
from devspace_tpu.training.data import markov_sampler

TARGET = tfm.TransformerConfig(
    vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
    ffn_dim=128, max_seq_len=128,
)
DRAFT = tfm.TransformerConfig(
    vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
    ffn_dim=64, max_seq_len=128,
)
CORPUS = {"active": 64, "noise": 0.02, "seed": 0}


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    from train_draft_pair import train_pair

    out = str(tmp_path_factory.mktemp("spec_pair"))
    meta = train_pair(
        out, TARGET, DRAFT, CORPUS,
        steps=300, batch=16, seq=33, lr=1e-2, log=lambda *a: None,
    )
    return out, meta


def test_pair_training_learns_the_corpus(pair):
    """Both models must beat the corpus's noise-driven accuracy floor by
    a wide margin, and agree with each other — the static proxy for
    speculative acceptance."""
    _, meta = pair
    # random floor is 1/active ~= 0.016; the corpus ceiling is ~1-noise
    assert meta["target_accuracy"] > 0.6, meta
    assert meta["draft_accuracy"] > 0.5, meta
    assert meta["target_draft_agreement"] > 0.6, meta
    assert meta["params_ratio"] > 2.0


def test_trained_draft_accepts_and_stays_lossless(pair):
    """The engine's measured acceptance with the trained draft must sit
    far above the random-draft floor, and speculative output must equal
    the plain engine's token-for-token."""
    out, _ = pair
    sample = markov_sampler(**CORPUS)
    prompts = [list(sample(1, n, seed=50 + n)[0]) for n in (6, 11, 17)]

    def drive(engine):
        engine.start()
        try:
            return [
                h.result(timeout=120)
                for h in [engine.submit(p, 24) for p in prompts]
            ]
        finally:
            engine.stop()

    plain = drive(
        InferenceEngine.from_checkpoint(
            os.path.join(out, "target"), TARGET, max_slots=2, max_len=64
        )
    )
    spec_engine = InferenceEngine.from_checkpoint(
        os.path.join(out, "target"),
        TARGET,
        draft_checkpoint=os.path.join(out, "draft"),
        draft_cfg=DRAFT,
        max_slots=2,
        max_len=64,
    )
    spec = drive(spec_engine)
    assert spec == plain, "speculative decoding must be lossless"
    assert spec_engine.spec_proposed > 0
    acceptance = spec_engine.spec_accepted / spec_engine.spec_proposed
    # the corpus is order-2-predictable: a draft that learned it tracks
    # the target's greedy chain; random drafts sit at ~1/64
    assert acceptance > 0.5, f"trained draft acceptance only {acceptance:.3f}"


def test_bench_draft_dir_contract(pair):
    """scripts/bench_inference.py consumes the pair via pair.json — pin
    the keys it reads so the artifact contract can't silently drift."""
    out, meta = pair
    import json

    with open(os.path.join(out, "pair.json")) as f:
        on_disk = json.load(f)
    for key in (
        "target", "draft", "corpus", "params_ratio",
        "target_draft_agreement",
    ):
        assert key in on_disk, key
    assert on_disk["target"]["dim"] == TARGET.dim
    rebuilt = tfm.TransformerConfig(**on_disk["draft"])
    assert rebuilt.dim == DRAFT.dim and rebuilt.n_layers == DRAFT.n_layers
    assert on_disk["target_draft_agreement"] == meta["target_draft_agreement"]
