"""Worker process for the multi-host bootstrap test (VERDICT r2 next #3).

Spawned (2x) by tests/test_multihost.py with exactly the env contract the
TPU chart templates inject into slice pods
(generator/templates/chart-tpu/templates/statefulset.yaml):
``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``TPU_WORKER_ID``,
``TPU_WORKER_HOSTNAMES``. Proves ``multihost_initialize`` + ``host_shard``
actually bring up a cross-process mesh and train a psum step — the same
path examples/jax-resnet-tpu/train.py runs on a real slice.

Runs on the CPU backend with 4 virtual devices per process; the psum over
the 8-device ``data`` axis therefore crosses the process boundary (the
DCN stand-in).
"""

import os
import sys

# Platform setup must precede the first jax import (same rationale as
# tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", ""
    ).strip()
    + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from devspace_tpu.parallel.mesh import create_mesh, multihost_initialize  # noqa: E402
from devspace_tpu.training.data import host_shard  # noqa: E402


def main() -> int:
    assert os.environ.get("TPU_WORKER_HOSTNAMES"), "chart env contract missing"
    initialized = multihost_initialize()
    assert initialized is True, "multihost_initialize() did not trigger"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"data": 8})
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(16, 8)).astype(np.float32)
    gy = rng.normal(size=(16,)).astype(np.float32)
    # each host loads ONLY its shard of the global batch (input pipeline
    # contract), then assembles the global array from local data
    local = host_shard({"x": gx, "y": gy})
    shard = NamedSharding(mesh, P("data"))
    x = jax.make_array_from_process_local_data(shard, local["x"])
    y = jax.make_array_from_process_local_data(shard, local["y"])
    w = jax.device_put(jnp.zeros((8,), jnp.float32), NamedSharding(mesh, P()))

    def local_step(w, x, y):
        def loss_fn(w):
            return jnp.sum((x @ w - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        # explicit data-parallel all-reduce: devices 0-3 live in process
        # 0, devices 4-7 in process 1 — this psum crosses processes
        loss = jax.lax.psum(loss, "data") / 16.0
        g = jax.lax.psum(g, "data") / 16.0
        return w - 0.5 * g, loss

    step = jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
    w, l0 = step(w, x, y)
    w, l1 = step(w, x, y)
    print(f"MULTIHOST_LOSS {float(l0):.8f} {float(l1):.8f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
