"""CLI end-to-end tests against the fake backend.

The minimum end-to-end slice from SURVEY §7 step 6: init a jax project ->
dev -> edit train.py locally -> hot-reloaded on the (fake) TPU slice.
"""

import os
import threading
import time

import pytest

from devspace_tpu.cli.main import main
from devspace_tpu.utils import log as logutil
from devspace_tpu.utils.fsutil import write_file


@pytest.fixture
def project(tmp_path, monkeypatch):
    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_FAKE_BACKEND", str(tmp_path / "cluster"))
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    # a jax project
    write_file(
        str(proj / "train.py"),
        "import jax\nprint('step 0')\n",
    )
    logutil.set_logger(logutil.StdoutLogger())
    return proj


def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


def test_init_scaffolds_jax_project(project):
    assert main(["init"]) == 0
    assert (project / "Dockerfile").exists()
    assert "jax[tpu]" in (project / "Dockerfile").read_text()
    assert (project / "chart" / "chart.yaml").exists()
    assert "google.com/tpu" in (project / "chart" / "values.yaml").read_text()
    cfg = (project / ".devspace" / "config.yaml").read_text()
    assert "tpu:" in cfg and "workers: 2" in cfg
    # init twice refuses without --reconfigure
    assert main(["init"]) == 1


def test_startup_newer_version_notice(project, tmp_path, monkeypatch):
    """VERDICT r3 next #6 (reference cmd/root.go:42): with a newer
    stable archive in DEVSPACE_RELEASE_DIR, any command prints the
    upgrade hint — once per day (stamped under ~/.devspace), with
    pre-release archives never counting."""
    import io
    import json
    import tarfile

    class RecordingLogger(logutil.Logger):
        def __init__(self):
            super().__init__()
            self.lines = []

        def _write(self, tag, msg):
            self.lines.append(f"[{tag}] {msg}")

    rec = RecordingLogger()
    logutil.set_logger(rec)

    releases = tmp_path / "releases"
    releases.mkdir()

    def make_archive(version, name):
        init = f'__version__ = "{version}"\n'.encode()
        with tarfile.open(str(releases / name), "w:gz") as tf:
            info = tarfile.TarInfo("pkg/devspace_tpu/__init__.py")
            info.size = len(init)
            tf.addfile(info, io.BytesIO(init))

    make_archive("9.9.9", "devspace-tpu-9.9.9.tar.gz")
    make_archive("10.0.0-rc1", "devspace-tpu-10.0.0-rc1.tar.gz")
    home = tmp_path / "home"
    home.mkdir()
    monkeypatch.setenv("HOME", str(home))
    monkeypatch.setenv("DEVSPACE_RELEASE_DIR", str(releases))
    monkeypatch.delenv("DEVSPACE_SKIP_VERSION_CHECK", raising=False)

    assert main(["init"]) == 0
    combined = "\n".join(rec.lines)
    assert "newer version of devspace-tpu v9.9.9" in combined
    assert "10.0.0" not in combined  # pre-release ignored
    # stamped: the next run within a day stays silent
    assert (home / ".devspace" / "version_check.json").exists()
    rec.lines.clear()
    assert main(["status", "deployments"]) == 0
    assert "newer version" not in "\n".join(rec.lines)
    # stale stamp: the notice fires again
    stamp = home / ".devspace" / "version_check.json"
    data = json.loads(stamp.read_text())
    data["checked_at"] = 0
    stamp.write_text(json.dumps(data))
    rec.lines.clear()
    assert main(["status", "deployments"]) == 0
    assert "newer version of devspace-tpu v9.9.9" in "\n".join(rec.lines)
    # a stable release with a platform/build suffix in the FILENAME is
    # still an upgrade: the dash must not be misread as a pre-release
    # (only the embedded version decides that)
    make_archive("9.9.10", "devspace-tpu-9.9.10-linux-x86_64.tar.gz")
    data["checked_at"] = 0
    stamp.write_text(json.dumps(data))
    rec.lines.clear()
    assert main(["status", "deployments"]) == 0
    assert "newer version of devspace-tpu v9.9.10" in "\n".join(rec.lines)


def test_init_volume_flag_renders_claim_template(project):
    """`init --volume ckpt:20Gi:/ckpt` must wire persistence values into
    the config so the scaffolded TPU chart renders per-worker
    volumeClaimTemplates and the mount (VERDICT r3 next #5)."""
    assert main(["init", "--volume", "ckpt:20Gi:/ckpt"]) == 0
    from devspace_tpu.config.loader import ConfigLoader
    from devspace_tpu.deploy.chart import render_chart

    cfg = ConfigLoader(str(project)).load(interactive=False)
    values = dict(cfg.deployments[0].chart.values)
    assert values["persistence"]["volumes"] == [
        {"name": "ckpt", "size": "20Gi"}
    ]
    values.setdefault("image", "registry.local/t:1")
    manifests = render_chart(
        str(project / "chart"),
        release_name="proj",
        namespace="default",
        values=values,
        extra_context={
            "images": {},
            "pullSecrets": [],
            "tpu": {
                "accelerator": "v5litepod-8",
                "topology": "2x4",
                "workers": 2,
                "chipsPerWorker": 4,
                "workerHostnames": "h0,h1",
                "coordinatorAddress": "h0:8476",
            },
        },
    )
    sts = next(m for m in manifests if m["kind"] == "StatefulSet")
    tmpl = sts["spec"]["volumeClaimTemplates"][0]
    assert tmpl["metadata"]["name"] == "ckpt"
    assert tmpl["spec"]["resources"]["requests"]["storage"] == "20Gi"
    assert sts["spec"]["template"]["spec"]["containers"][0]["volumeMounts"] == [
        {"name": "ckpt", "mountPath": "/ckpt"}
    ]
    # malformed spec errors out cleanly
    proj2_cfg = project / ".devspace" / "config.yaml"
    proj2_cfg.unlink()
    assert main(["init", "--reconfigure", "--volume", "justaname"]) == 1


def test_deploy_and_status_and_purge(project, tmp_path):
    assert main(["init"]) == 0
    assert main(["deploy"]) == 0
    from devspace_tpu.kube.fake import FakeCluster

    fc = FakeCluster(str(tmp_path / "cluster"), persist=True)
    workers = fc.slice_workers({"app": "proj"}, expected=2, timeout=5)
    assert [p.tpu_worker_id for p in workers] == [0, 1]
    assert main(["status", "deployments"]) == 0
    assert main(["list", "deployments"]) == 0
    assert main(["list", "sync"]) == 0
    assert main(["analyze", "--no-wait"]) == 0
    assert main(["purge"]) == 0
    fc2 = FakeCluster(str(tmp_path / "cluster"), persist=True)
    assert fc2.list_pods(label_selector={"app": "proj"}) == []


def test_dev_loop_hot_reload(project, tmp_path):
    assert main(["init"]) == 0
    from devspace_tpu.cli.context import Context
    from devspace_tpu.cli.pipeline import DevLoop

    class Args:
        namespace = None
        kube_context = None
        config = None
        no_sync = False
        no_portforwarding = True  # no real server in the fake pods
        no_terminal = True
        verbose_sync = False
        force_build = False
        force_deploy = False

    ctx = Context(Args())
    loop = DevLoop(ctx, Args())
    t = threading.Thread(target=loop.run, daemon=True)
    t.start()
    try:
        wait_for(loop.services_ready.is_set, msg="services up")
        from devspace_tpu.kube.fake import FakeCluster

        fc = ctx.backend
        workers = fc.slice_workers({"app": "proj"}, expected=2, timeout=10)
        # initial sync pushed train.py to every worker
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(
                    os.path.join(fc.translate_path(w, "/app"), "train.py")
                ),
                msg=f"initial sync to {w.name}",
            )
        # hot edit -> propagates to all workers
        write_file(str(project / "train.py"), "import jax\nprint('edited')\n")
        future = time.time() + 3
        os.utime(str(project / "train.py"), (future, future))
        for w in workers:
            wait_for(
                lambda w=w: "edited"
                in open(
                    os.path.join(fc.translate_path(w, "/app"), "train.py")
                ).read(),
                msg=f"hot reload on {w.name}",
            )
        # remote-created file comes back (worker 0 authoritative)
        ckpt = os.path.join(fc.translate_path(workers[0], "/app"), "ckpt.txt")
        write_file(ckpt, "weights")
        wait_for(lambda: (project / "ckpt.txt").exists(), msg="download")
        # sync status from logs
        assert main(["status", "sync"]) == 0
    finally:
        loop.stop()
        loop.stop_services()
        t.join(timeout=5)


def test_add_remove_list_roundtrip(project):
    assert main(["init"]) == 0
    assert main(["add", "port", "9999"]) == 0
    cfg = (project / ".devspace" / "config.yaml").read_text()
    assert "9999" in cfg
    assert main(["remove", "port", "9999"]) == 0 or True
    assert main(["add", "selector", "extra", "--label-selector", "tier=db"]) == 0
    assert main(["add", "sync", "--selector", "extra", "--container", "/data"]) == 0
    assert main(["list", "ports"]) == 0
    assert main(["list", "selectors"]) == 0
    assert main(["print"]) == 0
    assert main(["update"]) == 0


def test_enter_runs_command(project, tmp_path, capsys):
    assert main(["init"]) == 0
    assert main(["deploy"]) == 0
    rc = main(["enter", "--worker", "1", "--", "echo", "hello-from-worker"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "hello-from-worker" in out


def test_reset_removes_state(project):
    assert main(["init"]) == 0
    assert main(["deploy"]) == 0
    assert main(["reset", "--all"]) == 0
    assert not (project / ".devspace").exists()
    assert not (project / "chart").exists()


def test_install_and_upgrade(tmp_path, monkeypatch):
    import subprocess
    import sys

    from devspace_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    bin_dir = tmp_path / "bin"
    assert main(["install", "--bin-dir", str(bin_dir)]) == 0
    launcher = bin_dir / "devspace-tpu"
    assert launcher.exists() and os.access(launcher, os.X_OK)
    out = subprocess.run(
        [str(launcher), "--version"], capture_output=True, text=True, timeout=60
    )
    assert out.returncode == 0

    # upgrade without --apply just prints instructions
    assert main(["upgrade"]) == 0


def test_install_update_path(tmp_path, monkeypatch):
    """--update-path persists the PATH addition to the shell rc
    (reference: pkg/util/envutil via cmd/install.go)."""
    from devspace_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.setenv("SHELL", "/bin/bash")
    monkeypatch.setenv("PATH", "/usr/bin")
    bin_dir = tmp_path / "bin"
    assert main(["install", "--bin-dir", str(bin_dir), "--update-path"]) == 0
    rc = (tmp_path / ".bashrc").read_text()
    assert f'export PATH="{bin_dir}:$PATH"' in rc
    # idempotent: second run doesn't duplicate the line
    assert main(["install", "--bin-dir", str(bin_dir), "--update-path"]) == 0
    assert rc.count("added by devspace-tpu") == (tmp_path / ".bashrc").read_text().count(
        "added by devspace-tpu"
    )


def test_enter_all_broadcasts(project, tmp_path, capsys):
    """enter --all runs the command on every slice worker with
    worker-prefixed output and propagates non-zero exits."""
    from devspace_tpu.cli.main import main

    from devspace_tpu.kube.fake import FakeCluster

    assert main(["init"]) == 0
    assert main(["deploy"]) == 0
    # the command must reach EVERY deployed worker, not just one
    fc = FakeCluster(os.environ["DEVSPACE_FAKE_BACKEND"], persist=True)
    n_workers = len(fc.list_pods())
    assert n_workers >= 1
    rc = main(["enter", "--all", "--", "sh", "-c", "echo hello-$TPU_WORKER_ID"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("hello-") == n_workers
    assert main(["enter", "--all", "--", "sh", "-c", "exit 3"]) == 3
    # --all without a command is an error
    assert main(["enter", "--all"]) == 1


def test_upgrade_degrades_gracefully_outside_git(tmp_path, monkeypatch):
    """VERDICT r1 missing #4: upgrade --apply on a non-git checkout warns
    cleanly instead of surfacing a git traceback."""
    from devspace_tpu.cli import main as cli_main_mod

    monkeypatch.setattr(cli_main_mod, "_checkout_root", lambda: str(tmp_path))
    rc = cli_main_mod.main(["upgrade", "--apply"])
    assert rc == 1  # failed, but gracefully (warn path, no exception)


def test_print_manifests_renders_without_applying(tmp_path, monkeypatch, capsys):
    """`print --manifests` is the helm-template equivalent: full render
    of every deployment, nothing applied."""
    import yaml as _yaml

    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_FAKE_BACKEND", str(tmp_path / "cluster"))
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    (proj / "train.py").write_text("print('x')\n")
    assert main(["init"]) == 0
    capsys.readouterr()
    assert main(["print", "--manifests"]) == 0
    out = capsys.readouterr().out
    docs = [d for d in _yaml.safe_load_all(out) if d]
    kinds = {d["kind"] for d in docs}
    assert "Deployment" in kinds or "StatefulSet" in kinds
    assert "Service" in kinds
    # nothing was applied to the cluster
    import json, os
    state = json.load(open(tmp_path / "cluster" / "cluster-state.json")) if (
        tmp_path / "cluster" / "cluster-state.json").exists() else {"objects": []}
    assert not state.get("objects")


def test_upgrade_from_release_archive(tmp_path, monkeypatch):
    """Reference parity (upgrade.go downloads a release artifact and swaps
    the binary): `upgrade --archive` validates a source tarball and
    atomically replaces the package, with rollback on failure."""
    import tarfile

    from devspace_tpu.cli import main as cli_main_mod
    from devspace_tpu.cli.main import main

    logutil.set_logger(logutil.StdoutLogger())
    # a fake installed checkout
    checkout = tmp_path / "install"
    (checkout / "devspace_tpu").mkdir(parents=True)
    (checkout / "devspace_tpu" / "__init__.py").write_text(
        '__version__ = "0.1.0"\n'
    )
    (checkout / "devspace_tpu" / "old_marker.py").write_text("OLD = 1\n")
    monkeypatch.setattr(cli_main_mod, "_checkout_root", lambda: str(checkout))

    # a release artifact at 0.2.0 wrapped in a top-level dir
    rel = tmp_path / "rel" / "devspace-tpu-0.2.0"
    (rel / "devspace_tpu").mkdir(parents=True)
    (rel / "devspace_tpu" / "__init__.py").write_text('__version__ = "0.2.0"\n')
    (rel / "devspace_tpu" / "new_marker.py").write_text("NEW = 2\n")
    archive = tmp_path / "release.tgz"
    with tarfile.open(archive, "w:gz") as tf:
        tf.add(str(rel), arcname="devspace-tpu-0.2.0")

    assert main(["upgrade", "--archive", str(archive)]) == 0
    assert (checkout / "devspace_tpu" / "new_marker.py").exists()
    assert not (checkout / "devspace_tpu" / "old_marker.py").exists()
    assert not (checkout / "devspace_tpu.bak").exists()
    assert "0.2.0" in (checkout / "devspace_tpu" / "__init__.py").read_text()

    # same INSTALLED version (read from the target checkout, now 0.2.0):
    # no-op without --force
    rel2 = tmp_path / "rel2" / "x"
    (rel2 / "devspace_tpu").mkdir(parents=True)
    (rel2 / "devspace_tpu" / "__init__.py").write_text('__version__ = "0.2.0"\n')
    same = tmp_path / "same.tgz"
    with tarfile.open(same, "w:gz") as tf:
        tf.add(str(rel2), arcname="x")
    assert main(["upgrade", "--archive", str(same)]) == 0
    assert (checkout / "devspace_tpu" / "new_marker.py").exists()  # untouched

    # an OLDER archive is refused (no silent downgrade)
    rel3 = tmp_path / "rel3" / "x"
    (rel3 / "devspace_tpu").mkdir(parents=True)
    (rel3 / "devspace_tpu" / "__init__.py").write_text('__version__ = "0.1.0"\n')
    old = tmp_path / "old.tgz"
    with tarfile.open(old, "w:gz") as tf:
        tf.add(str(rel3), arcname="x")
    assert main(["upgrade", "--archive", str(old)]) == 1
    assert "0.2.0" in (checkout / "devspace_tpu" / "__init__.py").read_text()

    # a fixture copy DEEPER in the tree must not shadow the real package
    rel4 = tmp_path / "rel4" / "devspace-tpu-0.3.0"
    (rel4 / "tests" / "fixtures" / "devspace_tpu").mkdir(parents=True)
    (rel4 / "tests" / "fixtures" / "devspace_tpu" / "__init__.py").write_text(
        '__version__ = "9.9.9"\n'
    )
    (rel4 / "devspace_tpu").mkdir(parents=True)
    (rel4 / "devspace_tpu" / "__init__.py").write_text('__version__ = "0.3.0"\n')
    (rel4 / "devspace_tpu" / "real_marker.py").write_text("REAL = 3\n")
    arc4 = tmp_path / "r4.tgz"
    with tarfile.open(arc4, "w:gz") as tf:
        # fixture added FIRST so naive first-match would pick it
        tf.add(
            str(rel4 / "tests"), arcname="devspace-tpu-0.3.0/tests"
        )
        tf.add(
            str(rel4 / "devspace_tpu"),
            arcname="devspace-tpu-0.3.0/devspace_tpu",
        )
    assert main(["upgrade", "--archive", str(arc4)]) == 0
    assert (checkout / "devspace_tpu" / "real_marker.py").exists()
    assert "0.3.0" in (checkout / "devspace_tpu" / "__init__.py").read_text()

    # a truncated tarball errors cleanly (rc 1, no traceback)
    trunc = tmp_path / "trunc.tgz"
    trunc.write_bytes(archive.read_bytes()[:200])
    assert main(["upgrade", "--archive", str(trunc)]) == 1

    # an archive with no package is rejected
    junk = tmp_path / "junk.tgz"
    (tmp_path / "junkfile").write_text("nope")
    with tarfile.open(junk, "w:gz") as tf:
        tf.add(str(tmp_path / "junkfile"), arcname="junkfile")
    assert main(["upgrade", "--archive", str(junk)]) == 1


def test_upgrade_archive_refuses_git_checkout(tmp_path, monkeypatch):
    """--archive on a git checkout must refuse without --force: swapping
    the package inside a working repo destroys uncommitted work."""
    import tarfile

    from devspace_tpu.cli import main as cli_main_mod
    from devspace_tpu.cli.main import main

    logutil.set_logger(logutil.StdoutLogger())
    checkout = tmp_path / "dev"
    (checkout / "devspace_tpu").mkdir(parents=True)
    (checkout / "devspace_tpu" / "__init__.py").write_text('__version__ = "0.1.0"\n')
    (checkout / ".git").mkdir()
    monkeypatch.setattr(cli_main_mod, "_checkout_root", lambda: str(checkout))
    rel = tmp_path / "rel" / "x"
    (rel / "devspace_tpu").mkdir(parents=True)
    (rel / "devspace_tpu" / "__init__.py").write_text('__version__ = "9.9.9"\n')
    archive = tmp_path / "r.tgz"
    with tarfile.open(archive, "w:gz") as tf:
        tf.add(str(rel), arcname="x")
    assert main(["upgrade", "--archive", str(archive)]) == 1
    assert "0.1.0" in (checkout / "devspace_tpu" / "__init__.py").read_text()
