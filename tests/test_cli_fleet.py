"""Fleet CLI tests (``collector serve``, ``top --fleet``, ``debug
bundle --fleet`` — ISSUE 10).

Stub ``http.server`` replicas serve real registry expositions; the
collector federates them over actual HTTP and the CLI surfaces are
pinned end-to-end — no engine, no sleeps. The live 3-replica pass is
the slow-marked test in test_fleet_live.py.
"""

import json
import socket
import tarfile
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from devspace_tpu.cli.main import main
from devspace_tpu.obs.metrics import Registry
from devspace_tpu.utils import log as logutil

TRACE = "cd" * 16


def _replica_metrics(tok_s, completed, ttft_obs):
    r = Registry()
    r.gauge("engine_tokens_per_sec_10s", "rate").set(tok_s)
    r.gauge("engine_active_slots", "a").set(2)
    r.gauge("engine_max_slots", "m").set(4)
    r.gauge("engine_queued_requests", "q").set(1)
    r.counter("engine_requests_completed_total", "done").inc(completed)
    h = r.histogram("ttft_seconds", "ttft")
    for v in ttft_obs:
        h.observe(v)
    return r.render()


class ReplicaHandler(BaseHTTPRequestHandler):
    metrics_text = _replica_metrics(40.0, 10, [0.01, 0.02])
    omit = ()

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        payloads = {
            "/metrics": ("text/plain", self.metrics_text.encode()),
            "/healthz": ("application/json", json.dumps(
                {"ok": True, "slo": {"status": "ok"}}).encode()),
            "/debug/events": ("application/json", json.dumps({
                "events": [{"time": 1754500000.0, "seq": 1, "level": "info",
                            "subsystem": "engine", "event": "admit"}],
            }).encode()),
            "/debug/spans": ("application/json", json.dumps({
                "process": "serve:1",
                "spans": [{"name": "generate", "trace_id": TRACE,
                           "span_id": "11" * 8, "start": 10.0,
                           "duration_s": 0.5, "track": "http"}],
            }).encode()),
            "/debug/requests": ("application/json", b'{"requests": []}'),
            "/debug/config": ("application/json", b'{"model": "tiny"}'),
        }
        if path in self.omit or path not in payloads:
            self.send_error(404)
            return
        ctype, body = payloads[path]
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


def _start(handler):
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


@pytest.fixture
def replica_urls():
    pairs = [_start(ReplicaHandler) for _ in range(2)]
    try:
        yield [u for _s, u in pairs]
    finally:
        for s, _u in pairs:
            s.shutdown()
            s.server_close()


class _DynStream:
    def write(self, s):
        import sys

        return sys.stdout.write(s)

    def flush(self):
        import sys

        sys.stdout.flush()

    def isatty(self):
        return False


@pytest.fixture(autouse=True)
def stdout_logger():
    logutil.set_logger(logutil.StdoutLogger(stream=_DynStream()))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- collector serve ---------------------------------------------------------
def test_collector_serve_federates_over_http(replica_urls):
    port = _free_port()
    paths = ["/metrics", "/healthz", "/debug/fleet",
             "/debug/events?limit=10", f"/debug/trace?trace_id={TRACE}"]
    rc = []
    t = threading.Thread(
        target=lambda: rc.append(main(
            ["collector", "serve", "--port", str(port),
             "--iterations", str(len(paths))]
            + [f for u in replica_urls for f in ("--target", u)])),
        daemon=True,
    )
    t.start()
    base = f"http://127.0.0.1:{port}"
    got = {}
    for path in paths:
        for _ in range(50):  # wait for the listener
            try:
                with urllib.request.urlopen(base + path, timeout=5) as resp:
                    got[path] = resp.read()
                break
            except OSError:
                import time

                time.sleep(0.05)
        else:
            pytest.fail(f"collector never answered {path}")
    t.join(timeout=10)
    assert rc == [0]
    metrics = got["/metrics"].decode()
    # counters summed across both replicas, merged histogram intact
    assert "engine_requests_completed_total 20" in metrics
    assert "ttft_seconds_count 4" in metrics
    assert "collector_fleet_targets_up 2" in metrics
    health = json.loads(got["/healthz"])
    assert health["ok"] and health["up"] == 2
    fleet = json.loads(got["/debug/fleet"])
    assert len(fleet["targets"]) == 2
    assert fleet["fleet"]["tok_s"] == pytest.approx(80.0)
    assert fleet["hpa"]["metrics"]
    events = json.loads(got["/debug/events?limit=10"])
    assert events["events"] and events["events"][0]["target"]
    trace = json.loads(got[f"/debug/trace?trace_id={TRACE}"])
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(lanes) == 2  # one lane per replica process


def test_collector_serve_requires_targets(capsys):
    assert main(["collector", "serve"]) == 1
    assert "no targets" in capsys.readouterr().out


# -- top --fleet -------------------------------------------------------------
FLEET_DOC = {
    "fleet": {"targets": 3, "up": 2, "quarantined": 1, "tok_s": 85.0,
              "active_slots": 4.0, "max_slots": 8.0, "queued": 2.0},
    "targets": [
        {"target": "replica0:8000", "url": "http://replica0:8000", "up": True,
         "staleness_s": 1.2, "tok_s": 42.5, "active_slots": 2.0,
         "max_slots": 4.0, "queued": 1.0, "occupancy": 1.71, "slo": "ok"},
        {"target": "replica1:8000", "url": "http://replica1:8000", "up": True,
         "staleness_s": 0.8, "tok_s": 42.5, "active_slots": 2.0,
         "max_slots": 4.0, "queued": 1.0, "occupancy": 0.4, "slo": "warn"},
        {"target": "replica2:8000", "url": "http://replica2:8000",
         "up": False, "quarantined": True, "staleness_s": 93.0,
         "tok_s": None, "slo": None},
    ],
    "slo": {"ready": False, "status": "breach", "slos": [
        {"name": "ttft_p99", "status": "breach",
         "burn_short": 8.0, "burn_long": 7.0},
    ]},
    "notes": ["histogram bucket-edge mismatch for ttft_seconds"],
    "hpa": {"metrics": []},
}

FLEET_EVENTS = {"events": [
    {"time": 1754500000.0, "seq": 4, "level": "error", "subsystem": "engine",
     "event": "request_failed", "target": "replica1:8000",
     "reason": "decode failed"},
]}


class CollectorStubHandler(BaseHTTPRequestHandler):
    omit = ()

    def do_GET(self):  # noqa: N802
        path = self.path.split("?")[0]
        payloads = {
            "/debug/fleet": json.dumps(FLEET_DOC).encode(),
            "/debug/events": json.dumps(FLEET_EVENTS).encode(),
            "/metrics": b"collector_fleet_targets 3\n",
            "/debug/trace": json.dumps(
                {"traceEvents": [], "stitched": True}).encode(),
        }
        if path in self.omit or path not in payloads:
            self.send_error(404)
            return
        body = payloads[path]
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture
def collector_url():
    server, url = _start(CollectorStubHandler)
    try:
        yield url
    finally:
        server.shutdown()
        server.server_close()


def test_top_fleet_renders_matrix(collector_url, capsys):
    rc = main(["top", "--fleet", "--url", collector_url, "--iterations", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "top — fleet" in out
    assert "FLEET  2/3 up  (1 quarantined)" in out
    assert "replica0:8000" in out and "replica2:8000" in out
    assert "QUAR" in out  # quarantined row flagged
    assert "42.5" in out and "1.71" in out
    assert "FLEET SLO" in out and "breach" in out
    assert "!! FLEET NOT READY" in out
    assert "note: histogram bucket-edge mismatch" in out
    assert "[replica1:8000]" in out  # merged events carry their origin
    assert "reason=decode failed" in out
    assert "seq=" not in out  # envelope keys pruned from event lines


def test_top_fleet_survives_missing_events(collector_url, capsys, monkeypatch):
    monkeypatch.setattr(CollectorStubHandler, "omit", ("/debug/events",))
    assert main(["top", "--fleet", "--url", collector_url,
                 "--iterations", "1"]) == 0
    out = capsys.readouterr().out
    assert "FLEET  2/3 up" in out
    assert "RECENT EVENTS" not in out


def test_top_fleet_no_collector_fails(capsys):
    rc = main(["top", "--fleet", "--url", "http://127.0.0.1:9",
               "--iterations", "1"])
    assert rc == 1
    assert "no collector endpoint" in capsys.readouterr().out


# -- debug bundle --fleet ----------------------------------------------------
def test_debug_bundle_explicit_targets_with_partial_failure(
        replica_urls, tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(
        ReplicaHandler, "omit", ("/debug/requests", "/debug/spans"))
    out = str(tmp_path / "fleet.tar.gz")
    rc = main(["debug", "bundle", "--fleet", "--out", out, "--seconds", "0"]
              + [f for u in replica_urls for f in ("--target", u)])
    assert rc == 0
    with tarfile.open(out, "r:gz") as tar:
        names = sorted(tar.getnames())
        manifest = json.load(tar.extractfile("bundle/manifest.json"))
        assert manifest["fleet"] is True
        assert len(manifest["targets"]) == 2
        for safe, entry in manifest["targets"].items():
            assert f"bundle/{safe}/metrics.txt" in names
            assert f"bundle/{safe}/healthz.json" in names
            assert f"bundle/{safe}/events.json" in names
            # the 404ed members are recorded, not fatal
            assert set(entry["errors"]) == {"requests.json", "spans.json"}
            assert f"bundle/{safe}/requests.json" not in names
        metrics = tar.extractfile(
            "bundle/" + sorted(manifest["targets"])[0] + "/metrics.txt"
        ).read().decode()
        assert "engine_tokens_per_sec_10s" in metrics
    assert "2 target(s)" in capsys.readouterr().out


def test_debug_bundle_fleet_discovers_targets_from_collector(
        replica_urls, tmp_path):
    doc = dict(FLEET_DOC)
    doc["targets"] = [
        {"target": f"replica{i}", "url": u, "up": True}
        for i, u in enumerate(replica_urls)
    ]

    class DiscoveryHandler(CollectorStubHandler):
        def do_GET(self):  # noqa: N802
            path = self.path.split("?")[0]
            if path == "/debug/fleet":
                body = json.dumps(doc).encode()
            elif path == "/metrics":
                body = b"collector_fleet_targets 2\n"
            elif path == "/debug/trace":
                body = json.dumps({"traceEvents": []}).encode()
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server, url = _start(DiscoveryHandler)
    try:
        out = str(tmp_path / "fleet.tar.gz")
        rc = main(["debug", "bundle", "--fleet", "--url", url,
                   "--out", out, "--seconds", "0"])
        assert rc == 0
        with tarfile.open(out, "r:gz") as tar:
            names = sorted(tar.getnames())
            # collector-level evidence rides along
            assert "bundle/fleet.json" in names
            assert "bundle/fleet_metrics.txt" in names
            assert "bundle/fleet_trace.json" in names
            assert "bundle/replica0/metrics.txt" in names
            assert "bundle/replica1/metrics.txt" in names
            fleet = json.load(tar.extractfile("bundle/fleet.json"))
            assert len(fleet["targets"]) == 2
    finally:
        server.shutdown()
        server.server_close()


def test_debug_bundle_fleet_no_targets_fails(capsys):
    rc = main(["debug", "bundle", "--fleet", "--url", "http://127.0.0.1:9",
               "--out", "/tmp/never.tar.gz", "--seconds", "0"])
    assert rc == 1
    assert "no collector endpoint" in capsys.readouterr().out
