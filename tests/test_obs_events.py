"""Structured-event pipeline tests (obs/events.py — ISSUE 9).

Unit-level: the catalog lint, the one-branch no-sink fast path, sink
fan-out and drop accounting, trace stamping, FlightRecorder bounds and
dump ordering, JsonlSink rotation, the ``DEVSPACE_ENGINE_EVENTS``
escape hatch, and the rebuilt utils/log.py FileLogger riding the event
pipeline while keeping its historical ``{"time","level","msg"}`` line
shape.

Chaos-marked (registered in scripts/chaos_check.py): a poisoned
dispatch window must dump flight-recorder events carrying the failing
request's trace id, and a supervisor restart ladder under an injected
fault must land its events on the session trace captured at start().
"""

import json
import os

import pytest

from devspace_tpu.obs import events as obs_events
from devspace_tpu.obs.events import (
    EVENT_CATALOG,
    Event,
    EventBus,
    FlightRecorder,
    JsonlSink,
    events_enabled,
    lint_catalog,
    make_event,
)
from devspace_tpu.obs.tracing import get_tracer


class ListSink:
    def __init__(self):
        self.events = []

    def record(self, event):
        self.events.append(event)


class RaisingSink:
    def record(self, event):
        raise RuntimeError("sink exploded")


@pytest.fixture
def recorder():
    """FlightRecorder attached to the process-default bus for the
    duration of one test."""
    rec = obs_events.add_sink(FlightRecorder())
    try:
        yield rec
    finally:
        obs_events.remove_sink(rec)


# -- catalog ----------------------------------------------------------------
def test_catalog_lints_clean():
    assert lint_catalog() == []


def test_catalog_covers_instrumented_names():
    """The names the instrumentation sites actually emit must all be in
    the closed catalog (a grep-level contract; the lint enforces shape,
    this pins membership of the load-bearing ones)."""
    names = {(s, n) for s, n, _ in EVENT_CATALOG}
    for pair in [
        ("engine", "admit"),
        ("engine", "preempt"),
        ("engine", "poisoned_window"),
        ("engine", "fail_outstanding"),
        ("engine", "request_failed"),
        ("dispatch", "depth_change"),
        ("dispatch", "window_abandoned"),
        ("kv_tier", "spill"),
        ("kv_tier", "restore"),
        ("kv_tier", "restore_fallback"),
        ("kv_tier", "corrupt_drop"),
        ("sync", "worker_quarantined"),
        ("sync", "worker_revived"),
        ("supervisor", "restarting"),
        ("supervisor", "degraded"),
        ("resilience", "circuit_open"),
        ("resilience", "retries_exhausted"),
        ("slo", "breach"),
        ("slo", "recovered"),
        ("cli", "log"),
    ]:
        assert pair in names, f"{pair} missing from EVENT_CATALOG"


# -- bus --------------------------------------------------------------------
def test_emit_without_sinks_is_a_noop():
    bus = EventBus()
    before = bus.emitted
    assert bus.emit("engine", "admit", slot=1) is None
    assert bus.emitted == before == 0


def test_emit_fans_out_and_counts():
    bus = EventBus(clock=lambda: 42.0)
    a, b = ListSink(), ListSink()
    bus.add_sink(a)
    assert bus.add_sink(b) is b  # add_sink returns the sink
    ev = bus.emit("engine", "admit", level="info", slot=3)
    assert bus.emitted == 1 and bus.dropped == 0
    assert a.events == [ev] and b.events == [ev]
    assert ev.ts == 42.0
    assert ev.subsystem == "engine" and ev.name == "admit"
    assert ev.attrs == {"slot": 3}
    bus.remove_sink(a)
    bus.emit("engine", "admit", slot=4)
    assert len(a.events) == 1 and len(b.events) == 2


def test_raising_sink_is_counted_not_fatal():
    bus = EventBus()
    good = ListSink()
    bus.add_sink(RaisingSink())
    bus.add_sink(good)
    bus.emit("engine", "admit")
    assert bus.dropped == 1
    assert len(good.events) == 1  # the raising sink didn't stop fan-out


def test_to_dict_envelope_and_reserved_keys():
    ev = Event(
        1.5, "warn", "engine", "preempt",
        attrs={"slot": 2, "time": "shadowed", "level": "shadowed"},
        trace_id="t" * 32, span_id="s" * 16,
    )
    d = ev.to_dict()
    assert d["time"] == 1.5 and d["level"] == "warn"
    assert d["subsystem"] == "engine" and d["event"] == "preempt"
    assert d["trace_id"] == "t" * 32 and d["span_id"] == "s" * 16
    assert d["slot"] == 2
    # attrs may not overwrite the envelope
    assert "shadowed" not in (d["time"], d["level"])


def test_emit_stamps_current_tracer_context():
    bus = EventBus()
    sink = ListSink()
    bus.add_sink(sink)
    with get_tracer().span("unit-test-op") as sp:
        bus.emit("engine", "admit")
        ev_explicit = bus.emit(
            "engine", "admit", trace_id="x" * 32, span_id="y" * 16
        )
    outside = bus.emit("engine", "admit")
    assert sink.events[0].trace_id == sp.trace_id
    assert sink.events[0].span_id == sp.span_id
    assert ev_explicit.trace_id == "x" * 32  # explicit id beats the stack
    assert outside.trace_id is None


def test_make_event_stamps_context_like_emit():
    with get_tracer().span("unit-test-op") as sp:
        ev = make_event("cli", "log", level="info", attrs={"msg": "hi"})
    assert ev.trace_id == sp.trace_id
    assert ev.attrs["msg"] == "hi"


# -- flight recorder --------------------------------------------------------
def test_flight_recorder_bounds_and_dump_order():
    rec = FlightRecorder(per_subsystem=4)
    for i in range(10):
        rec.record(Event(float(i), "info", "engine", "admit", {"i": i}))
    rec.record(Event(3.5, "info", "sync", "worker_revived"))
    engine = rec.dump("engine")
    assert [e.attrs["i"] for e in engine] == [6, 7, 8, 9]  # ring of 4
    merged = rec.dump()
    assert [e.ts for e in merged] == sorted(e.ts for e in merged)
    assert [e.ts for e in rec.dump(limit=2)] == [8.0, 9.0]  # newest 2
    assert rec.subsystems() == ["engine", "sync"]
    dicts = rec.dump_dicts("sync")
    assert dicts[0]["event"] == "worker_revived"
    rec.clear()
    assert rec.dump() == []


def test_flight_recorder_equal_timestamps_order_by_seq():
    """Pin: the merged dump is ordered by (ts, seq), so events sharing a
    wall-clock timestamp keep emission order instead of flapping with
    ring-interleave — the fleet collector relies on this to stitch
    deterministic cross-worker timelines."""
    rec = FlightRecorder(per_subsystem=8)
    # interleave subsystems at one frozen timestamp
    e1 = Event(10.0, "info", "engine", "admit", {"n": 1})
    e2 = Event(10.0, "info", "sync", "worker_revived", {"n": 2})
    e3 = Event(10.0, "info", "engine", "admit", {"n": 3})
    for ev in (e2, e3, e1):  # record order deliberately shuffled
        rec.record(ev)
    assert e1.seq < e2.seq < e3.seq  # process-wide monotone counter
    merged = rec.dump()
    assert [e.attrs["n"] for e in merged] == [1, 2, 3]
    # explicit seq round-trips through the dict envelope
    d = e2.to_dict()
    assert d["seq"] == e2.seq
    assert Event(10.0, "info", "sync", "worker_revived", seq=77).seq == 77


# -- jsonl sink -------------------------------------------------------------
def test_jsonl_sink_writes_and_rotates(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    sink = JsonlSink(path)
    sink.record(Event(1.0, "info", "engine", "admit", {"slot": 0}))
    sink.close()
    assert sink.closed
    sink.record(Event(2.0, "info", "engine", "admit"))  # no-op after close
    lines = [json.loads(line) for line in open(path, encoding="utf-8")]
    assert len(lines) == 1
    assert isinstance(lines[0].pop("seq"), int)
    assert lines[0] == {
        "time": 1.0, "level": "info", "subsystem": "engine",
        "event": "admit", "slot": 0,
    }
    # oversized file rotates to .old on open
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("x" * 100)
    JsonlSink(path, max_bytes=10).close()
    assert os.path.getsize(path) == 0
    assert os.path.getsize(path + ".old") == 100


# -- escape hatch -----------------------------------------------------------
def test_events_enabled_resolution(monkeypatch):
    monkeypatch.delenv("DEVSPACE_ENGINE_EVENTS", raising=False)
    assert events_enabled() is True
    assert events_enabled(False) is False
    for off in ("off", "0", "false", "NO"):
        monkeypatch.setenv("DEVSPACE_ENGINE_EVENTS", off)
        assert events_enabled() is False
        assert events_enabled(True) is True
    monkeypatch.setenv("DEVSPACE_ENGINE_EVENTS", "on")
    assert events_enabled() is True


# -- the rebuilt FileLogger rides the pipeline ------------------------------
def test_file_logger_lines_are_events_with_legacy_shape(tmp_path, recorder):
    from devspace_tpu.utils.log import FileLogger

    path = str(tmp_path / "logs" / "sync.log")
    fl = FileLogger(path)
    with get_tracer().span("sync-op") as sp:
        fl.warn("upload failed for %s", "a.py")
    fl.close()
    assert fl.closed
    (line,) = [json.loads(ln) for ln in open(path, encoding="utf-8")]
    # the historical contract: scrapers key on these three
    assert line["level"] == "warn"
    assert line["msg"] == "upload failed for a.py"
    assert isinstance(line["time"], float)
    # the new envelope: trace-correlated, cataloged
    assert line["subsystem"] == "cli" and line["event"] == "log"
    assert line["trace_id"] == sp.trace_id
    assert line["logger"] == "sync"
    # and the line was also published on the process bus
    cli = recorder.dump("cli")
    assert cli and cli[-1].attrs["msg"] == "upload failed for a.py"


# -- chaos: poisoned window dumps the flight recorder -----------------------
@pytest.mark.chaos
def test_chaos_poisoned_window_events_carry_request_trace(
    recorder, monkeypatch
):
    """Counter-based fault on the second readback (the
    test_engine_dispatch idiom — at that point the next chunk is still
    in flight, so the window is abandoned non-empty): the flight
    recorder must hold the poisoned_window -> fail_outstanding ->
    request_failed ladder, and every request_failed event must carry
    the trace id stamped on the request at submit — the pivot an
    operator follows from the event log into /debug/requests."""
    import jax

    import devspace_tpu.inference.dispatch as dispatch_mod
    from devspace_tpu.inference import InferenceEngine
    from devspace_tpu.models import transformer as tfm

    cfg = tfm.TINY
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    engine = InferenceEngine(
        params, cfg, max_slots=2, max_len=64, dispatch_depth=2
    )
    real = jax.device_get
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected readback fault")
        return real(x)

    monkeypatch.setattr(dispatch_mod.jax, "device_get", flaky)
    h1 = engine.submit([5, 1, 4], 24)
    h2 = engine.submit([2, 9], 24)
    engine.start()
    try:
        with pytest.raises(RuntimeError, match="decode failed"):
            h1.result(timeout=300)
        with pytest.raises(RuntimeError, match="decode failed"):
            h2.result(timeout=300)
    finally:
        engine.stop()
    names = [e.name for e in recorder.dump("engine")]
    assert "poisoned_window" in names
    assert "fail_outstanding" in names
    failed = [e for e in recorder.dump("engine") if e.name == "request_failed"]
    assert len(failed) >= 2
    want = {h1._obs_trace.trace_id, h2._obs_trace.trace_id}
    got = {e.trace_id for e in failed}
    assert want <= got, f"request_failed events missing trace ids: {want - got}"
    for e in failed:
        assert e.level == "error"
        assert e.attrs.get("reason")
    # the dispatcher's in-flight depth changes were journaled too (the
    # non-empty-window abandon case is pinned deterministically in
    # test_abandon_nonempty_window_emits below — on a fast device the
    # window is usually drained by the time the failure lands, but when
    # the fault DOES catch a chunk in flight the ring also holds a
    # window_abandoned event, which carries no "direction")
    dispatch = recorder.dump("dispatch")
    depth_changes = [e for e in dispatch if e.name == "depth_change"]
    assert depth_changes
    assert {e.attrs["direction"] for e in depth_changes} >= {"up", "down"}


def test_abandon_nonempty_window_emits(recorder):
    """``abandon()`` with entries still in flight must journal how many
    windows it dropped (and stay silent on an empty window — the common
    stop() path)."""
    import jax

    from devspace_tpu.inference import InferenceEngine
    from devspace_tpu.models import transformer as tfm

    cfg = tfm.TINY
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    engine = InferenceEngine(params, cfg, max_slots=1, max_len=32)
    d = engine._dispatcher
    d.abandon()  # empty window: no event
    assert recorder.dump("dispatch") == []
    d.window.append(object())  # abandon never touches the entries
    d.window.append(object())
    d.abandon()
    (ev,) = recorder.dump("dispatch")
    assert ev.name == "window_abandoned"
    assert ev.level == "warn"
    assert ev.attrs["dropped"] == 2
    assert not d.window


# -- chaos: supervisor restart ladder lands on the session trace ------------
@pytest.mark.chaos
def test_chaos_supervisor_restart_events_on_session_trace(recorder):
    """A service death with a factory that keeps failing must emit
    died -> restarting -> degraded stamped with the trace that was
    current when start() ran (the monitor thread has no tracer stack of
    its own — the supervisor must carry the session context across)."""
    import time

    from devspace_tpu.resilience import RetryPolicy, SessionSupervisor

    class FakeService:
        def __init__(self):
            self._alive = True
            self.error = None

        def alive(self):
            return self._alive

        def stop(self):
            self._alive = False

        def die(self, error):
            self.error = error
            self._alive = False

    made = []

    def factory():
        if made:
            raise RuntimeError("restart refused")
        s = FakeService()
        made.append(s)
        return s

    sup = SessionSupervisor(
        restart="on-failure", poll_interval=0.01,
        default_policy=RetryPolicy(
            max_attempts=2, base_delay=0.01, max_delay=0.02
        ),
    )
    sup.add("ports", factory, failure=lambda s: s.error, critical=False)
    with get_tracer().span("dev-session") as sp:
        sup.start()
    try:
        made[0].die("listener died")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if any(e.name == "degraded" for e in recorder.dump("supervisor")):
                break
            time.sleep(0.01)
    finally:
        sup.stop()
    events = recorder.dump("supervisor")
    names = [e.name for e in events]
    for kind in ("started", "died", "restarting", "degraded"):
        assert kind in names, f"missing supervisor event {kind}: {names}"
    for e in events:
        if e.name in ("died", "restarting", "degraded"):
            assert e.trace_id == sp.trace_id, (
                f"{e.name} not on the session trace"
            )
    died = next(e for e in events if e.name == "died")
    assert died.level == "error"
    assert died.attrs["service"] == "ports"
    assert "listener died" in died.attrs["detail"]
