"""Cloud provider tests against an in-process fake GraphQL control plane.

Reference test strategy (SURVEY §4): stand in for the remote side with a
local process. Here the stand-in is a stdlib HTTP server speaking the same
GraphQL contract as the provider client (manager_* operations).
"""

from __future__ import annotations

import base64
import http.server
import json
import threading
import time

import pytest

from devspace_tpu.cloud.config import CloudProvider, ProviderRegistry
from devspace_tpu.cloud.configure import (
    bind_space,
    configure,
    kube_context_name,
    remove_kube_context,
)
from devspace_tpu.cloud.provider import (
    CloudError,
    Provider,
    parse_token_claims,
    token_valid,
)
from devspace_tpu.config.generated import GeneratedConfig
from devspace_tpu.kube.kubeconfig import KubeConfig

VALID_KEY = "test-access-key"


def make_jwt(exp_offset: float = 3600.0) -> str:
    header = base64.urlsafe_b64encode(json.dumps({"alg": "none"}).encode()).decode()
    claims = base64.urlsafe_b64encode(
        json.dumps({"exp": time.time() + exp_offset, "sub": "tester"}).encode()
    ).decode()
    return f"{header.rstrip('=')}.{claims.rstrip('=')}.sig"


class FakeCloud(http.server.BaseHTTPRequestHandler):
    """GraphQL endpoint with an in-memory space table."""

    spaces: dict[int, dict] = {}
    next_id = 1

    def do_POST(self):
        if self.path != "/graphql":
            self.send_response(404)
            self.end_headers()
            return
        length = int(self.headers.get("Content-Length", 0))
        req = json.loads(self.rfile.read(length))
        query = req.get("query", "")
        variables = req.get("variables", {})
        cls = type(self)

        def reply(payload, status=200):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        if "manager_getToken" in query:
            if variables.get("key") != VALID_KEY:
                reply({"errors": [{"message": "invalid access key"}]})
                return
            reply({"data": {"manager_getToken": make_jwt()}})
            return

        # everything else requires a bearer token
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("Bearer ") or not token_valid(auth[7:], slack=0):
            reply({"errors": [{"message": "unauthorized"}]})
            return

        if "manager_createSpace" in query:
            sid = cls.next_id
            cls.next_id += 1
            space = {
                "id": sid,
                "name": variables["name"],
                "namespace": f"space-{variables['name']}",
                "created": "2026-01-01T00:00:00Z",
                "domain": f"{variables['name']}.spaces.test",
            }
            cls.spaces[sid] = space
            reply({"data": {"manager_createSpace": space}})
        elif "manager_spaces" in query:
            reply({"data": {"manager_spaces": list(cls.spaces.values())}})
        elif "manager_deleteSpace" in query:
            cls.spaces.pop(variables["id"], None)
            reply({"data": {"manager_deleteSpace": True}})
        elif "manager_serviceAccount" in query:
            space = cls.spaces.get(variables["id"])
            if not space:
                reply({"errors": [{"message": "space not found"}]})
                return
            reply(
                {
                    "data": {
                        "manager_serviceAccount": {
                            "namespace": space["namespace"],
                            "server": "https://1.2.3.4:6443",
                            "caCert": base64.b64encode(b"FAKE-CA").decode(),
                            "token": make_jwt(),
                        }
                    }
                }
            )
        elif "manager_registryAuth" in query:
            reply(
                {
                    "data": {
                        "manager_registryAuth": {
                            "registry": "registry.test",
                            "username": "sa",
                            "password": "pw",
                        }
                    }
                }
            )
        else:
            reply({"errors": [{"message": f"unknown operation: {query[:60]}"}]})

    def log_message(self, *args):
        pass


@pytest.fixture
def cloud_env(tmp_path, monkeypatch):
    FakeCloud.spaces = {}
    FakeCloud.next_id = 1
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeCloud)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    host = f"http://127.0.0.1:{server.server_address[1]}"
    clouds = tmp_path / "clouds.yaml"
    kube = tmp_path / "kubeconfig"
    monkeypatch.setenv("DEVSPACE_CLOUD_CONFIG", str(clouds))
    monkeypatch.setenv("KUBECONFIG", str(kube))
    registry = ProviderRegistry.load()
    registry.providers["test"] = CloudProvider(name="test", host=host)
    registry.default = "test"
    registry.save()
    yield {"host": host, "registry_path": str(clouds), "kube_path": str(kube),
           "tmp": tmp_path}
    server.shutdown()
    server.server_close()


def _provider(key: str | None = VALID_KEY) -> Provider:
    registry = ProviderRegistry.load()
    entry = registry.get("test")
    entry.key = key
    return Provider(entry, registry)


def test_jwt_parse_and_validity():
    token = make_jwt(3600)
    claims = parse_token_claims(token)
    assert claims["sub"] == "tester"
    assert token_valid(token)
    assert not token_valid(make_jwt(-10))
    assert not token_valid(make_jwt(60))  # inside the 300s renewal slack
    assert not token_valid("garbage")
    assert not token_valid(None)


def test_registry_roundtrip_and_default_provider(cloud_env):
    registry = ProviderRegistry.load()
    assert "test" in registry.providers
    # the implicit default cloud entry always exists
    from devspace_tpu.cloud.config import DEFAULT_PROVIDER_NAME

    assert DEFAULT_PROVIDER_NAME in registry.providers
    with pytest.raises(KeyError):
        registry.get("nope")


def test_login_with_key_and_token_refresh(cloud_env):
    provider = _provider(key=None)
    provider.login(key=VALID_KEY)
    assert provider.entry.token is not None
    # persisted
    saved = ProviderRegistry.load().get("test")
    assert saved.key == VALID_KEY
    assert saved.token == provider.entry.token

    # expired cached token is re-minted transparently
    provider.entry.token = make_jwt(-10)
    token = provider.token()
    assert token_valid(token)


def test_login_bad_key_fails(cloud_env):
    provider = _provider(key=None)
    with pytest.raises(CloudError, match="invalid access key"):
        provider.login(key="wrong")


def test_not_logged_in_error(cloud_env):
    provider = _provider(key=None)
    provider.entry.token = None
    with pytest.raises(CloudError, match="not logged in"):
        provider.token()


def test_space_crud(cloud_env):
    provider = _provider()
    space = provider.create_space("dev1")
    assert space.space_id == 1
    assert space.namespace == "space-dev1"
    spaces = provider.get_spaces()
    assert [s.name for s in spaces] == ["dev1"]
    assert provider.get_space("dev1").space_id == 1
    assert provider.get_space("1").space_id == 1
    with pytest.raises(CloudError, match="not found"):
        provider.get_space("ghost")
    provider.delete_space(space.space_id)
    assert provider.get_spaces() == []


def test_bind_space_materializes_kubeconfig(cloud_env):
    provider = _provider()
    space = provider.create_space("dev2")
    generated = GeneratedConfig(str(cloud_env["tmp"]))
    context = bind_space(provider, space, generated)
    assert context == kube_context_name("dev2") == "devspace-dev2"

    kc = KubeConfig.load(cloud_env["kube_path"])
    assert kc.current_context == "devspace-dev2"
    cluster, user, ctx = kc.resolve()
    assert cluster.server == "https://1.2.3.4:6443"
    assert cluster.ca_data == b"FAKE-CA"
    assert token_valid(user.token, slack=0)
    assert ctx.namespace == "space-dev2"

    # binding recorded in the generated cache (and survives reload)
    reloaded = GeneratedConfig.load(str(cloud_env["tmp"]))
    assert reloaded.space is not None
    assert reloaded.space.name == "dev2"
    assert reloaded.space.provider_name == "test"

    remove_kube_context("dev2", cloud_env["kube_path"])
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert "devspace-dev2" not in kc.contexts
    assert kc.current_context == ""


def test_configure_refreshes_stale_space_token(cloud_env):
    provider = _provider()
    space = provider.create_space("dev3")
    generated = GeneratedConfig(str(cloud_env["tmp"]))
    bind_space(provider, space, generated)

    # stale the cached space token; configure() must refresh it
    generated.space.token = make_jwt(-10)
    context = configure(generated)
    assert context == "devspace-dev3"
    assert token_valid(generated.space.token, slack=0)

    # fresh token short-circuits (no API call needed): corrupt the host to
    # prove configure doesn't hit the network when the token is valid
    registry = ProviderRegistry.load()
    registry.providers["test"].host = "http://127.0.0.1:1"
    registry.save()
    assert configure(generated) == "devspace-dev3"


def test_configure_no_space_is_noop(cloud_env):
    generated = GeneratedConfig(str(cloud_env["tmp"] / "other"))
    assert configure(generated) is None


def test_configure_unreachable_provider_uses_cache(cloud_env):
    provider = _provider()
    space = provider.create_space("dev4")
    generated = GeneratedConfig(str(cloud_env["tmp"]))
    bind_space(provider, space, generated)
    generated.space.token = make_jwt(-10)
    registry = ProviderRegistry.load()
    registry.providers["test"].host = "http://127.0.0.1:1"
    registry.save()
    # degraded: warns and returns the cached context rather than dying
    assert configure(generated) == "devspace-dev4"


def test_registry_auth(cloud_env):
    provider = _provider()
    auth = provider.get_registry_auth()
    assert auth == {"registry": "registry.test", "username": "sa", "password": "pw"}


def test_cli_use_registry_and_remove_context(cloud_env, tmp_path, monkeypatch):
    """use registry writes docker auth; remove context [--all] drops
    devspace-created kube contexts (reference: cmd/use/registry.go,
    cmd/remove/context.go)."""
    from devspace_tpu.cli.main import main

    proj = tmp_path / "proj2"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    docker_dir = tmp_path / "dockercfg"
    monkeypatch.setenv("DOCKER_CONFIG", str(docker_dir))

    assert main(["login", "--key", VALID_KEY, "--provider", "test"]) == 0
    assert main(["use", "registry", "--provider", "test"]) == 0
    cfg = json.loads((docker_dir / "config.json").read_text())
    auth = base64.b64decode(cfg["auths"]["registry.test"]["auth"]).decode()
    assert auth == "sa:pw"
    # explicit registry name wins over the provider's default
    assert main(["use", "registry", "alt.registry.test", "--provider", "test"]) == 0
    cfg = json.loads((docker_dir / "config.json").read_text())
    assert "alt.registry.test" in cfg["auths"]

    assert main(["create", "space", "ctx1", "--provider", "test"]) == 0
    assert main(["create", "space", "ctx2", "--provider", "test"]) == 0
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert "devspace-ctx1" in kc.contexts and "devspace-ctx2" in kc.contexts
    assert main(["remove", "context", "ctx1"]) == 0
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert "devspace-ctx1" not in kc.contexts
    assert "devspace-ctx2" in kc.contexts
    # --all is purely local (kubeconfig prefix scan): no provider needed
    assert main(["remove", "context", "--all"]) == 0
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert "devspace-ctx2" not in kc.contexts
    assert main(["remove", "context"]) == 1  # no name, no --all


def test_cli_cloud_flow(cloud_env, tmp_path, monkeypatch):
    """login --key -> create space -> list spaces -> remove space via CLI."""
    from devspace_tpu.cli.main import main

    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")

    assert main(["login", "--key", VALID_KEY, "--provider", "test"]) == 0
    assert main(["login", "--key", "wrong", "--provider", "test"]) == 1
    assert main(["create", "space", "clidev", "--provider", "test"]) == 0
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert kc.current_context == "devspace-clidev"
    assert main(["list", "spaces", "--provider", "test"]) == 0
    assert main(["list", "providers"]) == 0
    assert main(["use", "space", "clidev", "--provider", "test"]) == 0
    assert main(["remove", "space", "clidev", "--provider", "test"]) == 0
    assert FakeCloud.spaces == {}
    kc = KubeConfig.load(cloud_env["kube_path"])
    assert "devspace-clidev" not in kc.contexts
    # provider management
    assert main(["add", "provider", "alt", "--host", "http://127.0.0.1:9"]) == 0
    assert main(["remove", "provider", "alt"]) == 0
    assert main(["remove", "provider", "ghost"]) == 1


def test_cli_unknown_provider_is_clean_error(cloud_env, tmp_path, monkeypatch):
    from devspace_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    assert main(["login", "--provider", "nope", "--key", "x"]) == 1
    assert main(["list", "spaces", "--provider", "nope"]) == 1


def test_add_provider_preserves_credentials(cloud_env):
    from devspace_tpu.cli.main import main

    provider = _provider()
    provider.login(key=VALID_KEY)
    assert main(["add", "provider", "test", "--host", provider.entry.host]) == 0
    saved = ProviderRegistry.load().get("test")
    assert saved.key == VALID_KEY


def test_context_namespace_uses_bound_space(cloud_env, tmp_path, monkeypatch):
    """With a bound space and no explicit namespace, commands must target the
    space's service-account namespace (it is namespace-scoped)."""
    import argparse

    from devspace_tpu.cli.context import Context

    provider = _provider()
    space = provider.create_space("nsdev")
    proj = tmp_path / "nsproj"
    (proj / ".devspace").mkdir(parents=True)
    (proj / ".devspace" / "config.yaml").write_text("version: tpu/v1\n")
    monkeypatch.chdir(proj)
    generated = GeneratedConfig(str(proj))
    bind_space(provider, space, generated)

    args = argparse.Namespace(namespace=None, kube_context=None, config=None)
    ctx = Context(args, require_config=False)
    assert ctx.namespace == "space-nsdev"
    # explicit flag still wins
    args = argparse.Namespace(namespace="override", kube_context=None, config=None)
    assert Context(args, require_config=False).namespace == "override"
