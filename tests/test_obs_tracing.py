"""Distributed tracing + timeline profiler (ISSUE 8).

Pins the tentpole's contracts:

- golden span parentage for a full request lifecycle (deterministic
  clock, literal derived span ids — blake2b is stable, so these hex
  strings must never drift);
- W3C traceparent round-trip under fuzz plus strict rejection of
  malformed headers;
- tracer context mechanics (nesting, cross-thread attach, detached
  roots, ring bounds);
- timeline lane assignment in the Chrome export;
- two chaos-marked propagation tests (scripts/chaos_check.py): span
  context survives a sync retry after a shell revive, and a worker
  dropped mid-upload closes its span with ``outcome=failed``.
"""

import random
import time

import pytest

from devspace_tpu.obs.request_trace import ServingTelemetry
from devspace_tpu.obs.tracing import (
    SpanContext,
    TimelineRecorder,
    Tracer,
    derive_span_id,
    device_decode_track,
    get_tracer,
    lint_tracks,
    new_span_id,
    new_trace_id,
)

TRACE_ID = "ab" * 16
PARENT_SPAN = "cd" * 8
TRACEPARENT = f"00-{TRACE_ID}-{PARENT_SPAN}-01"

# golden derived ids: derive_span_id is blake2b-8 over "parent/name" —
# a pure function, so the lifecycle's ids are literal constants
ROOT_SID = "77390ce345112f59"  # derive_span_id(TRACE_ID, "request-1")
QUEUE_SID = "ce9b8d1228398faf"
PREFILL_SID = "5300739846f8314b"
DECODE_SID = "9c117bdf9b1eca16"


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeReq:
    def __init__(self, traceparent=None):
        self.prompt_ids = [1, 2, 3]
        self.max_new_tokens = 4
        self.traceparent = traceparent


# -- golden span parentage ---------------------------------------------------
def test_golden_request_lifecycle_span_parentage():
    """enqueue->admit->prefill->3 tokens->finish under a hand-ticked
    clock: every span id, parent link, lane and duration is asserted
    literally."""
    clock = FakeClock()
    tel = ServingTelemetry(clock=clock)
    req = FakeReq(traceparent=TRACEPARENT)
    tel.on_submit(req)
    trace = req._obs_trace
    assert trace.trace_id == TRACE_ID  # joined the caller's trace
    assert trace.parent_span_id == PARENT_SPAN
    assert trace.span_id == ROOT_SID
    assert trace.span_id == derive_span_id(TRACE_ID, "request-1")

    clock.t = 101.0
    tel.on_admit(req)
    clock.t = 102.0
    tel.on_prefill_done(req)
    for t in (103.0, 104.0, 105.0):
        clock.t = t
        tel.on_emit(req)
    clock.t = 106.0
    tel.on_finish(req, "completed")

    spans = {s["name"]: s for s in trace.to_spans()}
    assert set(spans) == {"queue_wait", "prefill", "decode", "request-1"}

    root = spans["request-1"]
    assert root["span_id"] == ROOT_SID
    assert root["parent_span_id"] == PARENT_SPAN
    assert root["trace_id"] == TRACE_ID
    assert root["duration_s"] == pytest.approx(6.0)
    assert root["outcome"] == "completed" and root["ok"] is True

    golden = {
        "queue_wait": (QUEUE_SID, 1.0),
        "prefill": (PREFILL_SID, 1.0),
        "decode": (DECODE_SID, 2.0),
    }
    for name, (sid, dur) in golden.items():
        sp = spans[name]
        assert sp["span_id"] == sid
        assert sp["span_id"] == derive_span_id(ROOT_SID, name)
        assert sp["parent_span_id"] == ROOT_SID
        assert sp["trace_id"] == TRACE_ID
        assert sp["duration_s"] == pytest.approx(dur)
        # lane assignment: every request-lifecycle span renders on the
        # "serving" lane of the shared Chrome-trace writer
        assert sp["thread"] == "serving"
    assert root["thread"] == "serving"
    assert spans["decode"]["tokens"] == 3

    row = trace.to_dict()
    assert row["trace_id"] == TRACE_ID  # /debug/requests cross-link
    assert row["ttft_s"] == pytest.approx(3.0)


def test_request_without_traceparent_roots_fresh_trace():
    tel = ServingTelemetry(clock=FakeClock())
    req = FakeReq()
    tel.on_submit(req)
    trace = req._obs_trace
    assert trace.parent_span_id is None
    assert len(trace.trace_id) == 32 and int(trace.trace_id, 16)
    assert trace.span_id == derive_span_id(trace.trace_id, "request-1")


def test_malformed_inbound_traceparent_is_dropped_not_joined():
    tel = ServingTelemetry(clock=FakeClock())
    req = FakeReq(traceparent=f"00-{'0' * 32}-{PARENT_SPAN}-01")
    tel.on_submit(req)
    assert req._obs_trace.trace_id != "0" * 32
    assert req._obs_trace.parent_span_id is None


# -- traceparent round-trip --------------------------------------------------
def test_traceparent_round_trip_fuzz():
    rng = random.Random(0)
    rand = lambda n: bytes(rng.getrandbits(8) for _ in range(n))  # noqa: E731
    for _ in range(300):
        ctx = SpanContext.generate(rand=rand)
        header = ctx.to_traceparent()
        version, tid, sid, flags = header.split("-")
        assert (version, flags) == ("00", "01")
        assert (len(tid), len(sid)) == (32, 16)
        back = SpanContext.from_traceparent(header)
        assert back == ctx


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        f"00-{TRACE_ID}-{PARENT_SPAN}",  # missing flags
        f"00-{TRACE_ID}-{PARENT_SPAN}-01-extra",
        f"ff-{TRACE_ID}-{PARENT_SPAN}-01",  # forbidden version
        f"00-{'0' * 32}-{PARENT_SPAN}-01",  # all-zero trace id
        f"00-{TRACE_ID}-{'0' * 16}-01",  # all-zero span id
        f"00-{TRACE_ID.upper()}-{PARENT_SPAN}-01",  # uppercase hex
        f"00-{TRACE_ID[:-1]}-{PARENT_SPAN}-01",  # short trace id
        f"00-{TRACE_ID}-{PARENT_SPAN}-0g",  # non-hex flags
        f"00-{TRACE_ID}-{PARENT_SPAN[:-1]}x-01",  # non-hex span id
    ],
)
def test_traceparent_rejects_malformed(header):
    assert SpanContext.from_traceparent(header) is None


def test_id_generators_never_all_zero():
    zero_then_real = [b"\x00" * 16, b"\xab" * 16, b"\x00" * 8, b"\xcd" * 8]
    rand = lambda n: zero_then_real.pop(0)[:n]  # noqa: E731
    assert new_trace_id(rand) == "ab" * 16
    assert new_span_id(rand) == "cd" * 8


# -- tracer context mechanics ------------------------------------------------
def test_nested_spans_parent_and_share_trace():
    tr = Tracer(clock=FakeClock(), perf=FakeClock(0.0))
    with tr.span("outer") as outer:
        with tr.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert tr.current_context() is None
    assert [s.name for s in tr.recent()] == ["inner", "outer"]


def test_attach_carries_context_across_threads():
    import threading

    tr = Tracer()
    root = tr.start_span("root", push=False)  # detached: stack untouched
    assert tr.current_context() is None
    seen = {}

    def worker():
        with tr.attach(root.context):
            with tr.span("child") as sp:
                seen["parent"] = sp.parent_id
                seen["trace"] = sp.trace_id

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    tr.end_span(root, ok=True)
    assert seen == {"parent": root.span_id, "trace": root.trace_id}


def test_attach_none_is_noop():
    tr = Tracer()
    with tr.attach(None):
        assert tr.current_context() is None


def test_ring_keeps_newest_and_counts_drops():
    tr = Tracer(ring=3)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert [s.name for s in tr.recent()] == ["s2", "s3", "s4"]
    assert tr.dropped == 2 and tr.started == 5


# -- timeline lanes ----------------------------------------------------------
def test_timeline_chrome_export_lane_assignment():
    tl = TimelineRecorder()
    t0 = time.monotonic()
    tl.add("host sched", "iteration", t0, t0 + 0.001)
    tl.add(device_decode_track(0), "decode x4", t0, t0 + 0.002, slots=[0])
    tl.add(device_decode_track(1), "decode x4", t0 + 0.001, t0 + 0.003)
    doc = tl.chrome()
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["tid"] for e in xs] == [
        "host sched", "device decode/0", "device decode/1",
    ]
    assert all(e["pid"] == 1 and e["dur"] >= 0 for e in xs)
    named = {
        e["tid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert named == {t: t for t in (
        "host sched", "device decode/0", "device decode/1",
    )}
    assert doc["metadata"]["events"] == 3


def test_timeline_rejects_unnamed_track_and_bounds_events():
    tl = TimelineRecorder(max_events=2)
    tl.add("a", "e1", 0.0, 1.0)
    tl.add("a", "e2", 0.0, 1.0)
    tl.add("a", "e3", 0.0, 1.0)  # over the cap: dropped, counted
    assert tl.dropped == 1
    bad = TimelineRecorder()
    bad.add("  ", "anon", 0.0, 1.0)
    with pytest.raises(ValueError, match="unnamed track"):
        bad.chrome()
    assert lint_tracks() == []  # the static lane catalog itself is clean


# -- chaos: context propagation under sync failure (scripts/chaos_check.py) --
def _wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _make_session(tmp_path, cluster, n_workers):
    from devspace_tpu.sync.session import SyncOptions, SyncSession
    from devspace_tpu.utils.fsutil import write_file

    local = tmp_path / "local"
    local.mkdir(exist_ok=True)
    write_file(str(local / "base.py"), "v0")
    workers = [
        cluster.add_pod(f"w-{i}", labels={"app": "t"}, worker_id=i)
        for i in range(n_workers)
    ]
    opts = SyncOptions(
        local_path=str(local),
        container_path="/app",
        upstream_quiet=0.15,
        upstream_tick=0.05,
        downstream_interval=0.15,
    )
    return SyncSession(cluster, workers, opts), local, workers


def _upload_spans(trace_id):
    return [
        s
        for s in get_tracer().find(trace_id)
        if s.name == "sync.upload"
    ]


@pytest.mark.chaos
def test_span_context_survives_sync_retry(tmp_path):
    """A transient upload failure followed by a successful shell revive:
    the retry's span must re-attach the SAME trace as the first attempt —
    a retry that roots a fresh trace would orphan the recovery from the
    operation it recovered."""
    from devspace_tpu.kube.fake import FakeCluster
    from devspace_tpu.resilience.chaos import ByteBudgetStream
    from devspace_tpu.utils.fsutil import write_file

    cluster = FakeCluster(str(tmp_path / "cluster"))
    session, local, workers = _make_session(tmp_path, cluster, n_workers=2)
    session.start()
    try:
        trace_id = session._session_span.trace_id
        _wait_for(
            lambda: session.initial_sync_done.is_set(), msg="initial sync"
        )
        # next byte to worker 1 fails; revive (exec_stream intact) succeeds
        session._shells[1].proc = ByteBudgetStream(session._shells[1].proc, 0)
        write_file(str(local / "edit.py"), "v1")
        _wait_for(
            lambda: any(
                s.attrs.get("retry") for s in _upload_spans(trace_id)
            ),
            msg="retried upload span",
        )
    finally:
        session.stop()
    assert session.error is None and not session.worker_errors
    retries = [
        s for s in _upload_spans(trace_id) if s.attrs.get("retry")
    ]
    assert retries, "revive path recorded no retry span"
    sp = retries[-1]
    assert sp.trace_id == trace_id  # context survived the retry
    assert sp.attrs["worker"] == 1
    assert sp.attrs["outcome"] == "delivered" and sp.ok is True
    # the failed first attempt is on the same trace too
    firsts = [
        s
        for s in _upload_spans(trace_id)
        if not s.attrs.get("retry") and s.attrs.get("worker") == 1
        and s.attrs.get("outcome") == "failed"
    ]
    assert firsts and firsts[-1].ok is False


@pytest.mark.chaos
def test_dropped_worker_closes_span_with_outcome_failed(
    tmp_path, monkeypatch
):
    """A worker dropped mid-upload (stream dead, revive impossible) is
    quarantined — and its last upload span must close failed with the
    error recorded, not leak open or report delivered."""
    from devspace_tpu.kube.fake import FakeCluster
    from devspace_tpu.resilience.chaos import ByteBudgetStream
    from devspace_tpu.utils.fsutil import write_file

    cluster = FakeCluster(str(tmp_path / "cluster"))
    session, local, workers = _make_session(tmp_path, cluster, n_workers=3)
    session.start()
    try:
        trace_id = session._session_span.trace_id
        _wait_for(
            lambda: session.initial_sync_done.is_set(), msg="initial sync"
        )
        real_exec = cluster.exec_stream

        def exec_stream(pod, *a, **kw):
            if getattr(pod, "name", pod) == workers[1].name:
                raise RuntimeError("pod gone")
            return real_exec(pod, *a, **kw)

        monkeypatch.setattr(cluster, "exec_stream", exec_stream)
        session._shells[1].proc = ByteBudgetStream(session._shells[1].proc, 0)
        write_file(str(local / "edit.py"), "v1")
        _wait_for(lambda: 1 in session.worker_errors, msg="quarantine")
    finally:
        session.stop()
    assert session.error is None  # graded ladder: session survives
    failed = [
        s
        for s in _upload_spans(trace_id)
        if s.attrs.get("worker") == 1 and s.attrs.get("outcome") == "failed"
    ]
    assert failed, "dropped worker left no failed upload span"
    assert all(s.ok is False and s.error for s in failed)
    # survivors' deliveries stay on the same trace, marked delivered
    delivered = [
        s
        for s in _upload_spans(trace_id)
        if s.attrs.get("outcome") == "delivered"
    ]
    assert delivered and all(s.ok for s in delivered)
