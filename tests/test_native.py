"""Native fast-path tests: libdevsync builds, and its walk agrees exactly
with the pure-Python implementations it accelerates.

The reference keeps the whole sync engine native (Go); our invariant is
weaker and testable: native and Python paths are interchangeable —
identical walk_local_tree results and bit-identical directory hashes.
"""

from __future__ import annotations

import os

import pytest

from devspace_tpu.utils import native
from devspace_tpu.utils.hashutil import directory_hash
from devspace_tpu.utils.ignoreutil import IgnoreMatcher


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.fail("libdevsync failed to build — g++ toolchain is required")
    return lib


def build_tree(root):
    os.makedirs(root / "src" / "nested", exist_ok=True)
    os.makedirs(root / ".git" / "objects", exist_ok=True)
    os.makedirs(root / "node_modules" / "pkg", exist_ok=True)
    (root / "train.py").write_text("print('hi')\n")
    (root / "src" / "model.py").write_text("x = 1\n")
    (root / "src" / "nested" / "deep.txt").write_text("deep\n")
    (root / ".git" / "objects" / "blob").write_text("blob\n")
    (root / "node_modules" / "pkg" / "index.js").write_text("js\n")
    (root / "data.bin").write_bytes(b"\x00" * 1024)
    os.symlink("train.py", root / "link_to_file")
    os.symlink("src", root / "link_to_dir")
    os.symlink("missing-target", root / "dangling")


def test_native_walk_matches_python_walk(lib, tmp_path, monkeypatch):
    from devspace_tpu.sync.session import walk_local_tree

    build_tree(tmp_path)
    matcher = IgnoreMatcher([".git/", "node_modules", "*.bin"])

    native_result = walk_local_tree(str(tmp_path), matcher)
    monkeypatch.setattr(native, "walk", lambda *a, **k: None)
    python_result = walk_local_tree(str(tmp_path), matcher)

    assert set(native_result) == set(python_result)
    for rel, info in python_result.items():
        n = native_result[rel]
        assert (n.size, n.mtime, n.is_directory, n.is_symlink) == (
            info.size,
            info.mtime,
            info.is_directory,
            info.is_symlink,
        ), rel
    assert "src/model.py" in native_result
    assert "src/nested/deep.txt" in native_result
    assert not any(r.startswith(".git") for r in native_result)
    assert not any(r.startswith("node_modules") for r in native_result)
    assert "data.bin" not in native_result
    # symlinks: followed for stat, flagged as links
    assert native_result["link_to_file"].is_symlink
    assert native_result["link_to_dir"].is_directory
    # symlinked dir contents appear (follow semantics) exactly like Python
    assert ("link_to_dir/model.py" in native_result) == (
        "link_to_dir/model.py" in python_result
    )
    assert "dangling" not in native_result  # dangling links are unstatable


def test_directory_hash_native_matches_python(lib, tmp_path, monkeypatch):
    build_tree(tmp_path)
    excludes = [".git/", "node_modules"]
    h_native = directory_hash(str(tmp_path), excludes)
    monkeypatch.setattr(native, "walk", lambda *a, **k: None)
    h_python = directory_hash(str(tmp_path), excludes)
    assert h_native == h_python

    # hash reacts to edits either way
    (tmp_path / "train.py").write_text("print('changed')\n")
    os.utime(tmp_path / "train.py", ns=(1, 10**18))
    assert directory_hash(str(tmp_path), excludes) != h_python


def test_symlink_cycle_terminates(lib, tmp_path):
    from devspace_tpu.sync.session import walk_local_tree

    os.makedirs(tmp_path / "a" / "b")
    os.symlink(str(tmp_path / "a"), tmp_path / "a" / "b" / "loop")
    result = walk_local_tree(str(tmp_path), None)
    assert "a/b" in result  # finished without spinning


def test_prune_names():
    assert native.prune_names([".git/", "node_modules", "*.pyc", "a/b", "/top"]) == [
        ".git",
        "node_modules",
    ]
    # negations disable pruning entirely
    assert native.prune_names([".git/", "!keep"]) == []
    assert native.prune_names(None) == []


def test_disable_via_env(lib, monkeypatch):
    monkeypatch.setenv("DEVSPACE_NATIVE", "0")
    assert native.load() is None
    assert native.walk("/tmp") is None
