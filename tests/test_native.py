"""Native fast-path tests: libdevsync builds, and its walk agrees exactly
with the pure-Python implementations it accelerates.

The reference keeps the whole sync engine native (Go); our invariant is
weaker and testable: native and Python paths are interchangeable —
identical walk_local_tree results and bit-identical directory hashes.
"""

from __future__ import annotations

import os

import pytest

from devspace_tpu.utils import native
from devspace_tpu.utils.hashutil import directory_hash
from devspace_tpu.utils.ignoreutil import IgnoreMatcher


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.fail("libdevsync failed to build — g++ toolchain is required")
    return lib


def build_tree(root):
    os.makedirs(root / "src" / "nested", exist_ok=True)
    os.makedirs(root / ".git" / "objects", exist_ok=True)
    os.makedirs(root / "node_modules" / "pkg", exist_ok=True)
    (root / "train.py").write_text("print('hi')\n")
    (root / "src" / "model.py").write_text("x = 1\n")
    (root / "src" / "nested" / "deep.txt").write_text("deep\n")
    (root / ".git" / "objects" / "blob").write_text("blob\n")
    (root / "node_modules" / "pkg" / "index.js").write_text("js\n")
    (root / "data.bin").write_bytes(b"\x00" * 1024)
    os.symlink("train.py", root / "link_to_file")
    os.symlink("src", root / "link_to_dir")
    os.symlink("missing-target", root / "dangling")


def test_native_walk_matches_python_walk(lib, tmp_path, monkeypatch):
    from devspace_tpu.sync.session import walk_local_tree

    build_tree(tmp_path)
    matcher = IgnoreMatcher([".git/", "node_modules", "*.bin"])

    native_result = walk_local_tree(str(tmp_path), matcher)
    monkeypatch.setattr(native, "walk", lambda *a, **k: None)
    python_result = walk_local_tree(str(tmp_path), matcher)

    assert set(native_result) == set(python_result)
    for rel, info in python_result.items():
        n = native_result[rel]
        assert (n.size, n.mtime, n.is_directory, n.is_symlink) == (
            info.size,
            info.mtime,
            info.is_directory,
            info.is_symlink,
        ), rel
    assert "src/model.py" in native_result
    assert "src/nested/deep.txt" in native_result
    assert not any(r.startswith(".git") for r in native_result)
    assert not any(r.startswith("node_modules") for r in native_result)
    assert "data.bin" not in native_result
    # symlinks: followed for stat, flagged as links
    assert native_result["link_to_file"].is_symlink
    assert native_result["link_to_dir"].is_directory
    # symlinked dir contents appear (follow semantics) exactly like Python
    assert ("link_to_dir/model.py" in native_result) == (
        "link_to_dir/model.py" in python_result
    )
    assert "dangling" not in native_result  # dangling links are unstatable


def test_directory_hash_native_matches_python(lib, tmp_path, monkeypatch):
    build_tree(tmp_path)
    excludes = [".git/", "node_modules"]
    h_native = directory_hash(str(tmp_path), excludes)
    monkeypatch.setattr(native, "walk", lambda *a, **k: None)
    h_python = directory_hash(str(tmp_path), excludes)
    assert h_native == h_python

    # hash reacts to edits either way
    (tmp_path / "train.py").write_text("print('changed')\n")
    os.utime(tmp_path / "train.py", ns=(1, 10**18))
    assert directory_hash(str(tmp_path), excludes) != h_python


def test_symlink_cycle_terminates(lib, tmp_path):
    from devspace_tpu.sync.session import walk_local_tree

    os.makedirs(tmp_path / "a" / "b")
    os.symlink(str(tmp_path / "a"), tmp_path / "a" / "b" / "loop")
    result = walk_local_tree(str(tmp_path), None)
    assert "a/b" in result  # finished without spinning


def test_native_pack_tar_matches_python_tarfile(lib, tmp_path, monkeypatch):
    """VERDICT r3 next #8: the native tar packer must be member-for-member
    identical to the Python tarfile builder — names (incl. GNU longname
    >= 100 chars), dir entries, remote mode/uid/gid overrides, mtimes,
    sizes and content — across the build_tar entry point."""
    import io
    import random
    import tarfile

    from devspace_tpu.sync.index import FileInformation
    from devspace_tpu.sync.shell import build_tar

    root = tmp_path / "tree"
    rng = random.Random(0)
    entries = []
    for d in range(8):
        dd = root / f"pkg{d}"
        os.makedirs(dd)
        entries.append(
            FileInformation(
                name=f"pkg{d}", size=0, mtime=1700000000 + d,
                is_directory=True,
            )
        )
        for f in range(12):
            p = dd / f"m{f}.py"
            p.write_bytes(bytes(rng.getrandbits(8) for _ in range(200)))
            st = os.stat(p)
            entries.append(
                FileInformation(
                    name=f"pkg{d}/m{f}.py", size=st.st_size,
                    mtime=int(st.st_mtime), is_directory=False,
                )
            )
    long_dir = "d" * 60 + "/" + "e" * 60
    os.makedirs(root / long_dir)
    lp = long_dir + "/" + "f" * 40 + ".txt"
    (root / lp).write_bytes(b"longname content")
    entries.append(
        FileInformation(
            name=lp, size=16, mtime=int(os.stat(root / lp).st_mtime),
            is_directory=False,
        )
    )
    # remote metadata overrides ride through
    e = entries[1]
    entries[1] = FileInformation(
        name=e.name, size=e.size, mtime=e.mtime, is_directory=False,
        remote_mode=0o600, remote_uid=1234, remote_gid=99,
    )
    # mode 0 is a real value, not "unset": both paths must emit 000 for
    # a dir whose recorded remote mode is 0 (not coerce it to 0o755)
    d0 = entries[0]
    assert d0.is_directory
    entries[0] = FileInformation(
        name=d0.name, size=0, mtime=d0.mtime, is_directory=True,
        remote_mode=0,
    )
    assert len(entries) >= 64  # the native routing threshold

    def members(gz):
        out = {}
        with tarfile.open(fileobj=io.BytesIO(gz), mode="r:gz") as tf:
            for m in tf.getmembers():
                data = tf.extractfile(m).read() if m.isfile() else b""
                out[m.name.rstrip("/")] = (
                    m.isdir(), m.mode, m.uid, m.gid, m.mtime, m.size, data
                )
        return out

    monkeypatch.setenv("DEVSPACE_NATIVE", "0")
    native._lib = None
    native._load_failed = False
    py = members(build_tar(str(root), entries))
    monkeypatch.delenv("DEVSPACE_NATIVE")
    native._lib = None
    native._load_failed = False
    nat = members(build_tar(str(root), entries))
    assert set(py) == set(nat)
    for k in py:
        assert py[k] == nat[k], k
    # deleted-underneath files are skipped, not fatal
    (root / "pkg0" / "m0.py").unlink()
    nat2 = members(build_tar(str(root), entries))
    assert "pkg0/m0.py" not in nat2 and "pkg0/m1.py" in nat2


def test_prune_names():
    assert native.prune_names([".git/", "node_modules", "*.pyc", "a/b", "/top"]) == [
        ".git",
        "node_modules",
    ]
    # negations disable pruning entirely
    assert native.prune_names([".git/", "!keep"]) == []
    assert native.prune_names(None) == []


def test_disable_via_env(lib, monkeypatch):
    monkeypatch.setenv("DEVSPACE_NATIVE", "0")
    assert native.load() is None
    assert native.walk("/tmp") is None


def test_load_degrades_when_library_lacks_symbols(monkeypatch):
    """A prebuilt libdevsync from an older ABI may lack newer symbols
    (ds_pack): ctypes raises AttributeError at the attribute bind,
    before ds_abi_version() can reject it — load() must degrade to None
    (Python fallback), not crash every walk()/build_tar() caller."""
    import ctypes

    class OldLib:
        class _Sym:  # ds_walk exists on any ABI
            restype = None
            argtypes = None

        ds_walk = _Sym()

        def __getattr__(self, name):  # ds_pack & co: not exported
            raise AttributeError(name)

    monkeypatch.setattr(ctypes, "CDLL", lambda path: OldLib())
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_failed", False)
    assert native.load() is None
    assert native._load_failed  # sticky: no rebind attempt per call
    # module state is monkeypatch-restored; the real lib reloads after


def test_build_tar_zero_fills_file_truncated_mid_copy(tmp_path):
    """A file that shrinks between build_tar's stat and the content copy
    must yield a well-formed archive (shortfall zero-filled, later
    members intact) — not abort mid-member and misalign the stream."""
    import io
    import tarfile

    from devspace_tpu.sync.index import FileInformation
    from devspace_tpu.sync import shell as shellmod

    root = tmp_path / "tree"
    os.makedirs(root)
    (root / "a.txt").write_bytes(b"A" * 100)
    (root / "b.txt").write_bytes(b"B" * 50)
    entries = [
        FileInformation(name="a.txt", size=100, mtime=1700000000,
                        is_directory=False),
        FileInformation(name="b.txt", size=50, mtime=1700000000,
                        is_directory=False),
    ]

    real_open = open

    def racing_open(path, *a, **kw):
        fh = real_open(path, *a, **kw)
        if str(path).endswith("a.txt"):
            # simulate a concurrent truncation AFTER the stat: the
            # reader sees EOF at 30 of the 100 stat'd bytes
            data = fh.read(30)
            fh.close()
            return io.BytesIO(data)
        return fh

    import builtins

    orig = builtins.open
    builtins.open = racing_open
    try:
        gz = shellmod.build_tar(str(root), entries)
    finally:
        builtins.open = orig

    with tarfile.open(fileobj=io.BytesIO(gz), mode="r:gz") as tf:
        a = tf.extractfile("a.txt").read()
        b = tf.extractfile("b.txt").read()
    assert a == b"A" * 30 + b"\0" * 70  # header size honored, padded
    assert b == b"B" * 50  # the NEXT member is untouched


def test_exact_size_reader_truncates_grown_file():
    import io

    from devspace_tpu.sync.shell import _ExactSizeReader

    r = _ExactSizeReader(io.BytesIO(b"x" * 99), 10)
    assert r.read(7) == b"x" * 7
    assert r.read() == b"x" * 3  # stops at the stat'd size
    assert r.read() == b""
