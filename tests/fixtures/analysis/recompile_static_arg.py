# expect: JIT501
# The PR 7 bug class, distilled: a Python int in a static_argnums
# position varies per loop iteration -> one XLA compile per block id.
import jax

decode_jit = jax.jit(lambda pool, idx: pool[idx], static_argnums=(1,))


def drain(pool, block_ids):
    out = []
    for bid in block_ids:
        out.append(decode_jit(pool, bid))  # recompiles per distinct bid
    return out
