# expect: CON600
# Two call paths taking the same two locks in opposite orders: two
# threads (one per path) wedge forever.
import threading


class Pool:
    def __init__(self):
        self._alloc_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.free = []
        self.stats = {}

    def take(self):
        with self._alloc_lock:
            with self._stats_lock:
                self.stats["takes"] = self.stats.get("takes", 0) + 1
                return self.free.pop()

    def report(self):
        with self._stats_lock:
            with self._alloc_lock:
                return dict(self.stats, free=len(self.free))
