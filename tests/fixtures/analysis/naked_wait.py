# expect: CON602
# Condition.wait() guarded by a bare if: spurious wakeups and stolen
# notifications act on stale state -- the predicate must re-check in a
# while loop.
import threading


class Mailbox:
    def __init__(self):
        self._cond = threading.Condition()
        self.items = []

    def get(self):
        with self._cond:
            if not self.items:
                self._cond.wait(1.0)
            return self.items.pop(0)
