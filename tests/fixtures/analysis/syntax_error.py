# expect: PY500
# A module that does not parse is itself a finding -- nothing else can
# be checked until it does.
def broken(:
    return 1
