# expect: JIT504
# A slice with non-constant bounds straight into a jitted call inside a
# loop: the argument shape varies per iteration and recompiles per shape.
import jax

score_jit = jax.jit(lambda toks: toks * 2)


def score_prefixes(toks, lengths):
    outs = []
    for n in lengths:
        outs.append(score_jit(toks[:n]))
    return outs
