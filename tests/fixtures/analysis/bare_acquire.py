# expect: CON604
# acquire() with the matching release() outside any finally: an
# exception in between leaks the lock forever.
import threading

_lock = threading.Lock()
_state = {}


def update(key, value):
    _lock.acquire()
    _state[key] = value  # a KeyError/MemoryError here leaks _lock
    _lock.release()
