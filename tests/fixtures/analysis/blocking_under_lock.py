# expect: CON601
# The RateLimiter.throttle bug class: sleeping while holding the lock
# stalls every other thread contending for it.
import threading
import time


class Limiter:
    def __init__(self, rate):
        self._lock = threading.Lock()
        self.rate = rate
        self.allowance = rate

    def throttle(self):
        with self._lock:
            if self.allowance < 1:
                time.sleep(1.0 / self.rate)
            self.allowance -= 1
