# expect: JIT500
# A fresh jax.jit per iteration: nothing ever hits the compile cache.
import jax


def sweep(xs, scale):
    out = []
    for x in xs:
        f = jax.jit(lambda v: v * scale)
        out.append(f(x))
    return out
