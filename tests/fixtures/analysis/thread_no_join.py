# expect: CON603
# A non-daemon thread with no join() anywhere in the module: the
# process cannot exit while it runs.
import threading


def start_worker(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
