# expect: JIT503
# The donated buffer is read after the call without being rebound from
# the results -- it no longer exists on device.
import jax

step_jit = jax.jit(lambda carry, x: (carry + x, carry), donate_argnums=(0,))


def run(carry, xs):
    outs = []
    for x in xs:
        new_carry, out = step_jit(carry, x)
        outs.append(out)
    return carry.sum(), outs  # carry was donated above
