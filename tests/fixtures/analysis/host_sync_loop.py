# expect: JIT502
# Implicit device->host syncs inside the hot loop: .item() and
# np.asarray over a jnp result both block the host per iteration.
import jax.numpy as jnp
import numpy as np


def accumulate(logits_seq):
    total = 0.0
    rows = []
    for logits in logits_seq:
        probs = jnp.exp(logits)
        total += probs.max().item()
        rows.append(np.asarray(probs))
    return total, rows
