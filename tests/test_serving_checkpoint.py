"""The train -> checkpoint -> serve seam (VERDICT r4 next #2): training
writes step-managed Orbax checkpoints; ``load_serving_params`` /
``InferenceEngine.from_checkpoint`` restore the params subtree alone into
the serving engine — single-chip, tensor-parallel (elastic placement), or
int8 weight-quantized — and generation must match serving the in-memory
trained params."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from devspace_tpu.inference import InferenceEngine, load_serving_params
from devspace_tpu.models import transformer as tfm
from devspace_tpu.training.checkpoint import CheckpointManager, save_checkpoint
from devspace_tpu.training.trainer import make_lm_train_step, train_loop

CFG = tfm.TINY
PROMPTS = [[5, 1, 4], [2, 2, 2, 2, 2]]


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """Train TINY for 6 LM steps, checkpointing every 3 -> (root dir,
    in-memory trained params)."""
    root = tmp_path_factory.mktemp("train_ckpt")
    opt = optax.adam(1e-2)
    params = tfm.init_params(CFG, jax.random.PRNGKey(0))
    state = {
        "params": params,
        "opt_state": opt.init(params),
        "step": jnp.zeros((), jnp.int32),
    }
    step_fn = make_lm_train_step(tfm.forward, CFG, opt, donate=False)
    rng = np.random.default_rng(0)
    batches = [
        jnp.asarray(rng.integers(1, CFG.vocab_size, (2, 17)))
        for _ in range(6)
    ]
    mgr = CheckpointManager(str(root), save_interval=3, max_to_keep=2)
    state, loss = train_loop(step_fn, state, batches, checkpoint_manager=mgr)
    assert float(loss) == float(loss)  # trained without NaNs
    return str(root), state["params"]


def engine_generate(params, prompts, n=6, **engine_kwargs):
    engine = InferenceEngine(params, CFG, max_slots=2, max_len=48, **engine_kwargs)
    return _drive(engine, prompts, n)


def _drive(engine, prompts, n):
    engine.start()
    try:
        handles = [engine.submit(p, n) for p in prompts]
        return [h.result(timeout=120) for h in handles]
    finally:
        engine.stop()


def test_restored_params_serve_identically(trained):
    """The flagship story in one test: train with the framework,
    checkpoint, restore into the engine — generation must equal serving
    the in-memory trained params."""
    root, live_params = trained
    params, step = load_serving_params(root, CFG)
    assert step == 6, "latest step dir must win"
    assert not isinstance(params, dict) or "opt_state" not in params
    assert engine_generate(params, PROMPTS) == engine_generate(
        live_params, PROMPTS
    )


def test_restore_selects_step_and_direct_dir(trained):
    root, _ = trained
    p3, s3 = load_serving_params(root, CFG, step=3)
    p6, s6 = load_serving_params(root, CFG, step=6)
    assert (s3, s6) == (3, 6)
    # training moved the weights between the two checkpoints
    assert not np.allclose(
        np.asarray(p3["embed"], np.float32),
        np.asarray(p6["embed"], np.float32),
    )
    import os

    direct, sd = load_serving_params(
        os.path.join(root, "step_00000003"), CFG
    )
    assert sd == 3
    assert np.array_equal(
        np.asarray(direct["embed"], np.float32),
        np.asarray(p3["embed"], np.float32),
    )
    with pytest.raises(FileNotFoundError):
        load_serving_params(root, CFG, step=99)


def test_tp_elastic_restore_serves_identically(trained):
    """A checkpoint saved from single-device training restores DIRECTLY
    sharded onto a 2-way tensor-parallel serving mesh (no host bounce)
    and the TP engine generates the same tokens."""
    from jax.sharding import PartitionSpec as P

    from devspace_tpu.parallel.mesh import create_mesh

    root, live_params = trained
    mesh = create_mesh({"model": 2}, devices=jax.devices()[:2])
    params, _ = load_serving_params(root, CFG, mesh=mesh)
    wq = params["layers"][0]["wq"]
    assert wq.sharding.spec == P(None, "model"), "restore must land sharded"
    got = _drive(
        InferenceEngine(params, CFG, max_slots=2, max_len=48, mesh=mesh),
        PROMPTS,
        6,
    )
    assert got == engine_generate(live_params, PROMPTS)


def test_from_checkpoint_int8_and_self_draft(trained):
    """``from_checkpoint`` composes the seam with the engine features:
    int8 weight quantization matches quantizing the live params exactly,
    and a restored draft (self-draft here) stays lossless."""
    from devspace_tpu.inference.quantization import quantize_params

    root, live_params = trained
    engine = InferenceEngine.from_checkpoint(
        root, CFG, quantize="int8", max_slots=2, max_len=48
    )
    got_q = _drive(engine, PROMPTS, 6)
    assert got_q == engine_generate(quantize_params(live_params), PROMPTS)

    spec_engine = InferenceEngine.from_checkpoint(
        root, CFG, draft_checkpoint=root, draft_cfg=CFG,
        max_slots=2, max_len=48,
    )
    got_spec = _drive(spec_engine, PROMPTS, 6)
    assert spec_engine.spec_rounds > 0, "speculative path must have run"
    assert got_spec == engine_generate(live_params, PROMPTS)
    with pytest.raises(ValueError, match="draft_cfg without"):
        InferenceEngine.from_checkpoint(root, CFG, draft_cfg=CFG)


def test_bare_params_checkpoint_loads(trained, tmp_path):
    root, live_params = trained
    path = str(tmp_path / "bare")
    save_checkpoint(path, live_params)
    params, step = load_serving_params(path, CFG)
    assert step is None
    assert engine_generate(params, PROMPTS[:1]) == engine_generate(
        live_params, PROMPTS[:1]
    )


def test_wrong_config_fails_clearly(trained):
    root, _ = trained
    wrong = dataclasses.replace(CFG, dim=CFG.dim * 2)
    with pytest.raises(ValueError, match="does not match the serving config"):
        load_serving_params(root, wrong)
    with pytest.raises(FileNotFoundError):
        load_serving_params(root + "_nonexistent", CFG)
