"""Metrics registry + Prometheus exposition (obs/metrics.py — ISSUE 6).

Pins the primitives (counter monotonicity, histogram bucketing with the
cumulative +Inf invariant), labeled families and callback metrics, the
text-exposition renderer against a golden transcript (label escaping,
``_bucket``/``_sum``/``_count``, ``# TYPE`` lines), the WindowedRate
freshness gauge under a fake clock, the ``DEVSPACE_ENGINE_METRICS``
escape hatch, and the metrics-name lint (scripts/metrics_lint.py) over
every subsystem catalog.
"""

import os
import subprocess
import sys
import threading

import pytest

from devspace_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    WindowedRate,
    metrics_enabled,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- primitives -------------------------------------------------------------
def test_counter_monotonic():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_up_and_down():
    g = Gauge()
    g.set(10)
    g.dec(3)
    g.inc()
    assert g.value == 8.0


def test_histogram_bucketing_and_snapshot():
    h = Histogram(buckets=(0.25, 1.0, 4.0))
    for v in (0.25, 0.3, 2.0, 100.0):  # boundary value lands IN its bucket
        h.observe(v)
    snap = h.snapshot()
    # cumulative counts per le edge, +Inf last and == count
    assert snap["buckets"] == [
        (0.25, 1),
        (1.0, 2),
        (4.0, 3),
        (float("inf"), 4),
    ]
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(102.55)
    assert h.count == 4


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram(buckets=())
    with pytest.raises(ValueError):
        Histogram(buckets=(1.0, 1.0, 2.0))


def test_default_latency_buckets_are_increasing():
    assert list(DEFAULT_LATENCY_BUCKETS) == sorted(DEFAULT_LATENCY_BUCKETS)
    assert len(set(DEFAULT_LATENCY_BUCKETS)) == len(DEFAULT_LATENCY_BUCKETS)


# -- registry ---------------------------------------------------------------
def test_registry_idempotent_and_kind_checked():
    reg = Registry()
    a = reg.counter("x_total", "x")
    b = reg.counter("x_total", "x")
    assert a is b  # same family, same child
    with pytest.raises(ValueError):
        reg.gauge("x_total", "now a gauge?")
    with pytest.raises(ValueError):
        reg.counter("Bad-Name", "nope")


def test_labeled_family_schema_enforced():
    reg = Registry()
    fam = reg.counter("req_total", "requests", labels=("outcome",))
    fam.labels(outcome="ok").inc(2)
    fam.labels(outcome="err").inc()
    assert fam.labels(outcome="ok").value == 2.0
    with pytest.raises(ValueError):
        fam.labels(wrong="key")
    with pytest.raises(ValueError):
        fam.labels()


def test_callback_metrics_replace_and_conflict():
    reg = Registry()
    reg.register_callback("live_total", "counter", "live", lambda: 7)
    assert "live_total 7" in reg.render()
    # re-registering replaces (per-instance bridges re-bind on churn)
    reg.register_callback("live_total", "counter", "live", lambda: 9)
    assert "live_total 9" in reg.render()
    # labeled callback: fn returns (labels, value) pairs
    reg.register_callback(
        "by_kind", "gauge", "by kind",
        lambda: [({"kind": "a"}, 1), ({"kind": "b"}, 2)],
        labels=("kind",),
    )
    out = reg.render()
    assert 'by_kind{kind="a"} 1' in out and 'by_kind{kind="b"} 2' in out
    # a callback may not shadow a direct metric
    reg.counter("direct_total", "direct")
    with pytest.raises(ValueError):
        reg.register_callback("direct_total", "counter", "x", lambda: 0)
    # histograms can't be callbacks
    with pytest.raises(ValueError):
        reg.register_callback("h_seconds", "histogram", "x", lambda: 0)


def test_unregister_removes_family():
    reg = Registry()
    reg.counter("gone_total", "bye")
    reg.unregister("gone_total")
    assert reg.names() == []


# -- golden exposition transcript -------------------------------------------
def test_render_golden():
    """Exact text-exposition bytes: HELP/TYPE lines, label-value escaping
    of backslash/quote/newline, histogram _bucket/_sum/_count with +Inf,
    integer values bare, families sorted by name."""
    reg = Registry()
    c = reg.counter("jobs_done_total", "Jobs done")
    c.inc()
    c.inc(2)
    g = reg.gauge("queue_depth", "Depth", labels=("queue",))
    g.labels(queue='a"b\\c\nd').set(3)
    h = reg.histogram("op_seconds", "Op latency", buckets=(0.25, 1.0))
    for v in (0.25, 0.5, 4.0):  # dyadic values: float sums are exact
        h.observe(v)
    expected = "\n".join(
        [
            "# HELP jobs_done_total Jobs done",
            "# TYPE jobs_done_total counter",
            "jobs_done_total 3",
            "# HELP op_seconds Op latency",
            "# TYPE op_seconds histogram",
            'op_seconds_bucket{le="0.25"} 1',
            'op_seconds_bucket{le="1"} 2',
            'op_seconds_bucket{le="+Inf"} 3',
            "op_seconds_sum 4.75",
            "op_seconds_count 3",
            "# HELP queue_depth Depth",
            "# TYPE queue_depth gauge",
            'queue_depth{queue="a\\"b\\\\c\\nd"} 3',
            "",
        ]
    )
    assert reg.render() == expected


def test_render_escapes_help_newlines():
    reg = Registry()
    reg.counter("multi_total", "line one\nline two")
    assert "# HELP multi_total line one\\nline two" in reg.render()


def test_render_empty_registry():
    assert Registry().render() == ""


def test_render_concurrent_with_observes():
    """Scrapes render while the scheduler thread observes — no tearing,
    no exceptions, count never exceeds what was observed."""
    reg = Registry()
    h = reg.histogram("t_seconds", "t")
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            h.observe(0.01)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(50):
            out = reg.render()
            assert "# TYPE t_seconds histogram" in out
    finally:
        stop.set()
        t.join()
    snap = h.snapshot()
    assert snap["buckets"][-1][1] == snap["count"]


# -- windowed rate ----------------------------------------------------------
def test_windowed_rate_decays_where_lifetime_average_lies():
    clock = {"t": 0.0}
    r = WindowedRate(10.0, clock=lambda: clock["t"])
    for s in range(10):
        clock["t"] = float(s)
        r.add(5)
    clock["t"] = 9.0
    # 50 events over the 9s actually covered so far (the cold-start fix:
    # the divisor is the covered window, not the full 10s)
    assert r.rate() == pytest.approx(50 / 9)
    clock["t"] = 25.0  # 16s of silence: every bucket is stale
    assert r.rate() == 0.0
    r.add(10)
    assert r.rate() == pytest.approx(1.0)  # 10 events / 10s window


def test_windowed_rate_cold_start_uses_covered_window():
    """ISSUE 9 satellite regression: in the first seconds of traffic the
    denominator is the elapsed (covered) window, not the full window —
    a server 2s into serving 5 tok/s must report ~5, not 1."""
    clock = {"t": 100.0}
    r = WindowedRate(10.0, clock=lambda: clock["t"])
    assert r.rate() == 0.0  # no adds yet: no covered window, no rate
    r.add(5)
    clock["t"] = 101.0
    r.add(5)
    clock["t"] = 102.0
    # 10 events over 2 covered seconds — the old code said 10/10 = 1.0
    assert r.rate() == pytest.approx(5.0)
    # sub-second cold start clamps the divisor to 1s, never explodes
    clock["t"] = 200.0
    r2 = WindowedRate(10.0, clock=lambda: clock["t"])
    r2.add(3)
    clock["t"] = 200.1
    assert r2.rate() == pytest.approx(3.0)
    # steady state is unchanged: after the window fills, divide by window
    clock["t"] = 300.0
    r3 = WindowedRate(10.0, clock=lambda: clock["t"])
    for s in range(20):
        clock["t"] = 300.0 + s
        r3.add(2)
    clock["t"] = 319.5
    assert r3.rate() == pytest.approx(2.0)


def test_windowed_rate_bucket_reuse_after_wrap():
    clock = {"t": 0.0}
    r = WindowedRate(3.0, clock=lambda: clock["t"])
    r.add(100)  # t=0
    clock["t"] = 4.0  # wraps onto the t=0 bucket (4 % 4 == 0)
    r.add(1)
    assert r.rate() == pytest.approx(1 / 3)  # stale 100 must NOT leak in


# -- escape hatch -----------------------------------------------------------
def test_metrics_enabled_resolution(monkeypatch):
    monkeypatch.delenv("DEVSPACE_ENGINE_METRICS", raising=False)
    assert metrics_enabled() is True
    assert metrics_enabled(False) is False
    for off in ("off", "0", "false", "NO"):
        monkeypatch.setenv("DEVSPACE_ENGINE_METRICS", off)
        assert metrics_enabled() is False
        assert metrics_enabled(True) is True  # explicit arg beats env
    monkeypatch.setenv("DEVSPACE_ENGINE_METRICS", "on")
    assert metrics_enabled() is True


# -- the naming lint over every subsystem catalog ---------------------------
def test_metrics_lint_passes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "metrics_lint.py")],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok:" in proc.stdout
