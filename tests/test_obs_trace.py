"""Per-request serving traces (obs/request_trace.py — ISSUE 6).

TTFT/TPOT/queue-wait/prefill/e2e are asserted against HAND-COMPUTED
values under an injected clock (the hooks never read the wall clock
directly), plus: terminal idempotency (the failure ladder and stop()
racing to finish the same request must not double-count), the bounded
recent-request ring, JSONL + Chrome-trace export through the shared span
writer, the utils/trace ring's keep-newest rotation with its
``spans_dropped`` counter, and an end-to-end tiny-engine run pinning
that every histogram sees exactly one observation per request.
"""

import json
from types import SimpleNamespace

import pytest

from devspace_tpu.obs.request_trace import (
    SERVING_METRIC_FAMILIES,
    ServingTelemetry,
)
from devspace_tpu.utils import trace as trace_mod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def req(prompt_len=4, n=8):
    return SimpleNamespace(prompt_ids=list(range(prompt_len)), max_new_tokens=n)


# -- hand-computed latency derivations --------------------------------------
def test_lifecycle_latencies_exact():
    """enqueue t=0, admit t=1, prefill done t=2, tokens at t=3/4/5,
    finish t=5: queue_wait=1, prefill=1, ttft=3, tpot=(5-3)/(3-1)=1,
    e2e=5 — every histogram sees exactly these values."""
    clock = FakeClock()
    tel = ServingTelemetry(clock=clock)
    r = req()
    tel.on_submit(r)
    clock.t = 1.0
    tel.on_admit(r)
    clock.t = 2.0
    tel.on_prefill_done(r)
    for t in (3.0, 4.0, 5.0):
        clock.t = t
        tel.on_emit(r)
    tel.on_finish(r, "completed")

    assert (tel.queue_wait.sum, tel.queue_wait.count) == (1.0, 1)
    assert (tel.prefill.sum, tel.prefill.count) == (1.0, 1)
    assert (tel.ttft.sum, tel.ttft.count) == (3.0, 1)
    assert (tel.tpot.sum, tel.tpot.count) == (1.0, 1)
    assert (tel.e2e.sum, tel.e2e.count) == (5.0, 1)
    assert tel.finished.labels(outcome="completed").value == 1.0

    d = r._obs_trace.to_dict()
    assert d["outcome"] == "completed"
    assert d["queue_wait_s"] == 1.0
    assert d["prefill_s"] == 1.0
    assert d["ttft_s"] == 3.0
    assert d["tpot_s"] == 1.0
    assert d["e2e_s"] == 5.0
    assert d["tokens_generated"] == 3
    assert [name for name, _ in d["events"]] == [
        "enqueue", "admit", "prefill_done", "first_token", "completed",
    ]


def test_single_token_request_has_no_tpot():
    clock = FakeClock()
    tel = ServingTelemetry(clock=clock)
    r = req(n=1)
    tel.on_submit(r)
    tel.on_admit(r)
    clock.t = 2.0
    tel.on_emit(r)
    tel.on_finish(r, "completed")
    assert tel.ttft.count == 1
    assert tel.tpot.count == 0  # inter-token time needs >= 2 tokens
    assert r._obs_trace.to_dict()["tpot_s"] is None


def test_readmission_keeps_first_admit_and_preempt_count():
    """queue_wait is enqueue -> FIRST admission; a preempt + re-admit
    must not re-observe it (or shrink it)."""
    clock = FakeClock()
    tel = ServingTelemetry(clock=clock)
    r = req()
    tel.on_submit(r)
    clock.t = 1.0
    tel.on_admit(r)
    clock.t = 2.0
    tel.on_preempt(r)
    clock.t = 7.0
    tel.on_admit(r)  # resume
    assert tel.queue_wait.count == 1
    assert tel.queue_wait.sum == 1.0
    assert r._obs_trace.preemptions == 1


def test_finish_is_idempotent():
    """stop()'s fail-outstanding sweep and the scheduler's own failure
    path can both reach a request; the first terminal outcome wins."""
    tel = ServingTelemetry(clock=FakeClock())
    r = req()
    tel.on_submit(r)
    tel.on_finish(r, "failed")
    tel.on_finish(r, "completed")
    tel.on_finish(r, "failed")
    assert tel.finished.labels(outcome="failed").value == 1.0
    assert tel.finished.labels(outcome="completed").value == 0.0
    assert r._obs_trace.outcome == "failed"
    assert tel.e2e.count == 0  # failed requests don't pollute e2e/tpot


def test_untracked_request_is_ignored():
    """Hooks tolerate requests submitted before telemetry attached (or
    with metrics off): no _obs_trace -> every hook is a no-op."""
    tel = ServingTelemetry(clock=FakeClock())
    bare = SimpleNamespace(prompt_ids=[1], max_new_tokens=2)
    tel.on_admit(bare)
    tel.on_emit(bare)
    tel.on_finish(bare, "completed")
    assert tel.finished.labels(outcome="completed").value == 0.0


def test_recent_ring_is_bounded():
    tel = ServingTelemetry(clock=FakeClock(), ring=4)
    for _ in range(10):
        tel.on_submit(req())
    got = tel.recent(limit=100)
    assert len(got) == 4
    assert [g["id"] for g in got] == [7, 8, 9, 10]  # newest kept


def test_export_jsonl_and_chrome(tmp_path):
    clock = FakeClock()
    tel = ServingTelemetry(clock=clock)
    for i in range(3):
        r = req()
        tel.on_submit(r)
        clock.t += 1.0
        tel.on_admit(r)
        clock.t += 1.0
        tel.on_emit(r)
        tel.on_finish(r, "completed")
    jl = tmp_path / "reqs.jsonl"
    assert tel.export_jsonl(str(jl)) == 3
    rows = [json.loads(line) for line in jl.read_text().splitlines()]
    assert [r["id"] for r in rows] == [1, 2, 3]
    assert all(r["outcome"] == "completed" for r in rows)

    ct = tmp_path / "reqs.trace.json"
    n = tel.export_chrome(str(ct))
    events = json.loads(ct.read_text())["traceEvents"]
    assert len(events) == n and n > 0
    names = {e["name"] for e in events}
    assert "queue_wait" in names and "request-1" in names
    assert all(e["ph"] == "X" for e in events)


def test_catalog_matches_registered_families():
    tel = ServingTelemetry(clock=FakeClock())
    assert tel.registry.names() == sorted(n for n, _, _, _ in SERVING_METRIC_FAMILIES)


# -- utils/trace ring rotation + dropped counter ----------------------------
def test_span_ring_rotates_keeping_newest(monkeypatch):
    monkeypatch.setattr(trace_mod, "_MAX_SPANS", 5)
    monkeypatch.setattr(trace_mod, "_spans", [])
    monkeypatch.setattr(trace_mod, "_spans_dropped", 0)
    for i in range(8):
        with trace_mod.span(f"s{i}"):
            pass
    assert trace_mod.dropped() == 3
    assert [s["name"] for s in trace_mod.recent()] == [
        "s3", "s4", "s5", "s6", "s7",
    ]
    # the default registry's callback reads the same counter
    from devspace_tpu.obs.metrics import get_registry

    assert "trace_spans_dropped_total 3" in get_registry().render()


# -- end-to-end through the engine ------------------------------------------
@pytest.fixture(scope="module")
def engine_params():
    import jax

    from devspace_tpu.models import transformer as tfm

    return tfm.init_params(tfm.TINY, jax.random.PRNGKey(0))


def test_engine_histograms_count_one_observation_per_request(engine_params):
    from devspace_tpu.inference import InferenceEngine
    from devspace_tpu.models import transformer as tfm

    engine = InferenceEngine(
        engine_params, tfm.TINY, max_slots=2, max_len=64, chunk_max=16
    ).start()
    try:
        handles = [
            engine.submit([1 + i, 2, 3], 4 + i) for i in range(3)
        ]
        for h in handles:
            h.result(timeout=600)
        st = engine.stats()
        text = engine.metrics_text()
        tel = engine.telemetry
        assert tel is not None
        for hist in (tel.ttft, tel.queue_wait, tel.prefill, tel.e2e, tel.tpot):
            assert hist.count == 3
        assert tel.finished.labels(outcome="completed").value == 3.0
    finally:
        engine.stop()
    assert "tokens_per_sec_10s" in st
    assert st["requests_completed"] == 3
    # exposition text carries nonzero serving histograms + engine counters
    assert 'ttft_seconds_bucket{le="+Inf"} 3' in text
    assert "ttft_seconds_count 3" in text
    assert "tpot_seconds_count 3" in text
    assert "queue_wait_seconds_count 3" in text
    assert "engine_requests_completed_total 3" in text
    assert 'requests_finished_total{outcome="completed"} 3' in text
    traces = tel.recent()
    assert len(traces) == 3
    assert all(t["outcome"] == "completed" for t in traces)
    assert [t["tokens_generated"] for t in traces] == [4, 5, 6]


def test_engine_metrics_escape_hatch(engine_params):
    """metrics=False: no telemetry object, no per-token hook work, empty
    exposition — and stats() is byte-compatible either way."""
    from devspace_tpu.inference import InferenceEngine
    from devspace_tpu.models import transformer as tfm

    engine = InferenceEngine(
        engine_params, tfm.TINY, max_slots=1, max_len=64, metrics=False
    ).start()
    try:
        engine.submit([1, 2], 3).result(timeout=600)
        st = engine.stats()
    finally:
        engine.stop()
    assert engine.telemetry is None
    assert engine.metrics_text() == ""
    assert engine.metrics_registry is None
    assert st["requests_completed"] == 1
    assert "tokens_per_sec_10s" in st  # the windowed rate stays on
