"""`lint` and `update packages` (VERDICT r2 next #7).

Reference: helm lint renders with default values and schema-checks the
objects; helm/client.go:169 UpdateRepos refreshes repo indexes before
installs. Here lint additionally checks the TPU slice invariants at
render time (analyze's live-pod checks, shifted left)."""

import os

import pytest
import yaml

from devspace_tpu.cli.main import main
from devspace_tpu.config.latest import TPUConfig
from devspace_tpu.deploy.lint import (
    lint_chart,
    lint_tpu_consistency,
    validate_manifests,
)
from devspace_tpu.utils import log as logutil
from devspace_tpu.utils.fsutil import write_file

from test_packages import make_parent_chart, make_repo


@pytest.fixture
def project(tmp_path, monkeypatch):
    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_FAKE_BACKEND", str(tmp_path / "cluster"))
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    write_file(str(proj / "train.py"), "import jax\nprint('step 0')\n")
    logutil.set_logger(logutil.StdoutLogger())
    return proj


def test_validate_manifests_structural():
    good = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "ok-name"},
        "spec": {"ports": [{"port": 80}]},
    }
    assert validate_manifests([good]) == []
    issues = validate_manifests(
        [
            {"kind": "Service", "metadata": {"name": "Bad_Name"}},
            good,
            good,  # duplicate
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "d"},
                "spec": {
                    "selector": {"matchLabels": {"app": "x"}},
                    "template": {
                        "metadata": {"labels": {"app": "y"}},
                        "spec": {"containers": [{"name": "c"}]},
                    },
                },
            },
        ]
    )
    text = "\n".join(issues)
    assert "missing apiVersion" in text
    assert "not DNS-1123" in text
    assert "duplicate object" in text
    assert "no image" in text
    assert "selector.matchLabels not matched" in text


def test_tpu_consistency_checks():
    tpu = TPUConfig(accelerator="v5litepod-16", topology="4x4", workers=4)
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": "slice"},
        "spec": {
            "replicas": 2,  # != workers
            "serviceName": "slice",
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "w",
                            "image": "img",
                            "env": [
                                {"name": "TPU_WORKER_ID", "value": "0"},
                                {
                                    "name": "TPU_WORKER_HOSTNAMES",
                                    "value": "a,b",  # 2 != 4 workers
                                },
                            ],
                        }
                    ]
                }
            },
        },
    }
    issues = lint_tpu_consistency([sts], tpu)
    text = "\n".join(issues)
    assert "replicas 2 != tpu.workers 4" in text
    assert "no container requests" in text  # env wired but no google.com/tpu
    assert "JAX_COORDINATOR_ADDRESS" in text
    assert "lists 2 host(s), expected 4" in text
    # topology product mismatch: 4x4=16 chips but 4 workers x 1 chip
    assert "topology 4x4 has 16" in text
    # a tpu block with NO slice workload at all is itself a finding
    assert any(
        "no rendered workload" in i for i in lint_tpu_consistency([], tpu)
    )


def test_lint_chart_catches_broken_fixture(tmp_path):
    chart = tmp_path / "broken"
    (chart / "templates").mkdir(parents=True)
    (chart / "chart.yaml").write_text("name: broken\nversion: 0.1.0\n")
    (chart / "values.yaml").write_text("name: ok\n")
    # object missing kind + container without image
    (chart / "templates" / "bad.yaml").write_text(
        "apiVersion: v1\nmetadata:\n  name: ${{ values.name }}\n"
    )
    issues = lint_chart(str(chart))
    # the chart renderer itself refuses kind-less docs; lint surfaces it
    assert any("no kind" in i for i in issues)

    # a render error IS the lint finding
    (chart / "templates" / "bad.yaml").write_text(
        "apiVersion: v1\nkind: X\nmetadata:\n  name: ${{ values.nosuch.deep }}\n"
    )
    issues = lint_chart(str(chart))
    assert issues and "render failed" in issues[0]


def test_cli_lint_scaffolded_project_clean_and_catches_breakage(project):
    assert main(["init"]) == 0
    assert main(["lint"]) == 0  # the scaffolded chart must lint clean
    # break the chart: statefulset replicas fixed to 1 while workers=2
    sts = project / "chart" / "templates" / "statefulset.yaml"
    if sts.exists():
        text = sts.read_text().replace("${{ tpu.workers }}", "1")
        sts.write_text(text)
        assert main(["lint"]) == 1


def test_cli_lint_standalone_chart(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    logutil.set_logger(logutil.StdoutLogger())
    chart = tmp_path / "c"
    (chart / "templates").mkdir(parents=True)
    (chart / "chart.yaml").write_text("name: c\nversion: 0.1.0\n")
    (chart / "templates" / "x.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: UPPER\n"
    )
    assert main(["lint", "--chart", str(chart)]) == 1


def test_check_updates_and_upgrade(tmp_path):
    from devspace_tpu.deploy.packages import (
        add_package,
        check_updates,
        load_requirements,
        upgrade_package,
    )

    repo_root = tmp_path / "repo"
    repo = make_repo(repo_root)  # only 1.0.0 exists
    chart_dir = make_parent_chart(tmp_path)
    add_package(chart_dir, repo, "redis")
    rows = check_updates(chart_dir)
    assert rows == [
        {
            "name": "redis",
            "current": "1.0.0",
            "latest": "1.0.0",
            "repository": repo,
            "update": False,
            "error": "",
        }
    ]

    # user customizes a value, then the repo publishes 2.0.0
    values_path = os.path.join(chart_dir, "values.yaml")
    vals = yaml.safe_load(open(values_path))
    vals["packages"]["redis"]["tag"] = "custom"
    yaml.safe_dump(vals, open(values_path, "w"), sort_keys=False)
    # the repo publishes 2.0.0 (new chart dir + refreshed index)
    from test_packages import REDIS_TEMPLATE

    chart2 = repo_root / "charts" / "redis-2"
    (chart2 / "templates").mkdir(parents=True)
    (chart2 / "chart.yaml").write_text("name: redis\nversion: 2.0.0\n")
    (chart2 / "values.yaml").write_text("replicas: 2\ntag: '7.2'\n")
    (chart2 / "templates" / "deployment.yaml").write_text(REDIS_TEMPLATE)
    (repo_root / "index.yaml").write_text(
        yaml.safe_dump(
            {
                "entries": {
                    "redis": [
                        {"version": "2.0.0", "path": "charts/redis-2"},
                        {"version": "1.0.0", "path": "charts/redis"},
                    ]
                }
            }
        )
    )
    rows = check_updates(chart_dir)
    assert rows[0]["latest"] == "2.0.0" and rows[0]["update"] is True

    upgrade_package(chart_dir, "redis")
    deps = load_requirements(chart_dir)
    assert deps[0]["version"] == "2.0.0"
    assert "7.2" in (
        open(os.path.join(chart_dir, "packages", "redis", "values.yaml")).read()
    )
    # the user's override survives the upgrade
    vals = yaml.safe_load(open(values_path))
    assert vals["packages"]["redis"]["tag"] == "custom"


def test_semver_spaced_operator():
    from devspace_tpu.deploy.gotemplate import _semver_compare

    assert _semver_compare(">= 1.25", "1.27.0") is True
    assert _semver_compare("> 1.25", "1.27.0") is True
    assert _semver_compare(">= 1.28", "1.27.0") is False


def test_upgrade_tolerates_null_packages_key(tmp_path):
    """A hand-edited values.yaml with a bare `packages:` (null) key must
    not crash the upgrade, and a no-op merge must not rewrite the file."""
    from devspace_tpu.deploy.packages import add_package, upgrade_package

    repo_root = tmp_path / "repo"
    repo = make_repo(repo_root)
    chart_dir = make_parent_chart(tmp_path)
    add_package(chart_dir, repo, "redis")
    values_path = os.path.join(chart_dir, "values.yaml")
    with open(values_path, "w") as fh:
        fh.write("port: 8080\npackages:\n")  # null packages key
    from test_packages import REDIS_TEMPLATE

    chart2 = repo_root / "charts" / "redis-2"
    (chart2 / "templates").mkdir(parents=True)
    (chart2 / "chart.yaml").write_text("name: redis\nversion: 2.0.0\n")
    (chart2 / "values.yaml").write_text("replicas: 2\ntag: '7.2'\n")
    (chart2 / "templates" / "deployment.yaml").write_text(REDIS_TEMPLATE)
    (repo_root / "index.yaml").write_text(
        yaml.safe_dump(
            {
                "entries": {
                    "redis": [
                        {"version": "2.0.0", "path": "charts/redis-2"},
                        {"version": "1.0.0", "path": "charts/redis"},
                    ]
                }
            }
        )
    )
    upgrade_package(chart_dir, "redis")  # must not raise
    vals = yaml.safe_load(open(values_path))
    assert vals["packages"]["redis"]["tag"] == "7.2"  # new defaults added

    # second upgrade to the same version is a no-op and must not rewrite
    before = open(values_path).read()
    upgrade_package(chart_dir, "redis")
    assert open(values_path).read() == before


def test_cli_update_packages_unknown_name_errors(tmp_path, monkeypatch):
    from devspace_tpu.cli.main import main as cli_main

    proj = tmp_path / "proj"
    proj.mkdir()
    monkeypatch.chdir(proj)
    monkeypatch.setenv("DEVSPACE_FAKE_BACKEND", str(tmp_path / "cluster"))
    monkeypatch.setenv("DEVSPACE_NONINTERACTIVE", "1")
    write_file(str(proj / "app.py"), "print('x')\n")
    logutil.set_logger(logutil.StdoutLogger())
    assert cli_main(["init", "--language", "python"]) == 0
    assert cli_main(["update", "packages", "nosuch"]) == 1


def test_lint_persistence_checks():
    """PVC/volume lint layer (VERDICT r3 next #5): bad storage
    quantities, unknown access modes, mounts of undeclared volumes and
    nameless claim templates must all be flagged; a well-formed
    stateful pair passes."""
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": "data"},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": "5Gi"}},
        },
    }
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": "db"},
        "spec": {
            "serviceName": "db",
            "selector": {"matchLabels": {"app": "db"}},
            "template": {
                "metadata": {"labels": {"app": "db"}},
                "spec": {
                    "containers": [
                        {
                            "name": "db",
                            "image": "mysql:8.0",
                            "volumeMounts": [
                                {"name": "dbdata", "mountPath": "/var/lib"}
                            ],
                        }
                    ]
                },
            },
            "volumeClaimTemplates": [
                {
                    "metadata": {"name": "dbdata"},
                    "spec": {
                        "accessModes": ["ReadWriteOnce"],
                        "resources": {"requests": {"storage": "500Mi"}},
                    },
                }
            ],
        },
    }
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "db"},
        "spec": {"clusterIP": "None", "selector": {"app": "db"}},
    }
    assert validate_manifests([pvc, sts, svc]) == []

    import copy

    bad_qty = copy.deepcopy(pvc)
    bad_qty["spec"]["resources"]["requests"]["storage"] = "five gigs"
    assert any("not a k8s quantity" in i for i in validate_manifests([bad_qty]))

    no_storage = copy.deepcopy(pvc)
    del no_storage["spec"]["resources"]
    assert any(
        "no resources.requests.storage" in i
        for i in validate_manifests([no_storage])
    )

    bad_mode = copy.deepcopy(pvc)
    bad_mode["spec"]["accessModes"] = ["ReadWriteSometimes"]
    assert any("unknown accessMode" in i for i in validate_manifests([bad_mode]))

    ghost_mount = copy.deepcopy(sts)
    ghost_mount["spec"]["template"]["spec"]["containers"][0]["volumeMounts"] = [
        {"name": "nope", "mountPath": "/x"}
    ]
    assert any(
        "mounts undeclared volume 'nope'" in i
        for i in validate_manifests([ghost_mount, svc])
    )

    nameless = copy.deepcopy(sts)
    del nameless["spec"]["volumeClaimTemplates"][0]["metadata"]["name"]
    issues = validate_manifests([nameless, svc])
    assert any("missing metadata.name" in i for i in issues)


def test_chart_for_each_and_persistence_derivation(tmp_path):
    """Chart engine: x-devspace-for-each expands one doc per list item
    (dropping the doc on an empty list), and persistence.volumes derives
    claims/attach/claimTemplates."""
    import yaml as _yaml

    from devspace_tpu.deploy.chart import ChartError, render_chart

    chart = tmp_path / "chart"
    (chart / "templates").mkdir(parents=True)
    (chart / "chart.yaml").write_text("name: t\nversion: 0.1.0\n")
    (chart / "values.yaml").write_text(
        "persistence:\n  volumes: []\n  mounts: []\n"
    )
    (chart / "templates" / "volumes.yaml").write_text(
        "x-devspace-for-each: values.persistence.claims\n"
        "apiVersion: v1\nkind: PersistentVolumeClaim\n"
        "metadata:\n  name: ${{ item.name }}\n"
        "spec: ${{ item.spec }}\n"
    )
    (chart / "templates" / "cm.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: cm\n"
    )
    # empty volumes: the for-each doc renders nothing
    ms = render_chart(str(chart), "r", "default")
    assert [m["kind"] for m in ms] == ["ConfigMap"]
    # two volumes: two PVCs, storageClass only where given
    ms = render_chart(
        str(chart),
        "r",
        "default",
        values={
            "persistence": {
                "volumes": [
                    {"name": "a", "size": "1Gi", "storageClass": "fast"},
                    {"name": "b", "size": "2Gi"},
                ]
            }
        },
    )
    pvcs = {m["metadata"]["name"]: m for m in ms if m["kind"] != "ConfigMap"}
    assert set(pvcs) == {"a", "b"}
    assert pvcs["a"]["spec"]["storageClassName"] == "fast"
    assert "storageClassName" not in pvcs["b"]["spec"]
    assert pvcs["b"]["spec"]["resources"]["requests"]["storage"] == "2Gi"
    # a non-list for-each target is a chart error
    (chart / "templates" / "volumes.yaml").write_text(
        "x-devspace-for-each: values.port\n"
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: x\n"
    )
    with pytest.raises(ChartError, match="not a list"):
        render_chart(str(chart), "r", "default", values={"port": 8080})
    # malformed volume entry
    with pytest.raises(ChartError, match="name\\+size"):
        render_chart(
            str(chart),
            "r",
            "default",
            values={"persistence": {"volumes": [{"name": "x"}], "mounts": []}},
        )


def test_lint_accepts_subdomain_names_and_bad_replicas():
    """Dotted DNS-1123 subdomain names (CRDs!) are valid; non-integer
    replicas must be a lint issue, not a crash."""
    crd = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "certificates.cert-manager.io"},
    }
    assert validate_manifests([crd]) == []
    assert any(
        "not DNS-1123" in i
        for i in validate_manifests(
            [{"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": "Bad..x"}}]
        )
    )
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": "s"},
        "spec": {
            "replicas": "bogus",
            "serviceName": "s",
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "w",
                            "image": "i",
                            "env": [{"name": "TPU_WORKER_ID", "value": "0"}],
                        }
                    ]
                }
            },
        },
    }
    issues = lint_tpu_consistency([sts], TPUConfig(workers=2))
    assert any("replicas is not an integer" in i for i in issues)


def test_version_key_prerelease_below_release():
    """1.2.3-rc1 must sort BELOW 1.2.3 (update packages must never offer
    a pre-release as an upgrade over the vendored stable)."""
    from devspace_tpu.deploy.packages import _version_key

    assert _version_key("1.2.3-rc1") < _version_key("1.2.3")
    assert _version_key("1.2.3") < _version_key("1.2.4-alpha")
    assert _version_key("1.2.3-alpha") < _version_key("1.2.3-rc1")
    assert _version_key("2.0.0") > _version_key("1.9.9")


def test_semver_caret_zero_precision():
    """Masterminds ^ semantics at 0.x depend on constraint precision."""
    from devspace_tpu.deploy.gotemplate import _semver_compare

    assert _semver_compare("^0.0", "0.0.5") is True
    assert _semver_compare("^0.0", "0.1.0") is False
    assert _semver_compare("^0", "0.9.7") is True
    assert _semver_compare("^0", "1.0.0") is False
    assert _semver_compare("^0.0.3", "0.0.3") is True
    assert _semver_compare("^0.0.3", "0.0.4") is False
    assert _semver_compare("^0.2.3", "0.2.9") is True
    assert _semver_compare("^0.2.3", "0.3.0") is False


def test_autoscaling_derivation_renders_hpa_and_lints():
    """HPA parity (reference examples' pod-autoscaling.yaml): the
    generator chart's autoscaling values render an autoscaling/v2 HPA,
    gated the reference's way (maxReplicas must EXCEED replicas), and
    the release passes lint including the HPA checks."""
    from devspace_tpu.deploy.chart import render_chart

    cpu_chart = os.path.join(
        os.path.dirname(__file__), "..", "devspace_tpu", "generator",
        "templates", "chart-cpu",
    )

    def render(values):
        return render_chart(
            cpu_chart, release_name="web", namespace="default", values=values
        )

    hpas = [
        m for m in render({"replicas": 2})
        if m["kind"] == "HorizontalPodAutoscaler"
    ]
    assert hpas == [], "no autoscaling values -> no HPA"
    hpas = [
        m
        for m in render(
            {
                "replicas": 2,
                "autoscaling": {
                    "horizontal": {"maxReplicas": 2, "averageCPU": 80}
                },
            }
        )
        if m["kind"] == "HorizontalPodAutoscaler"
    ]
    assert hpas == [], "maxReplicas <= replicas must gate the HPA off"
    from devspace_tpu.deploy.chart import ChartError

    with pytest.raises(ChartError, match="needs maxReplicas"):
        render({"autoscaling": {"horizontal": {"averageCPU": 80}}})
    with pytest.raises(ChartError, match="needs averageCPU"):
        render({"autoscaling": {"horizontal": {"maxReplicas": 4}}})
    ms = render(
        {
            "replicas": 2,
            "autoscaling": {
                "horizontal": {
                    "maxReplicas": 6,
                    "averageCPU": 75,
                    "averageMemory": "512Mi",
                }
            },
        }
    )
    hpa = next(m for m in ms if m["kind"] == "HorizontalPodAutoscaler")
    assert hpa["apiVersion"] == "autoscaling/v2"
    assert hpa["spec"]["scaleTargetRef"] == {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "name": "web",
    }
    assert hpa["spec"]["minReplicas"] == 2
    assert hpa["spec"]["maxReplicas"] == 6
    by_name = {m["resource"]["name"]: m["resource"] for m in hpa["spec"]["metrics"]}
    assert by_name["cpu"]["target"] == {
        "type": "Utilization",
        "averageUtilization": 75,
    }
    assert by_name["memory"]["target"] == {
        "type": "AverageValue",
        "averageValue": "512Mi",
    }
    assert validate_manifests(ms) == []


def test_lint_hpa_structural_checks():
    base = {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
    }
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "web"},
        "spec": {
            "template": {
                "spec": {"containers": [{"name": "m", "image": "x:y"}]}
            }
        },
    }
    good = {
        **base,
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1", "kind": "Deployment", "name": "web",
            },
            "minReplicas": 1,
            "maxReplicas": 4,
            "metrics": [{"type": "Resource"}],
        },
    }
    assert validate_manifests([dep, good]) == []
    dangling = {
        **base,
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": "ghost"},
            "maxReplicas": 4,
            "metrics": [{"type": "Resource"}],
        },
    }
    issues = validate_manifests([dep, dangling])
    assert any("not among the rendered objects" in i for i in issues)
    inverted = {
        **base,
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": "web"},
            "minReplicas": 5,
            "maxReplicas": 2,
            "metrics": [{"type": "Resource"}],
        },
    }
    issues = validate_manifests([dep, inverted])
    assert any("minReplicas 5 > maxReplicas 2" in i for i in issues)
    metricless = {
        **base,
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": "web"},
            "maxReplicas": 4,
        },
    }
    issues = validate_manifests([dep, metricless])
    assert any("no metrics" in i for i in issues)
    stringy = {
        **base,
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": "web"},
            "minReplicas": "2",
            "maxReplicas": 4,
            "metrics": [{"type": "Resource"}],
        },
    }
    issues = validate_manifests([dep, stringy])
    assert any("minReplicas must be an integer" in i for i in issues)


def test_lint_hpa_rejects_multihost_slice_target():
    """TPU-first autoscaling semantics: a multi-host slice's worker count
    is topology (static TPU_WORKER_HOSTNAMES roster) — an HPA pointing
    at it must be flagged; a single-host slice workload may scale (each
    replica is an independent server on its own TPU host)."""
    def slice_sts(workers):
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": "srv"},
            "spec": {
                "serviceName": "srv",
                "replicas": workers,
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": "m",
                                "image": "x:y",
                                "resources": {"limits": {"google.com/tpu": 4}},
                                "env": [
                                    {"name": "TPU_WORKER_ID", "value": "0"},
                                    {
                                        "name": "TPU_WORKER_HOSTNAMES",
                                        "value": ",".join(
                                            f"srv-{i}.srv" for i in range(workers)
                                        ),
                                    },
                                    {
                                        "name": "JAX_COORDINATOR_ADDRESS",
                                        "value": "srv-0.srv:8476",
                                    },
                                ],
                            }
                        ]
                    }
                },
            },
        }

    hpa = {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "srv"},
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1", "kind": "StatefulSet", "name": "srv",
            },
            "maxReplicas": 8,
            "metrics": [{"type": "Resource"}],
        },
    }
    multi = TPUConfig(workers=2, chips_per_worker=4)
    issues = lint_tpu_consistency([slice_sts(2), hpa], multi)
    assert any("topology, not load" in i for i in issues)
    single = TPUConfig(workers=1, chips_per_worker=4)
    issues = lint_tpu_consistency([slice_sts(1), hpa], single)
    assert not any("topology, not load" in i for i in issues)


def test_autoscaling_null_override_disables_cleanly():
    """`autoscaling: null` — the standard disable-override idiom — must
    render with no HPA, not crash the for-each lookup."""
    from devspace_tpu.deploy.chart import render_chart

    example = os.path.join(
        os.path.dirname(__file__), "..", "examples", "kaniko", "chart"
    )
    ms = render_chart(
        example, release_name="k", namespace="default",
        values={"image": "x:y", "autoscaling": None},
        extra_context={"images": {}, "pullSecrets": [], "tpu": {}},
    )
    assert not [m for m in ms if m["kind"] == "HorizontalPodAutoscaler"]


def test_autoscaling_metric_errors_surface_even_when_gated_off():
    """A bad averageCPU must fail at authoring time even while the
    maxReplicas gate keeps the HPA un-rendered."""
    from devspace_tpu.deploy.chart import ChartError, render_chart

    cpu_chart = os.path.join(
        os.path.dirname(__file__), "..", "devspace_tpu", "generator",
        "templates", "chart-cpu",
    )
    with pytest.raises(ChartError, match="averageCPU must be an integer"):
        render_chart(
            cpu_chart, release_name="w", namespace="default",
            values={
                "replicas": 2,
                "autoscaling": {
                    "horizontal": {"maxReplicas": 2, "averageCPU": "eighty"}
                },
            },
        )
    # gated-off WITHOUT metrics is the lower-maxReplicas disable idiom —
    # it must render cleanly (metrics absence only matters when the gate
    # is on); raising it only when an HPA would render keeps old values
    # files working
    ms = render_chart(
        cpu_chart, release_name="w", namespace="default",
        values={
            "replicas": 2,
            "autoscaling": {"horizontal": {"maxReplicas": 2}},
        },
    )
    assert not [m for m in ms if m["kind"] == "HorizontalPodAutoscaler"]


def test_render_refuses_hpa_on_multihost_slice():
    """The chart-tpu HPA + a multi-host slice must fail AT RENDER TIME
    (deploy performs no lint): an HPA would shrink the slice below its
    static TPU_WORKER_HOSTNAMES roster. Single-host renders fine."""
    from devspace_tpu.deploy.chart import ChartError, render_chart

    tpu_chart = os.path.join(
        os.path.dirname(__file__), "..", "devspace_tpu", "generator",
        "templates", "chart-tpu",
    )

    def ctx(workers):
        hosts = ",".join(f"t-{i}.t" for i in range(workers))
        return {
            "images": {},
            "pullSecrets": [],
            "tpu": {
                "accelerator": "v5litepod-8",
                "topology": "2x4",
                "workers": workers,
                "chipsPerWorker": 4,
                "runtimeVersion": "",
                "workerHostnames": hosts,
                "coordinatorAddress": "t-0.t:8476",
            },
        }

    vals = {
        "image": "x:y",
        "autoscaling": {"horizontal": {"maxReplicas": 5, "averageCPU": 80}},
    }
    with pytest.raises(ChartError, match="topology, not load"):
        render_chart(
            tpu_chart, release_name="t", namespace="default",
            values=vals, extra_context=ctx(2),
        )
    ms = render_chart(
        tpu_chart, release_name="t", namespace="default",
        values=vals, extra_context=ctx(1),
    )
    assert any(m["kind"] == "HorizontalPodAutoscaler" for m in ms)


def test_render_hpa_check_scans_init_containers():
    """ADVICE r5: a workload wiring TPU_WORKER_HOSTNAMES via an INIT
    container is the same multi-host slice — the render-time HPA hard
    error must fire for it too, not only for spec.template.spec
    .containers."""
    from devspace_tpu.deploy.chart import (
        ChartError,
        _check_hpa_slice_conflict,
    )

    def sts(workers, via_init):
        env = [
            {
                "name": "TPU_WORKER_HOSTNAMES",
                "value": ",".join(f"s-{i}.s" for i in range(workers)),
            }
        ]
        container = {"name": "m", "image": "x:y", "env": env}
        pod = (
            {"initContainers": [container], "containers": [{"name": "m"}]}
            if via_init
            else {"containers": [container]}
        )
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": "s"},
            "spec": {"replicas": workers, "template": {"spec": pod}},
        }

    hpa = {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "s"},
        "spec": {
            "scaleTargetRef": {"kind": "StatefulSet", "name": "s"},
            "maxReplicas": 8,
            "metrics": [{"type": "Resource"}],
        },
    }
    with pytest.raises(ChartError, match="topology, not load"):
        _check_hpa_slice_conflict([sts(2, via_init=True), hpa])
    # parity with the containers path, and single-host stays scalable
    with pytest.raises(ChartError, match="topology, not load"):
        _check_hpa_slice_conflict([sts(2, via_init=False), hpa])
    _check_hpa_slice_conflict([sts(1, via_init=True), hpa])


def test_lint_accepts_autoscaling_v1_hpa():
    """autoscaling/v1 HPAs (vendored upstream charts) scale via
    targetCPUUtilizationPercentage and have no metrics list — lint must
    not flag them."""
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "web"},
        "spec": {
            "template": {
                "spec": {"containers": [{"name": "m", "image": "x:y"}]}
            }
        },
    }
    v1 = {
        "apiVersion": "autoscaling/v1",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1", "kind": "Deployment", "name": "web",
            },
            "minReplicas": 1,
            "maxReplicas": 3,
            "targetCPUUtilizationPercentage": 80,
        },
    }
    assert validate_manifests([dep, v1]) == []
