from devspace_tpu.utils.ignoreutil import IgnoreMatcher


def test_basic_patterns():
    m = IgnoreMatcher(["*.log", "node_modules/", "/build", "# comment", ""])
    assert m.matches("foo.log")
    assert m.matches("sub/dir/foo.log")
    assert not m.matches("foo.log.txt")
    assert m.matches("node_modules", is_dir=True)
    assert m.matches("node_modules/pkg/index.js")
    assert not m.matches("node_modules")  # dir-only rule, leaf is a file
    assert m.matches("build", is_dir=True)
    assert m.matches("build/out.bin")
    assert not m.matches("src/build/out.bin")  # anchored


def test_negation_last_match_wins():
    m = IgnoreMatcher(["*.log", "!keep.log"])
    assert m.matches("debug.log")
    assert not m.matches("keep.log")
    m2 = IgnoreMatcher(["!keep.log", "*.log"])
    assert m2.matches("keep.log")


def test_doublestar():
    m = IgnoreMatcher(["**/__pycache__/", "docs/**/*.tmp", "a/**"])
    assert m.matches("__pycache__", is_dir=True)
    assert m.matches("x/y/__pycache__", is_dir=True)
    assert m.matches("x/__pycache__/mod.pyc")
    assert m.matches("docs/a/b/file.tmp")
    assert not m.matches("docs/file.tmp2")
    assert m.matches("docs/x.tmp")  # ** matches zero dirs
    assert m.matches("a/anything/below")


def test_question_and_class():
    m = IgnoreMatcher(["file?.txt", "data[0-9].csv"])
    assert m.matches("file1.txt")
    assert not m.matches("file12.txt")
    assert m.matches("data5.csv")
    assert not m.matches("dataX.csv")


def test_everything_under_match():
    m = IgnoreMatcher([".git"])
    assert m.matches(".git", is_dir=True)
    assert m.matches(".git/objects/ab/cd")
