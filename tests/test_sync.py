"""Sync engine integration tests against the fake local backend.

Mirrors the reference's strategy (sync/sync_config_test.go: TestInitialSync /
TestNormalSync build local+remote temp trees, run the real pipes, and
poll-assert convergence) — generalized to N fake slice workers per SURVEY §4.
"""

import os
import time

import pytest

from devspace_tpu.kube.fake import FakeCluster
from devspace_tpu.sync.session import SyncOptions, SyncSession, copy_to_container
from devspace_tpu.utils.fsutil import write_file


def wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def cluster(tmp_path):
    fc = FakeCluster(str(tmp_path / "cluster"))
    yield fc


def make_session(tmp_path, cluster, n_workers=2, **opt_kw):
    local = tmp_path / "local"
    local.mkdir(exist_ok=True)
    workers = [
        cluster.add_pod(f"w-{i}", labels={"app": "t"}, worker_id=i)
        for i in range(n_workers)
    ]
    opts = SyncOptions(
        local_path=str(local),
        container_path="/app",
        upstream_quiet=0.15,
        upstream_tick=0.05,
        downstream_interval=0.15,
        **opt_kw,
    )
    session = SyncSession(cluster, workers, opts)
    return session, local, workers


def remote_path(cluster, worker, rel):
    return os.path.join(cluster.translate_path(worker, "/app"), rel)


def test_initial_sync_converges(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    now = time.time()
    # local-only file
    write_file(str(local / "local_only.txt"), "local")
    write_file(str(local / "sub" / "nested.txt"), "nested")
    # remote-only file on worker 0
    w0 = cluster.translate_path(workers[0], "/app")
    write_file(os.path.join(w0, "remote_only.txt"), "remote")
    # conflict: remote newer
    write_file(str(local / "conflict_remote_newer.txt"), "old local")
    os.utime(str(local / "conflict_remote_newer.txt"), (now - 100, now - 100))
    write_file(os.path.join(w0, "conflict_remote_newer.txt"), "new remote")
    # conflict: local newer
    write_file(str(local / "conflict_local_newer.txt"), "new local")
    write_file(os.path.join(w0, "conflict_local_newer.txt"), "old remote")
    os.utime(
        os.path.join(w0, "conflict_local_newer.txt"), (now - 100, now - 100)
    )
    session.start()
    try:
        # both sides converge; all workers mirror local
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "local_only.txt")),
                msg="upload fan-out",
            )
            assert (
                open(remote_path(cluster, w, "sub/nested.txt")).read() == "nested"
            )
            assert (
                open(remote_path(cluster, w, "conflict_local_newer.txt")).read()
                == "new local"
            )
        assert (local / "remote_only.txt").read_text() == "remote"
        assert (local / "conflict_remote_newer.txt").read_text() == "new remote"
    finally:
        session.stop()
    assert session.error is None


def test_upstream_create_modify_delete(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=3)
    session.start()
    try:
        write_file(str(local / "new.py"), "print(1)")
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "new.py")),
                msg="create propagated",
            )
        # modify (bump mtime so the 1s-resolution protocol sees it)
        write_file(str(local / "new.py"), "print(2)")
        future = time.time() + 2
        os.utime(str(local / "new.py"), (future, future))
        for w in workers:
            wait_for(
                lambda w=w: open(remote_path(cluster, w, "new.py")).read()
                == "print(2)",
                msg="modify propagated",
            )
        # delete
        os.unlink(str(local / "new.py"))
        for w in workers:
            wait_for(
                lambda w=w: not os.path.exists(remote_path(cluster, w, "new.py")),
                msg="delete propagated",
            )
        # new directory tree
        write_file(str(local / "pkg" / "deep" / "mod.py"), "x = 1")
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(
                    remote_path(cluster, w, "pkg/deep/mod.py")
                ),
                msg="dir tree propagated",
            )
    finally:
        session.stop()
    assert session.error is None


def test_downstream_create_modify_delete(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    write_file(str(local / "existing.txt"), "v1")
    session.start()
    try:
        w0 = cluster.translate_path(workers[0], "/app")
        wait_for(lambda: os.path.exists(os.path.join(w0, "existing.txt")))
        # remote create
        write_file(os.path.join(w0, "made_remote.txt"), "hello")
        wait_for(
            lambda: (local / "made_remote.txt").exists(), msg="remote create"
        )
        # ...mirrored to worker 1
        wait_for(
            lambda: os.path.exists(remote_path(cluster, workers[1], "made_remote.txt")),
            msg="mirror to w1",
        )
        # remote modify (newer mtime)
        future = time.time() + 2
        write_file(os.path.join(w0, "existing.txt"), "v2-remote")
        os.utime(os.path.join(w0, "existing.txt"), (future, future))
        wait_for(
            lambda: (local / "existing.txt").read_text() == "v2-remote",
            msg="remote modify",
        )
        # remote delete propagates after stable polls + triple check
        os.unlink(os.path.join(w0, "made_remote.txt"))
        wait_for(
            lambda: not (local / "made_remote.txt").exists(), msg="remote delete"
        )
    finally:
        session.stop()
    assert session.error is None


def test_exclude_rules(tmp_path, cluster):
    session, local, workers = make_session(
        tmp_path,
        cluster,
        n_workers=1,
        exclude_paths=["ignored/"],
        upload_exclude_paths=["*.secret"],
        download_exclude_paths=["logs/"],
    )
    write_file(str(local / "ignored" / "junk.txt"), "x")
    write_file(str(local / "creds.secret"), "shh")
    write_file(str(local / "normal.txt"), "ok")
    w0 = cluster.translate_path(workers[0], "/app")
    write_file(os.path.join(w0, "logs", "app.log"), "remote log")
    session.start()
    try:
        wait_for(lambda: os.path.exists(os.path.join(w0, "normal.txt")))
        time.sleep(1.0)  # give wrong behavior a chance to manifest
        assert not os.path.exists(os.path.join(w0, "ignored/junk.txt"))
        assert not os.path.exists(os.path.join(w0, "creds.secret"))
        assert not (local / "logs").exists()
    finally:
        session.stop()
    assert session.error is None


def test_local_newer_not_clobbered_by_downstream(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=1)
    session.start()
    try:
        w0 = cluster.translate_path(workers[0], "/app")
        # A remote file appears, but the local copy is newer.
        write_file(str(local / "hot.py"), "local newest")
        future = time.time() + 5
        os.utime(str(local / "hot.py"), (future, future))
        write_file(os.path.join(w0, "hot.py"), "remote stale")
        past = time.time() - 100
        os.utime(os.path.join(w0, "hot.py"), (past, past))
        # downstream must NOT overwrite; upstream pushes local over it
        wait_for(
            lambda: open(os.path.join(w0, "hot.py")).read() == "local newest",
            msg="upstream wins",
        )
        assert (local / "hot.py").read_text() == "local newest"
    finally:
        session.stop()
    assert session.error is None


def test_copy_to_container_one_shot(tmp_path, cluster):
    local = tmp_path / "ctx"
    write_file(str(local / "Dockerfile"), "FROM scratch")
    write_file(str(local / "src" / "main.py"), "pass")
    worker = cluster.add_pod("builder")
    n = copy_to_container(cluster, worker, str(local), "/workspace")
    assert n == 3
    root = cluster.translate_path(worker, "/workspace")
    assert open(os.path.join(root, "Dockerfile")).read() == "FROM scratch"
    assert open(os.path.join(root, "src/main.py")).read() == "pass"


def test_rename_propagates(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    write_file(str(local / "old_name.txt"), "data")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "old_name.txt"))
            )
        os.rename(str(local / "old_name.txt"), str(local / "new_name.txt"))
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "new_name.txt"))
                and not os.path.exists(remote_path(cluster, w, "old_name.txt")),
                msg="rename",
            )
    finally:
        session.stop()
    assert session.error is None


def test_rate_limiter_smaller_than_chunk():
    """A limit below the 64KiB chunk size must drain incrementally, not hang."""
    from devspace_tpu.sync.shell import RateLimiter

    rl = RateLimiter(50)  # 50 KB/s < 64 KiB chunk
    t0 = time.monotonic()
    rl.throttle(65536)  # first chunk partially pre-paid by initial allowance
    rl.throttle(65536)
    elapsed = time.monotonic() - t0
    assert 1.0 < elapsed < 10.0  # ~1.3-2.6s expected; must terminate


def test_remote_dir_delete_spares_local_edits(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=1)
    write_file(str(local / "d" / "f.txt"), "v1")
    session.start()
    try:
        w0 = cluster.translate_path(workers[0], "/app")
        wait_for(lambda: os.path.exists(os.path.join(w0, "d/f.txt")))
        # pause upstream by editing right before remote delete
        import shutil

        shutil.rmtree(os.path.join(w0, "d"))
        write_file(str(local / "d" / "f.txt"), "v2-local-edit-longer")
        fut = time.time() + 5
        os.utime(str(local / "d" / "f.txt"), (fut, fut))
        # eventually upstream re-uploads the edited file; it must never be lost
        wait_for(
            lambda: os.path.exists(os.path.join(w0, "d/f.txt"))
            and open(os.path.join(w0, "d/f.txt")).read() == "v2-local-edit-longer",
            msg="local edit survives remote dir delete",
        )
        assert (local / "d" / "f.txt").read_text() == "v2-local-edit-longer"
    finally:
        session.stop()


def test_dropped_worker_does_not_kill_session(tmp_path, cluster, monkeypatch):
    """Graded partial-failure semantics (SURVEY §7 hard part #2): after a
    non-authoritative worker is permanently dropped from the fan-out,
    removes, uploads and downstream mirrors must keep flowing to the
    surviving workers instead of raising through the dead worker's closed
    shell and tearing the session down."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=3)
    write_file(str(local / "keep.txt"), "v1")
    write_file(str(local / "doomed.txt"), "bye")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "doomed.txt")),
                msg="initial fan-out",
            )
        # Permanently lose worker 2: mark it failed and make any revive
        # attempt (a fresh exec) fail like a deleted pod would.
        real_exec = cluster.exec_stream

        def exec_stream(pod, *a, **kw):
            name = getattr(pod, "name", pod)
            if name == workers[2].name:
                raise RuntimeError("pod gone")
            return real_exec(pod, *a, **kw)

        monkeypatch.setattr(cluster, "exec_stream", exec_stream)
        session._mark_worker_failed(2, RuntimeError("pod gone"))

        # upstream remove must fan out to survivors without dying
        os.unlink(str(local / "doomed.txt"))
        for w in workers[:2]:
            wait_for(
                lambda w=w: not os.path.exists(remote_path(cluster, w, "doomed.txt")),
                msg="remove on survivors",
            )
        # downstream change on worker 0 must still mirror to worker 1
        w0 = cluster.translate_path(workers[0], "/app")
        write_file(os.path.join(w0, "from_remote.txt"), "hello")
        wait_for(
            lambda: (local / "from_remote.txt").exists(),
            msg="download from authority",
        )
        wait_for(
            lambda: os.path.exists(remote_path(cluster, workers[1], "from_remote.txt")),
            msg="mirror to surviving worker",
        )
        # upstream create still reaches survivors
        write_file(str(local / "late.txt"), "late")
        for w in workers[:2]:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "late.txt")),
                msg="upload to survivors",
            )
        assert session.error is None
        assert 2 in session.worker_errors
    finally:
        session.stop()
    assert session.error is None


def test_worker_shell_revive_after_exec_death(tmp_path, cluster):
    """A worker whose exec shell dies (container restart) must be revived
    on the next fan-out: fresh shell + index catch-up, no session error
    (SURVEY §7 hard part #2; reference has no equivalent — single pod is
    all-or-nothing, sync_config.go:439)."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=3)
    write_file(str(local / "base.txt"), "v1")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "base.txt")),
                msg="initial fan-out",
            )
        # Simulate container restart: kill worker 1's upstream shell out
        # from under the session (the pod itself stays exec-able).
        session._shells[1].close()
        # While it's dead, change a file so catch-up has work to do.
        write_file(str(local / "base.txt"), "v2-after-restart")
        write_file(str(local / "fresh.txt"), "new")
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "fresh.txt"))
                and open(remote_path(cluster, w, "base.txt")).read()
                == "v2-after-restart",
                msg=f"revive catch-up on {w.name}",
            )
        assert session.error is None
        assert 1 not in session.worker_errors
    finally:
        session.stop()
    assert session.error is None


def test_authority_worker_loss_is_fatal(tmp_path, cluster, monkeypatch):
    """Worker 0 is the downstream authority: losing it permanently must
    stop the session with an error (graded semantics stop at the
    authority — there is no one left to define remote truth)."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    write_file(str(local / "a.txt"), "1")
    session.start()
    try:
        wait_for(
            lambda: os.path.exists(remote_path(cluster, workers[0], "a.txt")),
            msg="initial sync",
        )
        real_exec = cluster.exec_stream

        def exec_stream(pod, *a, **kw):
            if getattr(pod, "name", pod) == workers[0].name:
                raise RuntimeError("authority gone")
            return real_exec(pod, *a, **kw)

        monkeypatch.setattr(cluster, "exec_stream", exec_stream)
        session._shells[0].close()
        write_file(str(local / "b.txt"), "2")
        wait_for(lambda: session.error is not None, msg="fatal session error")
        assert "worker 0" in str(session.error)
    finally:
        session.stop()


def test_all_workers_lost_is_fatal(tmp_path, cluster, monkeypatch):
    """Losing EVERY worker permanently must stop the session with an error
    (pins the bottom of the graded-failure ladder: mirror lost -> continue;
    worker 0 or all lost -> fatal)."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    write_file(str(local / "a.txt"), "1")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "a.txt")),
                msg="initial fan-out",
            )
        # Every pod vanishes: all shells die and no revive can succeed.
        monkeypatch.setattr(
            cluster,
            "exec_stream",
            lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("slice gone")),
        )
        for shell in list(session._shells):
            shell.close()
        write_file(str(local / "b.txt"), "2")
        wait_for(lambda: session.error is not None, msg="fatal session error")
        # worker 0 is among the lost, so the authority message wins
        assert "worker 0" in str(session.error) or "every worker" in str(
            session.error
        )
        assert session._stopped.is_set()
    finally:
        session.stop()


def test_concurrent_bidirectional_stress(tmp_path, cluster):
    """Many files changing on both sides at once must converge with no
    lost updates (reference test matrix analogue: TestNormalSync's
    create/modify/rename matrix, run concurrently)."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    session.start()
    w0 = cluster.translate_path(workers[0], "/app")
    n = 25
    try:
        future = time.time() + 5
        for i in range(n):
            write_file(str(local / f"up_{i}.txt"), f"local {i}")
            write_file(os.path.join(w0, f"down_{i}.txt"), f"remote {i}")
            os.utime(os.path.join(w0, f"down_{i}.txt"), (future, future))

        def converged():
            for i in range(n):
                for w in workers:
                    if not os.path.exists(remote_path(cluster, w, f"up_{i}.txt")):
                        return False
                if not (local / f"down_{i}.txt").exists():
                    return False
                if not os.path.exists(remote_path(cluster, workers[1], f"down_{i}.txt")):
                    return False
            return True

        wait_for(converged, timeout=30, msg="bidirectional convergence")
        for i in range(n):
            assert (local / f"down_{i}.txt").read_text() == f"remote {i}"
            assert (
                open(remote_path(cluster, workers[1], f"up_{i}.txt")).read()
                == f"local {i}"
            )
        assert session.error is None
    finally:
        session.stop()


def test_file_index_thread_safety():
    """Hammer the shared FileIndex from concurrent writers/readers —
    the TPU-build analogue of the reference's `go test -race` discipline
    over fileMapMutex (SURVEY §5.2)."""
    import threading

    from devspace_tpu.sync.file_info import FileInformation
    from devspace_tpu.sync.index import FileIndex

    index = FileIndex()
    errors = []

    def writer(tid: int):
        try:
            for i in range(300):
                info = FileInformation(
                    name=f"t{tid}/f{i}", size=i, mtime=i, is_directory=False
                )
                index.set(info)
                if i % 3 == 0:
                    index.remove(f"t{tid}/f{i}")
                _ = index.get(f"t{tid}/f{i}")
                if i % 50 == 0:
                    index.transact(lambda m: m.update({}))
                    _ = len(index)
                    _ = index.snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # every thread left exactly the non-multiple-of-3 files, plus the
    # auto-created parent-dir entry per thread (CreateDirInFileMap
    # analogue, reference: sync/file_index.go)
    expect_per_thread = len([i for i in range(300) if i % 3 != 0])
    assert len(index) == 8 * expect_per_thread + 8


def test_drift_detection_repairs_corrupted_worker(tmp_path, cluster):
    """VERDICT round-1 next #5: a non-authoritative worker whose tree
    diverges WITHOUT its shell dying (in-container rm / rogue write) is
    detected by the verify loop, repaired, and reported."""
    session, local, workers = make_session(
        tmp_path, cluster, n_workers=3, verify_interval=0.2
    )
    write_file(str(local / "train.py"), "x = 1\n")
    write_file(str(local / "lib" / "util.py"), "y = 2\n")
    session.start()
    try:
        w2 = cluster.translate_path(workers[2], "/app")
        wait_for(
            lambda: os.path.exists(os.path.join(w2, "lib", "util.py")),
            msg="initial mirror to worker 2",
        )
        # corrupt worker 2 in-container: delete a synced file, alter
        # another, and drop a rogue file — all without touching the shell
        os.unlink(os.path.join(w2, "train.py"))
        write_file(os.path.join(w2, "lib", "util.py"), "corrupted")
        write_file(os.path.join(w2, "rogue.txt"), "not ours")
        wait_for(
            lambda: (
                os.path.exists(os.path.join(w2, "train.py"))
                and open(os.path.join(w2, "lib", "util.py")).read() == "y = 2\n"
                and not os.path.exists(os.path.join(w2, "rogue.txt"))
            ),
            timeout=10,
            msg="worker 2 repaired",
        )
        # reported: per-worker repair count + session stats
        health = {h["worker"]: h for h in session.worker_health()}
        assert health["w-2"]["state"] == "mirror"
        assert health["w-2"]["repairs"] >= 3
        assert session.stats["repaired"] >= 3
        assert health["w-0"]["state"] == "authority"
        # worker 0 (authority) must never be "repaired" by the verifier:
        # its divergence is the downstream's business
        assert health["w-0"]["repairs"] == 0
        # other workers untouched
        w1 = cluster.translate_path(workers[1], "/app")
        assert open(os.path.join(w1, "train.py")).read() == "x = 1\n"
    finally:
        session.stop()


def test_status_file_published_with_worker_health(tmp_path, cluster):
    status_path = str(tmp_path / "logs" / "sync-status.json")
    session, local, workers = make_session(
        tmp_path, cluster, n_workers=2, verify_interval=0.2,
        status_path=status_path,
    )
    write_file(str(local / "a.txt"), "a")
    session.start()
    try:
        import json

        def published_ok():
            try:
                with open(status_path) as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                return False
            st = next(iter(data.values()), None)
            return bool(st and st["workers"] and st["stats"]["uploaded"] >= 0)

        wait_for(published_ok, msg="status file published")
        with open(status_path) as fh:
            st = next(iter(json.load(fh).values()))
        states = {w["worker"]: w["state"] for w in st["workers"]}
        assert states == {"w-0": "authority", "w-1": "mirror"}
        assert st["error"] is None
    finally:
        session.stop()
    # stop publishes a final snapshot (updated_at advances)
    with open(status_path) as fh:
        assert next(iter(json.load(fh).values()))["updated_at"] > 0
