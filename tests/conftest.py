"""Test session setup.

JAX-touching tests run on a virtual 8-device CPU mesh (SURVEY §4: the
reference's fake-backend trick generalized — fake a TPU slice with
``xla_force_host_platform_device_count``). Env must be set before the first
``import jax`` anywhere in the test process.
"""

import os

# Force, don't setdefault: the driver environment pre-sets JAX_PLATFORMS to
# the real TPU platform; tests always run on the virtual CPU slice.
import re
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Force exactly 8 virtual devices, replacing any pre-set count — the tests
# hard-require an 8-way mesh.
flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("DEVSPACE_NONINTERACTIVE", "1")

# The driver image ships a sitecustomize.py that pre-imports jax internals at
# interpreter startup, freezing the platform default before this conftest
# runs — there the env var alone is too late and we must force the platform
# through the config API. On clean environments (no jax modules loaded yet)
# the env vars above suffice and we skip the import cost for non-JAX tests.
if any(m == "jax" or m.startswith(("jax.", "jaxlib")) for m in sys.modules):
    import jax

    jax.config.update("jax_platforms", "cpu")
