"""Test session setup.

JAX-touching tests run on a virtual 8-device CPU mesh (SURVEY §4: the
reference's fake-backend trick generalized — fake a TPU slice with
``xla_force_host_platform_device_count``). Env must be set before the first
``import jax`` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DEVSPACE_NONINTERACTIVE", "1")
