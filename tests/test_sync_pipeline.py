"""Content-addressed, pipelined sync fan-out (ISSUE 4).

Pins the tentpole's three mechanisms — digest gating (touch with unchanged
bytes transfers zero payload), the tar artifact cache (one build per batch
serves every worker), and the bounded pipeline's graded failure semantics
(a worker killed mid-broadcast degrades without wedging the producer) —
plus the RateLimiter lock fix and build_tar's concurrent-writer fix.
"""

import io
import os
import tarfile
import threading
import time

import pytest

import devspace_tpu.sync.session as session_mod
from devspace_tpu.kube.fake import FakeCluster
from devspace_tpu.resilience.chaos import ByteBudgetStream
from devspace_tpu.sync.artifacts import TarArtifactCache, batch_key
from devspace_tpu.sync.file_info import (
    DigestCache,
    FileInformation,
    file_digest,
)
from devspace_tpu.sync.index import FileIndex
from devspace_tpu.sync.shell import RateLimiter, build_tar
from devspace_tpu.sync.session import SyncOptions, SyncSession
from devspace_tpu.utils.fsutil import write_file

def wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def cluster(tmp_path):
    return FakeCluster(str(tmp_path / "cluster"))


def make_session(tmp_path, cluster, n_workers=2, **opt_kw):
    local = tmp_path / "local"
    local.mkdir(exist_ok=True)
    workers = [
        cluster.add_pod(f"w-{i}", labels={"app": "t"}, worker_id=i)
        for i in range(n_workers)
    ]
    opts = SyncOptions(
        local_path=str(local),
        container_path="/app",
        upstream_quiet=0.15,
        upstream_tick=0.05,
        downstream_interval=0.15,
        **opt_kw,
    )
    return SyncSession(cluster, workers, opts), local, workers


def remote_path(cluster, worker, rel):
    return os.path.join(cluster.translate_path(worker, "/app"), rel)


# -- digests ----------------------------------------------------------------
def test_file_digest_and_cache_memoization(tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("hello")
    d1 = file_digest(str(p))
    assert d1 is not None and len(d1) == 32  # blake2b-128 hex
    assert file_digest(str(tmp_path / "missing")) is None

    cache = DigestCache()
    info = FileInformation(name="a.txt", size=5, mtime=int(os.stat(p).st_mtime))
    assert cache.digest(str(tmp_path), info) == d1
    # memo hit: content changed on disk but stat identity unchanged -> the
    # cache answers from the memo (this IS the point: no re-hash per event)
    p.write_text("HELLO")
    os.utime(p, (info.mtime, info.mtime))
    assert cache.digest(str(tmp_path), info) == d1
    # stat change -> re-hash
    info2 = FileInformation(name="a.txt", size=5, mtime=info.mtime + 7)
    os.utime(p, (info2.mtime, info2.mtime))
    assert cache.digest(str(tmp_path), info2) == file_digest(str(p)) != d1


def test_index_preserves_digest_on_statless_reindex():
    idx = FileIndex()
    idx.set(FileInformation(name="a", size=3, mtime=100, digest="d" * 32))
    # digest-less re-index with identical stat (remote snapshot echo)
    idx.set(FileInformation(name="a", size=3, mtime=100))
    assert idx.get("a").digest == "d" * 32
    # stat moved -> stale digest must NOT survive
    idx.set(FileInformation(name="a", size=3, mtime=200))
    assert idx.get("a").digest is None


# -- batch key / artifact cache ---------------------------------------------
def _infos(*specs):
    return [
        FileInformation(name=n, size=s, mtime=m, digest=d)
        for (n, s, m, d) in specs
    ]


def test_batch_key_stability_and_sensitivity():
    a = _infos(("x.py", 3, 100, None), ("y.py", 5, 200, "a" * 32))
    assert batch_key(a) == batch_key(_infos(("x.py", 3, 100, None), ("y.py", 5, 200, "a" * 32)))
    assert batch_key(a) != batch_key(_infos(("x.py", 4, 100, None), ("y.py", 5, 200, "a" * 32)))
    assert batch_key(a) != batch_key(_infos(("x.py", 3, 101, None), ("y.py", 5, 200, "a" * 32)))
    assert batch_key(a) != batch_key(_infos(("x.py", 3, 100, "b" * 32), ("y.py", 5, 200, "a" * 32)))
    # order matters: tar member order is part of the artifact
    assert batch_key(a) != batch_key(list(reversed(a)))


def test_artifact_cache_builds_once_and_evicts_by_bytes(tmp_path):
    write_file(str(tmp_path / "a.txt"), "aaaa")
    write_file(str(tmp_path / "b.txt"), "bbbb")
    st_a = os.stat(tmp_path / "a.txt")
    st_b = os.stat(tmp_path / "b.txt")
    batch_a = [FileInformation(name="a.txt", size=4, mtime=int(st_a.st_mtime))]
    batch_b = [FileInformation(name="b.txt", size=4, mtime=int(st_b.st_mtime))]

    cache = TarArtifactCache()
    t1 = cache.get_or_build(str(tmp_path), batch_a)
    t2 = cache.get_or_build(str(tmp_path), batch_a)
    assert t1 == t2 and cache.builds == 1 and cache.hits == 1

    # tiny budget: caching batch_b evicts batch_a (LRU by bytes)
    small = TarArtifactCache(max_bytes=1)
    small.get_or_build(str(tmp_path), batch_a)
    small.get_or_build(str(tmp_path), batch_b)
    small.get_or_build(str(tmp_path), batch_a)
    assert small.builds == 3  # every call rebuilt: nothing fits the budget
    assert small.stats()["artifact_entries"] == 1


# -- mirror pass: one build per batch, byte-identical convergence -----------
@pytest.mark.parametrize("n_workers", [4, 16])
def test_mirror_pass_one_build_per_batch(tmp_path, cluster, monkeypatch, n_workers):
    """Initial-sync mirror: regardless of worker count, each batch is
    tarred ONCE (artifact cache) and every mirrored worker ends up
    byte-identical to worker 0."""
    monkeypatch.setattr(session_mod, "UPLOAD_BATCH_FILES", 5)
    session, local, workers = make_session(
        tmp_path, cluster, n_workers=n_workers, verify_interval=0
    )
    now = int(time.time())
    names = [f"f{i:02d}.py" for i in range(12)]  # 3 batches of <=5
    for i, name in enumerate(names):
        write_file(str(local / name), f"content {i}")
        os.utime(str(local / name), (now, now))
        # worker 0 already matches local exactly -> the authority pass
        # uploads nothing; only the mirror pass moves data
        w0 = os.path.join(cluster.translate_path(workers[0], "/app"), name)
        write_file(w0, f"content {i}")
        os.utime(w0, (now, now))
    session.start()
    try:
        for w in workers[1:]:
            wait_for(
                lambda w=w: all(
                    os.path.exists(remote_path(cluster, w, n)) for n in names
                ),
                msg="mirror fan-out",
            )
        n_batches = 3
        assert session.artifacts.builds == n_batches
        assert session.artifacts.hits == n_batches * (n_workers - 2)
        for w in workers[1:]:
            for name in names:
                assert (
                    open(remote_path(cluster, w, name), "rb").read()
                    == open(remote_path(cluster, workers[0], name), "rb").read()
                )
    finally:
        session.stop()
    assert session.error is None


# -- digest gating: no-op touch moves zero payload bytes --------------------
def test_noop_touch_transfers_zero_payload(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    session.start()
    try:
        # steady-state create: upload computes and indexes the digest
        write_file(str(local / "app.py"), "print('v1')")
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "app.py")),
                msg="initial upload",
            )
        wait_for(
            lambda: session.index.get("app.py") is not None
            and session.index.get("app.py").digest is not None,
            msg="digest recorded on upload",
        )
        bytes_before = session.stats["bytes_sent"]
        uploaded_before = session.stats["uploaded"]

        # no-op touch: same bytes, new mtime
        new_mtime = int(time.time()) + 5
        os.utime(str(local / "app.py"), (new_mtime, new_mtime))
        wait_for(
            lambda: session.stats["meta_fixes"] >= 1,
            msg="metadata-only fix",
        )
        # remote mtimes were fixed in place on every worker...
        for w in workers:
            wait_for(
                lambda w=w: int(
                    os.stat(remote_path(cluster, w, "app.py")).st_mtime
                )
                == new_mtime,
                msg="remote mtime fixed",
            )
        # ...the index moved with them (no downstream echo / verify churn)...
        assert session.index.get("app.py").mtime == new_mtime
        assert session.index.get("app.py").digest is not None
        # ...and ZERO payload bytes crossed the wire (the acceptance pin)
        assert session.stats["bytes_sent"] == bytes_before
        assert session.stats["uploaded"] == uploaded_before
        assert session.stats["bytes_saved_digest"] > 0

        # control: a same-size content change MUST still upload
        bytes_before = session.stats["bytes_sent"]
        write_file(str(local / "app.py"), "print('v2')")
        later = new_mtime + 5
        os.utime(str(local / "app.py"), (later, later))
        for w in workers:
            wait_for(
                lambda w=w: open(remote_path(cluster, w, "app.py")).read()
                == "print('v2')",
                msg="content change still uploads",
            )
        assert session.stats["bytes_sent"] > bytes_before
    finally:
        session.stop()
    assert session.error is None


def test_digest_gating_off_reuploads_on_touch(tmp_path, cluster):
    session, local, workers = make_session(
        tmp_path, cluster, n_workers=1, digest_gating=False
    )
    session.start()
    try:
        write_file(str(local / "a.py"), "x = 1")
        wait_for(
            lambda: os.path.exists(remote_path(cluster, workers[0], "a.py")),
            msg="upload",
        )
        wait_for(lambda: session.index.get("a.py") is not None, msg="indexed")
        bytes_before = session.stats["bytes_sent"]
        new_mtime = int(time.time()) + 5
        os.utime(str(local / "a.py"), (new_mtime, new_mtime))
        wait_for(
            lambda: session.index.get("a.py").mtime == new_mtime,
            msg="touch re-synced",
        )
        assert session.stats["meta_fixes"] == 0
        assert session.stats["bytes_sent"] > bytes_before  # full re-upload
    finally:
        session.stop()
    assert session.error is None


# -- pipelined broadcast under failure (chaos) ------------------------------
@pytest.mark.chaos
def test_worker_killed_mid_broadcast_degrades_not_wedges(
    tmp_path, cluster, monkeypatch
):
    """A mirror worker dying mid-broadcast (stream drop + failed revive)
    is quarantined per the graded ladder; the pipeline's producer and the
    surviving consumers keep flowing — later uploads still land."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=3)
    write_file(str(local / "base.py"), "v0")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "base.py")),
                msg="initial fan-out",
            )
        # Kill worker 1 mid-broadcast: its stream dies on the next byte and
        # any revive exec fails like a deleted pod.
        real_exec = cluster.exec_stream

        def exec_stream(pod, *a, **kw):
            if getattr(pod, "name", pod) == workers[1].name:
                raise RuntimeError("pod gone")
            return real_exec(pod, *a, **kw)

        monkeypatch.setattr(cluster, "exec_stream", exec_stream)
        session._shells[1].proc = ByteBudgetStream(session._shells[1].proc, 0)

        write_file(str(local / "during.py"), "v1")
        for w in (workers[0], workers[2]):
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "during.py")),
                msg="broadcast to survivors",
            )
        wait_for(lambda: 1 in session.worker_errors, msg="quarantine")
        assert session.error is None

        # the producer queue is not wedged: a follow-up batch still flows
        write_file(str(local / "after.py"), "v2")
        for w in (workers[0], workers[2]):
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "after.py")),
                msg="pipeline still flowing after quarantine",
            )
        assert session.index.get("after.py") is not None
    finally:
        session.stop()
    assert session.error is None


@pytest.mark.chaos
def test_pod_killed_mid_broadcast_pipeline_completes(tmp_path, cluster):
    """kill_pod (streams die AND pod gone, revive impossible): the
    broadcast completes on survivors and the index still commits."""
    session, local, workers = make_session(tmp_path, cluster, n_workers=3)
    write_file(str(local / "seed.py"), "s")
    session.start()
    try:
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "seed.py")),
                msg="initial fan-out",
            )
        uploaded_before = session.stats["uploaded"]
        cluster.kill_pod("w-2")
        write_file(str(local / "next.py"), "n")
        for w in workers[:2]:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "next.py")),
                msg="broadcast to survivors",
            )
        wait_for(
            lambda: session.stats["uploaded"] > uploaded_before,
            msg="batch committed despite dead worker",
        )
        wait_for(lambda: 2 in session.worker_errors, msg="quarantine")
        assert session.error is None
    finally:
        session.stop()
    assert session.error is None


# -- RateLimiter: sleep outside the lock ------------------------------------
def test_rate_limiter_does_not_serialize_threads():
    """Satellite regression: a large throttled transfer must not block a
    peer that still has budget. Old code slept holding self._lock, so B's
    tiny request waited out A's multi-second drain."""
    limiter = RateLimiter(10)  # 10 KB/s bucket
    t_b = {}

    def big():
        limiter.throttle(30 * 1024)  # ~2s of deficit

    def small():
        time.sleep(0.3)  # let A drain the bucket and start sleeping
        t0 = time.monotonic()
        limiter.throttle(1)
        t_b["elapsed"] = time.monotonic() - t0

    a = threading.Thread(target=big)
    b = threading.Thread(target=small)
    a.start()
    b.start()
    b.join(timeout=10)
    assert t_b["elapsed"] < 1.0, (
        f"B blocked {t_b['elapsed']:.2f}s — limiter slept holding the lock"
    )
    a.join(timeout=10)


# -- build_tar: indexed size/mtime under concurrent writers -----------------
def test_build_tar_records_indexed_stat_not_fresh_stat(tmp_path):
    """Satellite regression: the Python fallback used to re-stat the file,
    so a write between indexing and tarring made the remote copy disagree
    with the index forever (neither side ever sees a further change)."""
    p = tmp_path / "grow.txt"
    p.write_bytes(b"abcd")
    mtime = int(os.stat(p).st_mtime)
    info = FileInformation(name="grow.txt", size=4, mtime=mtime)
    # concurrent writer: file grows and its mtime moves after indexing
    p.write_bytes(b"abcdEFGH")
    os.utime(p, (mtime + 50, mtime + 50))

    data = build_tar(str(tmp_path), [info])  # 1 entry -> Python fallback
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tf:
        ti = tf.getmember("grow.txt")
        assert ti.size == 4  # indexed size, not the fresh 8
        assert int(ti.mtime) == mtime  # indexed mtime, not mtime+50
        assert tf.extractfile(ti).read() == b"abcd"

    # shrink case: deliver exactly info.size, zero-filled
    p.write_bytes(b"ab")
    data = build_tar(str(tmp_path), [info])
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tf:
        ti = tf.getmember("grow.txt")
        assert ti.size == 4
        assert tf.extractfile(ti).read() == b"ab\0\0"


# -- stats surface ----------------------------------------------------------
def test_status_snapshot_surfaces_perf_stats(tmp_path, cluster):
    session, local, workers = make_session(tmp_path, cluster, n_workers=2)
    session.start()
    try:
        write_file(str(local / "m.py"), "pass")
        for w in workers:
            wait_for(
                lambda w=w: os.path.exists(remote_path(cluster, w, "m.py")),
                msg="upload",
            )
        snap = session.status_snapshot()
        for key in (
            "bytes_sent",
            "bytes_saved_digest",
            "meta_fixes",
            "pipeline_stall_s",
            "artifact_builds",
            "artifact_hits",
        ):
            assert key in snap["stats"], key
        assert snap["stats"]["bytes_sent"] > 0
    finally:
        session.stop()
    assert session.error is None
