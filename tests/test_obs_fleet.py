"""Fleet telemetry federation tests (ISSUE 10).

Pure-Python coverage of obs/fleet.py + obs/collector.py: exposition
round-trip, the hand-computed three-worker histogram merge golden,
aggregation-hint gauge semantics, fleet-SLO breach parity (merged
buckets vs one process emitting the union of events), trace stitching,
HPA-convention export, and the chaos ladder (hard-down target, garbage
exposition -> quarantine). The live 3-replica demo is the slow-marked
test in test_fleet_live.py; the CLI/HTTP surface is test_cli_fleet.py.
"""

import math

import pytest

from devspace_tpu.obs.collector import (
    COLLECTOR_METRIC_FAMILIES,
    TelemetryCollector,
)
from devspace_tpu.obs.fleet import (
    DEFAULT_AGG,
    ExpositionParseError,
    aggregation_hints,
    family_agg,
    merge_snapshots,
    parse_exposition,
    stitch_chrome_trace,
)
from devspace_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Registry,
    render_snapshot,
)
from devspace_tpu.obs.slo import SLOEvaluator, SLOSpec

EDGES = list(DEFAULT_LATENCY_BUCKETS) + [float("inf")]


# -- exposition round-trip ---------------------------------------------------
def _sample_registry():
    r = Registry()
    r.counter("engine_requests_completed_total", "done").inc(7)
    r.gauge("engine_tokens_per_sec_10s", "rate").set(12.5)
    g = r.gauge("slo_status", "state", labels=("slo",))
    g.labels(slo="ttft_p99").set(2)
    g.labels(slo='we"ird\\label').set(1)
    h = r.histogram("ttft_seconds", "ttft")
    h.observe(0.002)
    h.observe(0.3)
    return r


def test_parse_exposition_round_trip():
    reg = _sample_registry()
    snap = parse_exposition(reg.render())
    orig = reg.snapshot()
    assert snap["engine_requests_completed_total"]["kind"] == "counter"
    assert snap["engine_requests_completed_total"]["samples"] == [({}, 7.0)]
    assert snap["engine_tokens_per_sec_10s"]["samples"] == [({}, 12.5)]
    labels = {l["slo"]: v for l, v in snap["slo_status"]["samples"]}
    assert labels == {"ttft_p99": 2.0, 'we"ird\\label': 1.0}
    hist = snap["ttft_seconds"]["samples"][0][1]
    want = orig["ttft_seconds"]["samples"][0][1]
    assert hist["count"] == want["count"] == 2
    assert hist["sum"] == pytest.approx(want["sum"])
    assert [le for le, _ in hist["buckets"]] == EDGES
    assert [c for _, c in hist["buckets"]] == [c for _, c in want["buckets"]]
    # render(parse(render())) is a fixed point
    assert render_snapshot(snap) == render_snapshot(parse_exposition(
        render_snapshot(snap)))


def test_parse_rejects_garbage_and_truncation():
    with pytest.raises(ExpositionParseError):
        parse_exposition("this is not { an exposition !!!")
    # a histogram cut off before its _sum/_count series must not merge
    reg = _sample_registry()
    text = reg.render()
    cut = text[: text.index("ttft_seconds_sum")]
    with pytest.raises(ExpositionParseError):
        parse_exposition(cut)
    # non-cumulative buckets are nonsense
    bad = (
        "# TYPE x_seconds histogram\n"
        'x_seconds_bucket{le="0.1"} 5\n'
        'x_seconds_bucket{le="+Inf"} 3\n'
        "x_seconds_sum 1.0\n"
        "x_seconds_count 3\n"
    )
    with pytest.raises(ExpositionParseError):
        parse_exposition(bad)


def test_parse_untyped_falls_back_on_name_convention():
    snap = parse_exposition("foo_total 3\nbar_depth 2\n")
    assert snap["foo_total"]["kind"] == "counter"
    assert snap["bar_depth"]["kind"] == "gauge"


# -- the hand-computed three-worker histogram merge golden -------------------
def _hist_snap(observations):
    h = Registry().histogram("ttft_seconds", "ttft")
    for v in observations:
        h.observe(v)
    return {
        "ttft_seconds": {
            "kind": "histogram", "help": "ttft",
            "samples": [({}, h.snapshot())],
        }
    }


def test_histogram_merge_three_workers_golden():
    a = _hist_snap([0.002, 0.04])          # worker A
    b = _hist_snap([0.0009, 0.2, 0.7])     # worker B
    c = _hist_snap([3.0])                  # worker C
    merged, notes = merge_snapshots([a, b, c], hints={})
    assert notes == []
    got = merged["ttft_seconds"]["samples"][0][1]
    # hand-computed cumulative counts per DEFAULT_LATENCY_BUCKETS edge:
    # 0.001: B's 0.0009                                    -> 1
    # 0.0025: + A's 0.002                                  -> 2
    # 0.05:   + A's 0.04                                   -> 3
    # 0.25:   + B's 0.2                                    -> 4
    # 1.0:    + B's 0.7                                    -> 5
    # 5.0:    + C's 3.0                                    -> 6
    golden = [
        (0.001, 1), (0.0025, 2), (0.005, 2), (0.01, 2), (0.025, 2),
        (0.05, 3), (0.1, 3), (0.25, 4), (0.5, 4), (1.0, 5),
        (2.5, 5), (5.0, 6), (10.0, 6), (30.0, 6), (60.0, 6),
        (float("inf"), 6),
    ]
    assert got["buckets"] == [(le, float(c)) for le, c in golden]
    assert got["count"] == 6
    assert got["sum"] == pytest.approx(0.002 + 0.04 + 0.0009 + 0.2 + 0.7 + 3.0)
    # the merge must equal one histogram observing the union of events
    union = _hist_snap([0.002, 0.04, 0.0009, 0.2, 0.7, 3.0])
    assert got["buckets"] == union["ttft_seconds"]["samples"][0][1]["buckets"]


def test_histogram_merge_rejects_mismatched_edges():
    a = _hist_snap([0.01])
    h = Registry().histogram("ttft_seconds", "ttft", buckets=(0.5, 1.0))
    h.observe(0.7)
    b = {"ttft_seconds": {"kind": "histogram", "help": "ttft",
                          "samples": [({}, h.snapshot())]}}
    merged, notes = merge_snapshots([a, b], hints={})
    assert any("bucket-edge mismatch" in n for n in notes)
    # first-seen edges win; the divergent series is dropped, not mixed in
    assert merged["ttft_seconds"]["samples"][0][1]["count"] == 1


# -- gauge aggregation hints -------------------------------------------------
def _gauge_snap(name, value):
    return {name: {"kind": "gauge", "help": "g", "samples": [({}, value)]}}


@pytest.mark.parametrize(
    "hint,values,want",
    [("sum", [1.0, 2.0, 4.0], 7.0),
     ("max", [1.0, 5.0, 3.0], 5.0),
     ("avg", [1.0, 2.0, 6.0], 3.0),
     ("last", [1.0, 2.0, 6.0], 6.0)],
)
def test_gauge_merge_per_hint(hint, values, want):
    snaps = [_gauge_snap("g_depth", v) for v in values]
    merged, _notes = merge_snapshots(snaps, hints={"g_depth": hint})
    assert merged["g_depth"]["samples"][0][1] == pytest.approx(want)


def test_counters_always_sum_and_unknown_gauges_note_fallback():
    snaps = [
        {"c_total": {"kind": "counter", "help": "c", "samples": [({}, 2.0)]},
         **_gauge_snap("mystery_depth", 1.0)},
        {"c_total": {"kind": "counter", "help": "c", "samples": [({}, 3.0)]},
         **_gauge_snap("mystery_depth", 2.0)},
    ]
    merged, notes = merge_snapshots(snaps, hints={})
    assert merged["c_total"]["samples"][0][1] == 5.0
    assert any("mystery_depth" in n and DEFAULT_AGG in n for n in notes)


def test_labeled_series_merge_per_label_set():
    a = {"slo_status": {"kind": "gauge", "help": "s", "samples": [
        ({"slo": "ttft"}, 0.0), ({"slo": "err"}, 2.0)]}}
    b = {"slo_status": {"kind": "gauge", "help": "s", "samples": [
        ({"slo": "ttft"}, 1.0)]}}
    merged, _ = merge_snapshots([a, b], hints={"slo_status": "max"})
    got = {l["slo"]: v for l, v in merged["slo_status"]["samples"]}
    assert got == {"ttft": 1.0, "err": 2.0}


def test_every_declared_hint_is_valid_and_collector_families_declared():
    hints = aggregation_hints()
    # all 9 catalogs imported in this environment
    assert hints["engine_requests_completed_total"] == "sum"
    assert hints["engine_dispatch_depth_occupancy"] == "avg"
    assert hints["engine_uptime_seconds"] == "max"
    assert hints["slo_status"] == "max"
    assert hints["ttft_seconds"] == "sum"
    for fam in COLLECTOR_METRIC_FAMILIES:
        assert family_agg(fam) in ("sum", "max", "avg", "last")
    with pytest.raises(ValueError):
        family_agg(("x_total", "counter", "help with no hint"))


# -- fleet SLO parity: merged buckets == union-of-events ---------------------
def test_fleet_slo_burn_parity_with_union_process():
    spec = SLOSpec(
        name="ttft_p99", kind="latency", objective=0.99,
        histogram="ttft_seconds", threshold_s=0.25,
        short_window_s=60.0, long_window_s=300.0,
    )
    workers = [Registry() for _ in range(3)]
    hists = [r.histogram("ttft_seconds", "ttft") for r in workers]
    union_reg = Registry()
    union_hist = union_reg.histogram("ttft_seconds", "ttft")

    def fleet_source():
        merged, _ = merge_snapshots([r.snapshot() for r in workers], hints={})
        return merged

    clock = {"now": 1000.0}
    fleet_eval = SLOEvaluator([spec], [fleet_source],
                              clock=lambda: clock["now"])
    union_eval = SLOEvaluator([spec], [union_reg.snapshot],
                              clock=lambda: clock["now"])
    fleet_eval.evaluate()
    union_eval.evaluate()
    # per-worker traffic: worker 0 healthy, 1 mixed, 2 slow
    traffic = [
        [0.01, 0.02, 0.05],
        [0.1, 0.6],
        [1.2, 2.0, 3.0, 0.02],
    ]
    for worker_obs, hist in zip(traffic, hists):
        for v in worker_obs:
            hist.observe(v)
            union_hist.observe(v)
    clock["now"] += 30.0
    f = {s.name: s for s in fleet_eval.evaluate()}["ttft_p99"]
    u = {s.name: s for s in union_eval.evaluate()}["ttft_p99"]
    assert f.status == u.status == "breach"  # 5/9 above threshold >> budget
    assert f.burn_short == pytest.approx(u.burn_short)
    assert f.burn_long == pytest.approx(u.burn_long)


# -- collector ---------------------------------------------------------------
def _fake_fleet(metrics_by_url, events_by_url=None, spans_by_url=None,
                health_by_url=None):
    """fetch(url, timeout) over canned per-target documents."""
    events_by_url = events_by_url or {}
    spans_by_url = spans_by_url or {}
    health_by_url = health_by_url or {}

    def fetch(url, timeout):
        import json

        base, _, path = url.partition("/")
        for known in metrics_by_url:
            if url.startswith(known + "/"):
                path = url[len(known):]
                if path.startswith("/metrics"):
                    doc = metrics_by_url[known]
                    if isinstance(doc, Exception):
                        raise doc
                    return doc.encode()
                if path.startswith("/debug/events"):
                    return json.dumps(
                        {"events": events_by_url.get(known, [])}).encode()
                if path.startswith("/debug/spans"):
                    return json.dumps(
                        {"spans": spans_by_url.get(known, [])}).encode()
                if path.startswith("/healthz"):
                    return json.dumps(
                        health_by_url.get(known, {"ok": True})).encode()
        raise OSError(f"unknown target {url}")

    return fetch


def _mk_collector(metrics_by_url, clock=None, **kw):
    return TelemetryCollector(
        sorted(metrics_by_url),
        fetch=_fake_fleet(metrics_by_url, **kw.pop("docs", {})),
        clock=clock or (lambda: 0.0),
        **kw,
    )


def test_collector_federates_counters_and_histograms():
    texts = {}
    for i, obs in enumerate(([0.002, 0.04], [0.0009, 0.2, 0.7], [3.0])):
        r = Registry()
        r.counter("engine_requests_completed_total", "done").inc(10 * (i + 1))
        h = r.histogram("ttft_seconds", "ttft")
        for v in obs:
            h.observe(v)
        texts[f"http://replica{i}:8000"] = r.render()
    c = _mk_collector(texts)
    c.scrape_once()
    snap = c.fleet_snapshot()
    assert snap["engine_requests_completed_total"]["samples"][0][1] == 60.0
    hist = snap["ttft_seconds"]["samples"][0][1]
    assert hist["count"] == 6
    assert snap["collector_fleet_targets_up"]["samples"][0][1] == 3.0
    # the exposition of the fleet snapshot parses right back
    assert parse_exposition(c.render_metrics())["ttft_seconds"][
        "samples"][0][1]["count"] == 6


@pytest.mark.chaos
def test_collector_target_hard_down_degrades_to_staleness():
    """Chaos: one target dead. Its staleness gauge is set (and up=0),
    the other targets still federate, and the fleet snapshot renders —
    the collector never fails because a target did."""
    clock = {"now": 100.0}
    good = Registry()
    good.counter("engine_requests_completed_total", "done").inc(5)
    texts = {
        "http://up:8000": good.render(),
        "http://dead:8000": OSError("connection refused"),
    }
    c = _mk_collector(texts, clock=lambda: clock["now"])
    c.scrape_once()
    dead = next(t for t in c.targets if "dead" in t.name)
    up = next(t for t in c.targets if t.name == "up:8000")
    assert not dead.up and up.up
    clock["now"] += 60.0
    snap = c.fleet_snapshot()
    assert snap["engine_requests_completed_total"]["samples"][0][1] == 5.0
    by_target = {l["target"]: v for l, v in
                 snap["collector_target_up"]["samples"]}
    assert by_target == {"dead:8000": 0.0, "up:8000": 1.0}
    stale = {l["target"]: v for l, v in
             snap["collector_target_staleness_seconds"]["samples"]}
    assert math.isinf(stale["dead:8000"])  # never scraped OK
    assert stale["up:8000"] == pytest.approx(60.0)
    assert snap["collector_scrape_errors_total"]["samples"][0][1] == 1.0
    # and the whole thing still renders as one well-formed exposition
    assert "collector_target_staleness_seconds" in c.render_metrics()


@pytest.mark.chaos
def test_collector_garbage_exposition_quarantines_never_raises():
    """Chaos: a target returns truncated/garbage exposition text. Every
    bad round counts a parse error; after quarantine_after consecutive
    failures the target is quarantined (excluded from the merge), and a
    later clean parse readmits it. Nothing ever raises."""
    good = Registry()
    good.counter("engine_requests_completed_total", "done").inc(5)
    docs = {"http://liar:8000": "garbage {{{ not metrics",
            "http://up:8000": good.render()}
    c = TelemetryCollector(
        sorted(docs), clock=lambda: 0.0, quarantine_after=2,
        fetch=lambda url, _t: (
            docs[url[: url.index("/metrics")]].encode()
            if url.endswith("/metrics") else (_ for _ in ()).throw(
                OSError("no sidecar"))
        ),
    )
    c.scrape_once()
    liar = next(t for t in c.targets if "liar" in t.name)
    assert not liar.up and not liar.quarantined  # 1 of 2 strikes
    c.scrape_once()
    assert liar.quarantined
    snap = c.fleet_snapshot()
    assert snap["collector_parse_errors_total"]["samples"][0][1] == 2.0
    assert snap["engine_requests_completed_total"]["samples"][0][1] == 5.0
    by_target = {l["target"]: v for l, v in
                 snap["collector_target_quarantined"]["samples"]}
    assert by_target["liar:8000"] == 1.0
    # the liar starts telling the truth -> readmitted next round
    docs["http://liar:8000"] = good.render()
    c.scrape_once()
    assert not liar.quarantined and liar.up
    assert c.fleet_snapshot()["engine_requests_completed_total"][
        "samples"][0][1] == 10.0


def test_collector_merged_events_stable_order_and_target_stamp():
    texts = {u: Registry().render() or "# empty\n"
             for u in ("http://a:1", "http://b:1")}
    events = {
        "http://a:1": [
            {"time": 5.0, "seq": 2, "subsystem": "engine", "event": "admit"},
            {"time": 7.0, "seq": 9, "subsystem": "engine", "event": "admit"},
        ],
        "http://b:1": [
            {"time": 5.0, "seq": 1, "subsystem": "slo", "event": "warn"},
        ],
    }
    c = _mk_collector(texts, docs={"events_by_url": events})
    c.scrape_once()
    merged = c.merged_events()
    assert [(e["time"], e["seq"], e["target"]) for e in merged] == [
        (5.0, 1, "b:1"), (5.0, 2, "a:1"), (7.0, 9, "a:1")]
    assert c.merged_events(subsystem="slo")[0]["event"] == "warn"


def test_stitched_trace_one_lane_per_process():
    tid = "ab" * 16
    spans = {
        "http://a:1": [
            {"name": "generate", "trace_id": tid, "span_id": "11" * 8,
             "start": 10.0, "duration_s": 0.5, "track": "http"},
            {"name": "other", "trace_id": "ff" * 16, "span_id": "33" * 8,
             "start": 11.0, "duration_s": 0.1, "track": "http"},
        ],
        "http://b:1": [
            {"name": "decode", "trace_id": tid, "span_id": "22" * 8,
             "parent_span_id": "11" * 8, "start": 10.1, "duration_s": 0.3,
             "track": "engine"},
        ],
    }
    doc = stitch_chrome_trace(spans, trace_id=tid)
    pids = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"}
    assert set(pids) == {"http://a:1", "http://b:1"}
    assert len(set(pids.values())) == 2  # distinct process lanes
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"generate", "decode"}  # filtered
    gen = next(e for e in xs if e["name"] == "generate")
    dec = next(e for e in xs if e["name"] == "decode")
    assert gen["pid"] != dec["pid"]
    assert dec["ts"] == pytest.approx(10.1e6)
    assert dec["args"]["parent_span_id"] == "11" * 8
    # collector plumbing produces the same document
    texts = {u: "# empty\n" for u in spans}
    c = _mk_collector(texts, docs={"spans_by_url": spans})
    c.scrape_once()
    via_collector = c.stitched_trace(tid)
    assert {e["name"] for e in via_collector["traceEvents"]
            if e["ph"] == "X"} == {"generate", "decode"}


def test_hpa_signals_follow_chart_convention():
    texts = {}
    for i, (occ, queued) in enumerate([(1.0, 2), (3.0, 4)]):
        r = Registry()
        r.gauge("engine_dispatch_depth_occupancy", "occ").set(occ)
        r.gauge("engine_queued_requests", "q").set(queued)
        r.gauge("engine_tokens_per_sec_10s", "rate").set(10.0)
        texts[f"http://r{i}:1"] = r.render()
    c = _mk_collector(texts)
    c.scrape_once()
    metrics = c.hpa_signals()
    # exactly the autoscaling/v2 entry shape chart.py's
    # values.autoscaling.objects carries
    by_name = {m["pods"]["metric"]["name"]: m for m in metrics}
    occ = by_name["engine_dispatch_depth_occupancy"]
    assert occ["type"] == "Pods"
    assert occ["pods"]["target"]["type"] == "AverageValue"
    assert occ["pods"]["target"]["averageValue"] == pytest.approx(2.0)
    assert by_name["engine_queued_requests"]["pods"]["target"][
        "averageValue"] == pytest.approx(3.0)
    status = c.fleet_status()
    assert status["hpa"]["metrics"] == metrics


def test_fleet_status_matrix_rows():
    r = Registry()
    r.gauge("engine_tokens_per_sec_10s", "rate").set(42.5)
    r.gauge("engine_active_slots", "a").set(3)
    r.gauge("engine_max_slots", "m").set(4)
    r.gauge("engine_queued_requests", "q").set(1)
    texts = {"http://solo:8000": r.render()}
    c = _mk_collector(
        texts,
        docs={"health_by_url": {"http://solo:8000": {
            "ok": True, "slo": {"status": "ok"}}}},
    )
    c.scrape_once()
    status = c.fleet_status()
    row = status["targets"][0]
    assert row["target"] == "solo:8000" and row["up"]
    assert row["tok_s"] == 42.5 and row["max_slots"] == 4.0
    assert row["slo"] == "ok"
    assert status["fleet"]["up"] == 1
    assert status["slo"]["slos"]  # fleet evaluator ran
