"""Stochastic speculative sampling (Leviathan-style accept/resample).

``speculative.spec_accept_commit`` must (a) reduce exactly to the
classic greedy rule for temps <= 0 rows, and (b) for stochastic rows
commit tokens distributed EXACTLY as sequential temperature sampling
from the target alone. (b) is pinned two ways: the analytic acceptance
probability ``sum_x min(p_t(x), p_d(x))`` and a Monte-Carlo marginal
check of the first committed token against ``p_t`` (fixed seeds —
deterministic, not flaky). Engine-level tests prove temperature
requests actually ride the speculative path and stay well-formed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from devspace_tpu.inference import InferenceEngine
from devspace_tpu.inference.speculative import spec_accept_commit
from devspace_tpu.models import transformer as tfm

CFG = tfm.TINY


def _keys(n, seed=0):
    return jax.vmap(jax.random.PRNGKey)(jnp.arange(seed, seed + n))


def test_greedy_rows_reduce_to_exact_match_rule():
    """temps<=0 rows: committed = leading argmax matches + the target's
    corrected/bonus token — byte-identical to the old host rule."""
    rng = np.random.default_rng(0)
    B, k, V = 4, 3, 11
    props = jnp.asarray(rng.integers(0, V, (B, k)), jnp.int32)
    d_probs = jnp.asarray(rng.dirichlet(np.ones(V), (B, k)), jnp.float32)
    t_logits = jnp.asarray(rng.normal(size=(B, k + 1, V)), jnp.float32)
    commit, n_commit, _ = spec_accept_commit(
        props, d_probs, t_logits, jnp.zeros((B,), jnp.float32), _keys(B)
    )
    choices = np.argmax(np.asarray(t_logits), axis=-1)
    for i in range(B):
        match = np.asarray(props)[i] == choices[i, :k]
        a = int(k if match.all() else match.argmin())
        assert int(n_commit[i]) == a + 1
        want = list(np.asarray(props)[i, :a]) + [choices[i, a]]
        assert list(np.asarray(commit)[i, : a + 1]) == [int(t) for t in want]


def test_stochastic_first_token_marginal_matches_target():
    """The Leviathan theorem, empirically: over many keys, the first
    committed token's marginal equals p_t exactly — independent of how
    bad the draft is. Also pins the analytic acceptance rate."""
    rng = np.random.default_rng(1)
    V, k, N = 8, 1, 40_000
    p_t = rng.dirichlet(np.ones(V) * 0.7)
    p_d = rng.dirichlet(np.ones(V) * 0.7)  # deliberately mismatched draft
    t_logits = jnp.log(jnp.asarray(p_t, jnp.float32))[None, None, :].repeat(
        N, 0
    ).repeat(k + 1, 1)
    d_probs = jnp.asarray(p_d, jnp.float32)[None, None, :].repeat(N, 0)
    # draft proposals sampled from p_d with independent keys
    pk = jax.vmap(jax.random.PRNGKey)(jnp.arange(N))
    props = jax.vmap(
        lambda s: jax.random.categorical(s, jnp.log(d_probs[0, 0]))
    )(pk)[:, None].astype(jnp.int32)
    commit, n_commit, _ = spec_accept_commit(
        props, d_probs, t_logits, jnp.ones((N,), jnp.float32),
        _keys(N, seed=500_000),
    )
    first = np.asarray(commit)[:, 0]
    emp = np.bincount(first, minlength=V) / N
    tv = 0.5 * np.abs(emp - p_t).sum()
    assert tv < 0.02, f"first-token marginal TV {tv:.4f} vs p_t"
    # acceptance prob of proposal 0 = sum_x min(p_t, p_d)
    acc_rate = float((np.asarray(n_commit) - 1).mean())
    want = float(np.minimum(p_t, p_d).sum())
    assert abs(acc_rate - want) < 0.02, (acc_rate, want)


def test_stochastic_commit_shapes_and_mixed_batch():
    """Mixed greedy+stochastic batch: every row's commit tokens are
    in-vocab, n_commit in 1..k+1, and greedy rows are unaffected by
    their stochastic neighbors."""
    rng = np.random.default_rng(2)
    B, k, V = 6, 4, 13
    props = jnp.asarray(rng.integers(0, V, (B, k)), jnp.int32)
    d_probs = jnp.asarray(rng.dirichlet(np.ones(V), (B, k)), jnp.float32)
    t_logits = jnp.asarray(rng.normal(size=(B, k + 1, V)), jnp.float32)
    temps = jnp.asarray([0.0, 1.0, 0.7, 0.0, 1.3, 0.0], jnp.float32)
    commit, n_commit, keys = spec_accept_commit(
        props, d_probs, t_logits, temps, _keys(B)
    )
    assert commit.shape == (B, k + 1) and n_commit.shape == (B,)
    assert (np.asarray(n_commit) >= 1).all()
    assert (np.asarray(n_commit) <= k + 1).all()
    assert (np.asarray(commit) >= 0).all() and (np.asarray(commit) < V).all()
    greedy_only, n_greedy, _ = spec_accept_commit(
        props, d_probs, t_logits, jnp.zeros((B,), jnp.float32), _keys(B)
    )
    for i in (0, 3, 5):
        assert int(n_commit[i]) == int(n_greedy[i])
        n = int(n_commit[i])
        assert list(np.asarray(commit)[i, :n]) == list(
            np.asarray(greedy_only)[i, :n]
        )


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def test_engine_temperature_rides_speculative_path(params):
    """A plain-temperature request must be spec-eligible (draft prefill
    + spec rounds run), produce the right token count in-vocab, and be
    reproducible for the same seed; a top-k request rides spec too."""
    engine = InferenceEngine(
        params, CFG, max_slots=2, max_len=64,
        draft_params=params, draft_cfg=CFG, spec_k=3, spec_depth=2,
    ).start()
    try:
        toks = engine.submit(
            [4, 8, 1], 14, temperature=0.8, seed=7
        ).result(timeout=120)
        rounds_after_temp = engine.spec_rounds
        engine.submit(
            [4, 8, 1], 6, temperature=0.8, top_k=5, seed=7
        ).result(timeout=120)
        rounds_after_topk = engine.spec_rounds
    finally:
        engine.stop()
    assert rounds_after_temp > 0, "temperature request must ride spec"
    assert len(toks) == 14
    assert all(0 <= t < CFG.vocab_size for t in toks)
    # filtered sampling rides the spec path too (the accept rule runs
    # against the filtered target distribution)
    assert rounds_after_topk > rounds_after_temp

    # same seed, fresh engine, deterministic scheduling (single request)
    # -> identical stream
    engine2 = InferenceEngine(
        params, CFG, max_slots=2, max_len=64,
        draft_params=params, draft_cfg=CFG, spec_k=3, spec_depth=2,
    ).start()
    try:
        toks2 = engine2.submit(
            [4, 8, 1], 14, temperature=0.8, seed=7
        ).result(timeout=120)
    finally:
        engine2.stop()
    assert toks2 == toks


def test_engine_greedy_unchanged_with_stochastic_neighbor(params):
    """A greedy request co-resident with a sampling request keeps its
    exact greedy stream (greedy commits never depend on keys)."""
    prompt = [5, 1, 4]
    ref = tfm.generate(
        params, jnp.asarray([prompt], jnp.int32), CFG, max_new_tokens=8
    )
    engine = InferenceEngine(
        params, CFG, max_slots=2, max_len=64,
        draft_params=params, draft_cfg=CFG, spec_k=3, spec_depth=2,
    ).start()
    try:
        h_greedy = engine.submit(prompt, 8)
        h_temp = engine.submit([2, 2, 6], 8, temperature=1.1, seed=3)
        greedy = h_greedy.result(timeout=120)
        temp = h_temp.result(timeout=120)
    finally:
        engine.stop()
    assert greedy == [int(t) for t in ref[0]]
    assert len(temp) == 8


def test_stochastic_filtered_marginal_matches_filtered_target():
    """top-k filtered speculative sampling: the first committed token's
    marginal must equal the RENORMALIZED top-k target distribution (the
    same distribution the plain path samples), with out-of-filter draft
    proposals auto-rejecting."""
    rng = np.random.default_rng(3)
    V, k, N, TOPK = 8, 1, 40_000, 3
    p_t = rng.dirichlet(np.ones(V) * 0.7)
    p_d = rng.dirichlet(np.ones(V) * 0.7)
    t_logits_row = np.log(p_t)
    keep = np.argsort(t_logits_row)[::-1][:TOPK]
    p_t_filt = np.zeros(V)
    p_t_filt[keep] = p_t[keep] / p_t[keep].sum()
    t_logits = jnp.asarray(t_logits_row, jnp.float32)[None, None, :].repeat(
        N, 0
    ).repeat(k + 1, 1)
    d_probs = jnp.asarray(p_d, jnp.float32)[None, None, :].repeat(N, 0)
    pk = jax.vmap(jax.random.PRNGKey)(jnp.arange(N))
    props = jax.vmap(
        lambda s: jax.random.categorical(s, jnp.log(d_probs[0, 0]))
    )(pk)[:, None].astype(jnp.int32)
    commit, n_commit, _ = spec_accept_commit(
        props, d_probs, t_logits, jnp.ones((N,), jnp.float32),
        _keys(N, seed=900_000),
        top_ks=jnp.full((N,), TOPK, jnp.int32),
        top_ps=jnp.ones((N,), jnp.float32),
    )
    first = np.asarray(commit)[:, 0]
    emp = np.bincount(first, minlength=V) / N
    tv = 0.5 * np.abs(emp - p_t_filt).sum()
    assert tv < 0.02, f"filtered marginal TV {tv:.4f}"
    # out-of-filter tokens never commit
    assert emp[[i for i in range(V) if i not in set(keep)]].sum() == 0
    # acceptance = sum_x min(p_t_filt, p_d)
    acc = float((np.asarray(n_commit) - 1).mean())
    want = float(np.minimum(p_t_filt, p_d).sum())
    assert abs(acc - want) < 0.02, (acc, want)
