"""Kube transport conformance fixtures (the channel-protocol edge cases a
fake backend can't exercise).

The repo's exec/attach/portforward client has only ever spoken to
``kube/fake.py`` (no cluster exists in this environment), and the
loopback tests reuse the module's own frame helpers — a symmetric
encode/decode bug would cancel itself out. These fixtures replay frames
HAND-AUTHORED as raw bytes the way a real kubelet/apiserver emits them
(unmasked server frames, RFC 6455 length encodings, channel-prefixed
payloads, ``v1.Status`` on channel 3, 2-byte little-endian port
confirmations, pings mid-stream, close sequencing) against the real
client demux, and parse the client's frames with an independent
hand-written parser (masking included).

Reference behavior being conformed to:
``/root/reference/pkg/devspace/kubectl/exec.go:63`` (SPDY exec streams —
our transport is the modern ``v4.channel.k8s.io`` WebSocket equivalent)
and the kubelet's remotecommand/portforward wire formats.
"""

import json
import socket
import struct
import threading
import time

import pytest

from devspace_tpu.kube.exec import WSRemoteProcess
from devspace_tpu.kube.portforward import WSPortTunnel
from devspace_tpu.kube.websocket import WebSocket, WebSocketError, client_handshake

# -- independent wire helpers (deliberately NOT the module's) ---------------


def raw_frame(op: int, payload: bytes, fin: bool = True) -> bytes:
    """A server frame as the kubelet sends it: unmasked, hand-packed."""
    b0 = (0x80 if fin else 0) | op
    n = len(payload)
    if n < 126:
        hdr = bytes([b0, n])
    elif n < 1 << 16:
        hdr = bytes([b0, 126]) + n.to_bytes(2, "big")
    else:
        hdr = bytes([b0, 127]) + n.to_bytes(8, "big")
    return hdr + payload


def read_client_frame(sock: socket.socket, buf: bytearray):
    """Parse one masked client frame with an independent implementation."""

    def need(n):
        while len(buf) < n:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("client closed")
            buf.extend(chunk)
        out = bytes(buf[:n])
        del buf[:n]
        return out

    b0, b1 = need(2)
    op = b0 & 0x0F
    assert b1 & 0x80, "client frames MUST be masked (RFC 6455 §5.1)"
    n = b1 & 0x7F
    if n == 126:
        n = int.from_bytes(need(2), "big")
    elif n == 127:
        n = int.from_bytes(need(8), "big")
    key = need(4)
    masked = need(n)
    return op, bytes(b ^ key[i % 4] for i, b in enumerate(masked))


def pair():
    a, b = socket.socketpair()
    a.settimeout(10)
    b.settimeout(10)
    return a, b


def serve(script):
    """Run ``script(server_sock)`` in a thread; returns (client_sock, thread)."""
    client_side, server_side = pair()
    t = threading.Thread(target=script, args=(server_side,), daemon=True)
    t.start()
    return client_side, t


# kubelet-shaped v1.Status payloads (channel 3)
STATUS_EXIT_3 = json.dumps(
    {
        "metadata": {},
        "status": "Failure",
        "message": "command terminated with non-zero exit code: exit status 3",
        "reason": "NonZeroExitCode",
        "details": {"causes": [{"reason": "ExitCode", "message": "3"}]},
        "code": 500,
    }
).encode()
STATUS_SUCCESS = json.dumps({"metadata": {}, "status": "Success"}).encode()


def test_exec_trace_exit_code_and_streams():
    """stdout + stderr + Failure status with ExitCode cause + clean close:
    the demux must split channels and surface rc=3."""

    def script(s):
        s.sendall(raw_frame(0x2, b"\x01" + b"hello "))
        s.sendall(raw_frame(0x2, b"\x01" + b"world\n"))
        s.sendall(raw_frame(0x2, b"\x02" + b"oops\n"))
        s.sendall(raw_frame(0x2, b"\x03" + STATUS_EXIT_3))
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, _ = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    assert proc.wait(10) == 3
    assert proc.stdout.drain() == b"hello world\n"
    assert proc.stderr.drain() == b"oops\n"
    assert "non-zero exit code" in proc.error_message


def test_exec_trace_success_and_fragmentation():
    """A Success status => rc 0; a stdout message fragmented across
    BINARY(fin=0)+CONT(fin=1) frames carries its channel byte only in
    the FIRST fragment and must reassemble to one payload. Also covers
    the 16-bit extended length encoding (>125-byte frame)."""
    big = b"x" * 300

    def script(s):
        s.sendall(raw_frame(0x2, b"\x01" + b"frag-", fin=False))
        s.sendall(raw_frame(0x0, b"mented\n", fin=True))
        s.sendall(raw_frame(0x2, b"\x01" + big))  # 301 bytes -> len==126 path
        s.sendall(raw_frame(0x2, b"\x03" + STATUS_SUCCESS))
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, _ = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    assert proc.wait(10) == 0
    assert proc.stdout.drain() == b"frag-mented\n" + big


def test_exec_trace_ping_is_answered_with_masked_pong():
    """An unmasked server ping mid-stream must get a MASKED pong echoing
    the payload, without disturbing the data stream."""
    got = {}

    def script(s):
        s.sendall(raw_frame(0x2, b"\x01" + b"before "))
        s.sendall(raw_frame(0x9, b"ka-ping"))  # literal unmasked ping
        buf = bytearray()
        op, payload = read_client_frame(s, buf)
        got["pong"] = (op, payload)
        s.sendall(raw_frame(0x2, b"\x01" + b"after"))
        s.sendall(raw_frame(0x2, b"\x03" + STATUS_SUCCESS))
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, t = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    assert proc.wait(10) == 0
    t.join(10)
    assert got["pong"] == (0xA, b"ka-ping")
    assert proc.stdout.drain() == b"before after"


def test_exec_trace_abrupt_drop_is_not_success():
    """TCP drop before any status frame: partial output must NOT read as
    rc 0 (the sync shell protocol trusts exit codes)."""

    def script(s):
        s.sendall(raw_frame(0x2, b"\x01" + b"partial"))
        time.sleep(0.05)
        s.close()

    sock, _ = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    assert proc.wait(10) == -1
    assert proc.stdout.drain() == b"partial"


def test_exec_trace_clean_close_without_status_is_success():
    """A proper close frame with no channel-3 payload: the v4 protocol
    reads this as success (kubelet omits the status only on rc 0 paths)."""

    def script(s):
        s.sendall(raw_frame(0x2, b"\x01" + b"done\n"))
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, _ = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    assert proc.wait(10) == 0


def test_exec_client_frames_stdin_and_resize_wire_format():
    """What the CLIENT puts on the wire: masked frames, channel-0 prefix
    for stdin bytes, channel-4 resize JSON with kubelet's Width/Height
    capitalization."""
    got = {}

    def script(s):
        buf = bytearray()
        got["stdin"] = read_client_frame(s, buf)
        got["resize"] = read_client_frame(s, buf)
        s.sendall(raw_frame(0x2, b"\x03" + STATUS_SUCCESS))
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, t = serve(script)
    proc = WSRemoteProcess(WebSocket(sock))
    proc.write_stdin(b"ls -la\n")
    proc.resize(80, 24)
    assert proc.wait(10) == 0
    t.join(10)
    assert got["stdin"] == (0x2, b"\x00" + b"ls -la\n")
    op, payload = got["resize"]
    assert op == 0x2 and payload[0] == 4
    assert json.loads(payload[1:]) == {"Width": 80, "Height": 24}


def test_handshake_with_coalesced_first_frame():
    """The apiserver may coalesce the 101 response and the first data
    frame into one TCP segment; the leftover bytes must reach the
    WebSocket prebuffer, not be dropped with the HTTP head."""
    from devspace_tpu.kube.websocket import accept_key

    def script(s):
        head = b""
        while b"\r\n\r\n" not in head:
            head += s.recv(4096)
        key = ""
        for ln in head.decode("latin-1").split("\r\n"):
            if ln.lower().startswith("sec-websocket-key:"):
                key = ln.split(":", 1)[1].strip()
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept_key(key)}\r\n"
            "Sec-WebSocket-Protocol: v4.channel.k8s.io\r\n\r\n"
        ).encode()
        # ONE send: 101 + first stdout frame + status + close coalesced
        s.sendall(
            resp
            + raw_frame(0x2, b"\x01" + b"coalesced\n")
            + raw_frame(0x2, b"\x03" + STATUS_SUCCESS)
            + raw_frame(0x8, struct.pack("!H", 1000))
        )

    sock, _ = serve(script)
    proto, leftover = client_handshake(
        sock, "kubelet", "/exec", subprotocols=["v4.channel.k8s.io"]
    )
    assert proto == "v4.channel.k8s.io"
    proc = WSRemoteProcess(WebSocket(sock, prebuffer=leftover))
    assert proc.wait(10) == 0
    assert proc.stdout.drain() == b"coalesced\n"


class _Transport:
    """Just enough KubeTransport surface for WSPortTunnel."""

    def __init__(self, ws):
        self._ws = ws

    def connect_websocket(self, path, query=None, subprotocols=None):
        return self._ws


def test_portforward_trace_confirmations_then_data():
    """The kubelet's first frame on EACH channel is a 2-byte LE port
    confirmation; real data follows on channel 0 — including a 2-byte
    data payload right after confirmation, which must NOT be swallowed."""

    def script(s):
        s.sendall(raw_frame(0x2, b"\x00" + struct.pack("<H", 9090)))
        s.sendall(raw_frame(0x2, b"\x01" + struct.pack("<H", 9090)))
        s.sendall(raw_frame(0x2, b"\x00" + b"OK"))  # 2 bytes, real data
        s.sendall(raw_frame(0x2, b"\x00" + b"payload"))
        buf = bytearray()
        op, payload = read_client_frame(s, buf)
        assert payload == b"\x00ping-through"
        s.sendall(raw_frame(0x8, struct.pack("!H", 1000)))

    sock, t = serve(script)
    tunnel = WSPortTunnel(_Transport(WebSocket(sock)), "pod", "ns", 9090)
    assert tunnel.recv() == b"OK"
    assert tunnel.recv() == b"payload"
    tunnel.send(b"ping-through")
    assert tunnel.recv() == b""  # clean close
    t.join(10)


def test_portforward_trace_error_frame_raises():
    """A non-empty channel-1 frame after confirmation is the kubelet's
    forward error (e.g. connection refused in the pod) and must raise."""

    def script(s):
        s.sendall(raw_frame(0x2, b"\x00" + struct.pack("<H", 8080)))
        s.sendall(raw_frame(0x2, b"\x01" + struct.pack("<H", 8080)))
        s.sendall(
            raw_frame(
                0x2,
                b"\x01" + b"an error occurred forwarding 8080: connection refused",
            )
        )

    sock, _ = serve(script)
    tunnel = WSPortTunnel(_Transport(WebSocket(sock)), "pod", "ns", 8080)
    with pytest.raises(WebSocketError, match="connection refused"):
        tunnel.recv()
