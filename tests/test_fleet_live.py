"""Live fleet federation demo (ISSUE 10 acceptance, slow).

Boots three real serving replicas (examples/llama-inference/serve.py,
TINY model, CPU), drives one /generate through each, then runs the real
``TelemetryCollector`` over actual HTTP against them: the fleet
/metrics exposition carries summed counters and the bucket-merged TTFT
histogram, ``top --fleet`` renders the matrix, killing a replica flips
its staleness gauge without breaking the snapshot, and a traced request
(same W3C ``traceparent`` fanned to two replicas) shows up in one
stitched Chrome trace with a distinct process lane per worker.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from devspace_tpu.cli.main import main
from devspace_tpu.obs.collector import TelemetryCollector, make_http_server
from devspace_tpu.obs.fleet import parse_exposition
from devspace_tpu.utils import log as logutil

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
SERVE = os.path.join(REPO, "examples", "llama-inference", "serve.py")

TRACE = "fe" * 16
PARENT = "aa" * 8


def _post(url, body, headers=None, timeout=240):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _spawn_replica(port):
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        MODEL="tiny",
        MAX_SLOTS="2",
        PORT=str(port),
    )
    return subprocess.Popen(
        [sys.executable, SERVE],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )


@pytest.mark.slow
def test_fleet_collector_live_three_replicas(capsys):
    logutil.set_logger(logutil.StdoutLogger())
    ports = [18561, 18562, 18563]
    procs = [_spawn_replica(p) for p in ports]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    httpd = None
    collector = None
    try:
        deadline = time.monotonic() + 180
        pending = set(ports)
        while pending and time.monotonic() < deadline:
            for port, proc in zip(ports, procs):
                if port not in pending:
                    continue
                try:
                    with socket.create_connection(
                            ("127.0.0.1", port), timeout=1):
                        pending.discard(port)
                except OSError:
                    if proc.poll() is not None:
                        pytest.fail(
                            f"replica :{port} died: "
                            f"{proc.stdout.read()[-2000:]}")
            time.sleep(0.3)
        if pending:
            pytest.fail(f"replicas never opened: {sorted(pending)}")

        # one generate per replica; the SAME distributed trace fans out
        # to the first two so the stitched view spans two processes
        traceparent = f"00-{TRACE}-{PARENT}-01"
        for i, u in enumerate(urls):
            g = _post(
                u + "/generate",
                {"prompt_ids": [5, 1, 4], "max_new_tokens": 4},
                headers={"traceparent": traceparent} if i < 2 else None,
            )
            assert len(g["tokens"]) == 4

        collector = TelemetryCollector.from_replicas(urls, interval_s=30.0)
        collector.scrape_once()
        assert all(t.up for t in collector.targets)

        # -- fleet /metrics: summed counters, bucket-merged histogram --
        httpd = make_http_server(collector, "127.0.0.1", 0)
        import threading

        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        snap = parse_exposition(text)
        assert snap["engine_requests_completed_total"][
            "samples"][0][1] == 3.0
        ttft = snap["ttft_seconds"]["samples"][0][1]
        assert ttft["count"] == 3  # one observation per replica, merged
        assert snap["collector_fleet_targets_up"]["samples"][0][1] == 3.0

        # -- top --fleet renders the matrix over the live collector --
        assert main(["top", "--fleet", "--url", base,
                     "--iterations", "1"]) == 0
        out = capsys.readouterr().out
        assert "FLEET  3/3 up" in out
        for port in ports:
            assert f"127.0.0.1:{port}" in out

        # -- stitched Chrome trace: one lane per replica process --
        with urllib.request.urlopen(
                base + f"/debug/trace?trace_id={TRACE}", timeout=10) as resp:
            doc = json.loads(resp.read())
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(lanes) >= 2  # distinct process lanes
        assert len({e["pid"] for e in xs}) >= 2
        assert all(e["args"]["trace_id"] == TRACE for e in xs)

        # -- kill a replica: staleness flips, snapshot survives --
        procs[2].terminate()
        procs[2].wait(timeout=30)
        time.sleep(0.5)
        collector.scrape_once()
        dead = next(t for t in collector.targets
                    if str(ports[2]) in t.name)
        assert not dead.up
        snap2 = collector.fleet_snapshot()
        stale = {l["target"]: v for l, v in
                 snap2["collector_target_staleness_seconds"]["samples"]}
        assert stale[dead.name] > 0
        assert snap2["collector_fleet_targets_up"]["samples"][0][1] == 2.0
        # the dead replica's last-known counters still federate
        assert snap2["engine_requests_completed_total"][
            "samples"][0][1] == 3.0
        assert "collector_target_staleness_seconds" in (
            collector.render_metrics())

        assert main(["top", "--fleet", "--url", base,
                     "--iterations", "1"]) == 0
        out = capsys.readouterr().out
        assert "FLEET  2/3 up" in out
        assert "DOWN" in out
    finally:
        if collector is not None:
            collector.stop()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
