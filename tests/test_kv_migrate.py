"""KV-block migration suite (ISSUE 20): disaggregated prefill/decode.

Three layers, mirroring tests/test_kv_tier.py:

- **Wire format.** A chain envelope round-trips bit-exactly between two
  host tiers in different "processes" (independent tier objects — the
  bytes ARE the process boundary), and every tamper mode is a clean
  :class:`WireFormatError`: truncation, a single flipped bit anywhere,
  and version skew (a v2 envelope with a RECOMPUTED trailer, so the
  version check itself is exercised, not shadowed by the checksum).

- **Fetch client.** A 404-at-source fails fast as
  :class:`KVMigrateError` (no pointless retries against a replica that
  no longer holds the chain); transient transport errors retry under
  the resilience policy and succeed.

- **Engine equivalence.** Decode on engine B with ``kv_source`` pulling
  engine A's chain must produce byte-identical streams to a cold local
  prefill — migration is a pure optimization. The chaos-marked cases
  (registered in scripts/chaos_check.py) pin the degradation ladder:
  a dead source and a corrupted envelope must both end in
  recompute-prefill with ``kv_migrate_failures`` /
  ``kv_restore_fallbacks`` accounting and zero remote nodes left in
  the radix tree — never a corrupted or hung stream.
"""

import jax
import numpy as np
import pytest

from devspace_tpu.inference import InferenceEngine
from devspace_tpu.inference.kv_tier import (
    _WIRE_VERSION,
    HostKVTier,
    KVMigrateError,
    KVMigrationClient,
    WireFormatError,
    _checksum,
    export_chain,
    import_chain,
    pack_chain_envelope,
    pack_kv_payload,
    unpack_chain_envelope,
    unpack_kv_payload,
)
from devspace_tpu.models import transformer as tfm
from devspace_tpu.resilience.policy import RetryPolicy

CFG = tfm.TINY


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _payload(seed=0, shape=(2, 2, 4, 8)):
    rng = np.random.default_rng(seed)
    kq = rng.integers(-127, 128, size=shape).astype(np.int8)
    vq = rng.integers(-127, 128, size=shape).astype(np.int8)
    ks = rng.random(shape[:3], dtype=np.float32)
    vs = rng.random(shape[:3], dtype=np.float32)
    return pack_kv_payload(kq, ks, vq, vs)


def _chain(n=3):
    return [(f"digest-{i:02d}" + "ab" * 8, _payload(seed=i))
            for i in range(n)]


# -- wire format -------------------------------------------------------------
def test_envelope_roundtrip_is_bit_exact():
    blocks = _chain(4)
    out = unpack_chain_envelope(pack_chain_envelope(blocks))
    assert [d for d, _ in out] == [d for d, _ in blocks]
    for (_, a), (_, b) in zip(blocks, out):
        assert a == b  # byte equality, not just array equality


def test_cross_process_round_trip_bit_exact_pools():
    """Two independent tiers = two processes; the envelope is the only
    thing that crosses. Unpacked int8 pools must match bit-for-bit."""
    src, dst = HostKVTier(max_bytes=1 << 20), HostKVTier(max_bytes=1 << 20)
    blocks = _chain(3)
    for digest, payload in blocks:
        src.put(digest, payload)
    envelope = export_chain(src, [d for d, _ in blocks])
    assert envelope is not None
    imported = import_chain(dst, envelope)
    assert imported == [d for d, _ in blocks]
    for digest, payload in blocks:
        got = dst.get(digest)
        assert got == payload
        for a, b in zip(unpack_kv_payload(got), unpack_kv_payload(payload)):
            np.testing.assert_array_equal(a, b)


def test_export_chain_refuses_partial():
    tier = HostKVTier(max_bytes=1 << 20)
    blocks = _chain(3)
    for digest, payload in blocks[:-1]:  # leaf missing
        tier.put(digest, payload)
    assert export_chain(tier, [d for d, _ in blocks]) is None
    assert export_chain(tier, []) is None


def test_truncated_envelope_rejected():
    envelope = pack_chain_envelope(_chain(2))
    for cut in (1, 8, len(envelope) // 2, len(envelope) - 1):
        with pytest.raises(WireFormatError):
            unpack_chain_envelope(envelope[:cut])


def test_bit_flip_rejected_everywhere():
    """Flipping any single byte — magic, digest, payload, length field,
    trailer — must raise, never return altered blocks."""
    envelope = pack_chain_envelope(_chain(2))
    step = max(1, len(envelope) // 37)  # sample positions across it
    for pos in range(0, len(envelope), step):
        bad = (envelope[:pos]
               + bytes([envelope[pos] ^ 0x40])
               + envelope[pos + 1:])
        with pytest.raises(WireFormatError):
            unpack_chain_envelope(bad)


def test_version_skew_rejected_cleanly():
    """A future-version envelope with a VALID trailer (recomputed over
    the modified body) is rejected by the version check itself."""
    envelope = pack_chain_envelope(_chain(1))
    body = bytearray(envelope[:-len(_checksum(b""))])
    assert body[4] == _WIRE_VERSION
    body[4] = _WIRE_VERSION + 8
    skewed = bytes(body) + _checksum(bytes(body))
    with pytest.raises(WireFormatError, match="version"):
        unpack_chain_envelope(skewed)


def test_envelope_trailing_bytes_rejected():
    envelope = pack_chain_envelope(_chain(1))
    with pytest.raises(WireFormatError):
        unpack_chain_envelope(envelope + b"xx")


# -- fetch client ------------------------------------------------------------
def test_client_404_fails_fast_no_retry():
    calls = []

    def fetch(source, digest):
        calls.append(digest)
        raise KVMigrateError("gone at source")

    client = KVMigrationClient(fetch_fn=fetch)
    with pytest.raises(KVMigrateError):
        client.fetch("http://peer", "deadbeef")
    assert len(calls) == 1  # non-retryable: exactly one attempt


def test_client_retries_transient_then_succeeds():
    calls = []

    def fetch(source, digest):
        calls.append(source)
        if len(calls) < 3:
            raise OSError("connection reset")
        return b"the-envelope"

    client = KVMigrationClient(
        retry=RetryPolicy(max_attempts=3, base_delay=0.001,
                          retry_on=(OSError,), seed=0),
        fetch_fn=fetch)
    assert client.fetch("http://peer", "d0") == b"the-envelope"
    assert len(calls) == 3


# -- engine-level migration --------------------------------------------------
PROMPT = [(7 * i) % 49 + 1 for i in range(40)]  # 4 full blocks at bs=8
N_NEW = 8


def _mk_engine(params, **kw):
    defaults = dict(max_slots=2, max_len=64, block_size=8, n_blocks=10,
                    prefill_chunk=8, chunk_max=4)
    defaults.update(kw)
    return InferenceEngine(params, CFG, kv_tier="host", **defaults)


@pytest.fixture(scope="module")
def baseline(params):
    """Cold local prefill+decode — the equivalence reference."""
    engine = _mk_engine(params).start()
    try:
        return engine.submit(PROMPT, N_NEW).result(timeout=600)
    finally:
        engine.stop()


def _exporting_fetch(source_engine):
    def fetch(source, digest):
        envelope = source_engine.export_kv_chain(digest)
        if envelope is None:
            raise KVMigrateError(f"no chain {digest[:16]} at source")
        return envelope
    return fetch


def test_engine_migration_is_byte_identical(params, baseline):
    """A -> B chain migration: B's decode output must equal a cold local
    prefill, with the migrate counters proving the pull happened."""
    a = _mk_engine(params).start()
    b = _mk_engine(params).start()
    try:
        assert a.submit(PROMPT, N_NEW).result(timeout=600) == baseline
        b._kv_client = KVMigrationClient(fetch_fn=_exporting_fetch(a))
        tokens = b.submit(
            PROMPT, N_NEW, kv_source="engine-a").result(timeout=600)
        st = b.stats()
    finally:
        a.stop()
        b.stop()
    assert tokens == baseline
    assert st["kv_migrate_chains"] == 1
    assert st["kv_migrate_blocks"] == 4
    assert st["kv_migrate_bytes"] > 0
    assert st["kv_migrate_failures"] == 0
    assert st["kv_tier_remote_nodes"] == 0  # all promoted + restored
    assert a.stats()["kv_export_chains"] == 1


def test_kv_source_ignored_when_chain_already_local(params, baseline):
    """A replica that already holds the prefix must not fetch at all."""
    calls = []

    def fetch(source, digest):
        calls.append(digest)
        raise AssertionError("must not fetch")

    engine = _mk_engine(params).start()
    try:
        engine._kv_client = KVMigrationClient(fetch_fn=fetch)
        assert engine.submit(PROMPT, N_NEW).result(timeout=600) == baseline
        again = engine.submit(
            PROMPT, N_NEW, kv_source="http://peer").result(timeout=600)
    finally:
        engine.stop()
    assert again == baseline
    assert calls == []


# -- chaos (registered in scripts/chaos_check.py) ----------------------------
@pytest.mark.chaos
def test_dead_source_degrades_to_recompute(params, baseline):
    """Every fetch attempt dies with a transport error: the request must
    recompute prefill locally and stream byte-identical output, counting
    one migrate failure and one restore fallback, leaving no remote
    nodes behind."""
    calls = []

    def fetch(source, digest):
        calls.append(digest)
        raise OSError("connection refused")

    b = _mk_engine(params).start()
    try:
        b._kv_client = KVMigrationClient(
            retry=RetryPolicy(max_attempts=2, base_delay=0.001,
                              retry_on=(OSError,), seed=0),
            fetch_fn=fetch)
        tokens = b.submit(
            PROMPT, N_NEW, kv_source="http://dead").result(timeout=600)
        st = b.stats()
    finally:
        b.stop()
    assert tokens == baseline
    assert len(calls) == 2  # retried once, then gave up
    assert st["kv_migrate_chains"] == 0
    assert st["kv_migrate_failures"] == 1
    assert st["kv_restore_fallbacks"] >= 1
    assert st["kv_tier_remote_nodes"] == 0  # pruned, not leaked


@pytest.mark.chaos
def test_corrupted_envelope_degrades_to_recompute(params, baseline):
    """A bit-flipped envelope from a live source must be REJECTED by the
    wire checksum and degrade to recompute — never scattered into the
    pool (output stays byte-identical)."""
    a = _mk_engine(params).start()
    b = _mk_engine(params).start()
    try:
        assert a.submit(PROMPT, N_NEW).result(timeout=600) == baseline
        real = _exporting_fetch(a)

        def corrupting(source, digest):
            envelope = real(source, digest)
            mid = len(envelope) // 2
            return (envelope[:mid] + bytes([envelope[mid] ^ 0xFF])
                    + envelope[mid + 1:])

        b._kv_client = KVMigrationClient(fetch_fn=corrupting)
        tokens = b.submit(
            PROMPT, N_NEW, kv_source="engine-a").result(timeout=600)
        st = b.stats()
    finally:
        a.stop()
        b.stop()
    assert tokens == baseline
    assert st["kv_migrate_chains"] == 0
    assert st["kv_migrate_failures"] == 1
    assert st["kv_restore_fallbacks"] >= 1
    assert st["kv_tier_remote_nodes"] == 0
