"""Tiered KV cache suite (inference/kv_tier.py — ISSUE 7).

Three layers: HostKVTier unit behavior (LRU-by-bytes, disk overflow +
promotion, checksum verification, eviction callbacks), the radix tree's
third node state (spill / match-through / revive / prune), and
engine-level equivalence — the tier must be INVISIBLE in outputs:
byte-identical token streams with the tier off, on, and disk-backed,
across eviction pressure, preemption and speculative interleave. The
chaos-marked cases pin the degradation ladder: a restore failure
mid-flight falls back to recompute-prefill, and a corrupted spilled
payload is dropped on digest mismatch — never scattered into the pool.
Satellites pinned here too: the ``prefix_hit_tokens`` /
``recompute_tokens_saved`` stats goldens and the ``_pop_block``
``_block_refs`` bookkeeping invariant.
"""

import os

import jax
import numpy as np
import pytest

from devspace_tpu.inference import InferenceEngine
from devspace_tpu.inference.kv_tier import (
    HostKVTier,
    _checksum,
    pack_kv_payload,
    resolve_kv_tier,
    unpack_kv_payload,
)
from devspace_tpu.inference.prefix_cache import RadixPrefixCache
from devspace_tpu.inference.quantization import (
    dequantize_kv_block,
    quantize_kv_block,
)
from devspace_tpu.models import transformer as tfm

CFG = tfm.TINY


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _payload(seed=0, shape=(2, 2, 4, 8)):
    rng = np.random.default_rng(seed)
    kq = rng.integers(-127, 128, size=shape).astype(np.int8)
    vq = rng.integers(-127, 128, size=shape).astype(np.int8)
    ks = rng.random(shape[:3], dtype=np.float32)
    vs = rng.random(shape[:3], dtype=np.float32)
    return pack_kv_payload(kq, ks, vq, vs), (kq, ks, vq, vs)


# -- payload format --------------------------------------------------------
def test_pack_unpack_roundtrip():
    buf, (kq, ks, vq, vs) = _payload()
    kq2, ks2, vq2, vs2 = unpack_kv_payload(buf)
    np.testing.assert_array_equal(kq, kq2)
    np.testing.assert_array_equal(vq, vq2)
    np.testing.assert_array_equal(ks, ks2)
    np.testing.assert_array_equal(vs, vs2)


def test_unpack_rejects_bad_magic_and_truncation():
    buf, _ = _payload()
    with pytest.raises(ValueError, match="magic"):
        unpack_kv_payload(b"XXXX" + buf[4:])
    with pytest.raises(ValueError, match="length"):
        unpack_kv_payload(buf[:-1])
    with pytest.raises(ValueError):
        pack_kv_payload(
            np.zeros((1, 1, 2, 4), np.float32),  # not int8
            np.ones((1, 1, 2), np.float32),
            np.zeros((1, 1, 2, 4), np.int8),
            np.ones((1, 1, 2), np.float32),
        )


def test_quantize_kv_block_roundtrip_accuracy():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 2, 8, 16)).astype(np.float32)
    q, scale = quantize_kv_block(x)
    assert q.dtype == np.int8 and scale.shape == (2, 2, 8)
    deq = dequantize_kv_block(q, scale)
    rel = np.abs(deq - x).max() / np.abs(x).max()
    assert rel < 0.01  # the ~0.5% int8 noise profile, with headroom
    # all-zero rows quantize cleanly (scale floor, no NaN)
    q0, s0 = quantize_kv_block(np.zeros((1, 1, 2, 4), np.float32))
    assert not np.isnan(s0).any() and (q0 == 0).all()
    np.testing.assert_array_equal(dequantize_kv_block(q0, s0), 0)


# -- HostKVTier ------------------------------------------------------------
def test_tier_lru_by_bytes_eviction_order():
    buf, _ = _payload()
    tier = HostKVTier(max_bytes=len(buf) * 2)
    gone = []
    tier.on_evict = gone.append
    tier.put("a", buf)
    tier.put("b", buf)
    tier.get("a")  # refresh: b is now oldest
    tier.put("c", buf)
    assert gone == ["b"]
    assert tier.get("b") is None and tier.get("a") is not None
    assert tier.resident_bytes == len(buf) * 2
    assert tier.stats()["evictions"] == 1


def test_tier_reput_refreshes_lru_without_duplicating_bytes():
    buf, _ = _payload()
    tier = HostKVTier(max_bytes=len(buf) * 2)
    tier.put("a", buf)
    tier.put("b", buf)
    tier.put("a", buf)  # refresh, not duplicate
    assert tier.resident_bytes == len(buf) * 2
    tier.put("c", buf)  # now b (oldest) ages out, a survives
    assert tier.get("a") is not None and tier.get("b") is None


def test_tier_disk_overflow_and_promotion(tmp_path):
    buf, _ = _payload()
    tier = HostKVTier(max_bytes=len(buf), disk_dir=str(tmp_path))
    gone = []
    tier.on_evict = gone.append
    tier.put("a", buf)
    tier.put("b", buf)  # a overflows to disk, not dropped
    assert gone == []
    st = tier.stats()
    assert st["ram_entries"] == 1 and st["disk_entries"] == 1
    assert os.path.exists(tmp_path / "a.kv")
    # read promotes a back to RAM (and b overflows down)
    assert tier.get("a") == buf
    st = tier.stats()
    assert st["ram_entries"] == 1 and st["disk_entries"] == 1
    assert not os.path.exists(tmp_path / "a.kv")
    assert len(tier) == 2


def test_tier_disk_budget_ages_off_end_of_tier(tmp_path):
    buf, _ = _payload()
    tier = HostKVTier(
        max_bytes=len(buf),
        disk_dir=str(tmp_path),
        disk_max_bytes=(len(buf) + 16) * 2,
    )
    gone = []
    tier.on_evict = gone.append
    for d in "abcd":
        tier.put(d, buf)
    # a,b,c overflowed to disk in order; disk holds 2 -> a aged off
    assert gone == ["a"]
    assert tier.get("a") is None
    assert tier.get("b") == buf  # promoted back from disk


def test_tier_corrupt_ram_payload_dropped_as_miss():
    buf, _ = _payload()
    tier = HostKVTier()
    tier.put("a", buf)
    payload, checksum = tier._ram["a"]
    bad = bytearray(payload)
    bad[30] ^= 0xFF
    tier._ram["a"] = (bytes(bad), checksum)
    assert tier.get("a") is None
    assert tier.stats()["corrupt_dropped"] == 1
    assert "a" not in tier._ram and tier.resident_bytes == 0


def test_tier_corrupt_disk_file_dropped_as_miss(tmp_path):
    buf, _ = _payload()
    tier = HostKVTier(max_bytes=len(buf), disk_dir=str(tmp_path))
    tier.put("a", buf)
    tier.put("b", buf)  # a -> disk
    path = tmp_path / "a.kv"
    raw = bytearray(path.read_bytes())
    raw[40] ^= 0xFF
    path.write_bytes(bytes(raw))
    assert tier.get("a") is None
    assert tier.stats()["corrupt_dropped"] == 1
    assert not path.exists()


def test_tier_discard_is_silent(tmp_path):
    buf, _ = _payload()
    tier = HostKVTier(max_bytes=len(buf), disk_dir=str(tmp_path))
    gone = []
    tier.on_evict = gone.append
    tier.put("a", buf)
    tier.put("b", buf)  # a on disk, b in RAM
    tier.discard("a")
    tier.discard("b")
    tier.discard("nope")
    assert gone == [] and len(tier) == 0 and tier.resident_bytes == 0
    assert not os.path.exists(tmp_path / "a.kv")


def test_resolve_kv_tier_modes(monkeypatch):
    assert resolve_kv_tier(None) == "off"
    assert resolve_kv_tier("host") == "host"
    assert resolve_kv_tier("HOST+DISK") == "host+disk"
    monkeypatch.setenv("DEVSPACE_KV_TIER", "host")
    assert resolve_kv_tier(None) == "host"
    assert resolve_kv_tier("off") == "off"  # explicit arg wins
    with pytest.raises(ValueError):
        resolve_kv_tier("sideways")


# -- radix tree: the third node state --------------------------------------
def _publish_chain(cache, blocks, start_blk=1):
    cur = cache.cursor()
    for i, edge in enumerate(blocks):
        cur.publish(tuple(edge), start_blk + i, 0)
    return cur


def test_spill_keeps_chain_matchable_and_revivable():
    cache = RadixPrefixCache(track_digests=True)
    edges = [(1, 2), (3, 4), (5, 6)]
    _publish_chain(cache, edges)
    spill, dropped = [], []
    # evict the whole chain root-first -> all three spill
    blk, freed = cache.pop_victim(collect_spill=spill, dropped=dropped)
    assert blk == 1 and sorted(freed) == [2, 3]
    assert len(spill) == 3 and dropped == []
    assert cache.spilled_count() == 3 and cache.evictable() == 0
    # plain step refuses spilled nodes; step_tiered walks through them
    cur = cache.cursor()
    assert cur.step((1, 2)) is None
    cur = cache.cursor()
    kinds = [cur.step_tiered(e) for e in edges]
    assert [k[0] for k in kinds] == ["spill"] * 3
    assert kinds[0][1] == spill[0][0]  # digest order matches spill order
    # revive mid-chain: publish makes the node resident again
    cur = cache.cursor()
    cur.publish(edges[0], 7, 1)
    assert cache.spilled_count() == 2
    cur2 = cache.cursor()
    assert cur2.step(edges[0]) == 7
    assert cur2.step_tiered(edges[1])[0] == "spill"


def test_drop_spilled_prunes_subtree_and_reports_digests():
    cache = RadixPrefixCache(track_digests=True)
    edges = [(1, 2), (3, 4), (5, 6)]
    _publish_chain(cache, edges)
    spill, dropped = [], []
    cache.pop_victim(collect_spill=spill, dropped=dropped)
    top_digest = spill[0][0]
    gone_digests, freed = cache.drop_spilled(top_digest)
    assert sorted(gone_digests) == sorted(d for d, _ in spill[1:])
    assert freed == [] and cache.spilled_count() == 0
    cur = cache.cursor()
    assert cur.step_tiered(edges[0]) is None
    # unknown digest is a no-op
    assert cache.drop_spilled("beef") == ([], [])


def test_broken_ancestor_chain_drops_orphaned_spilled_nodes():
    cache = RadixPrefixCache(track_digests=True)
    _publish_chain(cache, [(1, 2), (3, 4)])
    cache.cursor().step((1, 2))  # refresh parent: child is now LRU
    spill = []
    # evict the child first: only (3,4) spills, its parent stays resident
    cache.pop_victim(collect_spill=spill, dropped=[])
    assert len(spill) == 1 and cache.spilled_count() == 1
    # parent evicted WITHOUT spilling (untiered call): the orphaned
    # spilled child must be pruned and its digest reported
    dropped = []
    cache.pop_victim(dropped=dropped)
    assert dropped == [spill[0][0]]
    assert cache.spilled_count() == 0
    cur = cache.cursor()
    assert cur.step_tiered((1, 2)) is None


def test_tier_off_default_has_no_digest_overhead():
    cache = RadixPrefixCache()  # track_digests=False
    _publish_chain(cache, [(1, 2), (3, 4)])
    spill = []
    blk, freed = cache.pop_victim(collect_spill=spill)
    # without digests nothing can spill: old semantics exactly
    assert spill == [] and blk == 1 and freed == [2]
    assert cache.spilled_count() == 0


# -- engine equivalence: the tier must be invisible in outputs -------------
def _run(params, reqs, kv_tier="off", waves=None, **kw):
    """Serve requests (optionally in sequential waves to force eviction
    between them) and return (streams, stats)."""
    defaults = dict(
        max_slots=2, max_len=64, block_size=8, n_blocks=10,
        prefill_chunk=8, chunk_max=4,
    )
    defaults.update(kw)
    engine = InferenceEngine(params, CFG, kv_tier=kv_tier, **defaults).start()
    outs = []
    try:
        if waves:
            for lo, hi in waves:
                hs = [engine.submit(**r) for r in reqs[lo:hi]]
                outs.extend(h.result(timeout=600) for h in hs)
        else:
            hs = [engine.submit(**r) for r in reqs]
            outs = [h.result(timeout=600) for h in hs]
        st = engine.stats()
    finally:
        engine.stop()
    return outs, st


def _spill_restore_trace(seed=1, tail=(7, 9)):
    """Seed a prefix, flood it out of the pool, then re-hit it: wave
    boundaries force the eviction (spill) and the re-hit (restore)."""
    rng = np.random.default_rng(seed)
    shared = [int(t) for t in rng.integers(2, 200, size=24)]
    reqs = [dict(prompt_ids=shared, max_new_tokens=8)]
    for _ in range(4):
        reqs.append(dict(
            prompt_ids=[int(t) for t in rng.integers(2, 200, size=24)],
            max_new_tokens=8,
        ))
    reqs.append(dict(prompt_ids=shared + list(tail), max_new_tokens=8))
    waves = [(i, i + 1) for i in range(len(reqs))]
    return reqs, waves


# Tier-off baselines are pure functions of (trace, engine kw) — memoized
# so tests sharing a trace pay the engine build + compile once per
# process (each engine costs seconds of XLA compiles on a 1-core CI
# box). Keys are explicit, not derived, so a kw drift can't silently
# alias two different baselines.
_OFF_BASELINES: dict = {}


def _off_baseline(key, params, reqs, waves, **kw):
    if key not in _OFF_BASELINES:
        _OFF_BASELINES[key] = _run(params, reqs, "off", waves, **kw)
    return _OFF_BASELINES[key]


def test_restore_streams_identical_and_saves_recompute(params):
    """int8 KV pool: the resident representation IS the spill format, so
    restores are bit-exact and byte-identity is a hard invariant even
    through spill/restore cycles."""
    reqs, waves = _spill_restore_trace()
    kw = dict(max_slots=1, n_blocks=9, kv_dtype="int8")
    off, st_off = _off_baseline("seed1-int8", params, reqs, waves, **kw)
    host, st_host = _run(params, reqs, "host", waves, **kw)
    assert off == host
    assert st_off["kv_tier"] == "off" and st_host["kv_tier"] == "host"
    assert st_host["kv_spill_blocks"] > 0
    assert st_host["kv_restore_hits"] >= 3  # the 24-token shared prefix
    assert st_host["kv_restore_fallbacks"] == 0
    assert st_host["kv_restore_hit_rate"] == 1.0
    assert st_host["recompute_tokens_saved"] == (
        st_host["kv_restore_hits"] * 8
    )
    assert st_off["kv_spill_blocks"] == 0 and st_off["kv_tier_entries"] == 0


def test_float_pool_restore_identical_on_tie_free_trace(params):
    """Float (bf16) pool: restores dequantize int8 payloads, carrying
    the documented ~0.5% noise — greedy near-ties CAN flip, so exact
    equality holds only on tie-free trajectories. This trace is pinned
    tie-free for TINY at these lengths (same caveat-and-precedent as the
    preemption equivalence tests)."""
    reqs, waves = _spill_restore_trace(tail=(7, 7))
    kw = dict(max_slots=1, n_blocks=9)
    off, _ = _run(params, reqs, "off", waves, **kw)
    host, st = _run(params, reqs, "host", waves, **kw)
    assert off == host
    assert st["kv_restore_hits"] == 3


def test_disk_tier_streams_identical(params, tmp_path):
    """int8 pool (bit-exact restores) so equality is hard through the
    disk level too; shares the tier-off baseline with the host test."""
    reqs, waves = _spill_restore_trace()
    kw = dict(max_slots=1, n_blocks=9, kv_dtype="int8")
    off, _ = _off_baseline("seed1-int8", params, reqs, waves, **kw)
    disk, st = _run(
        params, reqs, "host+disk", waves,
        kv_tier_bytes=4096, kv_tier_dir=str(tmp_path), **kw
    )
    assert off == disk
    assert st["kv_restore_hits"] >= 1
    # the tiny RAM budget forced traffic through the disk level
    assert st["kv_spill_bytes"] > 4096


@pytest.mark.parametrize(
    "trial",
    # one trial in tier-1; the rest ride the slow lane (each trial costs
    # two engine builds' worth of XLA compiles on a 1-core CI box)
    [0, pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_randomized_traces_tier_invariant(params, trial):
    """Randomized admit/length/sampling matrix under pool pressure:
    byte-identical streams off vs host, preemption included (greedy and
    seeded-sampled requests). int8 KV pool so restores are bit-exact —
    equality is a hard invariant, not a tie-free-trace property.

    Runs under the OVERLAPPED loop (default depth 2): sampled streams
    are schedule-invariant across preemption since the position-keyed
    PRNG scheme (ROADMAP item 2) — the key for committed token k is
    ``fold_in(PRNGKey(seed), position_of(k-1))``, a function of k
    alone, so drain-timing-dependent preemption points can no longer
    move a sampled stream."""
    rng = np.random.default_rng(7 + trial)
    reqs = []
    for i in range(5):
        n = int(rng.integers(6, 24))
        r = dict(
            prompt_ids=[int(t) for t in rng.integers(2, 200, size=n)],
            max_new_tokens=int(rng.integers(4, 20)),
        )
        if i % 2:
            r.update(temperature=0.8, seed=trial * 10 + i, top_k=8)
        reqs.append(r)
    kw = dict(kv_dtype="int8", dispatch_depth=2)
    off, _ = _run(params, reqs, "off", **kw)
    host, _ = _run(params, reqs, "host", **kw)
    assert off == host, f"trial {trial} diverged"


@pytest.mark.slow
def test_preemption_resume_restores_spilled_chain(params):
    """Tight pool + long decodes force preemption; the preempted chain
    spills and the resumed request's streams still match tier-off.
    Greedy requests + int8 pool: resume-by-restore is bit-exact, so
    equality holds under the overlapped loop's timing-dependent
    preemption points (sampled requests would not — see the
    dispatch_depth=1 note on the randomized matrix)."""
    rng = np.random.default_rng(2)
    reqs = [
        dict(
            prompt_ids=[int(t) for t in rng.integers(2, 200, size=16)],
            max_new_tokens=24,
        )
        for _ in range(5)
    ]
    kw = dict(dispatch_depth=2, kv_dtype="int8")
    off, st_off = _run(params, reqs, "off", **kw)
    host, st_host = _run(params, reqs, "host", **kw)
    assert off == host
    assert st_off["requests_preempted"] > 0
    assert st_host["kv_spill_blocks"] > 0


@pytest.mark.slow
def test_speculative_interleave_tier_invariant(params):
    """Greedy speculative decoding (draft+verify through the window)
    with the tier on stays byte-identical to tier-off."""
    rng = np.random.default_rng(5)
    reqs = [
        dict(
            prompt_ids=[int(t) for t in rng.integers(2, 200, size=12)],
            max_new_tokens=16,
        )
        for _ in range(4)
    ]
    kw = dict(
        draft_params=params, draft_cfg=CFG, spec_k=3, dispatch_depth=2,
    )
    off, _ = _run(params, reqs, "off", **kw)
    host, st = _run(params, reqs, "host", **kw)
    assert off == host
    assert st["spec_rounds"] > 0


def test_unpressured_pool_never_touches_tier(params):
    """With no pool pressure the tier must be byte-inert: zero spills,
    zero restores, identical streams."""
    reqs = [
        dict(prompt_ids=[5, 1, 4, 9], max_new_tokens=8),
        dict(prompt_ids=[2, 3], max_new_tokens=8),
    ]
    kw = dict(n_blocks=32)
    off, _ = _run(params, reqs, "off", **kw)
    host, st = _run(params, reqs, "host", **kw)
    assert off == host
    assert st["kv_spill_blocks"] == 0 and st["kv_restore_hits"] == 0
    assert st["kv_tier_resident_bytes"] == 0


# -- stats goldens (satellite 2) -------------------------------------------
def test_prefix_hit_token_goldens(params):
    """Hand-computed: two identical 16-token prompts, block_size 8.
    The second request matches one full block (the cap leaves the last
    prompt token to prefill), so prefix_hit_blocks=1, and
    prefix_hit_tokens = 1 * 8. Nothing restored -> saved stays 0."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]
    reqs = [
        dict(prompt_ids=prompt, max_new_tokens=4),
        dict(prompt_ids=prompt, max_new_tokens=4),
    ]
    waves = [(0, 1), (1, 2)]
    _, st = _run(params, reqs, "host", waves, n_blocks=32)
    assert st["prefix_hit_blocks"] == 1
    assert st["prefix_hit_tokens"] == 8
    assert st["recompute_tokens_saved"] == 0
    assert st["kv_restore_hit_rate"] == 0.0


def test_restore_golden_saved_tokens(params):
    """Hand-computed restore golden: the 24-token shared prefix spills
    as 3 full blocks; the re-hit restores all 3 -> hit_tokens = 24 (3
    restored blocks, 0 resident matches) and saved = 24."""
    reqs, waves = _spill_restore_trace()
    _, st = _run(params, reqs, "host", waves, max_slots=1, n_blocks=9)
    assert st["kv_restore_hits"] == 3
    assert st["recompute_tokens_saved"] == 24
    assert st["prefix_hit_tokens"] >= 24


# -- _pop_block bookkeeping invariant (satellite 6) ------------------------
def test_pop_block_zeroes_block_refs_bookkeeping(params):
    """Evicted blocks must leave ``_block_refs`` with zero references —
    a stale nonzero entry means a table still points at a recycled
    block (the ``_pop_block`` assert). After a pressure trace with
    spill/restore churn no free block carries a reference."""
    reqs, waves = _spill_restore_trace(seed=3)
    engine = InferenceEngine(
        params, CFG, max_slots=1, max_len=64, block_size=8, n_blocks=9,
        prefill_chunk=8, chunk_max=4, kv_tier="host",
    ).start()
    try:
        for lo, hi in waves:
            hs = [engine.submit(**r) for r in reqs[lo:hi]]
            for h in hs:
                h.result(timeout=600)
        for b in engine._free_blocks:
            assert engine._block_refs.get(b, 0) == 0, (
                f"stale refs for free block {b}"
            )
        for b, refs in engine._block_refs.items():
            assert refs >= 0
    finally:
        engine.stop()


# -- chaos: degradation ladder (satellite 3) -------------------------------
@pytest.mark.chaos
def test_chaos_restore_failure_degrades_to_recompute(params):
    """Kill the host tier mid-flight: every restore attempt raises. The
    engine must fall back to recompute-prefill, count the fallbacks,
    prune the dead chain, and stream byte-identically."""
    reqs, waves = _spill_restore_trace()
    kw = dict(max_slots=1, n_blocks=9)
    off, _ = _off_baseline("seed1-float", params, reqs, waves, **kw)

    engine = InferenceEngine(
        params, CFG, max_slots=1, max_len=64, block_size=8, n_blocks=9,
        prefill_chunk=8, chunk_max=4, kv_tier="host",
    ).start()
    outs = []
    try:

        def flaky_get(digest):
            raise OSError("injected host-tier failure")

        for i, (lo, hi) in enumerate(waves):
            if i == len(waves) - 1:  # the restore wave
                engine._kv_tier.get = flaky_get
            hs = [engine.submit(**r) for r in reqs[lo:hi]]
            outs.extend(h.result(timeout=600) for h in hs)
        st = engine.stats()
    finally:
        engine.stop()
    assert outs == off
    assert st["kv_restore_fallbacks"] >= 1
    assert st["kv_restore_hits"] == 0
    assert st["kv_restore_hit_rate"] == 0.0
    # the failed chain was pruned: no dangling spilled nodes promising
    # restores the tier can no longer honor
    assert st["kv_tier_spilled_nodes"] == engine._prefix_cache.spilled_count()


@pytest.mark.chaos
def test_chaos_corrupt_spilled_block_never_scattered(params):
    """Flip bits in every spilled payload: the checksum re-verify must
    drop them all (corrupt_dropped counts), restores fall back to
    recompute, and the stream stays byte-identical — corrupted K/V is
    never scattered into the pool."""
    reqs, waves = _spill_restore_trace()
    kw = dict(max_slots=1, n_blocks=9)
    off, _ = _off_baseline("seed1-float", params, reqs, waves, **kw)

    engine = InferenceEngine(
        params, CFG, max_slots=1, max_len=64, block_size=8, n_blocks=9,
        prefill_chunk=8, chunk_max=4, kv_tier="host",
    ).start()
    outs = []
    try:
        for i, (lo, hi) in enumerate(waves):
            if i == len(waves) - 1:
                tier = engine._kv_tier
                assert len(tier._ram) > 0
                for d, (payload, checksum) in list(tier._ram.items()):
                    bad = bytearray(payload)
                    bad[len(bad) // 2] ^= 0xFF
                    tier._ram[d] = (bytes(bad), checksum)
            hs = [engine.submit(**r) for r in reqs[lo:hi]]
            outs.extend(h.result(timeout=600) for h in hs)
        st = engine.stats()
        tier_stats = engine._kv_tier.stats()
    finally:
        engine.stop()
    assert outs == off
    assert st["kv_restore_hits"] == 0
    assert st["kv_restore_fallbacks"] >= 1
    assert tier_stats["corrupt_dropped"] >= 1
