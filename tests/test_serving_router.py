"""Prefix-aware router tests: golden decision tables + live gateway.

The decision-core tests mirror tests/test_serving_autoscale.py: loads
and clock are injected, every expected replica choice is hand-computed
from the scoring formula in devspace_tpu/serving/router.py, and the
tables pin the RouterConfig defaults — change a weight and these fail
loudly with the arithmetic to re-derive.

The live tests run real stub subprocesses behind a real gateway. The
chaos-marked test (registered in scripts/chaos_check.py) SIGKILLs the
routed replica mid-stream and requires the retry to reroute with ZERO
corrupted outcomes — the gateway must never replay bytes into a
half-written client stream.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from devspace_tpu.inference.prefix_cache import _chain_digest, fingerprint_chain
from devspace_tpu.serving import ReplicaFleet, ReplicaSpec
from devspace_tpu.serving.gateway import RoutingGateway
from devspace_tpu.serving.loadgen import LoadGenerator, TraceSpec, generate_trace
from devspace_tpu.serving.router import (
    ADMIT,
    QUEUE,
    REJECT,
    PrefixRouter,
    ReplicaLoad,
    RouterConfig,
    ShadowRadixIndex,
    loads_from_collector,
)


def counter_value(router, name: str) -> float:
    fam = router.registry.snapshot().get(name)
    if not fam or not fam["samples"]:
        return 0.0
    return float(fam["samples"][0][1])


# -- fingerprint chain -------------------------------------------------------
def test_fingerprint_chain_matches_chain_digest():
    ids = list(range(20))
    chain = fingerprint_chain(ids, 8)
    d0 = _chain_digest("", tuple(ids[0:8]))
    d1 = _chain_digest(d0, tuple(ids[8:16]))
    assert chain == [d0, d1]  # trailing partial block (4 ids) excluded


def test_fingerprint_chain_edges():
    assert fingerprint_chain([], 8) == []
    assert fingerprint_chain([1, 2, 3], 8) == []  # under one block
    assert len(fingerprint_chain([1, 2, 3], 1)) == 3
    with pytest.raises(ValueError):
        fingerprint_chain([1], 0)
    # chains are prefix-consistent: extending the ids extends the chain
    a = fingerprint_chain(list(range(16)), 8)
    b = fingerprint_chain(list(range(24)), 8)
    assert b[: len(a)] == a


# -- shadow radix index ------------------------------------------------------
def test_shadow_overlap_is_leading_run_only():
    ix = ShadowRadixIndex()
    chain = fingerprint_chain(list(range(32)), 8)  # 4 digests
    ix.observe("r0", chain[:2])
    assert ix.overlap("r0", chain) == 2
    assert ix.overlap("r1", chain) == 0
    # a hole breaks the run: radix rule, block K needs blocks 0..K-1
    ix2 = ShadowRadixIndex()
    ix2.observe("r0", [chain[0], chain[2]])
    assert ix2.overlap("r0", chain) == 1


def test_shadow_lru_eviction_and_drop():
    ix = ShadowRadixIndex(max_blocks=2)
    ix.observe("r0", ["a", "b"])
    ix.overlap("r0", ["a"])        # touch "a" — "b" becomes LRU
    ix.observe("r0", ["c"])        # evicts "b"
    assert ix.overlap("r0", ["a"]) == 1
    assert ix.overlap("r0", ["b"]) == 0
    assert ix.blocks("r0") == 2
    ix.drop_replica("r0")
    assert ix.total_blocks() == 0


# -- golden decision tables --------------------------------------------------
def make_router(replicas=("a", "b"), loads=None, **cfg_kw):
    cfg_kw.setdefault("policy", "prefix")
    loads = dict(loads or {})
    return PrefixRouter(
        replicas_fn=lambda: {n: f"http://{n}" for n in replicas},
        loads_fn=lambda: loads,
        config=RouterConfig(**cfg_kw),
        clock=lambda: 0.0,
    )


def test_cold_start_ties_break_by_name():
    r = make_router(replicas=("b", "a", "c"))
    d = r.route(list(range(16)))
    assert (d.admission, d.replica, d.spilled) == (ADMIT, "a", False)
    assert d.scores == {"a": 0.0, "b": 0.0, "c": 0.0}


def test_prefix_affinity_sticks_to_the_chain_holder():
    r = make_router()
    prompt = list(range(16))  # exactly 2 blocks at block_size=8
    first = r.route(prompt)
    r.complete(first.replica, service_s=0.1)
    again = r.route(prompt)
    # overlap 16/16 on "a": score a = 1.0*1.0 - 0 - 0 = 1.0, b = 0.0
    assert (again.replica, again.overlap_tokens) == ("a", 16)
    assert again.scores["a"] == 1.0 and again.scores["b"] == 0.0
    # a longer prompt sharing the prefix still maps to the holder:
    # overlap 16 of 32 tokens -> score a = 0.5
    r.complete("a", service_s=0.1)
    longer = r.route(list(range(32)))
    assert (longer.replica, longer.overlap_tokens) == ("a", 16)
    assert longer.scores["a"] == 0.5


def test_hot_prefix_holder_spills_to_next_best():
    # "a" holds the whole chain (overlap ratio 1.0) but is loaded:
    #   load(a) = occupancy 1.0 + queued 6/6 + 0.5*0 = 2.0
    #   score(a) = 1.0*1.0 - 0.6*2.0 = -0.2 ;  score(b) = 0 - 0 = 0.0
    loads = {"a": ReplicaLoad(occupancy=1.0, queued=6, max_slots=6,
                              active=6)}
    r = make_router(loads=loads, admission=False)
    prompt = list(range(16))
    r.shadow.observe("a", fingerprint_chain(prompt, 8))
    d = r.route(prompt)
    assert (d.replica, d.spilled) == ("b", True)
    assert d.scores["a"] == pytest.approx(-0.2)
    assert d.scores["b"] == 0.0
    assert counter_value(r, "serving_router_spillovers_total") == 1


def test_slo_pressure_is_part_of_the_load_term():
    # equal otherwise, but "a" is in TTFT-burn warn (pressure 1.0):
    #   score(a) = -0.6 * (0 + 0 + 0.5*1.0) = -0.3 < score(b) = 0
    loads = {"a": ReplicaLoad(slo_pressure=1.0), "b": ReplicaLoad()}
    r = make_router(loads=loads)
    d = r.route(list(range(16)))
    assert d.replica == "b"
    assert d.scores["a"] == pytest.approx(-0.3)


def test_fairness_steers_a_dominating_tenant_away():
    r = make_router()
    prompt_alice = list(range(100, 108))
    for _ in range(2):  # alice takes "a" twice (tie-break, then prefix)
        d = r.route(prompt_alice, tenant="alice")
        assert d.replica == "a"
        r.complete("a", service_s=0.1)
    d = r.route(list(range(200, 208)), tenant="bob")  # bob: ties -> "a"
    assert d.replica == "a"
    r.complete("a", service_s=0.1)
    # window(a) = [alice, alice, bob]; tenants {alice, bob} -> fair 1/2
    # alice's share on a = 2/3 -> penalty 1/6; fresh prompt, no overlap:
    #   score(a) = -0.4 * 1/6 = -0.0667 < score(b) = 0  -> steered to b
    d = r.route(list(range(300, 308)), tenant="alice")
    assert d.replica == "b"
    assert d.scores["a"] == pytest.approx(-0.4 / 6)
    # anonymous traffic never pays a fairness penalty
    d2 = r.route(list(range(400, 408)))
    assert d2.scores["a"] == pytest.approx(0.0, abs=1e-9)


def test_least_loaded_policy_ignores_prefixes():
    loads = {"a": ReplicaLoad(occupancy=0.5), "b": ReplicaLoad()}
    r = make_router(loads=loads, policy="least_loaded")
    prompt = list(range(16))
    r.shadow.observe("a", fingerprint_chain(prompt, 8))
    d = r.route(prompt)
    assert (d.replica, d.overlap_tokens) == ("b", 0)
    assert d.scores == {"a": -0.5, "b": 0.0}


def test_round_robin_cycles_in_name_order():
    r = make_router(replicas=("c", "a", "b"), policy="round_robin")
    picks = [r.route([1, 2, 3, 4]).replica for _ in range(4)]
    assert picks == ["a", "b", "c", "a"]


def test_admission_bands_queue_then_reject():
    # projected_ttft = (queued + active)/slots * default_service_s(0.2)
    # vs target 1.0s: burn >= 1 queues, burn >= 6 rejects.
    r = make_router(loads={"a": ReplicaLoad(queued=4, active=1),
                           "b": ReplicaLoad(queued=4, active=1)})
    d = r.route(list(range(8)))
    assert d.admission == QUEUE
    assert d.projected_ttft_s == pytest.approx(1.0)

    r2 = make_router(loads={"a": ReplicaLoad(queued=29, active=1),
                            "b": ReplicaLoad(queued=29, active=1)})
    d2 = r2.route(list(range(8)))
    assert d2.admission == REJECT
    assert d2.projected_ttft_s == pytest.approx(6.0)
    assert counter_value(r2, "serving_router_rejected_total") == 1

    r3 = make_router(replicas=("a",),
                     loads={"a": ReplicaLoad(queued=29, active=1)},
                     admission=False)
    assert r3.route(list(range(8))).admission == ADMIT


def test_requeue_counts_the_queue_exactly_once():
    r = make_router(loads={"a": ReplicaLoad(queued=4, active=1),
                           "b": ReplicaLoad(queued=4, active=1)})
    prompt = list(range(8))
    assert r.route(prompt).admission == QUEUE
    assert r.route(prompt, requeue=True).admission == QUEUE
    assert counter_value(r, "serving_router_queued_total") == 1


def test_stamp_false_mutates_nothing():
    r = make_router()
    prompt = list(range(16))
    d = r.route(prompt, stamp=False)
    assert d.admission == ADMIT
    assert r.shadow.total_blocks() == 0
    assert counter_value(r, "serving_router_requests_total") == 0
    assert r.stats()["inflight"] == {}


def test_inflight_blends_with_scraped_load():
    # no scrape data at all: the router's own in-flight count still
    # produces back-pressure (1 in-flight / 1 slot -> occupancy 1.0)
    r = make_router(admission=False)
    prompt_a = list(range(16))
    r.route(prompt_a)  # lands on "a", stays in flight
    d = r.route(list(range(50, 58)))  # fresh prompt
    assert d.replica == "b"
    assert d.scores["a"] == pytest.approx(-0.6)
    r.complete("a", service_s=0.1)
    r.complete("b", service_s=0.1)
    assert r.stats()["inflight"] == {}


def test_forget_replica_clears_its_shadow():
    r = make_router()
    prompt = list(range(16))
    r.route(prompt)
    assert r.shadow.blocks("a") == 2
    r.forget_replica("a")
    assert r.shadow.blocks("a") == 0
    d = r.route(prompt)  # state gone: cold tie-break again, no overlap
    assert d.overlap_tokens == 0


def test_service_ewma_updates_on_success_only():
    r = make_router()
    r.route(list(range(8)))
    r.complete("a", service_s=1.2, ok=True)
    # ewma: 0.8*0.2 + 0.2*1.2 = 0.4
    assert r.stats()["service_s"]["a"] == pytest.approx(0.4)
    r.route(list(range(8)))
    r.complete("a", ok=False)  # failures never poison the EWMA
    assert r.stats()["service_s"]["a"] == pytest.approx(0.4)


def test_loads_from_collector_shapes():
    class FakeTarget:
        def __init__(self, name, snapshot, up=True, quarantined=False,
                     health=None):
            self.name, self.snapshot = name, snapshot
            self.up, self.quarantined = up, quarantined
            self.health = health or {}

    def fam(v):
        return {"samples": [({}, v)], "kind": "gauge", "help": ""}

    snap = {
        "engine_dispatch_depth_occupancy": fam(0.5),
        "engine_queued_requests": fam(3.0),
        "engine_max_slots": fam(4.0),
        "engine_active_slots": fam(2.0),
    }

    class FakeCollector:
        targets = [
            FakeTarget("r0", snap,
                       health={"slo": {"status": "warn"}}),
            FakeTarget("r1", snap, up=False),        # down: skipped
            FakeTarget("r2", None),                  # unscraped: skipped
            FakeTarget("r3", snap, quarantined=True),
        ]

    loads = loads_from_collector(FakeCollector())
    assert sorted(loads) == ["r0"]
    r0 = loads["r0"]
    assert (r0.occupancy, r0.queued, r0.max_slots, r0.active,
            r0.slo_pressure) == (0.5, 3.0, 4.0, 2.0, 1.0)


def test_no_replicas_rejects():
    r = PrefixRouter(replicas_fn=dict, clock=lambda: 0.0)
    d = r.route([1, 2, 3])
    assert d.admission == REJECT and "no routable replicas" in d.reason


def test_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(policy="sticky").validate()
    with pytest.raises(ValueError):
        RouterConfig(block_size=0).validate()
    with pytest.raises(ValueError):
        RouterConfig(warn_burn=2.0, breach_burn=1.0).validate()


# -- live gateway over a real stub fleet -------------------------------------
def wait_for(cond, timeout=20.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def fast_fleet(replicas=2, **env):
    env.setdefault("STUB_TOKEN_DELAY_S", "0.002")
    return ReplicaFleet(spec=ReplicaSpec(env=env), replicas=replicas,
                        poll_interval=0.1)


def make_gateway(fleet, **cfg_kw):
    cfg_kw.setdefault("policy", "prefix")
    router = PrefixRouter(replicas_fn=fleet.targets,
                          config=RouterConfig(**cfg_kw))
    gw = RoutingGateway(router, port=0)
    gw.start()
    return gw


def gw_get(gw, path):
    with urllib.request.urlopen(gw.base_url + path, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def gw_stream(gw, prompt, n):
    body = json.dumps({"prompt_ids": prompt, "max_new_tokens": n,
                       "stream": True}).encode()
    req = urllib.request.Request(gw.base_url + "/generate", data=body)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return [json.loads(line) for line in resp]


def test_gateway_streams_verified_and_sticks_to_prefix_holder():
    from devspace_tpu.serving.stub import token_at

    fleet = fast_fleet(replicas=2)
    fleet.start()
    gw = None
    try:
        gw = make_gateway(fleet)
        prompt = list(range(16))
        lines = gw_stream(gw, prompt, 5)
        assert [m["token"] for m in lines[:-1]] == [
            token_at(prompt, i) for i in range(5)]
        assert lines[-1] == {"done": True}
        # the follow-up turn (prompt + reply grown) routes to the same
        # replica and the stub's own prefix memory reports hit tokens
        grown = prompt + [token_at(prompt, i) for i in range(5)] + [7] * 8
        gw_stream(gw, grown, 3)
        _, dbg = gw_get(gw, "/debug/router")
        picks = [d["replica"] for d in dbg["recent_decisions"]]
        assert len(set(picks)) == 1
        assert dbg["recent_decisions"][-1]["overlap_tokens"] >= 16
        url = fleet.targets()[picks[0]]
        with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        hits = [line for line in text.splitlines()
                if line.startswith("engine_prefix_hit_tokens_total ")]
        assert hits and float(hits[0].split()[1]) >= 16
        # gateway surfaces its own catalog + health endpoints
        with urllib.request.urlopen(
                gw.base_url + "/metrics", timeout=10) as resp:
            assert "serving_router_requests_total 2" in resp.read().decode()
        assert gw_get(gw, "/healthz")[0] == 200
        assert gw_get(gw, "/readyz")[0] == 200
    finally:
        if gw is not None:
            gw.stop()
        fleet.stop()


def test_gateway_admission_rejects_with_429():
    router = PrefixRouter(
        replicas_fn=lambda: {"a": "http://127.0.0.1:1"},
        loads_fn=lambda: {"a": ReplicaLoad(queued=40, active=1)},
        config=RouterConfig(queue_timeout_s=0.2),
    )
    gw = RoutingGateway(router, port=0)
    gw.start()
    try:
        body = json.dumps({"prompt_ids": [1, 2, 3], "max_new_tokens": 2,
                           "stream": True}).encode()
        req = urllib.request.Request(gw.base_url + "/generate", data=body)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 429
        assert "breach band" in json.loads(exc.value.read())["reason"]
    finally:
        gw.stop()


def test_gateway_drain_flips_readyz():
    router = PrefixRouter(replicas_fn=lambda: {"a": "http://127.0.0.1:1"})
    gw = RoutingGateway(router, port=0)
    gw.start()
    try:
        assert gw_get(gw, "/readyz")[0] == 200
        req = urllib.request.Request(gw.base_url + "/drain", data=b"{}")
        urllib.request.urlopen(req, timeout=10)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(gw.base_url + "/readyz", timeout=10)
        assert exc.value.code == 503
    finally:
        gw.stop()


def test_gateway_reroutes_before_first_byte():
    # one dead address in the routing table: the gateway must absorb the
    # connect failure, drop the dead replica's shadow state, and serve
    # the stream from the live one — the client never sees the failure
    fleet = fast_fleet(replicas=1)
    fleet.start()
    gw = None
    try:
        def targets():
            t = dict(fleet.targets())
            t["dead"] = "http://127.0.0.1:9"  # discard port: refused
            return t

        router = PrefixRouter(replicas_fn=targets, config=RouterConfig())
        # pre-warm the dead replica's shadow so routing prefers it
        prompt = list(range(16))
        router.shadow.observe("dead", fingerprint_chain(prompt, 8))
        gw = RoutingGateway(router, port=0)
        gw.start()
        lines = gw_stream(gw, prompt, 4)
        assert lines[-1] == {"done": True}
        snap = router.registry.snapshot()
        assert snap["serving_router_retries_total"]["samples"][0][1] == 1
        assert router.shadow.blocks("dead") == 0  # forgotten on failure
    finally:
        if gw is not None:
            gw.stop()
        fleet.stop()


# -- chaos (registered in scripts/chaos_check.py) ----------------------------
@pytest.mark.chaos
def test_routed_replica_killed_mid_stream_reroutes_clean():
    """SIGKILL the replica currently holding the routed streams. Every
    client stream must end completed or retried — zero corrupted, zero
    hung: the gateway aborts half-written streams instead of replaying,
    and the loadgen's retry rides a fresh routing decision."""
    fleet = fast_fleet(replicas=2, STUB_TOKEN_DELAY_S="0.01")
    fleet.start()
    gw = None
    try:
        # admission off: this test is about reroute-on-death, and the
        # outcome must be deterministic across the chaos gate's repeats
        gw = make_gateway(fleet, admission=False)
        gen = LoadGenerator(targets_fn=lambda: {"gw": gw.base_url},
                            hang_timeout_s=60.0, max_attempts=4)
        # one shared prefix -> all streams route to one replica, so the
        # kill provably lands on routed traffic
        base = list(range(24))
        trace = [{"id": i, "at": 0.0, "prompt_ids": base,
                  "max_new_tokens": 40, "sampled": False, "session": 0}
                 for i in range(6)]

        killed = {}

        def kill_routed():
            wait_for(
                lambda: gw.router.stats()["recent_decisions"],
                msg="first routed decision")
            time.sleep(0.15)  # let streams get bytes in flight
            name = gw.router.stats()["recent_decisions"][-1]["replica"]
            killed["name"] = name
            fleet.kill(name)

        import threading

        killer = threading.Thread(target=kill_routed, daemon=True)
        killer.start()
        report = gen.run(trace)
        killer.join(timeout=30)
        counts = report.counts()
        assert counts["corrupted"] == 0, report.to_dict()
        assert counts["hung"] == 0, report.to_dict()
        assert counts["failed"] == 0, report.to_dict()
        assert counts["completed"] + counts["retried"] == len(trace)
        assert killed, "kill thread never fired"
        # the supervisor restarts the killed replica behind the gateway
        wait_for(fleet.all_healthy, msg="fleet recovered after kill")
    finally:
        if gw is not None:
            gw.stop()
        fleet.stop()


# -- rag trace shape (loadgen satellite) -------------------------------------
def test_rag_trace_is_byte_stable_and_shares_contexts():
    from devspace_tpu.serving.loadgen import trace_json

    spec = TraceSpec(kind="rag", seed=11, duration_s=4.0, rate_rps=10,
                     rag_contexts=2, rag_context_len=(64, 96),
                     rag_long_fraction=0.4)
    assert trace_json(spec) == trace_json(spec)
    trace = generate_trace(spec)
    assert trace, "empty rag trace"
    long = [e for e in trace if e["session"] >= 0]
    short = [e for e in trace if e["session"] == -1]
    assert long and short, "rag must interleave long and short prompts"
    # every long query embeds its context verbatim as the prompt prefix
    by_ctx = {}
    for e in long:
        by_ctx.setdefault(e["session"], []).append(e["prompt_ids"])
    for prompts in by_ctx.values():
        ctx_len = min(len(p) for p in prompts) - 1
        head = prompts[0][:64]  # at least the min context length
        assert all(p[:64] == head for p in prompts)
        assert ctx_len >= 64
    assert max(len(e["prompt_ids"]) for e in long) > max(
        len(e["prompt_ids"]) for e in short)
