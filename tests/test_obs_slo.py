"""SLO burn-rate engine goldens (obs/slo.py — ISSUE 9).

Every number here is hand-computed from the definitions: bad-fraction
over a window divided by the error budget (1 - objective) gives the
burn rate; a spec breaches only when BOTH windows burn above
``breach_burn``, warns when both exceed ``warn_burn``. The evaluator
runs under a fake clock against hand-built ``Registry.snapshot``-shaped
dicts, so each window's baseline entry is known exactly.
"""

import math

import pytest

from devspace_tpu.obs.events import EventBus
from devspace_tpu.obs.metrics import Registry
from devspace_tpu.obs.slo import (
    SLO_METRIC_FAMILIES,
    SLOEvaluator,
    SLOSpec,
    default_serving_slos,
)


def counter_fam(value):
    return {"kind": "counter", "help": "h", "samples": [({}, float(value))]}


def gauge_fam(value):
    return {"kind": "gauge", "help": "h", "samples": [({}, float(value))]}


def hist_fam(good, total, threshold=1.0):
    """Histogram family where ``good`` observations landed at or below
    ``threshold`` and the rest above it."""
    return {
        "kind": "histogram",
        "help": "h",
        "samples": [
            ({}, {
                "buckets": [(threshold, float(good)), (math.inf, float(total))],
                "count": float(total),
                "sum": 0.0,
            })
        ],
    }


class FakeSource:
    def __init__(self, snap=None):
        self.snap = snap or {}

    def __call__(self):
        return self.snap


def make_eval(spec, source, clock, bus=None):
    return SLOEvaluator([spec], [source], clock=lambda: clock["t"], bus=bus)


# -- spec validation ---------------------------------------------------------
def test_spec_validation():
    with pytest.raises(ValueError, match="unknown SLO kind"):
        SLOSpec(name="x", kind="vibes", objective=0.9)
    with pytest.raises(ValueError, match="objective"):
        SLOSpec(name="x", kind="error_rate", objective=1.0,
                bad=("b",), total=("t",))
    with pytest.raises(ValueError, match="histogram"):
        SLOSpec(name="x", kind="latency", objective=0.9)
    with pytest.raises(ValueError, match="bad"):
        SLOSpec(name="x", kind="error_rate", objective=0.9)
    with pytest.raises(ValueError, match="gauge"):
        SLOSpec(name="x", kind="throughput_floor", objective=0.9)
    with pytest.raises(ValueError, match="window"):
        SLOSpec(name="x", kind="error_rate", objective=0.9, bad=("b",),
                total=("t",), short_window_s=600, long_window_s=300)
    with pytest.raises(ValueError, match="duplicate"):
        specs = [
            SLOSpec(name="dup", kind="error_rate", objective=0.9,
                    bad=("b",), total=("t",))
        ] * 2
        SLOEvaluator(specs, [dict])
    # budget floor guards div-by-zero for extreme objectives
    s = SLOSpec(name="x", kind="error_rate", objective=0.99,
                bad=("b",), total=("t",))
    assert s.budget == pytest.approx(0.01)


# -- error-rate golden -------------------------------------------------------
def test_error_rate_burn_golden_and_recovery():
    """objective 0.99 (budget 0.01). 8 failures in 100 requests inside
    both windows -> bad_frac 0.08 -> burn 8.0 on both -> breach. Freeze
    the counters and slide the short window past the incident: short
    burn 0, long burn still 8 -> min gates back to ok (recovered)."""
    spec = SLOSpec(
        name="error_rate", kind="error_rate", objective=0.99,
        bad=("requests_failed_total",),
        total=("requests_failed_total", "requests_completed_total"),
        short_window_s=300, long_window_s=3600,
    )
    src = FakeSource({
        "requests_failed_total": counter_fam(0),
        "requests_completed_total": counter_fam(0),
    })
    clock = {"t": 0.0}
    bus = EventBus()
    seen = []

    class Sink:
        def record(self, ev):
            seen.append(ev)

    bus.add_sink(Sink())
    ev = make_eval(spec, src, clock, bus=bus)
    assert ev.ready() is True  # before any evaluation: never block startup
    (st,) = ev.evaluate()
    assert st.status == "ok" and st.burn_short == 0.0

    clock["t"] = 60.0
    src.snap = {
        "requests_failed_total": counter_fam(8),
        "requests_completed_total": counter_fam(92),
    }
    (st,) = ev.evaluate()
    # delta vs the t=0 baseline: 8 bad / 100 total = 0.08; 0.08/0.01 = 8
    assert st.status == "breach"
    assert st.burn_short == pytest.approx(8.0)
    assert st.burn_long == pytest.approx(8.0)
    assert st.bad_short == 8.0 and st.total_short == 100.0
    assert ev.ready() is False
    assert ev.worst() == "breach"
    assert [e.name for e in seen] == ["breach"]
    assert seen[-1].attrs["was"] == "ok"

    # 301s later with frozen counters the short baseline is the t=60
    # entry (delta 0) while the long baseline is still t=0 (burn 8):
    # min(0, 8) = 0 -> ok, and /readyz recovers
    clock["t"] = 361.0
    (st,) = ev.evaluate()
    assert st.status == "ok"
    assert st.burn_short == pytest.approx(0.0)
    assert st.burn_long == pytest.approx(8.0)
    assert ev.ready() is True
    assert [e.name for e in seen] == ["breach", "recovered"]
    assert seen[-1].attrs["was"] == "breach"


def test_error_rate_warn_band():
    """3 failures in 100 -> burn ~3.0: above warn (1.0), below breach
    (6.0) on both windows -> warn."""
    spec = SLOSpec(
        name="er", kind="error_rate", objective=0.99,
        bad=("bad_total",), total=("all_total",),
        short_window_s=300, long_window_s=3600,
    )
    src = FakeSource({"bad_total": counter_fam(0), "all_total": counter_fam(0)})
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    ev.evaluate()
    clock["t"] = 30.0
    src.snap = {"bad_total": counter_fam(3), "all_total": counter_fam(100)}
    (st,) = ev.evaluate()
    assert st.status == "warn"
    assert st.burn_short == pytest.approx(3.0, rel=1e-6)


def test_min_events_guard_no_data_is_ok():
    spec = SLOSpec(
        name="er", kind="error_rate", objective=0.99,
        bad=("bad_total",), total=("all_total",), min_events=10,
    )
    src = FakeSource({"bad_total": counter_fam(0), "all_total": counter_fam(0)})
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    ev.evaluate()
    clock["t"] = 30.0
    # 2 of 5 failed would be a 40x burn — but 5 < min_events: no data
    src.snap = {"bad_total": counter_fam(2), "all_total": counter_fam(5)}
    (st,) = ev.evaluate()
    assert st.status == "ok" and st.burn_short == 0.0
    assert st.total_short == 5.0


# -- latency golden ----------------------------------------------------------
def test_latency_burn_from_histogram_buckets():
    """p99 TTFT at threshold 1.0s, objective 0.99: 95 of 100 in-bucket
    -> bad_frac 0.05 -> burn 5.0 -> warn (both windows, 1.0 <= 5 < 6).
    Then 20 more all bad: window delta 25 bad / 120 total... but
    hand-compute the SHORT window against its own baseline."""
    spec = SLOSpec(
        name="ttft_p99", kind="latency", objective=0.99,
        histogram="ttft_seconds", threshold_s=1.0,
        short_window_s=300, long_window_s=3600,
    )
    src = FakeSource({"ttft_seconds": hist_fam(0, 0)})
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    ev.evaluate()
    clock["t"] = 60.0
    src.snap = {"ttft_seconds": hist_fam(95, 100)}
    (st,) = ev.evaluate()
    # 5 above-threshold of 100 = 0.05; burn 0.05/0.01 = 5 -> warn
    assert st.status == "warn"
    assert st.burn_short == pytest.approx(5.0, rel=1e-6)
    assert st.bad_short == 5.0 and st.total_short == 100.0
    clock["t"] = 120.0
    src.snap = {"ttft_seconds": hist_fam(95, 120)}
    (st,) = ev.evaluate()
    # short baseline is t=0 (<= 120-300 has no entry, falls to oldest):
    # 25 bad / 120 total = 0.2083 -> burn 20.8 -> breach on both windows
    assert st.status == "breach"
    assert st.burn_short == pytest.approx(25 / 120 / 0.01, rel=1e-3)


def test_latency_threshold_snaps_to_bucket_edge():
    """threshold 0.8 with edges (1.0, inf): goodness is read at the 1.0
    edge (documented bucket-resolution behavior)."""
    spec = SLOSpec(
        name="lat", kind="latency", objective=0.9,
        histogram="h_seconds", threshold_s=0.8,
    )
    src = FakeSource({"h_seconds": hist_fam(0, 0)})
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    ev.evaluate()
    clock["t"] = 10.0
    src.snap = {"h_seconds": hist_fam(90, 100, threshold=1.0)}
    (st,) = ev.evaluate()
    assert st.bad_short == 10.0  # read at the 1.0 edge, not interpolated


# -- throughput-floor golden -------------------------------------------------
def test_throughput_floor_counts_only_active_samples():
    """objective 0.9 (budget 0.1), floor 0.5 tok/s. Sample sequence
    (value, active): idle samples are excluded; 2 of 4 active samples
    below floor -> bad_frac 0.5 -> burn 5.0 -> warn."""
    spec = SLOSpec(
        name="tok_floor", kind="throughput_floor", objective=0.9,
        gauge="tok_per_sec", floor=0.5, activity=("active_slots",),
        short_window_s=300, long_window_s=3600,
    )
    src = FakeSource()
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    seq = [
        (0.0, 0),  # idle: engine drained — not a breach sample
        (2.0, 1),  # active, healthy
        (0.1, 1),  # active, below floor
        (0.2, 2),  # active, below floor
        (1.5, 1),  # active, healthy
    ]
    for i, (tok, slots) in enumerate(seq):
        clock["t"] = float(i * 10)
        src.snap = {
            "tok_per_sec": gauge_fam(tok),
            "active_slots": gauge_fam(slots),
        }
        (st,) = ev.evaluate()
    assert st.status == "warn"
    assert st.burn_short == pytest.approx(5.0, rel=1e-6)
    assert st.bad_short == 2.0 and st.total_short == 4.0


def test_throughput_floor_all_idle_is_ok():
    spec = SLOSpec(
        name="tok_floor", kind="throughput_floor", objective=0.9,
        gauge="tok_per_sec", floor=0.5, activity=("active_slots",),
    )
    src = FakeSource({
        "tok_per_sec": gauge_fam(0.0), "active_slots": gauge_fam(0),
    })
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    for i in range(5):
        clock["t"] = float(i * 10)
        (st,) = ev.evaluate()
    assert st.status == "ok" and st.total_short == 0.0


# -- evaluator plumbing ------------------------------------------------------
def test_sources_merge_and_dead_source_degrades():
    spec = SLOSpec(
        name="er", kind="error_rate", objective=0.99,
        bad=("bad_total",), total=("all_total",),
    )

    def dead():
        raise RuntimeError("engine stopped")

    srcs = [
        dead,
        lambda: {"bad_total": counter_fam(0)},
        lambda: {"all_total": counter_fam(0)},
    ]
    clock = {"t": 0.0}
    ev = SLOEvaluator([spec], srcs, clock=lambda: clock["t"])
    (st,) = ev.evaluate()  # no crash; both live sources merged
    assert st.status == "ok"


def test_history_trims_to_horizon_keeping_long_baseline():
    spec = SLOSpec(
        name="er", kind="error_rate", objective=0.99,
        bad=("b_total",), total=("t_total",),
        short_window_s=10, long_window_s=20,
    )
    src = FakeSource({"b_total": counter_fam(0), "t_total": counter_fam(0)})
    clock = {"t": 0.0}
    ev = make_eval(spec, src, clock)
    for i in range(100):
        clock["t"] = float(i)
        ev.evaluate()
    # horizon is long_window + 1: ring stays bounded, and one entry at
    # or beyond the long cutoff survives as the baseline
    assert len(ev._history) <= 24
    assert ev._history[0][0] <= clock["t"] - 20


def test_to_dict_and_register_metrics():
    spec = SLOSpec(
        name="er", kind="error_rate", objective=0.99,
        bad=("bad_total",), total=("all_total",),
    )
    src = FakeSource({"bad_total": counter_fam(0), "all_total": counter_fam(0)})
    clock = {"t": 5.0}
    ev = make_eval(spec, src, clock)
    reg = Registry()
    ev.register_metrics(reg)
    d = ev.to_dict()
    assert d["ready"] is True and d["status"] == "ok" and d["slos"] == []
    ev.evaluate()
    clock["t"] = 35.0
    src.snap = {"bad_total": counter_fam(8), "all_total": counter_fam(100)}
    ev.evaluate()
    d = ev.to_dict()
    assert d["ready"] is False and d["status"] == "breach"
    assert d["evaluated_at"] == 35.0
    assert d["slos"][0]["name"] == "er"
    assert d["slos"][0]["burn_short"] == pytest.approx(8.0, abs=1e-3)
    out = reg.render()
    assert 'slo_status{slo="er"} 2' in out
    assert 'slo_burn_ratio{slo="er",window="short"}' in out
    assert 'slo_burn_ratio{slo="er",window="long"}' in out


def test_default_serving_slos_shape():
    specs = default_serving_slos(
        ttft_threshold_s=2.0, tok_s_floor=1.0,
        short_window_s=60, long_window_s=600,
    )
    by_name = {s.name: s for s in specs}
    assert set(by_name) == {
        "ttft_p99", "error_rate", "availability", "tok_s_floor",
    }
    assert by_name["ttft_p99"].threshold_s == 2.0
    assert by_name["ttft_p99"].histogram == "ttft_seconds"
    assert by_name["tok_s_floor"].floor == 1.0
    assert by_name["availability"].breach_burn == 14.4
    assert by_name["availability"].short_window_s == 600
    # the catalog names stay in sync with the registered gauges
    assert [f[0] for f in SLO_METRIC_FAMILIES] == [
        "slo_status", "slo_burn_ratio",
    ]
    # each spec serializes for /healthz + debug bundles
    for s in specs:
        assert s.to_dict()["name"] == s.name
