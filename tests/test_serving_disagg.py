"""Disaggregated prefill/decode serving tests (ISSUE 20).

Three layers, mirroring tests/test_serving_router.py:

- **Golden two-phase decision table.** Loads are injected and every
  expected (decode, prefill) pair is hand-computed from
  ``_pick_prefill_locked`` in devspace_tpu/serving/router.py: the
  threshold and occupancy-band triggers, the one-full-block floor, pool
  preference and exclusion-from-decode, least-prefill-loaded balancing,
  and the ``prefill_complete`` token release.

- **Gateway QUEUE re-poll backoff.** The re-poll wait is pinned against
  a mirrored :class:`IdleBackoff` replay: unchanged projections double
  the wait, a projection change snaps it back to ``queue_poll_s``.

- **Live fleet.** Real stub subprocesses behind a real gateway: a long
  prompt prefills on the pool replica and the decode replica pulls the
  chain (``engine_kv_migrate_*`` on one side, ``engine_kv_export_*`` on
  the other); a short prompt stays unified. The chaos-marked test
  (registered in scripts/chaos_check.py) SIGKILLs the prefill-pool
  replica under mixed short+long load and requires every stream to end
  clean — orphaned migrations must degrade to recompute-prefill, never
  corrupt or hang a client.
"""

import json
import threading
import time
import urllib.request

import pytest

from devspace_tpu.resilience.policy import IdleBackoff
from devspace_tpu.serving import ReplicaFleet, ReplicaSpec
from devspace_tpu.serving.gateway import RoutingGateway
from devspace_tpu.serving.loadgen import LoadGenerator
from devspace_tpu.serving.router import (
    ADMIT,
    QUEUE,
    PrefixRouter,
    ReplicaLoad,
    RouterConfig,
)


def counter_value(router, name: str) -> float:
    fam = router.registry.snapshot().get(name)
    if not fam or not fam["samples"]:
        return 0.0
    return float(fam["samples"][0][1])


def make_router(replicas=("a", "b"), loads=None, **cfg_kw):
    cfg_kw.setdefault("policy", "prefix")
    loads = dict(loads or {})
    return PrefixRouter(
        replicas_fn=lambda: {n: f"http://{n}" for n in replicas},
        loads_fn=lambda: loads,
        config=RouterConfig(**cfg_kw),
        clock=lambda: 0.0,
    )


LONG = list(range(40))   # 5 full blocks at block_size=8, all uncached
SHORT = list(range(16))


# -- golden two-phase decision table -----------------------------------------
def test_disagg_off_by_default():
    r = make_router()
    d = r.route(LONG)
    assert (d.admission, d.prefill_replica) == (ADMIT, None)
    assert counter_value(r, "serving_router_prefill_dispatches_total") == 0


def test_short_prompt_stays_unified():
    r = make_router(disagg_threshold_tokens=32)
    d = r.route(SHORT)  # 16 uncached < 32, occupancy 0 < 0.85
    assert (d.replica, d.prefill_replica) == ("a", None)


def test_long_prompt_prefills_on_pool_member():
    r = make_router(replicas=("a", "b", "p0"),
                    disagg_threshold_tokens=32, prefill_pool=("p0",))
    d = r.route(LONG)
    # decode ties break to "a" among non-pool replicas; prefill goes to
    # the pool even though "b" is equally idle
    assert (d.admission, d.replica, d.prefill_replica) == (ADMIT, "a", "p0")
    assert counter_value(r, "serving_router_prefill_dispatches_total") == 1
    assert counter_value(r, "serving_router_prefill_tokens_total") == 40
    assert r.stats()["prefill_tokens"] == {"p0": 40}


def test_threshold_is_exact_and_counts_uncached_only():
    r = make_router(disagg_threshold_tokens=40)
    # probe without stamping so the 39-token miss leaves no shadow state
    assert r.route(list(range(39)), stamp=False).prefill_replica is None
    d = r.route(LONG)                                        # 40 == 40
    assert (d.replica, d.prefill_replica) == ("a", "b")
    # the chain is now cached on BOTH a (decode) and b (prefill): the
    # repeat prompt has 0 uncached tokens -> nothing worth migrating
    again = r.route(LONG)
    assert again.overlap_tokens == 40
    assert again.prefill_replica is None


def test_occupancy_band_triggers_below_threshold():
    loads = {"a": ReplicaLoad(occupancy=0.9),
             "b": ReplicaLoad(occupancy=0.9)}
    r = make_router(loads=loads, disagg_threshold_tokens=64)
    d = r.route(SHORT)  # 16 uncached < 64, but chosen occupancy >= 0.85
    assert (d.replica, d.prefill_replica) == ("a", "b")
    # under one full block there is nothing to migrate, band or not
    d2 = r.route([1, 2, 3])
    assert d2.prefill_replica is None


def test_no_pool_picks_least_prefill_loaded_other():
    r = make_router(replicas=("a", "b", "c"), disagg_threshold_tokens=32)
    # three distinct long prompts; each decode target is the idlest by
    # load, each prefill target the least-prefill-loaded non-chosen
    d1 = r.route(list(range(100, 140)))
    assert (d1.replica, d1.prefill_replica) == ("a", "b")
    d2 = r.route(list(range(200, 240)))     # a busy -> decode b; b holds
    assert (d2.replica, d2.prefill_replica) == ("b", "c")  # 40 prefill toks
    d3 = r.route(list(range(300, 340)))     # a,b busy -> decode c;
    assert (d3.replica, d3.prefill_replica) == ("c", "a")  # b,c loaded
    assert r.stats()["prefill_tokens"] == {"a": 40, "b": 40, "c": 40}


def test_pool_balances_by_inflight_prefill_tokens():
    r = make_router(replicas=("a", "p0", "p1"),
                    disagg_threshold_tokens=32,
                    prefill_pool=("p0", "p1"))
    d1 = r.route(list(range(100, 140)))
    d2 = r.route(list(range(200, 240)))
    assert (d1.replica, d2.replica) == ("a", "a")  # pool never decodes
    assert (d1.prefill_replica, d2.prefill_replica) == ("p0", "p1")
    # releasing p0's tokens makes it the idlest target again
    r.prefill_complete("p0", 40)
    assert r.stats()["prefill_tokens"] == {"p1": 40}
    d3 = r.route(list(range(300, 340)))
    assert d3.prefill_replica == "p0"


def test_prefill_failure_counts_and_releases():
    r = make_router(replicas=("a", "b"), disagg_threshold_tokens=32)
    d = r.route(LONG)
    assert d.prefill_replica == "b"
    r.prefill_complete("b", 40, ok=False)
    assert r.stats()["prefill_tokens"] == {}
    assert counter_value(r, "serving_router_prefill_failures_total") == 1


def test_pool_degrades_to_decode_when_nothing_else_routable():
    r = make_router(replicas=("a", "p0"),
                    disagg_threshold_tokens=32, prefill_pool=("p0",))
    assert r.route(LONG).replica == "a"
    d = r.route(LONG, exclude=frozenset({"a"}))
    # the pool is all that's left: it takes the decode stream itself,
    # and with no second replica there is no prefill target
    assert (d.admission, d.replica, d.prefill_replica) == (ADMIT, "p0", None)


def test_disagg_config_validation():
    with pytest.raises(ValueError, match="disagg_threshold_tokens"):
        RouterConfig(disagg_threshold_tokens=-1).validate()
    with pytest.raises(ValueError, match="disagg_occupancy_band"):
        RouterConfig(disagg_occupancy_band=0.0).validate()


# -- gateway QUEUE re-poll backoff -------------------------------------------
def test_queue_repoll_backoff_doubles_and_resets_on_projection_change():
    """Pinned replay of the gateway's IdleBackoff re-poll: waits double
    while the projection is unchanged (jitter from seed 0), and the
    projection moving snaps the wait back to ``queue_poll_s``."""
    loads = {"a": ReplicaLoad(queued=24, active=4, max_slots=4)}
    # projected = (24+4)/4 * 0.2s = 1.4s -> warn band -> QUEUE
    router = PrefixRouter(
        replicas_fn=lambda: {"a": "http://a"},
        loads_fn=lambda: dict(loads),
        config=RouterConfig(),
        clock=lambda: 0.0,
    )
    t = [0.0]
    gw = RoutingGateway(router, port=0, clock=lambda: t[0])
    try:
        waits = []

        def fake_sleep(s):
            waits.append(s)
            t[0] += s
            if len(waits) == 3:   # projection 1.4 -> 2.0: reset expected
                loads["a"] = ReplicaLoad(queued=36, active=4, max_slots=4)
            elif len(waits) == 5:  # capacity freed -> ADMIT
                loads["a"] = ReplicaLoad()

        gw._sleep = fake_sleep
        decision, wait = gw._admit(SHORT, tenant="")
        assert decision.admission == ADMIT
        assert wait == pytest.approx(sum(waits))
        assert len(waits) == 5

        # mirror the exact backoff the gateway builds; reset happens on
        # the route AFTER the third sleep, i.e. before draw #4
        mirror = IdleBackoff(
            initial=gw.queue_poll_s,
            maximum=max(gw.queue_poll_s,
                        router.config.queue_timeout_s / 8),
            jitter=0.5, seed=0)
        expected = []
        for i in range(1, 6):
            expected.append(mirror.next_wait())
            if i == 3:
                mirror.reset()
        assert waits == expected
        # the shape the mirror proves: doubling, then the snap-back
        assert waits[2] > waits[0]      # unchanged projection -> growth
        assert waits[3] < waits[2]      # reset snapped to queue_poll_s
    finally:
        gw._httpd.server_close()


def test_queue_repoll_times_out_to_reject():
    loads = {"a": ReplicaLoad(queued=24, active=4, max_slots=4)}
    router = PrefixRouter(
        replicas_fn=lambda: {"a": "http://a"},
        loads_fn=lambda: dict(loads),
        config=RouterConfig(queue_timeout_s=0.5),
        clock=lambda: 0.0,
    )
    t = [0.0]
    gw = RoutingGateway(router, port=0, clock=lambda: t[0])
    try:
        gw._sleep = lambda s: t.__setitem__(0, t[0] + s)
        decision, wait = gw._admit(SHORT, tenant="")
        assert decision.admission != ADMIT
        assert decision.admission != QUEUE
        assert "queue timeout" in decision.reason
        assert wait >= 0.5
    finally:
        gw._httpd.server_close()


# -- live fleet --------------------------------------------------------------
def wait_for(cond, timeout=20.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def fast_fleet(replicas=3, **env):
    env.setdefault("STUB_TOKEN_DELAY_S", "0.002")
    return ReplicaFleet(spec=ReplicaSpec(env=env), replicas=replicas,
                        poll_interval=0.1)


def make_gateway(fleet, **cfg_kw):
    cfg_kw.setdefault("policy", "prefix")
    router = PrefixRouter(replicas_fn=fleet.targets,
                          config=RouterConfig(**cfg_kw))
    gw = RoutingGateway(router, port=0)
    gw.start()
    return gw


def gw_stream(gw, prompt, n):
    body = json.dumps({"prompt_ids": prompt, "max_new_tokens": n,
                       "stream": True}).encode()
    req = urllib.request.Request(gw.base_url + "/generate", data=body)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return [json.loads(line) for line in resp]


def replica_metric(url: str, name: str) -> float:
    with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return 0.0


def test_live_disagg_migrates_chain_and_keeps_stream_exact():
    from devspace_tpu.serving.stub import token_at

    fleet = fast_fleet(replicas=3)
    fleet.start()
    gw = None
    try:
        gw = make_gateway(fleet, prefill_pool=("replica-2",),
                          disagg_threshold_tokens=32)
        prompt = list(range(96))
        lines = gw_stream(gw, prompt, 5)
        assert [m["token"] for m in lines[:-1]] == [
            token_at(prompt, i) for i in range(5)]
        assert lines[-1] == {"done": True}
        decisions = gw.router.stats()["recent_decisions"]
        d = decisions[-1]
        assert d["prefill_replica"] == "replica-2"
        assert d["replica"] in ("replica-0", "replica-1")
        targets = fleet.targets()
        # prefill side exported the chain; decode side pulled it whole
        assert replica_metric(
            targets["replica-2"], "engine_kv_export_chains_total") >= 1
        decode_url = targets[d["replica"]]
        assert replica_metric(
            decode_url, "engine_kv_migrate_chains_total") >= 1
        assert replica_metric(
            decode_url, "engine_kv_migrate_bytes_total") > 0
        assert replica_metric(
            decode_url, "engine_kv_migrate_failures_total") == 0
        # a short prompt stays unified and off the pool
        short_lines = gw_stream(gw, SHORT, 3)
        assert [m["token"] for m in short_lines[:-1]] == [
            token_at(SHORT, i) for i in range(3)]
        d2 = gw.router.stats()["recent_decisions"][-1]
        assert d2["prefill_replica"] is None
        assert d2["replica"] != "replica-2"
        # phase-1 accounting drains once the streams complete
        wait_for(lambda: gw.router.stats()["prefill_tokens"] == {},
                 msg="prefill tokens drained")
    finally:
        if gw is not None:
            gw.stop()
        fleet.stop()


@pytest.mark.chaos
def test_prefill_pool_replica_killed_mid_migration_degrades_clean():
    """SIGKILL the dedicated prefill replica while mixed short+long load
    is in flight. Long requests whose phase-1 or chain pull lands on the
    corpse must degrade — unified placement or recompute-prefill — with
    ZERO corrupted and ZERO hung client streams; decode replicas never
    scatter a partial migration into their pools."""
    fleet = fast_fleet(replicas=3, STUB_TOKEN_DELAY_S="0.01",
                       STUB_PREFILL_DELAY_PER_TOKEN_S="0.002")
    fleet.start()
    gw = None
    try:
        # admission off: the outcome must be deterministic across the
        # chaos gate's repeats, not dependent on queue timing
        gw = make_gateway(fleet, admission=False,
                          prefill_pool=("replica-2",),
                          disagg_threshold_tokens=32)
        gen = LoadGenerator(targets_fn=lambda: {"gw": gw.base_url},
                            hang_timeout_s=60.0, max_attempts=4)
        long_base = list(range(96))
        trace = []
        for i in range(10):
            # alternate short chat turns with long RAG-style prompts that
            # all share one context -> every long request wants the pool
            if i % 2 == 0:
                # a distinct leading token per request -> distinct chains,
                # so EVERY long request takes the two-phase path
                ids = [7000 + i] + long_base
                trace.append({"id": i, "at": 0.05 * i, "prompt_ids": ids,
                              "max_new_tokens": 12, "sampled": False,
                              "session": 0})
            else:
                trace.append({"id": i, "at": 0.05 * i,
                              "prompt_ids": [500 + i] * 12,
                              "max_new_tokens": 8, "sampled": False,
                              "session": -1})

        killed = {}

        def kill_prefill_pool():
            wait_for(
                lambda: any(d.get("prefill_replica")
                            for d in gw.router.stats()["recent_decisions"]),
                msg="first two-phase placement")
            killed["name"] = "replica-2"
            fleet.kill("replica-2")

        killer = threading.Thread(target=kill_prefill_pool, daemon=True)
        killer.start()
        report = gen.run(trace)
        killer.join(timeout=30)
        counts = report.counts()
        assert counts["corrupted"] == 0, report.to_dict()
        assert counts["hung"] == 0, report.to_dict()
        assert counts["failed"] == 0, report.to_dict()
        assert counts["completed"] + counts["retried"] == len(trace)
        assert killed, "kill thread never fired"
        # phase-1 token accounting drains even for orphaned migrations
        wait_for(lambda: gw.router.stats()["prefill_tokens"] == {},
                 msg="prefill tokens drained after kill")
        # the supervisor restarts the pool replica behind the gateway
        wait_for(fleet.all_healthy, msg="fleet recovered after kill")
    finally:
        if gw is not None:
            gw.stop()
        fleet.stop()
