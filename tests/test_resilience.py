"""Unit tests for the resilience primitives (policy/breaker/backoff).

All time is injected (fake sleep/clock), so these run in milliseconds and
every delay schedule asserted here is exact — the same determinism the
chaos suite depends on (scripts/chaos_check.py).
"""

import pytest

from devspace_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    IdleBackoff,
    RetryExhausted,
    RetryPolicy,
    format_ready_timeout,
    retry,
)


# -- RetryPolicy.delays ----------------------------------------------------
def test_delays_schedule_exponential_and_capped():
    p = RetryPolicy(max_attempts=5, base_delay=1.0, max_delay=4.0, multiplier=2.0)
    assert list(p.delays()) == [1.0, 2.0, 4.0, 4.0]


def test_delays_count_is_attempts_minus_one():
    assert len(list(RetryPolicy(max_attempts=1).delays())) == 0
    assert len(list(RetryPolicy(max_attempts=3).delays())) == 2


def test_delays_jitter_deterministic_with_seed():
    a = list(RetryPolicy(max_attempts=6, jitter=0.5, seed=42).delays())
    b = list(RetryPolicy(max_attempts=6, jitter=0.5, seed=42).delays())
    c = list(RetryPolicy(max_attempts=6, jitter=0.5, seed=7).delays())
    assert a == b
    assert a != c
    # jitter only shaves, never grows, and never goes negative
    full = list(RetryPolicy(max_attempts=6, jitter=0.0).delays())
    assert all(0.0 <= j <= f for j, f in zip(a, full))


# -- RetryPolicy.execute ---------------------------------------------------
def test_execute_success_first_try_no_sleep():
    sleeps = []
    p = RetryPolicy(max_attempts=3)
    out = p.execute(lambda: "ok", sleep=sleeps.append)
    assert out == "ok"
    assert sleeps == []


def test_execute_retries_then_succeeds():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "recovered"

    p = RetryPolicy(max_attempts=4, base_delay=0.5, multiplier=2.0)
    assert p.execute(flaky, sleep=sleeps.append) == "recovered"
    assert calls["n"] == 3
    assert sleeps == [0.5, 1.0]


def test_execute_exhausts_raises_retry_exhausted():
    p = RetryPolicy(max_attempts=3, base_delay=0.1)
    with pytest.raises(RetryExhausted) as exc:
        p.execute(
            lambda: (_ for _ in ()).throw(OSError("down")),
            describe="dial",
            sleep=lambda d: None,
        )
    assert exc.value.attempts == 3
    assert isinstance(exc.value.last, OSError)
    assert "dial" in str(exc.value)


def test_execute_reraise_preserves_original_exception_type():
    p = RetryPolicy(max_attempts=2, base_delay=0.1)

    def fail():
        raise ConnectionRefusedError("refused")

    with pytest.raises(ConnectionRefusedError):
        p.execute(fail, reraise=True, sleep=lambda d: None)


def test_execute_non_matching_exception_propagates_immediately():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("config, not transport")

    p = RetryPolicy(max_attempts=5, retry_on=(OSError,))
    with pytest.raises(ValueError):
        p.execute(boom, sleep=lambda d: None)
    assert calls["n"] == 1


def test_execute_deadline_stops_before_sleeping_past_it():
    # fake clock: each attempt costs 1s; deadline 2.5s allows attempt 1,
    # one 1s backoff and attempt 2 — then the next wait would cross it.
    now = {"t": 0.0}

    def clock():
        return now["t"]

    def fail():
        now["t"] += 1.0
        raise OSError("down")

    def sleep(d):
        now["t"] += d

    p = RetryPolicy(max_attempts=10, base_delay=1.0, multiplier=1.0, deadline=2.5)
    with pytest.raises(RetryExhausted) as exc:
        p.execute(fail, sleep=sleep, clock=clock)
    assert "deadline" in str(exc.value)
    assert exc.value.attempts == 2


def test_execute_on_retry_hook_sees_attempt_exc_delay():
    seen = []
    p = RetryPolicy(max_attempts=3, base_delay=0.5, multiplier=2.0)
    with pytest.raises(RetryExhausted):
        p.execute(
            lambda: (_ for _ in ()).throw(OSError("x")),
            on_retry=lambda a, e, d: seen.append((a, type(e).__name__, d)),
            sleep=lambda d: None,
        )
    assert seen == [(1, "OSError", 0.5), (2, "OSError", 1.0)]


def test_retry_decorator():
    calls = {"n": 0}

    @retry(RetryPolicy(max_attempts=3, base_delay=0.0))
    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("once")
        return 7

    assert flaky() == 7
    assert calls["n"] == 2


# -- CircuitBreaker --------------------------------------------------------
def test_circuit_opens_after_threshold():
    cb = CircuitBreaker(failure_threshold=3, reset_timeout=30.0)
    assert cb.state == CircuitBreaker.CLOSED
    for _ in range(3):
        assert cb.allow()
        cb.record_failure()
    assert cb.state == CircuitBreaker.OPEN
    assert not cb.allow()


def test_circuit_success_resets_failure_count():
    cb = CircuitBreaker(failure_threshold=2)
    cb.record_failure()
    cb.record_success()
    cb.record_failure()
    assert cb.state == CircuitBreaker.CLOSED


def test_circuit_half_open_probe_success_closes():
    now = {"t": 0.0}
    cb = CircuitBreaker(failure_threshold=1, reset_timeout=10.0, clock=lambda: now["t"])
    cb.record_failure()
    assert cb.state == CircuitBreaker.OPEN
    now["t"] = 10.0
    assert cb.state == CircuitBreaker.HALF_OPEN
    assert cb.allow()
    cb.record_success()
    assert cb.state == CircuitBreaker.CLOSED


def test_circuit_half_open_probe_failure_reopens_and_restarts_timer():
    now = {"t": 0.0}
    cb = CircuitBreaker(failure_threshold=1, reset_timeout=10.0, clock=lambda: now["t"])
    cb.record_failure()
    now["t"] = 10.0
    assert cb.state == CircuitBreaker.HALF_OPEN
    cb.record_failure()
    assert cb.state == CircuitBreaker.OPEN
    now["t"] = 15.0  # only 5s since re-open: still open
    assert not cb.allow()
    now["t"] = 20.0
    assert cb.allow()


def test_circuit_call_raises_circuit_open_without_running():
    cb = CircuitBreaker(failure_threshold=1, reset_timeout=100.0, name="api")
    with pytest.raises(RuntimeError):
        cb.call(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    calls = {"n": 0}

    def fn():
        calls["n"] += 1

    with pytest.raises(CircuitOpenError) as exc:
        cb.call(fn)
    assert calls["n"] == 0
    assert "api" in str(exc.value)


# -- IdleBackoff -----------------------------------------------------------
def test_idle_backoff_grows_and_caps():
    ib = IdleBackoff(initial=0.05, maximum=0.4, multiplier=2.0)
    assert [ib.next_wait() for _ in range(5)] == [0.05, 0.1, 0.2, 0.4, 0.4]


def test_idle_backoff_reset_snaps_back():
    ib = IdleBackoff(initial=0.05, maximum=1.0)
    for _ in range(4):
        ib.next_wait()
    ib.reset()
    assert ib.next_wait() == 0.05


# -- shared error formatting ----------------------------------------------
def test_format_ready_timeout_shape():
    msg = format_ready_timeout(
        "port-forward", "worker w-0", 20.04, "ports 8080->80"
    )
    assert msg == "port-forward to worker w-0 not ready after 20.0s (ports 8080->80)"
    assert (
        format_ready_timeout("sync", "w-1", 1.0)
        == "sync to w-1 not ready after 1.0s"
    )
