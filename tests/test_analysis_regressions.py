"""Regression pins for the fixes the hot-path/concurrency analyzers
surfaced, plus the analysis gate's end-to-end contract.

The lint-based pins strip the inline ``lint: allow`` pragmas before
linting, so they see the raw findings: each fix is pinned as "exactly
one designed sync point remains" — reintroducing the pre-fix pattern
(one blocking transfer per array instead of one per batch/round) makes
the count jump and the pin fail."""

import os
import subprocess
import sys

import jax
import pytest

from devspace_tpu.lint import lint_python_sources
from devspace_tpu.models import transformer as tfm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_without_pragmas(rel: str):
    with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
        text = fh.read()
    return lint_python_sources([(rel, text.replace("lint: allow", "lint-off"))])


def test_spill_blocks_single_readback_per_batch():
    """engine._spill_blocks: four np.asarray transfers per batch were
    consolidated into one jax.device_get — the lint must now see exactly
    one (allowed) sync point in that loop, not four."""
    spill = [
        f
        for f in _lint_without_pragmas("devspace_tpu/inference/engine.py")
        if f.rule_id == "JIT502"
        and f.location == "InferenceEngine._spill_blocks"
    ]
    assert len(spill) == 1, [f.message for f in spill]
    assert "device_get" in spill[0].message


def test_speculative_single_readback_per_round():
    """speculative.generate_speculative: two np.asarray readbacks per
    verification round became one jax.device_get over the pair."""
    syncs = [
        f
        for f in _lint_without_pragmas(
            "devspace_tpu/inference/speculative.py"
        )
        if f.rule_id == "JIT502" and f.location == "generate_speculative"
    ]
    assert len(syncs) == 1, [f.message for f in syncs]
    assert "device_get" in syncs[0].message


def test_stop_fails_outstanding_outside_submit_lock():
    """engine.stop() used to hold _submit_lock across the whole
    _fail_outstanding sweep (telemetry, event sinks, stream wakeups under
    the lock) — it must run with the lock released."""
    from devspace_tpu.inference import InferenceEngine

    params = tfm.init_params(tfm.TINY, jax.random.PRNGKey(0))
    engine = InferenceEngine(
        params, tfm.TINY, max_slots=2, max_len=64, chunk_max=4
    )
    seen = {}
    orig = engine._fail_outstanding

    def probe(reason, drain_queue=True):
        seen["locked_during_fail"] = engine._submit_lock.locked()
        return orig(reason, drain_queue=drain_queue)

    engine._fail_outstanding = probe
    engine.stop()
    assert seen == {"locked_during_fail": False}
    # and the stop flag still fails late submitters fast
    with pytest.raises(RuntimeError, match="stopped"):
        engine.submit([1, 2, 3], 4)


def test_analysis_gate_static_legs_pass():
    """The CI gate's static legs (self-lint, catalogs, seeded-fixture
    detection) exit 0 on the shipped tree; the serving tripwire has its
    own in-process coverage in test_lint_runtime.py."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "analysis_gate.py"),
            "--skip-serving",
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[gate] ok" in proc.stdout
    assert "0 missed" in proc.stdout
