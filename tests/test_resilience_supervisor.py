"""SessionSupervisor state-machine tests.

Services are tiny in-memory fakes with the same surface the dev loop's real
services expose (``alive()``/``stop()``/``error``); restart policies use
zero delays so every test settles in well under a second.
"""

import time

import pytest

from devspace_tpu.resilience import (
    RESTART_ALWAYS,
    RESTART_NEVER,
    RESTART_ON_FAILURE,
    RetryPolicy,
    ServiceState,
    SessionSupervisor,
)


def wait_for(cond, timeout=5.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class FakeService:
    def __init__(self):
        self._alive = True
        self.error = None
        self.stops = 0

    def alive(self):
        return self._alive

    def stop(self):
        self.stops += 1
        self._alive = False

    def die(self, error=None):
        self.error = error
        self._alive = False


def fast_policy(attempts=3):
    return RetryPolicy(max_attempts=attempts, base_delay=0.01, max_delay=0.02)


def make_supervisor(restart=RESTART_ON_FAILURE, **kw):
    return SessionSupervisor(
        restart=restart, poll_interval=0.01, default_policy=fast_policy(), **kw
    )


def svc_row(sup, name):
    return next(r for r in sup.status() if r["service"] == name)


def test_invalid_restart_policy_rejected():
    with pytest.raises(ValueError):
        SessionSupervisor(restart="sometimes")


def test_factory_failure_at_startup_is_loud():
    sup = make_supervisor()
    sup.add("bad", factory=lambda: (_ for _ in ()).throw(RuntimeError("no pods")))
    with pytest.raises(RuntimeError, match="no pods"):
        sup.start()


def test_restart_on_failure_restarts_and_recovers():
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor()
    sup.add("sync", factory, failure=lambda s: s.error, critical=True)
    sup.start()
    try:
        made[0].die("exec stream severed")
        wait_for(
            lambda: svc_row(sup, "sync")["restarts"] == 1, msg="service restarted"
        )
        assert len(made) == 2
        assert made[1].alive()
        assert svc_row(sup, "sync")["state"] == ServiceState.RUNNING
        assert svc_row(sup, "sync")["last_error"] == "exec stream severed"
        assert not sup.failed.is_set()
        kinds = [e.kind for e in sup.events]
        assert "died" in kinds and "restarting" in kinds and "restarted" in kinds
    finally:
        sup.stop()


def test_clean_exit_stops_under_on_failure():
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor(RESTART_ON_FAILURE)
    sup.add("term", factory, failure=lambda s: s.error)
    sup.start()
    try:
        made[0].die(error=None)  # clean exit: no error recorded
        wait_for(
            lambda: svc_row(sup, "term")["state"] == ServiceState.STOPPED,
            msg="clean exit observed",
        )
        assert len(made) == 1  # never restarted
        assert not sup.failed.is_set()
    finally:
        sup.stop()


def test_clean_exit_restarts_under_always():
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor(RESTART_ALWAYS)
    sup.add("term", factory, failure=lambda s: s.error)
    sup.start()
    try:
        made[0].die(error=None)
        wait_for(lambda: len(made) >= 2, msg="restart after clean exit")
    finally:
        sup.stop()


def test_never_policy_escalates_without_restart():
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor(RESTART_NEVER)
    sup.add("sync", factory, failure=lambda s: s.error, critical=True)
    sup.start()
    try:
        made[0].die("gone")
        wait_for(lambda: sup.failed.is_set(), msg="escalation")
        assert len(made) == 1
        assert "sync" in sup.error and "gone" in sup.error
    finally:
        sup.stop()


def test_noncritical_exhausted_goes_degraded_session_continues():
    calls = {"n": 0}

    def factory():
        calls["n"] += 1
        if calls["n"] == 1:
            s = FakeService()
            factory.first = s
            return s
        raise RuntimeError("bind refused")  # every restart attempt fails

    sup = make_supervisor()
    sup.add("ports", factory, failure=lambda s: s.error, critical=False,
            policy=fast_policy(attempts=2))
    sup.start()
    try:
        factory.first.die("listener died")
        wait_for(
            lambda: svc_row(sup, "ports")["state"] == ServiceState.DEGRADED,
            msg="degraded",
        )
        # non-critical exhaustion must NOT end the session
        assert not sup.failed.is_set()
        assert sup.error is None
        assert any(e.kind == "degraded" for e in sup.events)
    finally:
        sup.stop()


def test_critical_exhausted_sets_failed_and_error():
    calls = {"n": 0}

    def factory():
        calls["n"] += 1
        if calls["n"] == 1:
            s = FakeService()
            factory.first = s
            return s
        raise RuntimeError("no workers running")

    sup = make_supervisor()
    sup.add("sync", factory, failure=lambda s: s.error, critical=True,
            policy=fast_policy(attempts=2))
    sup.start()
    try:
        factory.first.die("authority lost")
        wait_for(lambda: sup.failed.is_set(), msg="critical escalation")
        assert svc_row(sup, "sync")["state"] == ServiceState.FAILED
        assert "sync" in sup.error
    finally:
        sup.stop()


def test_stop_stops_running_handles():
    s = FakeService()
    sup = make_supervisor()
    sup.add("svc", lambda: s)
    sup.start()
    sup.stop()
    assert s.stops == 1
    assert svc_row(sup, "svc")["state"] == ServiceState.STOPPED


def test_status_line_reports_health_and_restarts():
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor()
    sup.add("ports", factory, failure=lambda s: s.error)
    sup.add("sync", lambda: FakeService(), critical=True)
    sup.start()
    try:
        assert sup.status_line() == "2/2 services up"
        made[0].die("dropped")
        wait_for(lambda: svc_row(sup, "ports")["restarts"] == 1, msg="restart")
        line = sup.status_line()
        assert "2/2 services up" in line and "1 restart(s)" in line
    finally:
        sup.stop()


def test_on_event_callback_fires_and_cannot_kill_monitor():
    events = []

    def observer(ev):
        events.append((ev.service, ev.kind))
        raise RuntimeError("observer bug")  # must be swallowed

    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor(on_event=observer)
    sup.add("svc", factory, failure=lambda s: s.error)
    sup.start()
    try:
        made[0].die("x")
        wait_for(
            lambda: ("svc", "restarted") in events, msg="events despite bad observer"
        )
        assert ("svc", "started") in events
        assert ("svc", "died") in events
    finally:
        sup.stop()


def test_default_probe_uses_handle_alive():
    # no explicit probe/failure: handle.alive() + handle.error drive it
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor()
    sup.add("svc", factory)
    sup.start()
    try:
        made[0].die("imploded")
        wait_for(lambda: svc_row(sup, "svc")["restarts"] == 1, msg="restart")
        assert svc_row(sup, "svc")["last_error"] == "imploded"
    finally:
        sup.stop()


# -- cumulative restart budget + healthy-window reset (ISSUE 18) ------------
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_restart_budget_exhaustion_degrades():
    # restart_budget counts SUCCESSFUL restarts: a service that restarts
    # cleanly every time still degrades once the cumulative cap is hit
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    sup = make_supervisor(restart=RESTART_ALWAYS)
    sup.add("svc", factory, restart_budget=2)
    sup.start()
    try:
        for i in range(2):
            made[-1].die("crash-loop")
            wait_for(
                lambda i=i: svc_row(sup, "svc")["restarts"] == i + 1,
                msg=f"restart #{i + 1}",
            )
        assert svc_row(sup, "svc")["budget_used"] == 2
        made[-1].die("crash-loop")
        wait_for(
            lambda: svc_row(sup, "svc")["state"] == ServiceState.DEGRADED,
            msg="budget exhaustion degrades",
        )
        assert "restart budget" in svc_row(sup, "svc")["last_error"] or any(
            "budget" in e.detail for e in sup.events if e.kind == "degraded"
        )
        # only 2 of the 3 deaths were allowed to restart
        assert svc_row(sup, "svc")["restarts"] == 2
    finally:
        sup.stop()


def test_healthy_window_resets_restart_budget():
    # the pin for the ISSUE 18 satellite: staying continuously healthy
    # past healthy_window_s zeroes budget_used, so an occasional crash
    # never accumulates toward the cap. Driven through _check with an
    # injected clock — no wall-time dependence.
    made = []

    def factory():
        s = FakeService()
        made.append(s)
        return s

    clk = FakeClock()
    sup = SessionSupervisor(
        restart=RESTART_ALWAYS,
        poll_interval=0.01,
        default_policy=RetryPolicy(max_attempts=3, base_delay=0.0, max_delay=0.0),
        clock=clk.now,
    )
    sup.add("svc", factory, restart_budget=1, healthy_window_s=10.0)
    with sup._lock:
        svc = sup._services[0]
    svc.handle = svc.factory()
    svc.state = ServiceState.RUNNING
    svc.running_since = clk.now()

    # death -> immediate successful restart consumes the whole budget
    made[-1].die("crash")
    sup._check(svc)  # RUNNING -> RESTARTING (budget 0/1 used, allowed)
    sup._check(svc)  # restart attempt succeeds
    assert svc.state == ServiceState.RUNNING
    assert svc.budget_used == 1

    # healthy but window not yet elapsed: budget stays consumed
    clk.advance(9.0)
    sup._check(svc)
    assert svc.budget_used == 1

    # continuously healthy past the window: budget resets + event emitted
    clk.advance(1.5)
    sup._check(svc)
    assert svc.budget_used == 0
    assert any(e.kind == "budget_reset" for e in sup.events)

    # the NEXT crash gets a fresh budget instead of degrading
    made[-1].die("crash-after-quiet-day")
    sup._check(svc)
    sup._check(svc)
    assert svc.state == ServiceState.RUNNING
    assert svc.budget_used == 1


def test_dynamic_add_start_remove():
    # the fleet-manager seam: services join and leave a live supervisor
    sup = make_supervisor(restart=RESTART_ALWAYS)
    first = FakeService()
    sup.add("a", lambda: first)
    sup.start()
    try:
        late = FakeService()
        sup.add("b", lambda: late, restart_budget=5)
        handle = sup.start_service("b")
        assert handle is late
        assert svc_row(sup, "b")["state"] == ServiceState.RUNNING
        with pytest.raises(ValueError):
            sup.start_service("b")  # double start
        with pytest.raises(KeyError):
            sup.start_service("ghost")
        with pytest.raises(ValueError):
            sup.add("b", lambda: FakeService())  # duplicate name

        removed = sup.remove("b")
        assert removed is late
        assert late.stops == 1  # remove(stop=True) tore the handle down
        assert all(r["service"] != "b" for r in sup.status())
        # the monitor must not resurrect a removed service
        time.sleep(0.05)
        assert late.stops == 1
        with pytest.raises(KeyError):
            sup.remove("b")
    finally:
        sup.stop()
