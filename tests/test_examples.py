"""Every shipped example must have a loadable config and renderable charts."""

import glob
import os

import pytest

from devspace_tpu.config.loader import ConfigLoader
from devspace_tpu.deploy.chart import render_chart

EXAMPLES = sorted(
    os.path.dirname(os.path.dirname(p))
    for p in glob.glob(
        os.path.join(os.path.dirname(__file__), "..", "examples", "*", ".devspace", "config.yaml")
    )
)


@pytest.mark.parametrize("example", EXAMPLES, ids=[os.path.basename(e) for e in EXAMPLES])
def test_example_config_loads_and_renders(example):
    loader = ConfigLoader(example)
    cfg = loader.load(interactive=False)
    assert cfg.deployments
    tpu_ctx = {
        "accelerator": (cfg.tpu.accelerator if cfg.tpu else "") or "",
        "topology": (cfg.tpu.topology if cfg.tpu else "") or "",
        "workers": (cfg.tpu.workers if cfg.tpu else 1) or 1,
        "chipsPerWorker": (cfg.tpu.chips_per_worker if cfg.tpu else 1) or 1,
        "runtimeVersion": "",
        "workerHostnames": "h0",
        "coordinatorAddress": "h0:8476",
    }
    for d in cfg.deployments:
        if d.chart:
            values = dict(d.chart.values or {})
            values.setdefault("image", "registry.local/test:tag")
            manifests = render_chart(
                os.path.join(example, d.chart.path),
                release_name=d.name,
                namespace="default",
                values=values,
                extra_context={"images": {}, "pullSecrets": [], "tpu": tpu_ctx},
            )
            assert manifests


def test_examples_present():
    names = {os.path.basename(e) for e in EXAMPLES}
    assert {
        "quickstart",
        "microservices",
        "jax-mnist",
        "jax-resnet-tpu",
        "llama-inference",
        "long-context",
    } <= names
